"""Frontend AST: query clauses and patterns.

The reference delegates parsing to Neo4j's ``cypher-frontend 9.0`` (external
dependency, ``build.params.gradle:15``; pipeline ``CypherParser.scala:66-79``).
We own the parser, so this module defines our AST: clause nodes mirroring the
openCypher 9 query structure plus the multiple-graph extensions the reference
supports (FROM GRAPH / CONSTRUCT / CATALOG CREATE GRAPH|VIEW).

Expressions inside clauses are ``tpu_cypher.ir.expr`` nodes directly (single
shared expression tree — see that module's docstring).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Tuple

from ..ir.expr import Expr, MapLit, Var
from ..ir.pattern import BOTH, INCOMING, OUTGOING  # single source of truth
from ..trees import TreeNode


@dataclass(frozen=True)
class NodePattern(TreeNode):
    var: Optional[str]
    labels: Tuple[str, ...] = ()
    properties: Optional[MapLit] = None
    base_var: Optional[str] = None  # COPY OF base in CONSTRUCT: (n COPY OF m)

    def __repr__(self) -> str:
        lbl = "".join(f":{l}" for l in self.labels)
        return f"({self.var or ''}{lbl})"


@dataclass(frozen=True)
class RelPattern(TreeNode):
    var: Optional[str]
    types: Tuple[str, ...] = ()
    direction: str = OUTGOING  # OUTGOING | INCOMING | BOTH
    properties: Optional[MapLit] = None
    length: Optional[Tuple[int, Optional[int]]] = None  # (min, max|None) for var-length
    base_var: Optional[str] = None

    @property
    def is_var_length(self) -> bool:
        return self.length is not None

    def __repr__(self) -> str:
        t = "|".join(self.types)
        arrow = {
            OUTGOING: f"-[{self.var or ''}:{t}]->",
            INCOMING: f"<-[{self.var or ''}:{t}]-",
            BOTH: f"-[{self.var or ''}:{t}]-",
        }[self.direction]
        return arrow


@dataclass(frozen=True)
class PatternPart(TreeNode):
    """One comma-separated path: node (rel node)*; optionally named."""

    elements: Tuple[TreeNode, ...]  # alternating NodePattern / RelPattern
    path_var: Optional[str] = None

    @property
    def nodes(self) -> Tuple[NodePattern, ...]:
        return tuple(e for e in self.elements if isinstance(e, NodePattern))

    @property
    def rels(self) -> Tuple[RelPattern, ...]:
        return tuple(e for e in self.elements if isinstance(e, RelPattern))


@dataclass(frozen=True)
class Pattern(TreeNode):
    parts: Tuple[PatternPart, ...]


# ---------------------------------------------------------------------------
# Clause building blocks
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class SortItem(TreeNode):
    expr: Expr
    ascending: bool = True


@dataclass(frozen=True)
class ReturnItem(TreeNode):
    expr: Expr
    alias: Optional[str] = None

    @property
    def name(self) -> str:
        if self.alias:
            return self.alias
        if isinstance(self.expr, Var):
            return self.expr.name
        return self.expr.pretty_expr()


# ---------------------------------------------------------------------------
# Clauses
# ---------------------------------------------------------------------------


class Clause(TreeNode):
    pass


@dataclass(frozen=True)
class Match(Clause):
    pattern: Pattern
    where: Optional[Expr] = None
    optional: bool = False


@dataclass(frozen=True)
class Unwind(Clause):
    expr: Expr
    var: str


@dataclass(frozen=True)
class ProjectionClause(Clause):
    """Shared body of WITH / RETURN."""

    items: Tuple[ReturnItem, ...]
    star: bool = False  # WITH * / RETURN *
    distinct: bool = False
    order_by: Tuple[SortItem, ...] = ()
    skip: Optional[Expr] = None
    limit: Optional[Expr] = None
    where: Optional[Expr] = None  # WITH ... WHERE only


@dataclass(frozen=True)
class With(ProjectionClause):
    pass


@dataclass(frozen=True)
class Return(ProjectionClause):
    pass


@dataclass(frozen=True)
class FromGraph(Clause):
    """FROM GRAPH <qualified name> or a parameterized VIEW invocation
    ``FROM GRAPH v(g1, g2)`` (multiple-graph support)."""

    graph_name: str
    args: Tuple[str, ...] = ()


@dataclass(frozen=True)
class ReturnGraph(Clause):
    """RETURN GRAPH"""


@dataclass(frozen=True)
class ConstructClause(Clause):
    """CONSTRUCT [ON g1, g2] [CLONE a, b AS c] [NEW (...)] [SET ...]

    Reference IR: ``IRBuilder.scala:271-330`` / ``LogicalPatternGraph``.
    """

    on_graphs: Tuple[str, ...] = ()
    clones: Tuple[ReturnItem, ...] = ()  # expr must be Var; alias optional
    news: Tuple[Pattern, ...] = ()
    sets: Tuple["SetItem", ...] = ()


@dataclass(frozen=True)
class SetItem(TreeNode):
    """SET a.prop = expr | SET a:Label | SET a = {..} (CONSTRUCT / CREATE)"""

    target: Expr  # Property(var, key) or Var for label set
    value: Optional[Expr] = None
    labels: Tuple[str, ...] = ()


@dataclass(frozen=True)
class CreateClause(Clause):
    """CREATE pattern — a graph write against a mutable ambient graph
    (docs/mutation.md); also reused by the in-memory test-graph factory
    (reference ``CreateQueryParser.scala:97``) and CONSTRUCT NEW."""

    pattern: Pattern


@dataclass(frozen=True)
class MergeClause(Clause):
    """MERGE pattern [ON CREATE SET ...] [ON MATCH SET ...]"""

    pattern: Pattern  # single pattern part
    on_create: Tuple["SetItem", ...] = ()
    on_match: Tuple["SetItem", ...] = ()


@dataclass(frozen=True)
class SetClause(Clause):
    """SET item [, item]* as a standalone write clause."""

    items: Tuple["SetItem", ...]


@dataclass(frozen=True)
class DeleteClause(Clause):
    """[DETACH] DELETE expr [, expr]* — exprs must be bound element vars."""

    exprs: Tuple[Expr, ...]
    detach: bool = False


@dataclass(frozen=True)
class CallClause(Clause):
    """CALL proc.name(args) [YIELD item, ...] — parsed for a clean typed
    "unsupported" error downstream (the reference parses procedure calls via
    its frontend and blacklists ProcedureCallAcceptance at TCK level)."""

    procedure: str
    args: Tuple[Expr, ...] = ()
    yields: Tuple[ReturnItem, ...] = ()
    star: bool = False


# ---------------------------------------------------------------------------
# Queries / statements
# ---------------------------------------------------------------------------


class Statement(TreeNode):
    pass


@dataclass(frozen=True)
class SingleQuery(Statement):
    clauses: Tuple[Clause, ...]


@dataclass(frozen=True)
class UnionQuery(Statement):
    queries: Tuple[Statement, ...]
    all: bool = False


@dataclass(frozen=True)
class CreateGraphStatement(Statement):
    """CATALOG CREATE GRAPH <qgn> { <query> }"""

    qgn: str
    inner: Statement


@dataclass(frozen=True)
class CreateViewStatement(Statement):
    """CATALOG CREATE VIEW <name>($p1, $p2) { <query> }"""

    name: str
    params: Tuple[str, ...]
    inner_text: str


@dataclass(frozen=True)
class DropGraphStatement(Statement):
    qgn: str
    view: bool = False

"""Cypher lexer.

Hand-rolled tokenizer for the openCypher 9 surface (the reference uses Neo4j's
``cypher-frontend``; we own the whole frontend — SURVEY.md §7 step 2).

Keywords are not distinguished from identifiers at the token level (Cypher
keywords are contextual); the parser matches them case-insensitively via the
token's ``upper`` form.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional


class CypherSyntaxError(Exception):
    def __init__(self, msg: str, text: str = "", pos: int = 0):
        self.pos = pos
        if text:
            line = text.count("\n", 0, pos) + 1
            col = pos - (text.rfind("\n", 0, pos) + 1) + 1
            snippet = text[max(0, pos - 20) : pos + 20].replace("\n", " ")
            msg = f"{msg} (line {line}, column {col}, near {snippet!r})"
        super().__init__(msg)


@dataclass(frozen=True)
class Token:
    kind: str  # IDENT ESC_IDENT INT FLOAT STRING PARAM SYM EOF
    text: str
    pos: int

    @property
    def upper(self) -> str:
        return self.text.upper()


# multi-char symbols, longest first
_SYMBOLS = [
    "<=",
    ">=",
    "<>",
    "=~",
    "->",
    "<-",
    "..",
    "+=",
    "(",
    ")",
    "[",
    "]",
    "{",
    "}",
    ",",
    ":",
    ";",
    ".",
    "+",
    "-",
    "*",
    "/",
    "%",
    "^",
    "=",
    "<",
    ">",
    "|",
    "$",
]

_ID_START = set("abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ_")
_ID_CONT = _ID_START | set("0123456789")
_DIGITS = set("0123456789")
_HEX = _DIGITS | set("abcdefABCDEF")

_ESCAPES = {
    "n": "\n",
    "t": "\t",
    "r": "\r",
    "b": "\b",
    "f": "\f",
    "'": "'",
    '"': '"',
    "\\": "\\",
    "/": "/",
}


def tokenize(text: str) -> List[Token]:
    tokens: List[Token] = []
    i = 0
    n = len(text)
    while i < n:
        c = text[i]
        # whitespace
        if c in " \t\r\n":
            i += 1
            continue
        # comments
        if c == "/" and i + 1 < n and text[i + 1] == "/":
            j = text.find("\n", i)
            i = n if j < 0 else j + 1
            continue
        if c == "/" and i + 1 < n and text[i + 1] == "*":
            j = text.find("*/", i + 2)
            if j < 0:
                raise CypherSyntaxError("Unterminated block comment", text, i)
            i = j + 2
            continue
        # strings
        if c in "'\"":
            quote = c
            j = i + 1
            buf = []
            while j < n:
                ch = text[j]
                if ch == "\\":
                    if j + 1 >= n:
                        raise CypherSyntaxError("Unterminated escape", text, j)
                    esc = text[j + 1]
                    if esc == "u":
                        hexpart = text[j + 2 : j + 6]
                        if len(hexpart) < 4 or not all(c in _HEX for c in hexpart):
                            raise CypherSyntaxError("Bad unicode escape", text, j)
                        buf.append(chr(int(hexpart, 16)))
                        j += 6
                        continue
                    if esc not in _ESCAPES:
                        raise CypherSyntaxError(f"Unknown escape \\{esc}", text, j)
                    buf.append(_ESCAPES[esc])
                    j += 2
                    continue
                if ch == quote:
                    break
                buf.append(ch)
                j += 1
            else:
                raise CypherSyntaxError("Unterminated string literal", text, i)
            tokens.append(Token("STRING", "".join(buf), i))
            i = j + 1
            continue
        # escaped identifiers
        if c == "`":
            j = text.find("`", i + 1)
            if j < 0:
                raise CypherSyntaxError("Unterminated escaped identifier", text, i)
            tokens.append(Token("ESC_IDENT", text[i + 1 : j], i))
            i = j + 1
            continue
        # numbers
        if c in _DIGITS or (c == "." and i + 1 < n and text[i + 1] in _DIGITS):
            # hex / octal
            if c == "0" and i + 1 < n and text[i + 1] in "xX":
                j = i + 2
                while j < n and text[j] in _HEX:
                    j += 1
                if j == i + 2:
                    raise CypherSyntaxError("Malformed hex literal", text, i)
                tokens.append(Token("INT", str(int(text[i:j], 16)), i))
                i = j
                continue
            j = i
            is_float = False
            while j < n and text[j] in _DIGITS:
                j += 1
            # don't consume '..' (range), only '.' followed by a digit
            if j < n and text[j] == "." and j + 1 < n and text[j + 1] in _DIGITS:
                is_float = True
                j += 1
                while j < n and text[j] in _DIGITS:
                    j += 1
            if c == "." :
                is_float = True
            if j < n and text[j] in "eE":
                k = j + 1
                if k < n and text[k] in "+-":
                    k += 1
                if k < n and text[k] in _DIGITS:
                    is_float = True
                    j = k
                    while j < n and text[j] in _DIGITS:
                        j += 1
            kind = "FLOAT" if is_float else "INT"
            tokens.append(Token(kind, text[i:j], i))
            i = j
            continue
        # identifiers / keywords
        if c in _ID_START:
            j = i + 1
            while j < n and text[j] in _ID_CONT:
                j += 1
            tokens.append(Token("IDENT", text[i:j], i))
            i = j
            continue
        # symbols
        for sym in _SYMBOLS:
            if text.startswith(sym, i):
                tokens.append(Token("SYM", sym, i))
                i += len(sym)
                break
        else:
            raise CypherSyntaxError(f"Unexpected character {c!r}", text, i)
    tokens.append(Token("EOF", "", n))
    return tokens

"""Cypher recursive-descent parser.

Replaces the reference's external Neo4j ``cypher-frontend 9.0`` dependency
(pipeline wrapped at ``okapi-ir/.../impl/parse/CypherParser.scala:52-79``) with
an owned parser producing ``frontend.ast`` clauses over the shared
``ir.expr`` expression tree.

Grammar coverage: single/union read queries (MATCH / OPTIONAL MATCH / WHERE /
WITH / RETURN / UNWIND / ORDER BY / SKIP / LIMIT / DISTINCT), full expression
grammar (boolean ops, chained comparisons, string/list/null predicates,
arithmetic, CASE, list/map literals, comprehensions, quantifiers, reduce,
functions/aggregates, pattern predicates), patterns incl. undirected and
variable-length relationships, named paths, and the multiple-graph surface
(CATALOG CREATE GRAPH/VIEW, DROP, FROM GRAPH, CONSTRUCT, RETURN GRAPH) plus
CREATE for test-graph construction.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from ..ir import expr as E
from . import ast as A
from .lexer import CypherSyntaxError, Token, tokenize

AGG_NAMES = {
    "count",
    "sum",
    "avg",
    "min",
    "max",
    "collect",
    "stdev",
    "stdevp",
    "percentilecont",
    "percentiledisc",
}

QUANTIFIERS = {"any", "all", "none", "single"}

_CLAUSE_STARTS = {
    "MATCH",
    "OPTIONAL",
    "WITH",
    "RETURN",
    "UNWIND",
    "WHERE",
    "ORDER",
    "SKIP",
    "LIMIT",
    "UNION",
    "CREATE",
    "CONSTRUCT",
    "FROM",
    "CLONE",
    "NEW",
    "SET",
    "ON",
    "CATALOG",
    "DETACH",
    "DELETE",
    "MERGE",
}


class Parser:
    def __init__(self, text: str):
        self.text = text
        self.tokens = tokenize(text)
        self.i = 0

    # -- token helpers -----------------------------------------------------

    def peek(self, ahead: int = 0) -> Token:
        j = min(self.i + ahead, len(self.tokens) - 1)
        return self.tokens[j]

    def next(self) -> Token:
        t = self.peek()
        if t.kind != "EOF":
            self.i += 1
        return t

    def at_sym(self, s: str, ahead: int = 0) -> bool:
        t = self.peek(ahead)
        return t.kind == "SYM" and t.text == s

    def at_kw(self, *kws: str, ahead: int = 0) -> bool:
        t = self.peek(ahead)
        return t.kind == "IDENT" and t.upper in kws

    def eat_sym(self, s: str) -> Token:
        if not self.at_sym(s):
            self.fail(f"Expected {s!r}")
        return self.next()

    def eat_kw(self, kw: str) -> Token:
        if not self.at_kw(kw):
            self.fail(f"Expected {kw}")
        return self.next()

    def try_sym(self, s: str) -> bool:
        if self.at_sym(s):
            self.next()
            return True
        return False

    def try_kw(self, *kws: str) -> bool:
        if self.at_kw(*kws):
            self.next()
            return True
        return False

    def fail(self, msg: str):
        t = self.peek()
        raise CypherSyntaxError(f"{msg}, found {t.text!r}", self.text, t.pos)

    def name(self) -> str:
        t = self.peek()
        if t.kind in ("IDENT", "ESC_IDENT"):
            self.next()
            return t.text
        self.fail("Expected identifier")

    # -- entry points ------------------------------------------------------

    def parse_statement(self) -> A.Statement:
        if self.at_kw("CATALOG") or (
            self.at_kw("CREATE") and self.at_kw("GRAPH", "VIEW", ahead=1)
        ) or (self.at_kw("DROP") and self.at_kw("GRAPH", "VIEW", ahead=1)):
            stmt = self.parse_catalog_statement()
        else:
            stmt = self.parse_query()
        self.try_sym(";")
        if self.peek().kind != "EOF":
            self.fail("Unexpected input after query")
        return stmt

    def parse_query(self) -> A.Statement:
        first = self.parse_single_query()
        queries = [first]
        alls: List[bool] = []
        while self.at_kw("UNION"):
            self.next()
            alls.append(self.try_kw("ALL"))
            queries.append(self.parse_single_query())
        if len(queries) == 1:
            return first
        if any(alls) and not all(alls):
            self.fail("Cannot mix UNION and UNION ALL")
        return A.UnionQuery(tuple(queries), all=bool(alls and alls[0]))

    def parse_catalog_statement(self) -> A.Statement:
        self.try_kw("CATALOG")
        if self.try_kw("CREATE"):
            if self.try_kw("GRAPH"):
                qgn = self.parse_qgn()
                self.eat_sym("{")
                inner = self.parse_query()
                self.eat_sym("}")
                return A.CreateGraphStatement(qgn, inner)
            if self.try_kw("VIEW"):
                vname = self.name()
                params: List[str] = []
                if self.try_sym("("):
                    while not self.at_sym(")"):
                        self.eat_sym("$")
                        params.append(self.name())
                        self.try_sym(",")
                    self.eat_sym(")")
                self.eat_sym("{")
                start = self.peek().pos
                depth = 1
                while depth > 0:
                    t = self.next()
                    if t.kind == "EOF":
                        self.fail("Unterminated view body")
                    if t.kind == "SYM" and t.text == "{":
                        depth += 1
                    elif t.kind == "SYM" and t.text == "}":
                        depth -= 1
                        end = t.pos
                return A.CreateViewStatement(vname, tuple(params), self.text[start:end])
            self.fail("Expected GRAPH or VIEW")
        if self.try_kw("DROP"):
            if self.try_kw("GRAPH"):
                return A.DropGraphStatement(self.parse_qgn())
            if self.try_kw("VIEW"):
                return A.DropGraphStatement(self.parse_qgn(), view=True)
            self.fail("Expected GRAPH or VIEW")
        self.fail("Expected CREATE or DROP after CATALOG")

    def parse_qgn(self) -> str:
        parts = [self.name()]
        while self.try_sym("."):
            parts.append(self.name())
        return ".".join(parts)

    # -- single query ------------------------------------------------------

    def parse_single_query(self) -> A.SingleQuery:
        clauses: List[A.Clause] = []
        while True:
            t = self.peek()
            if t.kind == "EOF" or self.at_kw("UNION") or self.at_sym("}") or self.at_sym(";"):
                break
            clauses.append(self.parse_clause())
        if not clauses:
            self.fail("Empty query")
        return A.SingleQuery(tuple(clauses))

    def parse_clause(self) -> A.Clause:
        if self.at_kw("MATCH"):
            return self.parse_match(optional=False)
        if self.at_kw("OPTIONAL"):
            self.next()
            return self.parse_match(optional=True)
        if self.at_kw("UNWIND"):
            self.next()
            e = self.parse_expression()
            self.eat_kw("AS")
            return A.Unwind(e, self.name())
        if self.at_kw("WITH"):
            self.next()
            return self.parse_projection(A.With, allow_where=True)
        if self.at_kw("RETURN"):
            self.next()
            if self.try_kw("GRAPH"):
                return A.ReturnGraph()
            return self.parse_projection(A.Return, allow_where=False)
        if self.at_kw("FROM"):
            self.next()
            self.try_kw("GRAPH")
            name = self.parse_qgn()
            args: List[str] = []
            if self.try_sym("("):
                # parameterized view invocation: FROM GRAPH v(g1, g2)
                while not self.at_sym(")"):
                    args.append(self.parse_qgn())
                    if not self.at_sym(")"):
                        self.eat_sym(",")
                self.eat_sym(")")
            return A.FromGraph(name, tuple(args))
        if self.at_kw("CONSTRUCT"):
            self.next()
            return self.parse_construct()
        if self.at_kw("CREATE"):
            self.next()
            return A.CreateClause(self.parse_pattern())
        if self.at_kw("MERGE"):
            self.next()
            pattern = self.parse_pattern(single_part=True)
            on_create: List[A.SetItem] = []
            on_match: List[A.SetItem] = []
            while self.try_kw("ON"):
                if self.try_kw("CREATE"):
                    items = on_create
                elif self.try_kw("MATCH"):
                    items = on_match
                else:
                    self.fail("Expected CREATE or MATCH after ON")
                self.eat_kw("SET")
                items.append(self.parse_set_item())
                while self.try_sym(","):
                    items.append(self.parse_set_item())
            return A.MergeClause(pattern, tuple(on_create), tuple(on_match))
        if self.at_kw("SET"):
            self.next()
            items = [self.parse_set_item()]
            while self.try_sym(","):
                items.append(self.parse_set_item())
            return A.SetClause(tuple(items))
        if self.at_kw("DELETE") or self.at_kw("DETACH"):
            detach = self.try_kw("DETACH")
            self.eat_kw("DELETE")
            exprs = [self.parse_expression()]
            while self.try_sym(","):
                exprs.append(self.parse_expression())
            return A.DeleteClause(tuple(exprs), detach)
        if self.at_kw("CALL"):
            self.next()
            return self.parse_call()
        self.fail("Expected a clause")

    def parse_call(self) -> A.CallClause:
        parts = [self.name()]
        while self.try_sym("."):
            parts.append(self.name())
        args: List[E.Expr] = []
        if self.try_sym("("):
            while not self.at_sym(")"):
                args.append(self.parse_expression())
                if not self.at_sym(")"):
                    self.eat_sym(",")
            self.eat_sym(")")
        yields: List[A.ReturnItem] = []
        star = False
        if self.try_kw("YIELD"):
            if self.at_sym("*"):
                self.next()
                star = True
            else:
                yields.append(self.parse_return_item())
                while self.try_sym(","):
                    yields.append(self.parse_return_item())
        return A.CallClause(
            ".".join(parts), tuple(args), tuple(yields), star
        )

    def parse_match(self, optional: bool) -> A.Match:
        self.eat_kw("MATCH")
        pattern = self.parse_pattern()
        where = None
        if self.try_kw("WHERE"):
            where = self.parse_expression()
        return A.Match(pattern, where, optional)

    def parse_projection(self, cls, allow_where: bool) -> A.ProjectionClause:
        distinct = self.try_kw("DISTINCT")
        star = False
        items: List[A.ReturnItem] = []
        if self.at_sym("*"):
            self.next()
            star = True
            while self.try_sym(","):
                items.append(self.parse_return_item())
        else:
            items.append(self.parse_return_item())
            while self.try_sym(","):
                items.append(self.parse_return_item())
        order_by: Tuple[A.SortItem, ...] = ()
        skip = limit = where = None
        if self.at_kw("ORDER"):
            self.next()
            self.eat_kw("BY")
            sorts = [self.parse_sort_item()]
            while self.try_sym(","):
                sorts.append(self.parse_sort_item())
            order_by = tuple(sorts)
        if self.try_kw("SKIP"):
            skip = self.parse_expression()
        if self.try_kw("LIMIT"):
            limit = self.parse_expression()
        if allow_where and self.try_kw("WHERE"):
            where = self.parse_expression()
        return cls(
            items=tuple(items),
            star=star,
            distinct=distinct,
            order_by=order_by,
            skip=skip,
            limit=limit,
            where=where,
        )

    def parse_return_item(self) -> A.ReturnItem:
        e = self.parse_expression()
        alias = None
        if self.try_kw("AS"):
            alias = self.name()
        return A.ReturnItem(e, alias)

    def parse_sort_item(self) -> A.SortItem:
        e = self.parse_expression()
        asc = True
        if self.try_kw("ASC", "ASCENDING"):
            asc = True
        elif self.try_kw("DESC", "DESCENDING"):
            asc = False
        return A.SortItem(e, asc)

    def parse_construct(self) -> A.ConstructClause:
        on_graphs: List[str] = []
        clones: List[A.ReturnItem] = []
        news: List[A.Pattern] = []
        sets: List[A.SetItem] = []
        if self.try_kw("ON"):
            on_graphs.append(self.parse_qgn())
            while self.try_sym(","):
                on_graphs.append(self.parse_qgn())
        while True:
            if self.try_kw("CLONE"):
                clones.append(self.parse_return_item())
                while self.try_sym(","):
                    clones.append(self.parse_return_item())
            elif self.try_kw("NEW") or self.try_kw("CREATE"):
                news.append(self.parse_pattern(single_part=True))
            elif self.try_kw("SET"):
                sets.append(self.parse_set_item())
                while self.try_sym(","):
                    sets.append(self.parse_set_item())
            else:
                break
        return A.ConstructClause(tuple(on_graphs), tuple(clones), tuple(news), tuple(sets))

    def parse_set_item(self) -> A.SetItem:
        var = E.Var(self.name())
        if self.try_sym("."):
            key = self.name()
            self.eat_sym("=")
            return A.SetItem(E.Property(var, key), self.parse_expression())
        if self.at_sym(":"):
            labels = []
            while self.try_sym(":"):
                labels.append(self.name())
            return A.SetItem(var, labels=tuple(labels))
        self.eat_sym("=")
        return A.SetItem(var, self.parse_expression())

    # -- patterns ----------------------------------------------------------

    def parse_pattern(self, single_part: bool = False) -> A.Pattern:
        parts = [self.parse_pattern_part()]
        if not single_part:
            while self.try_sym(","):
                parts.append(self.parse_pattern_part())
        return A.Pattern(tuple(parts))

    def parse_pattern_part(self) -> A.PatternPart:
        path_var = None
        if (
            self.peek().kind in ("IDENT", "ESC_IDENT")
            and self.at_sym("=", ahead=1)
            and self.peek().upper not in _CLAUSE_STARTS
        ):
            path_var = self.name()
            self.eat_sym("=")
        elements: List = [self.parse_node_pattern()]
        while self.at_sym("-") or self.at_sym("<-") or self.at_sym("<"):
            rel = self.parse_rel_pattern()
            node = self.parse_node_pattern()
            elements.append(rel)
            elements.append(node)
        return A.PatternPart(tuple(elements), path_var)

    def parse_node_pattern(self) -> A.NodePattern:
        self.eat_sym("(")
        var = None
        base_var = None
        labels: List[str] = []
        props = None
        if self.peek().kind in ("IDENT", "ESC_IDENT") and not self.at_kw("COPY"):
            var = self.name()
        if self.try_kw("COPY"):
            self.eat_kw("OF")
            base_var = self.name()
        while self.try_sym(":"):
            labels.append(self.name())
        if self.at_sym("{"):
            props = self.parse_map_literal()
        self.eat_sym(")")
        return A.NodePattern(var, tuple(labels), props, base_var)

    def parse_rel_pattern(self) -> A.RelPattern:
        # entry token is '-', '<-' or '<'
        if self.try_sym("<-"):
            incoming_start = True
        elif self.try_sym("<"):
            self.eat_sym("-")
            incoming_start = True
        else:
            self.eat_sym("-")
            incoming_start = False
        var = None
        base_var = None
        types: List[str] = []
        props = None
        length = None
        if self.try_sym("["):
            if self.peek().kind in ("IDENT", "ESC_IDENT") and not self.at_kw("COPY"):
                var = self.name()
            if self.try_kw("COPY"):
                self.eat_kw("OF")
                base_var = self.name()
            if self.try_sym(":"):
                types.append(self.name())
                while self.try_sym("|"):
                    self.try_sym(":")
                    types.append(self.name())
            if self.try_sym("*"):
                lo, hi = 1, None
                if self.peek().kind == "INT":
                    lo = int(self.next().text)
                    hi = lo
                if self.try_sym(".."):
                    hi = None
                    if self.peek().kind == "INT":
                        hi = int(self.next().text)
                length = (lo, hi)
            if self.at_sym("{"):
                props = self.parse_map_literal()
            self.eat_sym("]")
        # closing arrow
        if self.try_sym("->"):
            outgoing_end = True
        elif self.try_sym("-"):
            outgoing_end = False
            if self.try_sym(">"):
                outgoing_end = True
        else:
            self.fail("Expected relationship arrow")
        if incoming_start and outgoing_end:
            direction = A.BOTH  # <-[]-> treated as undirected
        elif incoming_start:
            direction = A.INCOMING
        elif outgoing_end:
            direction = A.OUTGOING
        else:
            direction = A.BOTH
        return A.RelPattern(var, tuple(types), direction, props, length, base_var)

    def parse_map_literal(self) -> E.MapLit:
        self.eat_sym("{")
        keys: List[str] = []
        values: List[E.Expr] = []
        while not self.at_sym("}"):
            keys.append(self.name())
            self.eat_sym(":")
            values.append(self.parse_expression())
            if not self.try_sym(","):
                break
        self.eat_sym("}")
        return E.MapLit(tuple(keys), tuple(values))

    # -- expressions -------------------------------------------------------

    def parse_expression(self) -> E.Expr:
        return self.parse_or()

    def parse_or(self) -> E.Expr:
        e = self.parse_xor()
        if self.at_kw("OR"):
            terms = [e]
            while self.try_kw("OR"):
                terms.append(self.parse_xor())
            return E.Ors.of(*terms)
        return e

    def parse_xor(self) -> E.Expr:
        e = self.parse_and()
        while self.at_kw("XOR"):
            self.next()
            e = E.Xor(e, self.parse_and())
        return e

    def parse_and(self) -> E.Expr:
        e = self.parse_not()
        if self.at_kw("AND"):
            terms = [e]
            while self.try_kw("AND"):
                terms.append(self.parse_not())
            return E.Ands.of(*terms)
        return e

    def parse_not(self) -> E.Expr:
        if self.try_kw("NOT"):
            return E.Not(self.parse_not())
        return self.parse_comparison()

    _CMP = {
        "=": E.Equals,
        "<>": E.Neq,
        "<": E.LessThan,
        "<=": E.LessThanOrEqual,
        ">": E.GreaterThan,
        ">=": E.GreaterThanOrEqual,
    }

    def parse_comparison(self) -> E.Expr:
        e = self.parse_predicated()
        comparisons: List[E.Expr] = []
        left = e
        while self.peek().kind == "SYM" and self.peek().text in self._CMP:
            op = self.next().text
            right = self.parse_predicated()
            comparisons.append(self._CMP[op](left, right))
            left = right
        if not comparisons:
            return e
        if len(comparisons) == 1:
            return comparisons[0]
        return E.Ands.of(*comparisons)

    def parse_predicated(self) -> E.Expr:
        """STARTS WITH / ENDS WITH / CONTAINS / IN / =~ / IS [NOT] NULL."""
        e = self.parse_additive()
        while True:
            if self.at_kw("STARTS"):
                self.next()
                self.eat_kw("WITH")
                e = E.StartsWith(e, self.parse_additive())
            elif self.at_kw("ENDS"):
                self.next()
                self.eat_kw("WITH")
                e = E.EndsWith(e, self.parse_additive())
            elif self.at_kw("CONTAINS"):
                self.next()
                e = E.Contains(e, self.parse_additive())
            elif self.at_kw("IN"):
                self.next()
                e = E.In(e, self.parse_additive())
            elif self.at_sym("=~"):
                self.next()
                e = E.RegexMatch(e, self.parse_additive())
            elif self.at_kw("IS"):
                self.next()
                if self.try_kw("NOT"):
                    self.eat_kw("NULL")
                    e = E.IsNotNull(e)
                else:
                    self.eat_kw("NULL")
                    e = E.IsNull(e)
            else:
                return e

    def parse_additive(self) -> E.Expr:
        e = self.parse_multiplicative()
        while True:
            if self.at_sym("+"):
                self.next()
                e = E.Add(e, self.parse_multiplicative())
            elif self.at_sym("-"):
                self.next()
                e = E.Subtract(e, self.parse_multiplicative())
            else:
                return e

    def parse_multiplicative(self) -> E.Expr:
        e = self.parse_unary()
        while True:
            if self.at_sym("*"):
                self.next()
                e = E.Multiply(e, self.parse_unary())
            elif self.at_sym("/"):
                self.next()
                e = E.Divide(e, self.parse_unary())
            elif self.at_sym("%"):
                self.next()
                e = E.Modulo(e, self.parse_unary())
            else:
                return e

    def parse_unary(self) -> E.Expr:
        # power binds tighter than unary minus (openCypher: -2^2 = -(2^2))
        if self.try_sym("-"):
            inner = self.parse_unary()
            if (
                isinstance(inner, E.Lit)
                and isinstance(inner.value, (int, float))
                and not isinstance(inner.value, bool)
            ):
                return E.Lit(-inner.value)
            return E.Neg(inner)
        if self.try_sym("+"):
            return self.parse_unary()
        return self.parse_power()

    def parse_power(self) -> E.Expr:
        e = self.parse_postfix()
        if self.at_sym("^"):
            self.next()
            return E.Pow(e, self.parse_unary())  # right-assoc; exponent may be unary
        return e

    def parse_postfix(self) -> E.Expr:
        e = self.parse_atom()
        while True:
            if self.at_sym("."):
                self.next()
                e = E.Property(e, self.name())
            elif self.at_sym("["):
                self.next()
                lo: Optional[E.Expr] = None
                if not self.at_sym("..") and not self.at_sym("]"):
                    lo = self.parse_expression()
                if self.try_sym(".."):
                    hi: Optional[E.Expr] = None
                    if not self.at_sym("]"):
                        hi = self.parse_expression()
                    self.eat_sym("]")
                    e = E.ListSlice(e, lo, hi)
                else:
                    self.eat_sym("]")
                    if lo is None:
                        self.fail("Empty index")
                    e = E.Index(e, lo)
            elif (
                self.at_sym(":")
                and self.peek(1).kind in ("IDENT", "ESC_IDENT")
            ):
                # label/type predicate: n:Person[:Employee...]
                preds: List[E.Expr] = []
                while self.try_sym(":"):
                    preds.append(E.HasLabel(e, self.name()))
                e = E.Ands.of(*preds)
            else:
                return e

    def parse_atom(self) -> E.Expr:
        t = self.peek()
        if t.kind == "INT":
            self.next()
            return E.Lit(int(t.text))
        if t.kind == "FLOAT":
            self.next()
            return E.Lit(float(t.text))
        if t.kind == "STRING":
            self.next()
            return E.Lit(t.text)
        if t.kind == "SYM" and t.text == "$":
            self.next()
            p = self.peek()
            if p.kind in ("IDENT", "ESC_IDENT", "INT"):
                self.next()
                return E.Param(p.text)
            self.fail("Expected parameter name")
        if t.kind == "SYM" and t.text == "[":
            return self.parse_list_atom()
        if t.kind == "SYM" and t.text == "{":
            return self.parse_map_literal()
        if t.kind == "SYM" and t.text == "(":
            return self.parse_paren_or_pattern()
        if t.kind == "ESC_IDENT":
            self.next()
            return E.Var(t.text)
        if t.kind == "IDENT":
            u = t.upper
            if u == "TRUE":
                self.next()
                return E.TRUE
            if u == "FALSE":
                self.next()
                return E.FALSE
            if u == "NULL":
                self.next()
                return E.NULL
            if u == "CASE":
                return self.parse_case()
            if u == "COUNT" and self.at_sym("(", ahead=1) and self.at_sym("*", ahead=2):
                self.next()
                self.next()
                self.next()
                self.eat_sym(")")
                return E.CountStar()
            if u == "EXISTS" and self.at_sym("(", ahead=1):
                self.next()
                self.next()
                inner = self.parse_pattern_or_expr()
                self.eat_sym(")")
                if isinstance(inner, A.Pattern):
                    return E.ExistsPattern(inner)
                return E.IsNotNull(inner)
            if u == "REDUCE" and self.at_sym("(", ahead=1):
                self.next()
                self.next()
                acc = E.Var(self.name())
                self.eat_sym("=")
                init = self.parse_expression()
                self.eat_sym(",")
                var = E.Var(self.name())
                self.eat_kw("IN")
                lst = self.parse_expression()
                self.eat_sym("|")
                body = self.parse_expression()
                self.eat_sym(")")
                return E.Reduce(acc, init, var, lst, body)
            if t.text.lower() in QUANTIFIERS and self.at_sym("(", ahead=1):
                # any/all/none/single(x IN list WHERE pred) — must look like a
                # quantifier, not a same-named function with 1 plain arg
                save = self.i
                kind = t.text.lower()
                self.next()
                self.next()
                if self.peek().kind in ("IDENT", "ESC_IDENT") and self.at_kw("IN", ahead=1):
                    var = E.Var(self.name())
                    self.eat_kw("IN")
                    lst = self.parse_expression()
                    pred: E.Expr = E.TRUE
                    if self.try_kw("WHERE"):
                        pred = self.parse_expression()
                    self.eat_sym(")")
                    return E.Quantified(kind, var, lst, pred)
                self.i = save
            if u == "FILTER" and self.at_sym("(", ahead=1):
                self.next()
                self.next()
                var = E.Var(self.name())
                self.eat_kw("IN")
                lst = self.parse_expression()
                pred = None
                if self.try_kw("WHERE"):
                    pred = self.parse_expression()
                self.eat_sym(")")
                return E.ListComprehension(var, lst, pred, None)
            if u == "EXTRACT" and self.at_sym("(", ahead=1):
                self.next()
                self.next()
                var = E.Var(self.name())
                self.eat_kw("IN")
                lst = self.parse_expression()
                proj = None
                if self.try_sym("|"):
                    proj = self.parse_expression()
                self.eat_sym(")")
                return E.ListComprehension(var, lst, None, proj)
            # function call? (incl. qualified names like duration.between)
            if self.at_sym("(", ahead=1):
                return self.parse_function_call()
            if (
                self.at_sym(".", ahead=1)
                and self.peek(2).kind == "IDENT"
                and self.at_sym("(", ahead=3)
            ):
                return self.parse_function_call()
            # map projection: var{...}
            if self.at_sym("{", ahead=1):
                vname = self.name()
                return self.parse_map_projection(E.Var(vname))
            # plain variable
            self.next()
            return E.Var(t.text)
        self.fail("Expected expression")

    def parse_function_call(self) -> E.Expr:
        fname = self.name()
        while self.at_sym(".") and self.peek(1).kind == "IDENT":
            self.next()
            fname += "." + self.name()
        lowered = fname.lower()
        self.eat_sym("(")
        distinct = self.try_kw("DISTINCT")
        args: List[E.Expr] = []
        while not self.at_sym(")"):
            args.append(self.parse_expression())
            if not self.try_sym(","):
                break
        self.eat_sym(")")
        if lowered in AGG_NAMES:
            if not args:
                self.fail(f"Aggregator {fname} requires an argument")
            return E.Agg(lowered, args[0], distinct, tuple(args[1:]))
        if distinct:
            self.fail(f"DISTINCT only allowed in aggregations, not {fname}")
        return E.FunctionCall(lowered, tuple(args))

    def parse_map_projection(self, var: E.Var) -> E.Expr:
        self.eat_sym("{")
        items: List[Tuple[str, Optional[E.Expr]]] = []
        all_props = False
        while not self.at_sym("}"):
            if self.try_sym("."):
                if self.try_sym("*"):
                    all_props = True
                else:
                    items.append((self.name(), None))
            else:
                key = self.name()
                if self.try_sym(":"):
                    items.append((key, self.parse_expression()))
                else:
                    items.append((key, E.Var(key)))
            if not self.try_sym(","):
                break
        self.eat_sym("}")
        return E.MapProjection(var, tuple(items), all_props)

    def parse_case(self) -> E.Expr:
        self.eat_kw("CASE")
        operand = None
        if not self.at_kw("WHEN"):
            operand = self.parse_expression()
        whens: List[E.Expr] = []
        thens: List[E.Expr] = []
        while self.try_kw("WHEN"):
            whens.append(self.parse_expression())
            self.eat_kw("THEN")
            thens.append(self.parse_expression())
        default = None
        if self.try_kw("ELSE"):
            default = self.parse_expression()
        self.eat_kw("END")
        if not whens:
            self.fail("CASE requires at least one WHEN")
        return E.CaseExpr(operand, tuple(whens), tuple(thens), default)

    def parse_list_atom(self) -> E.Expr:
        """List literal or list comprehension."""
        self.eat_sym("[")
        # pattern comprehension: [p = (a)-[:R]->(b) WHERE pred | proj]
        # (path binding optional). Backtracks: a '[' may also open a list
        # literal whose first element is a parenthesized expression.
        save = self.i
        try:
            part = self.parse_pattern_part()
            if part.rels and (self.at_kw("WHERE") or self.at_sym("|")):
                where = None
                if self.try_kw("WHERE"):
                    where = self.parse_expression()
                self.eat_sym("|")
                proj = self.parse_expression()
                self.eat_sym("]")
                return E.PatternComprehension(
                    A.Pattern((part,)),
                    part.path_var,
                    E.Opaque(where) if where is not None else None,
                    E.Opaque(proj),
                )
        except CypherSyntaxError:
            pass
        self.i = save
        # list comprehension: [x IN expr WHERE p | proj]
        if self.peek().kind in ("IDENT", "ESC_IDENT") and self.at_kw("IN", ahead=1):
            var = E.Var(self.name())
            self.eat_kw("IN")
            lst = self.parse_expression()
            where = None
            proj = None
            if self.try_kw("WHERE"):
                where = self.parse_expression()
            if self.try_sym("|"):
                proj = self.parse_expression()
            self.eat_sym("]")
            return E.ListComprehension(var, lst, where, proj)
        items: List[E.Expr] = []
        while not self.at_sym("]"):
            items.append(self.parse_expression())
            if not self.try_sym(","):
                break
        self.eat_sym("]")
        return E.ListLit(tuple(items))

    def parse_paren_or_pattern(self) -> E.Expr:
        """'(' — either a parenthesized expression or a pattern predicate."""
        save = self.i
        try:
            part = self.parse_pattern_part()
            if part.rels:
                return E.ExistsPattern(A.Pattern((part,)))
        except CypherSyntaxError:
            pass
        self.i = save
        self.eat_sym("(")
        e = self.parse_expression()
        self.eat_sym(")")
        # a parenthesized expr may still begin a pattern: (a)-[:R]->(b);
        # but '(expr) - x' is arithmetic — backtrack only if a pattern parses
        if self.at_sym("-") or self.at_sym("<-"):
            after = self.i
            self.i = save
            try:
                part = self.parse_pattern_part()
                return E.ExistsPattern(A.Pattern((part,)))
            except CypherSyntaxError:
                self.i = after
        return e

    def parse_pattern_or_expr(self):
        save = self.i
        try:
            pattern = self.parse_pattern()
            if any(p.rels for p in pattern.parts) and self.at_sym(")"):
                return pattern
        except CypherSyntaxError:
            pass
        self.i = save
        return self.parse_expression()


def parse(text: str) -> A.Statement:
    """Parse a Cypher statement."""
    return Parser(text).parse_statement()


def parse_expr(text: str) -> E.Expr:
    """Parse a standalone expression (testing convenience)."""
    p = Parser(text)
    e = p.parse_expression()
    if p.peek().kind != "EOF":
        p.fail("Unexpected input after expression")
    return e

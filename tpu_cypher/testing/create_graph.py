"""In-memory test graphs from CREATE queries.

Re-design of the reference's test-graph factory
(``okapi-testing/.../propertygraph/CreateQueryParser.scala:97`` ->
``InMemoryTestGraph.scala:48`` -> backend ``ScanGraphFactory``): a CREATE
query (optionally preceded by UNWIND) is interpreted into nodes/relationships,
then grouped by label-combination / relationship type into element tables.
This is how every acceptance suite builds its fixture graph
(``initGraph("CREATE (a:Person)...")``)."""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ..api import types as T
from ..api.mapping import NodeMappingBuilder, RelationshipMappingBuilder
from ..api.values import Node, Relationship
from ..frontend import ast as A
from ..frontend.parser import parse as parse_cypher
from ..ir import expr as E
from ..relational.graphs import ElementTable, ScanGraph


class CreateQueryError(Exception):
    pass


@dataclass
class InMemoryTestGraph:
    nodes: List[Node] = field(default_factory=list)
    relationships: List[Relationship] = field(default_factory=list)


def _eval_literal(e: E.Expr, bindings: Dict[str, Any]) -> Any:
    if isinstance(e, E.Lit):
        return e.value
    if isinstance(e, E.ListLit):
        return [_eval_literal(i, bindings) for i in e.items]
    if isinstance(e, E.MapLit):
        return {k: _eval_literal(v, bindings) for k, v in zip(e.keys, e.values)}
    if isinstance(e, E.Neg):
        return -_eval_literal(e.expr, bindings)
    if isinstance(e, E.Var):
        if e.name in bindings:
            return bindings[e.name]
        raise CreateQueryError(f"Unbound variable {e.name!r} in CREATE property")
    if isinstance(e, E.FunctionCall):
        from ..ir.functions import lookup

        args = [_eval_literal(a, bindings) for a in e.args]
        return lookup(e.name).fn(*args)
    raise CreateQueryError(f"Unsupported expression in CREATE: {e.pretty_expr()}")


def parse_create_query(query: str) -> InMemoryTestGraph:
    stmt = parse_cypher(query)
    if not isinstance(stmt, A.SingleQuery):
        raise CreateQueryError("Expected a single CREATE query")
    graph = InMemoryTestGraph()
    next_id = itertools.count()
    env: Dict[str, Any] = {}

    def run_clauses(clauses: Tuple[A.Clause, ...], bindings: Dict[str, Any]):
        for clause in clauses:
            if isinstance(clause, A.Unwind):
                values = _eval_literal(clause.expr, bindings)
                rest = clauses[clauses.index(clause) + 1 :]
                for v in values:
                    b2 = dict(bindings)
                    b2[clause.var] = v
                    run_clauses(rest, b2)
                return
            if not isinstance(clause, A.CreateClause):
                raise CreateQueryError(
                    f"Only CREATE/UNWIND supported in test graphs, got {type(clause).__name__}"
                )
            _run_create(clause, bindings)

    def _run_create(clause: A.CreateClause, bindings: Dict[str, Any]):
        for part in clause.pattern.parts:
            elems = part.elements
            prev = _resolve_node(elems[0], bindings)
            for j in range(1, len(elems), 2):
                rp: A.RelPattern = elems[j]
                nxt = _resolve_node(elems[j + 1], bindings)
                if len(rp.types) != 1:
                    raise CreateQueryError("CREATE relationships need exactly one type")
                props = (
                    {
                        k: _eval_literal(v, bindings)
                        for k, v in zip(rp.properties.keys, rp.properties.values)
                    }
                    if rp.properties is not None
                    else {}
                )
                props = {k: v for k, v in props.items() if v is not None}
                if rp.direction == A.INCOMING:
                    src, dst = nxt, prev
                else:
                    src, dst = prev, nxt
                rel = Relationship(next(next_id), src.id, dst.id, rp.types[0], props)
                graph.relationships.append(rel)
                if rp.var:
                    bindings[rp.var] = rel
                prev = nxt

    def _resolve_node(np: A.NodePattern, bindings: Dict[str, Any]) -> Node:
        if np.var and np.var in bindings:
            existing = bindings[np.var]
            if not isinstance(existing, Node):
                raise CreateQueryError(f"{np.var!r} is not a node")
            return existing
        props = (
            {
                k: _eval_literal(v, bindings)
                for k, v in zip(np.properties.keys, np.properties.values)
            }
            if np.properties is not None
            else {}
        )
        props = {k: v for k, v in props.items() if v is not None}
        node = Node(next(next_id), np.labels, props)
        graph.nodes.append(node)
        if np.var:
            bindings[np.var] = node
        return node

    run_clauses(stmt.clauses, env)
    return graph


def scan_graph_from_test_graph(graph: InMemoryTestGraph, table_cls) -> ScanGraph:
    """Group by label-combo / rel-type into element tables
    (reference ``ScanGraphFactory``)."""
    tables: List[ElementTable] = []
    by_combo: Dict[frozenset, List[Node]] = {}
    for n in graph.nodes:
        by_combo.setdefault(frozenset(n.labels), []).append(n)
    for combo, nodes in sorted(by_combo.items(), key=lambda kv: sorted(kv[0])):
        keys = sorted({k for n in nodes for k in n.properties})
        cols: Dict[str, List[Any]] = {"id": [n.id for n in nodes]}
        for k in keys:
            cols[f"p_{k}"] = [n.properties.get(k) for n in nodes]
        if combo:
            builder = NodeMappingBuilder.on("id").with_implied_label(*sorted(combo))
            for k in keys:
                builder.with_property_key(k, f"p_{k}")
            mapping = builder.build()
        else:
            # unlabeled nodes: the empty label combination (valid in Cypher;
            # the builder's >=1-label validation targets user IO mappings)
            from ..api.mapping import NodeMapping

            mapping = NodeMapping(
                "id", frozenset(), (), tuple((k, f"p_{k}") for k in keys)
            )
        tables.append(ElementTable(mapping, table_cls.from_columns(cols)))
    by_type: Dict[str, List[Relationship]] = {}
    for r in graph.relationships:
        by_type.setdefault(r.rel_type, []).append(r)
    for rel_type, rels in sorted(by_type.items()):
        keys = sorted({k for r in rels for k in r.properties})
        cols = {
            "id": [r.id for r in rels],
            "src": [r.start for r in rels],
            "dst": [r.end for r in rels],
        }
        for k in keys:
            cols[f"p_{k}"] = [r.properties.get(k) for r in rels]
        builder = (
            RelationshipMappingBuilder.on("id")
            .from_("src")
            .to("dst")
            .with_relationship_type(rel_type)
        )
        for k in keys:
            builder.with_property_key(k, f"p_{k}")
        tables.append(ElementTable(builder.build(), table_cls.from_columns(cols)))
    return ScanGraph(tables)


def graph_from_create_query(session, query: str):
    from ..relational.session import PropertyGraph

    test_graph = parse_create_query(query)
    return PropertyGraph(
        session, scan_graph_from_test_graph(test_graph, session.table_cls)
    )

"""Bag (multiset) result assertions (reference ``okapi-testing/.../Bag.scala``
+ ``RecordMatchingTestSupport``)."""

from __future__ import annotations

from collections import Counter
from typing import Iterable, Mapping

from ..api.values import CypherMap


class Bag:
    def __init__(self, items: Iterable):
        self.counter = Counter(
            m if isinstance(m, CypherMap) else CypherMap(m) for m in items
        )

    def __eq__(self, other) -> bool:
        if isinstance(other, Bag):
            return self.counter == other.counter
        if isinstance(other, (list, tuple)):
            return self == Bag(other)
        return NotImplemented

    def __len__(self) -> int:
        return sum(self.counter.values())

    def __repr__(self) -> str:
        items = []
        for m, c in self.counter.items():
            items.append(f"{m!r} x{c}" if c > 1 else repr(m))
        return "Bag(" + ", ".join(items) + ")"


def bag_of(*maps: Mapping) -> Bag:
    return Bag(maps)

"""Write-ahead log: the durability half of transactional mutation.

One append-only file per mutable graph, one JSON line per committed write
batch. The commit point is the flushed (and, by default, fsynced) append:
a batch whose line is fully on disk is committed and MUST survive a
SIGKILL; a batch whose line is partial (the process died mid-append) or
absent is uncommitted and MUST be lost. Replay enforces exactly that: it
applies records in file order and stops at the first truncated or
CRC-damaged line — a partial tail is the expected signature of a crash
mid-append, not corruption worth failing boot over.

Record format (one line)::

    <crc32 hex8> <canonical JSON of {"lsn": n, "batch": {...}}>\\n

The CRC covers the JSON text, so a torn write anywhere in the line is
detected. ``append`` returns the file offset BEFORE the record so a failed
in-memory apply can roll the log back to it (``truncate``): an exception
between fsync and apply must not resurrect a write the client saw fail.

Multi-writer discipline: appends take an exclusive ``flock`` on the file,
reads a shared one. A failing-over cluster writer additionally holds
``exclusive()`` across catch-up + append (``MutableGraph.write_lock``) so
two workers can never interleave id allocation against the same log.
"""

from __future__ import annotations

import contextlib
import fcntl
import json
import os
import zlib
from typing import Any, Dict, Iterator, List, Optional, Tuple

from ..utils.config import WAL_DIR, WAL_SYNC


def wal_directory(
    explicit: Optional[str] = None, cache_dir: Optional[str] = None
) -> Optional[str]:
    """Where WAL files live: an explicit directory wins, then
    ``TPU_CYPHER_WAL_DIR``, then ``<compile cache>/wal`` (durability rides
    beside the compile artifacts it restarts with), else None — mutations
    stay in-memory only."""
    if explicit:
        return explicit
    configured = WAL_DIR.get().strip()
    if configured:
        return configured
    if cache_dir:
        return os.path.join(cache_dir, "wal")
    return None


def _crc(text: str) -> str:
    return format(zlib.crc32(text.encode("utf-8")) & 0xFFFFFFFF, "08x")


class WriteAheadLog:
    """Append-only JSON-lines log with CRC-framed records."""

    def __init__(self, path: str, sync: Optional[str] = None):
        self.path = path
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        # a+b: create if missing, never truncate an existing log
        self._fh = open(path, "a+b")
        self.sync = (sync if sync is not None else WAL_SYNC.get()).strip().lower()

    # -- write side ------------------------------------------------------

    @contextlib.contextmanager
    def exclusive(self):
        """Exclusive cross-process section (flock). Held by the mutation
        path across catch-up + evaluate + append so a failed-over writer
        can't race a dying one."""
        fcntl.flock(self._fh.fileno(), fcntl.LOCK_EX)
        try:
            yield self
        finally:
            fcntl.flock(self._fh.fileno(), fcntl.LOCK_UN)

    def append(self, record: Dict[str, Any]) -> int:
        """Durably append one record; returns the offset BEFORE it (the
        rollback point for ``truncate``). The record is committed once
        this returns."""
        text = json.dumps(record, sort_keys=True, separators=(",", ":"))
        line = f"{_crc(text)} {text}\n".encode("utf-8")
        fcntl.flock(self._fh.fileno(), fcntl.LOCK_EX)
        try:
            self._fh.seek(0, os.SEEK_END)
            off = self._fh.tell()
            self._fh.write(line)
            if self.sync != "off":
                self._fh.flush()
            if self.sync == "fsync":
                os.fsync(self._fh.fileno())
            return off
        finally:
            fcntl.flock(self._fh.fileno(), fcntl.LOCK_UN)

    def truncate(self, offset: int) -> None:
        """Roll the log back to ``offset`` — called when the in-memory
        apply of a just-appended record failed, so the record must not be
        replayed as committed."""
        fcntl.flock(self._fh.fileno(), fcntl.LOCK_EX)
        try:
            self._fh.truncate(offset)
            self._fh.flush()
            if self.sync == "fsync":
                os.fsync(self._fh.fileno())
        finally:
            fcntl.flock(self._fh.fileno(), fcntl.LOCK_UN)

    # -- read side -------------------------------------------------------

    def size(self) -> int:
        self._fh.seek(0, os.SEEK_END)
        return self._fh.tell()

    def read_from(self, offset: int) -> Tuple[List[Dict[str, Any]], int]:
        """Records appended at/after ``offset`` plus the offset of the end
        of the last WHOLE record — the catch-up primitive. A torn or
        CRC-bad tail is excluded (and not advanced past)."""
        fcntl.flock(self._fh.fileno(), fcntl.LOCK_SH)
        try:
            self._fh.seek(offset)
            data = self._fh.read()
        finally:
            fcntl.flock(self._fh.fileno(), fcntl.LOCK_UN)
        records: List[Dict[str, Any]] = []
        consumed = offset
        for raw in data.split(b"\n"):
            if not raw:
                continue
            rec = self._decode(raw)
            if rec is None:
                break  # torn/damaged tail: everything after is uncommitted
            records.append(rec)
            consumed += len(raw) + 1
        return records, consumed

    def replay(self) -> Iterator[Dict[str, Any]]:
        """Every committed record, in commit order."""
        records, _ = self.read_from(0)
        return iter(records)

    @staticmethod
    def _decode(raw: bytes) -> Optional[Dict[str, Any]]:
        try:
            line = raw.decode("utf-8")
            crc, text = line.split(" ", 1)
            if crc != _crc(text):
                return None
            return json.loads(text)
        except (ValueError, UnicodeDecodeError):
            return None

    def close(self) -> None:
        try:
            self._fh.close()
        except OSError:  # pragma: no cover - fault-ok: close on torn fd
            pass

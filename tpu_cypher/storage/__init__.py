"""Transactional mutation storage: delta-CSR overlays + WAL durability.

The reference architecture (CAPS) keeps the compiler stack storage-agnostic
— a ``RelationalCypherGraph`` is anything that answers ``scan_operator``.
This package exploits that seam to add writes without touching the read
path's contract: an immutable bucket-padded base (``ScanGraph``), a small
delta overlay whose extents round on the bucket lattice, versioned
read snapshots, and a write-ahead log for crash durability
(docs/mutation.md).
"""

from .delta import (
    DEAD_KEY,
    MutableGraph,
    SnapshotGraph,
    WriteBatch,
    mutable_graph_from_create_query,
)
from .wal import WriteAheadLog, wal_directory

__all__ = [
    "DEAD_KEY",
    "MutableGraph",
    "SnapshotGraph",
    "WriteAheadLog",
    "WriteBatch",
    "mutable_graph_from_create_query",
    "wal_directory",
]

"""Delta-CSR mutation layer: immutable base + bucket-padded delta overlay.

Layout (docs/mutation.md): the graph a reader scans is always a
**snapshot** — either the immutable base ``ScanGraph`` alone (no pending
delta) or a :class:`SnapshotGraph` unioning three members in keep-first
dedup order::

    [ delta-live, delta-dead, base ]

* **delta-live** holds every element created or rewritten since the last
  compaction (rewrites carry the FULL post-image property row);
* **delta-dead** holds one tombstone row (``__dead = true``) per base
  element that was deleted or rewritten, placed in the element's BASE
  label-combo/type table so the stale base row loses the dedup race;
* **base** is the last compaction's ``ScanGraph`` — bucket-padded,
  CSR-indexed, plan-cached, never touched by writes.

Dedup on element id keeps the FIRST member's row, then a fixed
``__dead IS NULL`` filter drops tombstones and pad lanes. All
data-dependence lives in table DATA: with bucketing on, delta tables are
host-padded to the bucket lattice with dead pad rows, so consecutive
write batches (and compactions, which fold the delta back into a
bucket-padded base) reuse the same compiled programs.

Durability: ``commit`` appends the batch to the WAL (fsync = commit
point) before applying it in memory; ``serve/worker.py`` boot replays the
WAL after its graph-CREATE replay, reconstructing committed state
byte-identically. Writers never block readers: a query pins the snapshot
object it started with; a commit publishes a new one.
"""

from __future__ import annotations

import hashlib
import itertools
import json
import threading
from contextlib import contextmanager
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from ..api import types as T
from ..api.mapping import NodeMapping, NodeMappingBuilder, RelationshipMappingBuilder
from ..api.schema import PropertyGraphSchema
from ..api.values import Node, Relationship
from ..errors import MutationError
from ..ir import expr as E
from ..relational.graphs import (
    ElementTable,
    RelationalCypherGraph,
    ScanGraph,
    TableOp,
    _member_union_scan,
)
from ..runtime import faults as F
from ..utils.config import COMPACT_DELTA_MAX, COMPACT_MIN_BUCKET
from .wal import WriteAheadLog

# reserved system property marking tombstone + pad rows; null on every live
# row (so it never surfaces in materialized element properties) and
# rejected in user property maps
DEAD_KEY = "__dead"


# ---------------------------------------------------------------------------
# write batches
# ---------------------------------------------------------------------------


class WriteBatch:
    """The effect record of one committed write query — explicit ids and
    post-image rows, so applying a batch is deterministic everywhere it
    happens (live commit, WAL replay, cross-process catch-up)."""

    __slots__ = (
        "nodes_created",
        "rels_created",
        "nodes_rewritten",
        "rels_rewritten",
        "nodes_deleted",
        "rels_deleted",
    )

    def __init__(self):
        # (id, sorted labels, props) / (id, src, dst, type, props)
        self.nodes_created: List[Tuple[int, Tuple[str, ...], Dict[str, Any]]] = []
        self.rels_created: List[Tuple[int, int, int, str, Dict[str, Any]]] = []
        self.nodes_rewritten: List[Tuple[int, Tuple[str, ...], Dict[str, Any]]] = []
        self.rels_rewritten: List[Tuple[int, int, int, str, Dict[str, Any]]] = []
        self.nodes_deleted: List[int] = []
        self.rels_deleted: List[int] = []

    def is_empty(self) -> bool:
        return not (
            self.nodes_created
            or self.rels_created
            or self.nodes_rewritten
            or self.rels_rewritten
            or self.nodes_deleted
            or self.rels_deleted
        )

    def to_json(self) -> Dict[str, Any]:
        return {
            "nc": [[i, list(l), p] for i, l, p in self.nodes_created],
            "rc": [[i, s, d, t, p] for i, s, d, t, p in self.rels_created],
            "nw": [[i, list(l), p] for i, l, p in self.nodes_rewritten],
            "rw": [[i, s, d, t, p] for i, s, d, t, p in self.rels_rewritten],
            "nd": list(self.nodes_deleted),
            "rd": list(self.rels_deleted),
        }

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "WriteBatch":
        b = WriteBatch()
        b.nodes_created = [(i, tuple(l), p) for i, l, p in d.get("nc", ())]
        b.rels_created = [(i, s, dd, t, p) for i, s, dd, t, p in d.get("rc", ())]
        b.nodes_rewritten = [(i, tuple(l), p) for i, l, p in d.get("nw", ())]
        b.rels_rewritten = [(i, s, dd, t, p) for i, s, dd, t, p in d.get("rw", ())]
        b.nodes_deleted = list(d.get("nd", ()))
        b.rels_deleted = list(d.get("rd", ()))
        return b

    def digest(self) -> str:
        """Canonical content digest — the fingerprint-chain increment."""
        text = json.dumps(self.to_json(), sort_keys=True, separators=(",", ":"))
        return hashlib.sha256(text.encode()).hexdigest()[:16]


def advance_fingerprint(prev: str, batch_digest: str) -> str:
    """Chain the statistics fingerprint one write batch forward. Chained
    (not recomputed from counts) so even a cardinality-neutral batch — a
    pure property SET — moves the fingerprint and invalidates stale
    result-cache entries."""
    return hashlib.sha256(f"{prev}|{batch_digest}".encode()).hexdigest()[:16]


# ---------------------------------------------------------------------------
# snapshot graph
# ---------------------------------------------------------------------------


class SnapshotGraph(RelationalCypherGraph):
    """One immutable ``(base, delta)`` pair. Readers pin the instance they
    started with; commits publish a new one. Scans union
    ``[delta-live, delta-dead, base]`` with keep-first dedup on id, then
    filter ``__dead IS NULL`` — a FIXED program shape, so consecutive
    snapshots replan on the host but reuse compiled device programs."""

    def __init__(
        self,
        base: RelationalCypherGraph,
        live: Optional[ScanGraph],
        dead: Optional[ScanGraph],
        version: int,
    ):
        self.base = base
        self.live = live
        self.dead = dead
        self.version = version
        self.members: List[RelationalCypherGraph] = [
            g for g in (live, dead) if g is not None
        ] + [base]
        schema = PropertyGraphSchema.empty()
        for g in self.members:
            schema = schema + g.schema
        self.schema = schema
        self._scan_cache: Dict[Tuple[str, object], tuple] = {}
        self._scan_lock = threading.Lock()

    def scan_operator(self, var_name, ct, ctx):
        # one union materialization per (snapshot, var, type): the
        # snapshot is immutable, so the merged scan table is too. Without
        # the memo every query between two commits replays the
        # union+dedup+dead-filter dispatches — and under a serving pool
        # every in-flight lane replays them concurrently, which is where
        # mixed read/write traffic loses its read throughput. The lock
        # makes racing lanes share one build instead of duplicating it.
        key = (var_name, ct)
        hit = self._scan_cache.get(key)
        if hit is None:
            with self._scan_lock:
                hit = self._scan_cache.get(key)
                if hit is None:
                    op = self._build_scan(var_name, ct, ctx)
                    hit = (op.header, op.table)
                    self._scan_cache[key] = hit
        h, t = hit
        return TableOp(self, ctx, h, t)

    def _build_scan(self, var_name, ct, ctx):
        op = _member_union_scan(
            self, self.members, var_name, ct, ctx, dedup_var=var_name
        )
        h = op.header
        var = h.var(var_name)
        dead_e = next(
            (e for e in h.properties_for(var) if e.key == DEAD_KEY), None
        )
        if dead_e is None:
            # no member of this combo carries tombstones/pads: pure scan
            return op
        t = op.table.filter(E.IsNull(dead_e).with_type(T.CTBoolean), h, {})
        return TableOp(self, ctx, h, t)

    @property
    def patterns(self) -> frozenset:
        return frozenset()


# ---------------------------------------------------------------------------
# delta-overlay element tables
# ---------------------------------------------------------------------------


def _pad_target(n: int) -> int:
    """Rows a delta table occupies on the bucket lattice (identity when
    bucketing is off — exact sizes, recompiles accepted)."""
    from ..backend.tpu import bucketing

    if not bucketing.enabled():
        return n
    return max(bucketing.round_size(n), max(int(COMPACT_MIN_BUCKET.get()), 1))


def _delta_scan_graph(
    nodes: Iterable[Node],
    rels: Iterable[Relationship],
    table_cls,
    dead: bool,
) -> Optional[ScanGraph]:
    """Group delta elements into bucket-padded element tables carrying the
    ``__dead`` column (null on live rows, true on tombstones and pads).
    Pad lanes use unique ids above ``bucketing.ID_SENTINEL`` so they
    survive dedup and die at the snapshot filter."""
    from ..backend.tpu.bucketing import ID_SENTINEL

    sentinel = itertools.count()
    tables: List[ElementTable] = []
    mark = True if dead else None

    by_combo: Dict[frozenset, List[Node]] = {}
    for n in nodes:
        by_combo.setdefault(frozenset(n.labels), []).append(n)
    for combo, group in sorted(by_combo.items(), key=lambda kv: sorted(kv[0])):
        group = sorted(group, key=lambda n: n.id)
        keys = sorted({} if dead else {k for n in group for k in n.properties})
        rows = len(group)
        pad = _pad_target(rows) - rows
        cols: Dict[str, List[Any]] = {
            "id": [n.id for n in group]
            + [int(ID_SENTINEL) + next(sentinel) for _ in range(pad)]
        }
        for k in keys:
            cols[f"p_{k}"] = [n.properties.get(k) for n in group] + [None] * pad
        cols[f"p_{DEAD_KEY}"] = [mark] * rows + [True] * pad
        prop_pairs = tuple((k, f"p_{k}") for k in keys) + ((DEAD_KEY, f"p_{DEAD_KEY}"),)
        if combo:
            builder = NodeMappingBuilder.on("id").with_implied_label(*sorted(combo))
            for k, col in prop_pairs:
                builder.with_property_key(k, col)
            mapping = builder.build()
        else:
            mapping = NodeMapping("id", frozenset(), (), prop_pairs)
        tables.append(ElementTable(mapping, table_cls.from_columns(cols)))

    by_type: Dict[str, List[Relationship]] = {}
    for r in rels:
        by_type.setdefault(r.rel_type, []).append(r)
    for rel_type, group in sorted(by_type.items()):
        group = sorted(group, key=lambda r: r.id)
        keys = sorted({} if dead else {k for r in group for k in r.properties})
        rows = len(group)
        pad = _pad_target(rows) - rows
        cols = {
            "id": [r.id for r in group]
            + [int(ID_SENTINEL) + next(sentinel) for _ in range(pad)],
            "src": [r.start for r in group] + [int(ID_SENTINEL)] * pad,
            "dst": [r.end for r in group] + [int(ID_SENTINEL)] * pad,
        }
        for k in keys:
            cols[f"p_{k}"] = [r.properties.get(k) for r in group] + [None] * pad
        builder = (
            RelationshipMappingBuilder.on("id")
            .from_("src")
            .to("dst")
            .with_relationship_type(rel_type)
        )
        for k in keys:
            builder.with_property_key(k, f"p_{k}")
        builder.with_property_key(DEAD_KEY, f"p_{DEAD_KEY}")
        cols[f"p_{DEAD_KEY}"] = [mark] * rows + [True] * pad
        tables.append(ElementTable(builder.build(), table_cls.from_columns(cols)))

    if not tables:
        return None
    return ScanGraph(tables)


# ---------------------------------------------------------------------------
# the mutable graph
# ---------------------------------------------------------------------------


class MutableGraph(RelationalCypherGraph):
    """Authoritative element store + delta overlay + WAL.

    The session never plans against this object directly: the query
    pipeline rebinds to ``snapshot()`` on entry, so reads run on immutable
    graphs (plan cache keys on snapshot identity) while ``commit``
    publishes new versions underneath."""

    def __init__(
        self,
        session,
        nodes: Sequence[Node] = (),
        relationships: Sequence[Relationship] = (),
        *,
        name: str = "graph",
    ):
        self._session = session
        self._table_cls = session.table_cls
        self.name = name
        self._lock = threading.RLock()
        self._nodes: Dict[int, Node] = {n.id: n for n in nodes}
        self._rels: Dict[int, Relationship] = {r.id: r for r in relationships}
        self._adj: Dict[int, set] = {i: set() for i in self._nodes}
        for r in self._rels.values():
            self._adj.setdefault(r.start, set()).add(r.id)
            self._adj.setdefault(r.end, set()).add(r.id)
        self._next_id = max([*self._nodes, *self._rels, -1]) + 1
        # incremental statistics: total + single-label/type cardinalities
        self._node_counts: Dict[Tuple[str, ...], int] = {(): len(self._nodes)}
        for n in self._nodes.values():
            for l in n.labels:
                k = (l,)
                self._node_counts[k] = self._node_counts.get(k, 0) + 1
        self._rel_counts: Dict[Tuple[str, ...], int] = {(): len(self._rels)}
        for r in self._rels.values():
            k = (r.rel_type,)
            self._rel_counts[k] = self._rel_counts.get(k, 0) + 1
        self._compact_into_base()
        self._fp = self._initial_fingerprint()
        self._version = 0
        self._snapshot: Optional[RelationalCypherGraph] = None
        self._wal: Optional[WriteAheadLog] = None
        self._wal_offset = 0
        # telemetry
        self.compactions = 0
        self.deferred_compactions = 0
        self.replayed_batches = 0
        self.committed_batches = 0

    # -- graph interface -------------------------------------------------

    @property
    def schema(self) -> PropertyGraphSchema:  # type: ignore[override]
        return self.snapshot().schema

    def scan_operator(self, var_name, ct, ctx):
        return self.snapshot().scan_operator(var_name, ct, ctx)

    @property
    def patterns(self) -> frozenset:
        return frozenset()

    # -- durability ------------------------------------------------------

    def attach_wal(self, wal: WriteAheadLog, replay: bool = True) -> "MutableGraph":
        """Adopt a WAL; replay whatever committed batches it already holds
        (the worker-boot recovery path: called right after the graph-CREATE
        rebuild, so recovered state is byte-identical to a from-scratch
        rebuild that applied the same batches)."""
        with self._lock:
            self._wal = wal
            if replay:
                n = 0
                for rec in wal.replay():
                    self._advance(WriteBatch.from_json(rec["batch"]))
                    n += 1
                self._wal_offset = wal.size()
                self.replayed_batches = n
                if n:
                    self._maybe_compact()
        return self

    def catch_up(self) -> int:
        """Apply batches other processes appended to the shared WAL since
        we last looked — the cluster single-writer failover path. Caller
        holds ``write_lock``."""
        if self._wal is None:
            return 0
        records, new_off = self._wal.read_from(self._wal_offset)
        for rec in records:
            self._advance(WriteBatch.from_json(rec["batch"]))
        self._wal_offset = new_off
        return len(records)

    def refresh(self) -> int:
        """Apply batches OTHER processes committed to the shared WAL — the
        cluster read path: a replica worker serving reads converges on the
        writer's state without taking the exclusive file lock (``read_from``
        stops cleanly at a torn in-progress append; the next refresh picks
        it up once the writer's fsync completes)."""
        if self._wal is None:
            return 0
        with self._lock:
            n = self.catch_up()
            if n:
                self._maybe_compact()
            return n

    @contextmanager
    def write_lock(self):
        """Serialize one write transaction: in-process lock, plus the WAL
        file lock + catch-up when a WAL is attached (so a failed-over
        writer sees every batch the previous writer committed)."""
        with self._lock:
            if self._wal is not None:
                with self._wal.exclusive():
                    self.catch_up()
                    yield self
            else:
                yield self

    # -- commit ----------------------------------------------------------

    def allocate_id(self) -> int:
        i = self._next_id
        self._next_id += 1
        return i

    def commit(self, batch: WriteBatch) -> None:
        """WAL append (the commit point) then in-memory apply then
        publish. An exception during apply rolls the WAL back to the
        pre-append offset — a write the client saw fail must not be
        resurrected at replay. A crash AFTER the fsync is a committed
        write whose ack was lost: replay applies it (in-doubt resolves
        committed). Caller holds ``write_lock``."""
        if batch.is_empty():
            return
        with self._lock:
            F.fault_point("wal_append")
            off = None
            if self._wal is not None:
                off = self._wal.append(
                    {"lsn": self._version + 1, "batch": batch.to_json()}
                )
            try:
                F.fault_point("delta_apply")
                self._advance(batch)
            except BaseException:
                if self._wal is not None and off is not None:
                    self._wal.truncate(off)
                raise
            if self._wal is not None:
                self._wal_offset = self._wal.size()
            self.committed_batches += 1
            self._maybe_compact()

    def _advance(self, batch: WriteBatch) -> None:
        self._apply(batch)
        self._fp = advance_fingerprint(self._fp, batch.digest())
        self._version += 1
        self._snapshot = None

    # -- apply (shared by live commit, replay, catch-up) -----------------

    def _apply(self, batch: WriteBatch) -> None:
        for i, labels, props in batch.nodes_created:
            if i in self._nodes:
                raise MutationError(f"node id {i} already exists")
            node = Node(i, labels, dict(props))
            self._nodes[i] = node
            self._delta_nodes[i] = node
            self._adj.setdefault(i, set())
            self._bump_nodes(node.labels, +1)
            self._next_id = max(self._next_id, i + 1)
        for i, s, d, t, props in batch.rels_created:
            if i in self._rels:
                raise MutationError(f"relationship id {i} already exists")
            if s not in self._nodes or d not in self._nodes:
                raise MutationError(f"relationship {i} endpoint does not exist")
            rel = Relationship(i, s, d, t, dict(props))
            self._rels[i] = rel
            self._delta_rels[i] = rel
            self._adj.setdefault(s, set()).add(i)
            self._adj.setdefault(d, set()).add(i)
            self._bump_rels(t, +1)
            self._next_id = max(self._next_id, i + 1)
        for i, labels, props in batch.nodes_rewritten:
            old = self._nodes.get(i)
            if old is None:
                raise MutationError(f"cannot SET on missing node {i}")
            self._tombstone_node(i)
            node = Node(i, labels, dict(props))
            self._nodes[i] = node
            self._delta_nodes[i] = node
            self._bump_nodes(old.labels, -1)
            self._bump_nodes(node.labels, +1)
        for i, s, d, t, props in batch.rels_rewritten:
            old = self._rels.get(i)
            if old is None:
                raise MutationError(f"cannot SET on missing relationship {i}")
            self._tombstone_rel(i)
            rel = Relationship(i, s, d, t, dict(props))
            self._rels[i] = rel
            self._delta_rels[i] = rel
            self._bump_rels(old.rel_type, -1)
            self._bump_rels(t, +1)
        for i in batch.rels_deleted:
            old = self._rels.pop(i, None)
            if old is None:
                continue  # idempotent: DETACH cascades may overlap DELETE r
            self._tombstone_rel(i)
            self._delta_rels.pop(i, None)
            self._adj.get(old.start, set()).discard(i)
            self._adj.get(old.end, set()).discard(i)
            self._bump_rels(old.rel_type, -1)
        for i in batch.nodes_deleted:
            old = self._nodes.pop(i, None)
            if old is None:
                raise MutationError(f"cannot DELETE missing node {i}")
            if self._adj.get(i):
                raise MutationError(
                    f"cannot delete node {i}: it still has relationships "
                    "(use DETACH DELETE)"
                )
            self._adj.pop(i, None)
            self._tombstone_node(i)
            self._delta_nodes.pop(i, None)
            self._bump_nodes(old.labels, -1)

    def _tombstone_node(self, i: int) -> None:
        base = self._base_nodes.get(i)
        if base is not None and i not in self._dead_nodes:
            self._dead_nodes[i] = base

    def _tombstone_rel(self, i: int) -> None:
        base = self._base_rels.get(i)
        if base is not None and i not in self._dead_rels:
            self._dead_rels[i] = base

    def _bump_nodes(self, labels, d: int) -> None:
        self._node_counts[()] = self._node_counts.get((), 0) + d
        for l in labels:
            k = (l,)
            self._node_counts[k] = self._node_counts.get(k, 0) + d

    def _bump_rels(self, rel_type: str, d: int) -> None:
        self._rel_counts[()] = self._rel_counts.get((), 0) + d
        k = (rel_type,)
        self._rel_counts[k] = self._rel_counts.get(k, 0) + d

    # -- compaction ------------------------------------------------------

    def delta_rows(self) -> int:
        return (
            len(self._delta_nodes)
            + len(self._delta_rels)
            + len(self._dead_nodes)
            + len(self._dead_rels)
        )

    def _maybe_compact(self) -> None:
        threshold = max(int(COMPACT_DELTA_MAX.get()), 1)
        if self.delta_rows() < threshold:
            return
        try:
            F.fault_point("compact")
            self._compact_into_base()
            self._snapshot = None
            self.compactions += 1
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception:  # fault-ok: the write is already durable in the
            # WAL — a failed compaction (injected or real) is deferred,
            # host-side only, and retried on the next commit over the
            # threshold; raising here would fail a committed write
            self.deferred_compactions += 1

    def _compact_into_base(self) -> None:
        """Fold the delta into a fresh immutable base (bucket-padded by
        the table materialize path exactly like any ingested graph) and
        reset the overlay. Sorted by id: the CSR build and the
        rebuild-from-scratch differential see identical tables."""
        from ..testing.create_graph import (
            InMemoryTestGraph,
            scan_graph_from_test_graph,
        )

        nodes = [self._nodes[i] for i in sorted(self._nodes)]
        rels = [self._rels[i] for i in sorted(self._rels)]
        self._base_graph = scan_graph_from_test_graph(
            InMemoryTestGraph(nodes, rels), self._table_cls
        )
        self._base_nodes = dict(self._nodes)
        self._base_rels = dict(self._rels)
        self._delta_nodes: Dict[int, Node] = {}
        self._delta_rels: Dict[int, Relationship] = {}
        self._dead_nodes: Dict[int, Node] = {}
        self._dead_rels: Dict[int, Relationship] = {}

    # -- snapshots -------------------------------------------------------

    def snapshot(self) -> RelationalCypherGraph:
        """The current immutable ``(base, delta)`` read view. Cached until
        the next commit publishes a new version; repeat reads between
        commits therefore hit the plan cache on snapshot identity."""
        snap = self._snapshot
        if snap is not None:
            return snap
        with self._lock:
            snap = self._snapshot
            if snap is not None:
                return snap
            if self.delta_rows() == 0:
                snap = self._base_graph
            else:
                live = _delta_scan_graph(
                    self._delta_nodes.values(),
                    self._delta_rels.values(),
                    self._table_cls,
                    dead=False,
                )
                dead = _delta_scan_graph(
                    self._dead_nodes.values(),
                    self._dead_rels.values(),
                    self._table_cls,
                    dead=True,
                )
                snap = SnapshotGraph(self._base_graph, live, dead, self._version)
            from ..optimizer.stats import seed_statistics

            seed_statistics(
                snap,
                node_counts=dict(self._node_counts),
                rel_counts=dict(self._rel_counts),
                fingerprint=self._fp,
            )
            self._snapshot = snap
            return snap

    def fingerprint(self) -> str:
        return self._fp

    def _initial_fingerprint(self) -> str:
        """Same digest format as ``GraphStatistics.fingerprint`` computed
        from the seeded counts, so an unwritten mutable graph agrees with
        the immutable graph built from the same CREATE query."""
        schema = self._base_graph.schema
        parts = [
            f"n={self._node_counts.get((), 0)}",
            f"r={self._rel_counts.get((), 0)}",
        ]
        for lbl in sorted(getattr(schema, "labels", ()) or ()):
            parts.append(f"l:{lbl}={self._node_counts.get((lbl,), 0)}")
        for typ in sorted(getattr(schema, "relationship_types", ()) or ()):
            parts.append(f"t:{typ}={self._rel_counts.get((typ,), 0)}")
        return hashlib.sha256("|".join(parts).encode()).hexdigest()[:16]


def mutable_graph_from_create_query(
    session, query: str, *, name: str = "graph", wal_path: Optional[str] = None
):
    """Build a writable graph from a CREATE fixture query, optionally
    durably backed: when ``wal_path`` is given, existing committed batches
    replay immediately (crash recovery) and future commits append."""
    from ..relational.session import PropertyGraph
    from ..testing.create_graph import parse_create_query

    tg = parse_create_query(query)
    mg = MutableGraph(session, tg.nodes, tg.relationships, name=name)
    if wal_path:
        mg.attach_wal(WriteAheadLog(wal_path))
    return PropertyGraph(session, mg)

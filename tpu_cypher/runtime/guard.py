"""Per-query execution guard: deadline, ladder rung, chunked materialize.

The guard is the context a query executes under. It is context-local
(``contextvars``) so concurrent/interleaved queries — threads, asyncio,
nested view execution — each see their own deadline and rung, mirroring the
context-local fallback counter in ``backend/tpu/table.py``.

* **Deadline**: ``CypherSession.tpu(query_deadline_seconds=..)`` /
  ``TPU_CYPHER_QUERY_DEADLINE_S``. Checked at every named fault site
  (``runtime.faults.fault_point``) — the natural interruption points
  between device dispatches — and between ladder rungs. Expiry raises the
  TERMINAL ``QueryTimeout``.

* **Rung**: which ladder rung is executing (``relational/session.py``).
  ``RUNG_DEVICE`` is the clean path; degraded rungs tighten the bucket
  policy, chunk materializes, or re-execute on the host oracle.

* **Chunking**: at ``RUNG_CHUNKED`` big device gathers split into bounded
  slices (``TPU_CYPHER_CHUNK_ROWS``) so no single materialize allocates the
  whole output at once; memory admission estimates per-chunk accordingly.
"""

from __future__ import annotations

import contextvars
import time
from typing import Optional

from ..errors import QueryTimeout
from ..obs.metrics import REGISTRY as _REGISTRY
from ..utils.config import (
    CHUNK_ROWS,
    DEADLINE_S,
    LADDER_MODE,
    SERVE_STREAM_CHUNK_ROWS,
)

# ladder rungs, in degradation order (docs/robustness.md)
RUNG_DEVICE = "device"
RUNG_BUCKET_EXACT = "bucket-exact"  # bucketing off: no pad memory overhead
RUNG_CHUNKED = "chunked"  # bounded-slice materializes
RUNG_HOST = "host-oracle"  # full local-backend re-execution

LADDER = (RUNG_DEVICE, RUNG_BUCKET_EXACT, RUNG_CHUNKED, RUNG_HOST)

# serving-layer rung, OUTSIDE the in-process LADDER: a read query whose
# engine-worker process died mid-flight was re-dispatched to a surviving
# replica by the router (serve/router.py). Stamped per failed attempt in
# ``execution_log`` just like the in-process rungs, so a client's ``done``
# message shows exactly which attempts a transparent retry cost.
RUNG_REPLICA = "replica"

# LADDER_MODE ("on": degrade-and-retry; "off": first-rung errors raise),
# CHUNK_ROWS (rows per gather slice at the chunked rung), and DEADLINE_S
# (0 = none; session option overrides the env) are declared in the typed
# registry (utils/config.py) and aliased here for their call sites.

# which ladder rungs actually executed, fleet-wide (the per-query view is
# the ``execute`` trace span's ``rung`` attr and ``result.execution_log``)
LADDER_ACTIVATIONS = _REGISTRY.counter(
    "tpu_cypher_ladder_activations_total",
    "execution-guard activations per ladder rung",
    labels=("rung",),
)


class ExecutionGuard:
    """State for ONE query execution attempt (one ladder rung). Per-site
    tracing rides the obs span tree (``obs.trace.note_site``), not the
    guard."""

    __slots__ = ("deadline_at", "rung")

    def __init__(self, deadline_at: Optional[float], rung: str):
        self.deadline_at = deadline_at
        self.rung = rung

    def check(self, site: str) -> None:
        if self.deadline_at is not None and time.monotonic() > self.deadline_at:
            raise QueryTimeout(
                f"query deadline exceeded at site {site!r}", site=site
            )


_CURRENT: contextvars.ContextVar[Optional[ExecutionGuard]] = (
    contextvars.ContextVar("tpu_cypher_guard", default=None)
)

# per-REQUEST deadline override (seconds), context-local: the serving layer
# (serve/) activates one around each client query so interleaved coroutines
# each see their own deadline. Resolution order in the ladder
# (relational/session.py): session option > request override > env default.
_REQUEST_DEADLINE_S: contextvars.ContextVar[Optional[float]] = (
    contextvars.ContextVar("tpu_cypher_request_deadline", default=None)
)


def request_deadline_s() -> Optional[float]:
    """The context-local per-request deadline (seconds), or None when no
    ``request_deadline`` scope is open in this context."""
    return _REQUEST_DEADLINE_S.get()


class request_deadline:
    """``with guard.request_deadline(1.5):`` — scope a per-request deadline
    over every query executed in this context. 0/None clears (queries fall
    back to the session/env deadline). Context-local, so concurrent server
    requests never see each other's deadlines."""

    def __init__(self, seconds: Optional[float]):
        self._seconds = float(seconds) if seconds and seconds > 0 else None
        self._token = None

    def __enter__(self) -> "request_deadline":
        self._token = _REQUEST_DEADLINE_S.set(self._seconds)
        return self

    def __exit__(self, *exc) -> None:
        _REQUEST_DEADLINE_S.reset(self._token)


def ladder_enabled() -> bool:
    return LADDER_MODE.get().strip().lower() != "off"


def current() -> Optional[ExecutionGuard]:
    return _CURRENT.get()


def current_rung() -> str:
    g = _CURRENT.get()
    return g.rung if g is not None else RUNG_DEVICE


def chunk_rows() -> Optional[int]:
    """Gather slice size when the chunked rung is active, else None."""
    g = _CURRENT.get()
    if g is None or g.rung != RUNG_CHUNKED:
        return None
    return max(int(CHUNK_ROWS.get()), 1024)


def stream_chunk_rows() -> int:
    """Row-chunk size for cursor streaming (serve/): the same bounded-slice
    discipline as the chunked ladder rung, but ALWAYS active — result
    delivery decodes and encodes at most this many rows at a time, which is
    what holds streaming's host-memory ceiling. Follows
    ``TPU_CYPHER_CHUNK_ROWS`` unless ``TPU_CYPHER_SERVE_STREAM_CHUNK_ROWS``
    pins it separately; clamped to the same floor as ``chunk_rows``."""
    n = int(SERVE_STREAM_CHUNK_ROWS.get())
    if n <= 0:
        n = int(CHUNK_ROWS.get())
    return max(n, 1024)


def check_deadline(site: str) -> None:
    g = _CURRENT.get()
    if g is not None:
        g.check(site)


class activate:
    """``with guard.activate(rung, deadline_seconds):`` — install a guard
    for one execution attempt. ``deadline_at`` is an ABSOLUTE monotonic
    stamp (the ladder passes the query-level deadline through every rung,
    so retries never extend it); resolving the session/env deadline config
    is the caller's job — ``relational/session.py`` is the single
    resolution point."""

    def __init__(
        self,
        rung: str = RUNG_DEVICE,
        deadline_seconds: Optional[float] = None,
        deadline_at: Optional[float] = None,
    ):
        if deadline_at is None and deadline_seconds and deadline_seconds > 0:
            deadline_at = time.monotonic() + float(deadline_seconds)
        self._guard = ExecutionGuard(deadline_at, rung)
        self._token = None

    def __enter__(self) -> ExecutionGuard:
        LADDER_ACTIVATIONS.inc(rung=self._guard.rung)
        self._token = _CURRENT.set(self._guard)
        return self._guard

    def __exit__(self, *exc) -> None:
        _CURRENT.reset(self._token)

"""Query-execution runtime services: the execution guard (deadline, ladder
rung, chunked materialize) and deterministic fault injection. See
docs/robustness.md."""

from . import faults, guard  # noqa: F401
from .faults import fault_point  # noqa: F401

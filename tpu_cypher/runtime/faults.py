"""Deterministic fault injection for the execution ladder.

``TPU_CYPHER_FAULTS`` names WHERE and WHEN synthetic device faults fire, so
the whole degrade-and-retry ladder is exercised under ``JAX_PLATFORMS=cpu``
in tier-1 — no real OOM or chip loss required. Grammar (comma-separated
specs):

    kind@site[:occurrence]

* ``kind``  — ``oom`` | ``compile`` | ``lost`` | ``timeout`` | ``crash``
* ``site``  — a named fault site (``join``, ``expand``, ``var_expand``,
  ``filter``, ``compact``, ``shuffle``, ``agg``, plus the Pallas kernel-tier sites
  ``kernel_join``/``kernel_expand``/``kernel_agg``/``kernel_frontier``
  fired by ``backend.tpu.pallas.dispatch.launch`` just before a kernel
  launch, and the write-path sites ``wal_append`` (before the WAL
  append: the write fails with nothing durable), ``delta_apply``
  (after the append, before the in-memory apply: commit rolls the WAL
  back to the pre-append offset) and ``compact`` again inside
  ``MutableGraph._maybe_compact`` (the already-committed write survives;
  compaction defers to the next commit) — see ``storage/delta.py``.
  Grep ``fault_point(`` and ``dispatch.register(`` for the full set)
* ``occurrence`` — WHICH invocations of the site fire, 1-based:
  ``:3`` (exactly the 3rd), ``:2-5`` (2nd through 5th), ``:*`` (every
  invocation — drives the ladder all the way to the host oracle). Default
  ``:1``.

Examples::

    TPU_CYPHER_FAULTS=oom@join:1                # first join OOMs once
    TPU_CYPHER_FAULTS=oom@join:*,compile@expand:1
    TPU_CYPHER_FAULTS=lost@compact:2-4

Each spec keeps its own per-site invocation counter; counters are
process-global and monotonically increasing across ladder retries — which
is exactly what makes the ladder testable: ``:1`` fails the device rung
once and the first retry rung succeeds, while ``:*`` fails every device
rung and lands on the host oracle.

Injected exceptions are RAW (``InjectedFault``, message carrying the same
status markers jaxlib uses) so they flow through ``tpu_cypher.errors
.classify`` exactly like real faults. ``timeout`` injects a typed
``QueryTimeout`` directly (deadline expiry is not a raw device error).

``crash`` is the process-death kind: inside an ARMED engine-worker process
(``serve/worker.py`` calls ``enable_crash()``), the covered invocation
``os._exit``\\ s the whole process — the deterministic stand-in for a
native libtpu abort, driving the supervisor/router recovery path
(restart, breaker, replica retry) without a real TPU death. In any
process that has NOT armed it (tests, the router front end, plain
sessions) the kind degrades to a raised lost-style ``InjectedFault``, so
a stray ``crash@...`` spec can never kill the test runner.
"""

from __future__ import annotations

import contextvars
import os
import threading
from typing import Dict, List, Optional, Tuple

from ..errors import QueryTimeout
from ..obs import trace as _obs_trace
from ..obs.metrics import REGISTRY as _REGISTRY
from ..utils.config import FAULTS as _FAULTS

ENV = _FAULTS.name

# per-site invocation counts, served by the unified obs registry — sites
# are exactly the engine's device sync points, so this series doubles as
# dispatch-boundary telemetry (docs/observability.md). The occurrence-
# window logic below keys off the same counter (inc-and-get is atomic),
# which is why ``set_spec``/``reset_counters`` reset it: a fresh spec
# means a fresh deterministic schedule.
FAULT_SITE_HITS = _REGISTRY.counter(
    "tpu_cypher_fault_site_hits_total",
    "invocations of each named fault site (join/expand/kernel_*/...)",
    labels=("site",),
)


class InjectedFault(RuntimeError):
    """Synthetic RAW device fault (classified by message, like jaxlib's
    ``XlaRuntimeError``). Carries the site + occurrence for diagnostics."""

    def __init__(self, message: str, site: str, n: int):
        super().__init__(message)
        self.site = site
        self.n = n


_KIND_MESSAGES = {
    "oom": "RESOURCE_EXHAUSTED: injected out of memory allocating "
    "1099511627776 bytes on device",
    "compile": "INTERNAL: injected XLA compilation failure while compiling "
    "fused computation",
    "lost": "UNAVAILABLE: injected device lost (TPU driver tunnel closed)",
    "crash": "UNAVAILABLE: injected worker crash (disarmed outside an "
    "engine-worker process)",
}

_INF = 1 << 62

# the ``crash`` kind is only ever allowed to take down a dedicated
# engine-worker process — serve/worker.py arms it at startup; everywhere
# else a crash spec degrades to a raised lost-style fault
_CRASH_EXIT_CODE = 137
_crash_armed = False


def enable_crash(enabled: bool = True) -> None:
    """Arm (or disarm) the ``crash`` fault kind for THIS process. Only an
    expendable engine-worker process may arm it; the default is disarmed."""
    global _crash_armed
    _crash_armed = bool(enabled)


def crash_armed() -> bool:
    return _crash_armed

_lock = threading.Lock()
# parsed spec cache, keyed by the raw env/override string
_parse_cache: Tuple[Optional[str], Dict[str, List[Tuple[str, int, int]]]] = (
    None,
    {},
)
# in-process override (tests/fuzz set this instead of mutating os.environ)
_override: Optional[str] = None


class FaultSpecError(ValueError):
    pass


def parse_spec(text: str) -> Dict[str, List[Tuple[str, int, int]]]:
    """``"oom@join:2,lost@expand:*"`` -> {site: [(kind, lo, hi), ...]}
    with 1-based inclusive occurrence bounds (``*`` -> (1, inf))."""
    out: Dict[str, List[Tuple[str, int, int]]] = {}
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        if "@" not in part:
            raise FaultSpecError(f"fault spec {part!r}: expected kind@site[:n]")
        kind, _, rest = part.partition("@")
        kind = kind.strip().lower()
        if kind not in ("oom", "compile", "lost", "timeout", "crash"):
            raise FaultSpecError(f"fault spec {part!r}: unknown kind {kind!r}")
        site, _, occ = rest.partition(":")
        site = site.strip()
        if not site:
            raise FaultSpecError(f"fault spec {part!r}: empty site")
        occ = occ.strip() or "1"
        if occ == "*":
            lo, hi = 1, _INF
        elif "-" in occ:
            a, _, b = occ.partition("-")
            lo, hi = int(a), int(b)
        else:
            lo = hi = int(occ)
        if lo < 1 or hi < lo:
            raise FaultSpecError(f"fault spec {part!r}: bad occurrence {occ!r}")
        out.setdefault(site, []).append((kind, lo, hi))
    return out


class _ScopedSchedule:
    """One context's private fault schedule: a parsed spec plus its OWN
    per-site occurrence counts, so two interleaved queries each see a fresh
    deterministic window (``:1`` means THEIR first invocation)."""

    __slots__ = ("spec", "counts")

    def __init__(self, spec: Dict[str, List[Tuple[str, int, int]]]):
        self.spec = spec
        self.counts: Dict[str, int] = {}

    def hit(self, site: str) -> int:
        n = self.counts.get(site, 0) + 1
        self.counts[site] = n
        return n


# context-local fault schedule: layered OVER the process-global
# set_spec/env spec (a scope shadows it entirely while open). The serving
# layer (serve/) opens one per chaos-mode client query so concurrent
# requests never share occurrence windows.
_CTX_SCHEDULE: contextvars.ContextVar[Optional[_ScopedSchedule]] = (
    contextvars.ContextVar("tpu_cypher_fault_schedule", default=None)
)


class scoped_spec:
    """``with faults.scoped_spec("oom@join:1"):`` — context-local fault
    schedule with its own occurrence counters, shadowing the process-global
    spec while open. None/empty installs an explicit no-fault scope (chaos
    harnesses use that to pin a clean query next to a faulted one)."""

    def __init__(self, text: Optional[str]):
        self._sched = _ScopedSchedule(parse_spec(text) if text else {})
        self._token = None

    def __enter__(self) -> "scoped_spec":
        self._token = _CTX_SCHEDULE.set(self._sched)
        return self

    def __exit__(self, *exc) -> None:
        _CTX_SCHEDULE.reset(self._token)


def set_spec(text: Optional[str]) -> None:
    """In-process override of ``TPU_CYPHER_FAULTS`` (None = back to the
    env). Resets the invocation counters: a fresh spec means a fresh
    deterministic schedule."""
    global _override
    with _lock:
        _override = text
    FAULT_SITE_HITS.reset()


def reset_counters() -> None:
    FAULT_SITE_HITS.reset()


def counters() -> Dict[str, int]:
    """Snapshot of per-site invocation counts (diagnostics/tests) — a view
    over the registry series; zero-hit sites are omitted."""
    return {
        lbl["site"]: int(v)
        for lbl, v in FAULT_SITE_HITS.items()
        if int(v) > 0
    }


def _active_spec() -> Dict[str, List[Tuple[str, int, int]]]:
    global _parse_cache
    raw = _override if _override is not None else (_FAULTS.get() or None)
    if not raw:
        return {}
    cached_raw, cached = _parse_cache
    if cached_raw == raw:
        return cached
    parsed = parse_spec(raw)
    _parse_cache = (raw, parsed)
    return parsed


def fault_point(site: str) -> None:
    """Named fault site. Counts the invocation in the unified registry,
    stamps the site on the enclosing trace span (sites are exactly the
    device sync points between dispatches), checks the active query
    deadline (``runtime.guard``), and raises when an active spec's
    occurrence window covers this invocation."""
    from . import guard as G

    G.check_deadline(site)
    n = int(FAULT_SITE_HITS.inc(site=site))
    _obs_trace.note_site(site)
    sched = _CTX_SCHEDULE.get()
    if sched is not None:
        # a context-local schedule shadows the global spec entirely and
        # evaluates its windows against ITS OWN per-site counts
        spec, n = sched.spec, sched.hit(site)
    else:
        spec = _active_spec()
    if not spec:
        return
    rules = spec.get(site)
    if not rules:
        return
    for kind, lo, hi in rules:
        if lo <= n <= hi:
            if kind == "timeout":
                raise QueryTimeout(
                    f"injected deadline expiry at site {site!r} "
                    f"(invocation {n})",
                    site=site,
                )
            if kind == "crash" and _crash_armed:
                # the worker-process analogue of a native libtpu abort:
                # no unwinding, no atexit — the supervisor sees a dead
                # child, the router sees a socket EOF
                os._exit(_CRASH_EXIT_CODE)
            raise InjectedFault(
                f"{_KIND_MESSAGES[kind]} [injected: {kind}@{site} "
                f"invocation {n}]",
                site,
                n,
            )

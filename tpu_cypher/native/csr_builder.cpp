// Native host-side hot paths: CSR topology build and SNAP edge-list parsing.
//
// The reference delegates its host-side heavy lifting to the JVM engines
// (Spark/Flink DataFrame machinery); our TPU runtime's host tier does the
// equivalent work here in C++ — the compute path stays JAX/XLA, but graph
// ingest (text -> edges) and topology compaction (edges -> CSR) are
// bandwidth-bound host loops where interpreter overhead dominates:
//
//  * parse_edge_list: single-pass scan of a SNAP-style buffer ('#' comments,
//    whitespace/comma separated int pairs) — replaces the per-line Python
//    loop in io/edge_list.py.
//  * build_csr: map raw int64 element ids to compact int32 indices (binary
//    search over the sorted unique id vector) and produce a CSR lexsorted by
//    (src, dst) via two stable counting sorts, O(E + N) — replaces
//    np.searchsorted + np.lexsort (O(E log E)) in CsrGraph.build.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in the image).
// Build: g++ -O3 -march=native -shared -fPIC csr_builder.cpp -o _native.so

#include <cstdint>
#include <cstring>
#include <vector>
#include <algorithm>

extern "C" {

// Parse whitespace/comma-separated "src dst" pairs; skip '#...' comment and
// blank lines. Returns number of edges, or -(byte offset + 1) on malformed
// input. out_src/out_dst must have room for one edge per input line.
int64_t parse_edge_list(const char* buf, int64_t len,
                        int64_t* out_src, int64_t* out_dst) {
    int64_t count = 0;
    int64_t i = 0;
    while (i < len) {
        // skip leading spaces/commas
        while (i < len && (buf[i] == ' ' || buf[i] == '\t' || buf[i] == ',' ||
                           buf[i] == '\r')) i++;
        if (i >= len) break;
        if (buf[i] == '\n') { i++; continue; }
        if (buf[i] == '#') {            // comment line
            while (i < len && buf[i] != '\n') i++;
            continue;
        }
        // parse two integers; each must be followed by a separator/EOL so
        // "2.5" or "2x" is rejected exactly like the Python loader's int()
        int64_t vals[2];
        for (int k = 0; k < 2; k++) {
            while (i < len && (buf[i] == ' ' || buf[i] == '\t' || buf[i] == ','))
                i++;
            bool neg = false;
            if (i < len && (buf[i] == '-' || buf[i] == '+')) {
                neg = buf[i] == '-';
                i++;
            }
            if (i >= len || buf[i] < '0' || buf[i] > '9') return -(i + 1);
            int64_t v = 0;
            while (i < len && buf[i] >= '0' && buf[i] <= '9') {
                v = v * 10 + (buf[i] - '0');
                i++;
            }
            if (i < len && buf[i] != ' ' && buf[i] != '\t' && buf[i] != ',' &&
                buf[i] != '\r' && buf[i] != '\n')
                return -(i + 1);
            vals[k] = neg ? -v : v;
        }
        out_src[count] = vals[0];
        out_dst[count] = vals[1];
        count++;
        // skip to end of line (ignore trailing columns, e.g. weights)
        while (i < len && buf[i] != '\n') i++;
    }
    return count;
}

// Deduplicate + sort node ids in place semantics: input ids (n_in), output
// into out_ids; returns unique count. out_ids must have room for n_in.
int64_t unique_sorted(const int64_t* ids, int64_t n_in, int64_t* out_ids) {
    std::vector<int64_t> v(ids, ids + n_in);
    std::sort(v.begin(), v.end());
    auto end = std::unique(v.begin(), v.end());
    int64_t n = end - v.begin();
    std::memcpy(out_ids, v.data(), n * sizeof(int64_t));
    return n;
}

// Build CSR from compact-mapped edges.
//   node_ids: sorted unique int64 ids (n of them)
//   src/dst:  raw int64 endpoint ids (e of them); every id MUST be present
//             in node_ids (returns -1 otherwise)
//   row_ptr:  out, n+1 int32
//   col_idx:  out, e int32 (dst compact ids, lexsorted by (src, dst))
//   src_idx:  out, e int32 (src compact id per edge, sorted)
// Two stable counting sorts give the (src, dst) lexsort in O(E + N).
int32_t build_csr(const int64_t* node_ids, int64_t n,
                  const int64_t* src, const int64_t* dst, int64_t e,
                  int32_t* row_ptr, int32_t* col_idx, int32_t* src_idx) {
    // compact-map endpoints via binary search
    std::vector<int32_t> s(e), d(e);
    const int64_t* begin = node_ids;
    const int64_t* end = node_ids + n;
    for (int64_t i = 0; i < e; i++) {
        const int64_t* ps = std::lower_bound(begin, end, src[i]);
        const int64_t* pd = std::lower_bound(begin, end, dst[i]);
        if (ps == end || *ps != src[i] || pd == end || *pd != dst[i]) return -1;
        s[i] = (int32_t)(ps - begin);
        d[i] = (int32_t)(pd - begin);
    }
    // counting sort by dst (stable)
    std::vector<int64_t> cnt(n + 1, 0);
    std::vector<int32_t> s1(e), d1(e);
    for (int64_t i = 0; i < e; i++) cnt[d[i] + 1]++;
    for (int64_t i = 0; i < n; i++) cnt[i + 1] += cnt[i];
    {
        std::vector<int64_t> pos(cnt.begin(), cnt.end());
        for (int64_t i = 0; i < e; i++) {
            int64_t p = pos[d[i]]++;
            s1[p] = s[i];
            d1[p] = d[i];
        }
    }
    // stable counting sort by src -> final lexsort (src, dst)
    std::fill(cnt.begin(), cnt.end(), 0);
    for (int64_t i = 0; i < e; i++) cnt[s1[i] + 1]++;
    for (int64_t i = 0; i < n; i++) cnt[i + 1] += cnt[i];
    for (int64_t i = 0; i <= n; i++) row_ptr[i] = (int32_t)cnt[i];
    {
        std::vector<int64_t> pos(cnt.begin(), cnt.end());
        for (int64_t i = 0; i < e; i++) {
            int64_t p = pos[s1[i]]++;
            col_idx[p] = d1[i];
            src_idx[p] = s1[i];
        }
    }
    return 0;
}

}  // extern "C"

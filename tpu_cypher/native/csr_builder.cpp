// Native host-side hot paths: CSR topology build and SNAP edge-list parsing.
//
// The reference delegates its host-side heavy lifting to the JVM engines
// (Spark/Flink DataFrame machinery); our TPU runtime's host tier does the
// equivalent work here in C++ — the compute path stays JAX/XLA, but graph
// ingest (text -> edges) and topology compaction (edges -> CSR) are
// bandwidth-bound host loops where interpreter overhead dominates:
//
//  * parse_edge_list: single-pass scan of a SNAP-style buffer ('#' comments,
//    whitespace/comma separated int pairs) — replaces the per-line Python
//    loop in io/edge_list.py.
//  * build_csr: map raw int64 element ids to compact int32 indices (binary
//    search over the sorted unique id vector) and produce a CSR lexsorted by
//    (src, dst) via two stable counting sorts, O(E + N) — replaces
//    np.searchsorted + np.lexsort (O(E log E)) in CsrGraph.build.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in the image).
// Build: g++ -O3 -march=native -shared -fPIC csr_builder.cpp -o _native.so

#include <cstdint>
#include <cstring>
#include <vector>
#include <algorithm>

extern "C" {

// Parse whitespace/comma-separated "src dst" pairs; skip '#...' comment and
// blank lines. Returns number of edges, or -(byte offset + 1) on malformed
// input. out_src/out_dst must have room for one edge per input line.
int64_t parse_edge_list(const char* buf, int64_t len,
                        int64_t* out_src, int64_t* out_dst) {
    int64_t count = 0;
    int64_t i = 0;
    while (i < len) {
        // skip leading spaces/commas
        while (i < len && (buf[i] == ' ' || buf[i] == '\t' || buf[i] == ',' ||
                           buf[i] == '\r')) i++;
        if (i >= len) break;
        if (buf[i] == '\n') { i++; continue; }
        if (buf[i] == '#') {            // comment line
            while (i < len && buf[i] != '\n') i++;
            continue;
        }
        // parse two integers; each must be followed by a separator/EOL so
        // "2.5" or "2x" is rejected exactly like the Python loader's int()
        int64_t vals[2];
        for (int k = 0; k < 2; k++) {
            while (i < len && (buf[i] == ' ' || buf[i] == '\t' || buf[i] == ','))
                i++;
            bool neg = false;
            if (i < len && (buf[i] == '-' || buf[i] == '+')) {
                neg = buf[i] == '-';
                i++;
            }
            if (i >= len || buf[i] < '0' || buf[i] > '9') return -(i + 1);
            int64_t v = 0;
            while (i < len && buf[i] >= '0' && buf[i] <= '9') {
                v = v * 10 + (buf[i] - '0');
                i++;
            }
            if (i < len && buf[i] != ' ' && buf[i] != '\t' && buf[i] != ',' &&
                buf[i] != '\r' && buf[i] != '\n')
                return -(i + 1);
            vals[k] = neg ? -v : v;
        }
        out_src[count] = vals[0];
        out_dst[count] = vals[1];
        count++;
        // skip to end of line (ignore trailing columns, e.g. weights)
        while (i < len && buf[i] != '\n') i++;
    }
    return count;
}

// Deduplicate + sort node ids in place semantics: input ids (n_in), output
// into out_ids; returns unique count. out_ids must have room for n_in.
int64_t unique_sorted(const int64_t* ids, int64_t n_in, int64_t* out_ids) {
    std::vector<int64_t> v(ids, ids + n_in);
    std::sort(v.begin(), v.end());
    auto end = std::unique(v.begin(), v.end());
    int64_t n = end - v.begin();
    std::memcpy(out_ids, v.data(), n * sizeof(int64_t));
    return n;
}

// Build CSR from compact-mapped edges.
//   node_ids: sorted unique int64 ids (n of them)
//   src/dst:  raw int64 endpoint ids (e of them); every id MUST be present
//             in node_ids (returns -1 otherwise)
//   row_ptr:  out, n+1 int32
//   col_idx:  out, e int32 (dst compact ids, lexsorted by (src, dst))
//   src_idx:  out, e int32 (src compact id per edge, sorted)
// Two stable counting sorts give the (src, dst) lexsort in O(E + N).
int32_t build_csr(const int64_t* node_ids, int64_t n,
                  const int64_t* src, const int64_t* dst, int64_t e,
                  int32_t* row_ptr, int32_t* col_idx, int32_t* src_idx) {
    // compact-map endpoints via binary search
    std::vector<int32_t> s(e), d(e);
    const int64_t* begin = node_ids;
    const int64_t* end = node_ids + n;
    for (int64_t i = 0; i < e; i++) {
        const int64_t* ps = std::lower_bound(begin, end, src[i]);
        const int64_t* pd = std::lower_bound(begin, end, dst[i]);
        if (ps == end || *ps != src[i] || pd == end || *pd != dst[i]) return -1;
        s[i] = (int32_t)(ps - begin);
        d[i] = (int32_t)(pd - begin);
    }
    // counting sort by dst (stable)
    std::vector<int64_t> cnt(n + 1, 0);
    std::vector<int32_t> s1(e), d1(e);
    for (int64_t i = 0; i < e; i++) cnt[d[i] + 1]++;
    for (int64_t i = 0; i < n; i++) cnt[i + 1] += cnt[i];
    {
        std::vector<int64_t> pos(cnt.begin(), cnt.end());
        for (int64_t i = 0; i < e; i++) {
            int64_t p = pos[d[i]]++;
            s1[p] = s[i];
            d1[p] = d[i];
        }
    }
    // stable counting sort by src -> final lexsort (src, dst)
    std::fill(cnt.begin(), cnt.end(), 0);
    for (int64_t i = 0; i < e; i++) cnt[s1[i] + 1]++;
    for (int64_t i = 0; i < n; i++) cnt[i + 1] += cnt[i];
    for (int64_t i = 0; i <= n; i++) row_ptr[i] = (int32_t)cnt[i];
    {
        std::vector<int64_t> pos(cnt.begin(), cnt.end());
        for (int64_t i = 0; i < e; i++) {
            int64_t p = pos[s1[i]]++;
            col_idx[p] = d1[i];
            src_idx[p] = s1[i];
        }
    }
    return 0;
}

// Stamped 2-hop DISTINCT-endpoints count: the host-tier replacement for
// materialize-20M-rows-then-sort (engine analog of a merge-free boolean
// SpGEMM row count). One pass over the path space with an O(N) timestamp
// array that lives in cache: stamp[c] == a marks pair (a, c) as seen.
// PRECONDITION (checked by the ctypes wrapper): equal akeys are contiguous
// (each source one run), so a stamp from an earlier source can never be
// confused with the current one.
//   rp1/ci1: hop-1 CSR (frontier -> b), rp2/ci2: hop-2 CSR (b -> c)
//   frontier/akeys: compact position + distinct-group key per input row
//   mask1/mask2: optional bool masks on b / c (null = unrestricted)
//   use_a/use_c: which endpoints the DISTINCT covers
int64_t two_hop_distinct(const int32_t* rp1, const int32_t* ci1,
                         const int32_t* rp2, const int32_t* ci2,
                         const int64_t* frontier, const int64_t* akeys,
                         int64_t nf, int64_t n, int32_t use_a, int32_t use_c,
                         const uint8_t* mask1, const uint8_t* mask2) {
    std::vector<int64_t> stamp(n, -1);
    int64_t cnt = 0;
    int64_t last_counted_a = -1;
    for (int64_t i = 0; i < nf; i++) {
        int64_t a = use_a ? akeys[i] : 0;  // !use_a: one global dedup group
        if (!use_c && use_a && a == last_counted_a) continue;
        int64_t p = frontier[i];
        bool found = false;
        for (int32_t e1 = rp1[p]; e1 < rp1[p + 1] && !(found && !use_c); e1++) {
            int32_t b = ci1[e1];
            if (mask1 && !mask1[b]) continue;
            for (int32_t e2 = rp2[b]; e2 < rp2[b + 1]; e2++) {
                int32_t c = ci2[e2];
                if (mask2 && !mask2[c]) continue;
                if (!use_c) { found = true; break; }
                if (stamp[c] != a) {
                    stamp[c] = a;
                    cnt++;
                }
            }
        }
        if (!use_c && found) {
            cnt++;
            last_counted_a = a;
        }
    }
    return cnt;
}

// Stamped 2-hop + ExpandInto close count (directed triangles / 2-hop
// cycles): per source a, pre-stamp the closing endpoints x reachable by a
// closing edge (rpc/cic = the close CSR oriented FROM a) with their edge
// multiplicities, then every surviving 2-hop path (a, b, c) adds the
// multiplicity of closing edges at c. Matches the searchsorted probe's
// hi-lo semantics exactly, parallel edges included. Same grouped-akeys
// precondition as two_hop_distinct.
int64_t two_hop_close_count(const int32_t* rp1, const int32_t* ci1,
                            const int32_t* rp2, const int32_t* ci2,
                            const int32_t* rpc, const int32_t* cic,
                            const int64_t* frontier, const int64_t* akeys,
                            int64_t nf, int64_t n,
                            const uint8_t* mask1, const uint8_t* mask2) {
    std::vector<int64_t> stamp(n, -1);
    std::vector<int32_t> mult(n, 0);
    int64_t cnt = 0;
    int64_t stamped_a = -1;
    for (int64_t i = 0; i < nf; i++) {
        int64_t a = akeys[i];
        if (a != stamped_a) {
            for (int32_t e = rpc[a]; e < rpc[a + 1]; e++) {
                int32_t x = cic[e];
                if (stamp[x] != a) {
                    stamp[x] = a;
                    mult[x] = 0;
                }
                mult[x]++;
            }
            stamped_a = a;
        }
        int64_t p = frontier[i];
        for (int32_t e1 = rp1[p]; e1 < rp1[p + 1]; e1++) {
            int32_t b = ci1[e1];
            if (mask1 && !mask1[b]) continue;
            for (int32_t e2 = rp2[b]; e2 < rp2[b + 1]; e2++) {
                int32_t c = ci2[e2];
                if (mask2 && !mask2[c]) continue;
                if (stamp[c] == a) cnt += mult[c];
            }
        }
    }
    return cnt;
}

// Bounded var-length walk count with relationship-distinctness (openCypher
// path isomorphism): iterative DFS per frontier row over the CSR, counting
// walks of length in [lo, hi] whose far node passes the label mask. The
// walked-edge stack holds canonical scan rows (eo) — undirected walks share
// one scan row per relationship, so reuse checks are direction-agnostic —
// and is at most `hi` deep, so the distinctness check is a linear scan of a
// register-resident array. Replaces materializing every partial-walk level
// on host backends (the device frontier loop keeps TPU/mesh paths).
int64_t varlen_count_forbid(const int32_t* rp, const int32_t* ci,
                            const int64_t* eo, const int64_t* frontier,
                            int64_t nf, int64_t lo, int64_t hi,
                            const uint8_t* far_mask,
                            const int64_t* forbid, int64_t nfb);

int64_t varlen_count(const int32_t* rp, const int32_t* ci, const int64_t* eo,
                     const int64_t* frontier, int64_t nf,
                     int64_t lo, int64_t hi, const uint8_t* far_mask) {
    return varlen_count_forbid(rp, ci, eo, frontier, nf, lo, hi, far_mask,
                               nullptr, 0);
}

// varlen_count with per-frontier-row forbidden edges: forbid is row-major
// [nf x nfb] canonical scan rows (-1 = unconstrained) that row i's walks may
// not use — the openCypher isomorphism between a var-length and the fixed
// relationships already bound in its input row (the device tier seeds the
// same values into the walked-edge masks).
int64_t varlen_count_forbid(const int32_t* rp, const int32_t* ci,
                            const int64_t* eo, const int64_t* frontier,
                            int64_t nf, int64_t lo, int64_t hi,
                            const uint8_t* far_mask,
                            const int64_t* forbid, int64_t nfb) {
    if (hi < 1 || hi > 64 || nfb < 0) return -1;  // caller falls back
    int64_t count = 0;
    std::vector<int64_t> estack(hi + 1);
    std::vector<int32_t> vstack(hi + 1);
    std::vector<int32_t> epos(hi + 1);
    for (int64_t i = 0; i < nf; i++) {
        int32_t s = (int32_t)frontier[i];
        const int64_t* fb = forbid ? forbid + i * nfb : nullptr;
        int depth = 0;
        vstack[0] = s;
        epos[0] = rp[s];
        while (depth >= 0) {
            if (epos[depth] < rp[vstack[depth] + 1]) {
                int32_t e = epos[depth]++;
                int64_t orig = eo[e];
                bool dup = false;
                for (int64_t k = 0; k < nfb; k++)
                    if (fb[k] == orig) { dup = true; break; }
                if (!dup)
                    for (int k = 0; k < depth; k++)
                        if (estack[k] == orig) { dup = true; break; }
                if (dup) continue;
                int32_t nb = ci[e];
                int d1 = depth + 1;
                if (d1 >= lo && (!far_mask || far_mask[nb])) count++;
                if (d1 < hi) {
                    estack[depth] = orig;
                    vstack[d1] = nb;
                    epos[d1] = rp[nb];
                    depth = d1;
                }
            } else {
                depth--;
            }
        }
    }
    return count;
}

}  // extern "C"

"""Native host-tier: C++ hot paths behind ctypes, with pure-NumPy fallback.

The shared library is compiled on first use with the system ``g++`` (the
image ships no pybind11; the C ABI + ctypes needs nothing extra). If no
compiler is available the callers fall back to their NumPy implementations —
behavior is identical, only slower.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
import threading
from typing import Optional, Tuple

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "csr_builder.cpp")
_LIB_PATH = os.path.join(_HERE, "_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _compile() -> bool:
    """Build the .so next to the source; atomic rename so concurrent
    importers never load a half-written library."""
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_HERE)
    os.close(fd)
    try:
        res = subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", tmp],
            capture_output=True,
            timeout=120,
        )
        if res.returncode != 0:
            return False
        os.replace(tmp, _LIB_PATH)
        return True
    except (OSError, subprocess.TimeoutExpired):
        return False
    finally:
        if os.path.exists(tmp):
            try:
                os.unlink(tmp)
            except OSError:
                pass


def get_lib() -> Optional[ctypes.CDLL]:
    """The native library, compiled on demand; None if unavailable."""
    global _lib, _build_failed
    if _lib is not None:
        return _lib
    if _build_failed:
        return None
    with _lock:
        if _lib is not None:
            return _lib
        stale = not os.path.exists(_LIB_PATH) or (
            os.path.getmtime(_LIB_PATH) < os.path.getmtime(_SRC)
        )
        if stale and not _compile():
            _build_failed = True
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            _build_failed = True
            return None
        lib.parse_edge_list.restype = ctypes.c_int64
        lib.parse_edge_list.argtypes = [
            ctypes.c_char_p,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.unique_sorted.restype = ctypes.c_int64
        lib.unique_sorted.argtypes = [
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.build_csr.restype = ctypes.c_int32
        lib.build_csr.argtypes = [
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
        ]
        p32 = ctypes.POINTER(ctypes.c_int32)
        p64 = ctypes.POINTER(ctypes.c_int64)
        pu8 = ctypes.POINTER(ctypes.c_uint8)
        lib.two_hop_distinct.restype = ctypes.c_int64
        lib.two_hop_distinct.argtypes = [
            p32, p32, p32, p32, p64, p64,
            ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int32, ctypes.c_int32, pu8, pu8,
        ]
        lib.two_hop_close_count.restype = ctypes.c_int64
        lib.two_hop_close_count.argtypes = [
            p32, p32, p32, p32, p32, p32, p64, p64,
            ctypes.c_int64, ctypes.c_int64, pu8, pu8,
        ]
        lib.varlen_count.restype = ctypes.c_int64
        lib.varlen_count.argtypes = [
            p32, p32, p64, p64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, pu8,
        ]
        lib.varlen_count_forbid.restype = ctypes.c_int64
        lib.varlen_count_forbid.argtypes = [
            p32, p32, p64, p64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, pu8,
            p64, ctypes.c_int64,
        ]
        _lib = lib
        return _lib


def _p64(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def _p32(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def parse_edge_list_native(data: bytes) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Parse a SNAP-style edge-list buffer; None if the native lib is
    unavailable. Raises ValueError on malformed input (byte offset in the
    message), matching the Python loader's strictness."""
    lib = get_lib()
    if lib is None:
        return None
    max_edges = data.count(b"\n") + 1
    src = np.empty(max_edges, dtype=np.int64)
    dst = np.empty(max_edges, dtype=np.int64)
    n = lib.parse_edge_list(data, len(data), _p64(src), _p64(dst))
    if n < 0:
        off = -int(n) - 1
        line = data[:off].count(b"\n") + 1
        raise ValueError(f"line {line} (byte offset {off})")
    return src[:n].copy(), dst[:n].copy()


def _csr32(rp, ci) -> Tuple[np.ndarray, np.ndarray]:
    return (
        np.ascontiguousarray(rp, dtype=np.int32),
        np.ascontiguousarray(ci, dtype=np.int32),
    )


def _mask_u8(mask) -> Optional[np.ndarray]:
    if mask is None:
        return None
    return np.ascontiguousarray(mask, dtype=np.uint8)


def _pm(m: Optional[np.ndarray]):
    return m.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)) if m is not None else None


def _grouped(ak: np.ndarray) -> bool:
    """True when equal values are contiguous (each source forms one run) —
    the stamping kernels' precondition. Scans emit unique rows, so this is
    almost always trivially true; exotic driving tables bail out."""
    if len(ak) < 2:
        return True
    changes = int(np.count_nonzero(ak[1:] != ak[:-1]))
    return changes == len(np.unique(ak)) - 1


def two_hop_distinct_native(
    rp1, ci1, rp2, ci2, frontier, akeys, n, use_a, use_c, mask1, mask2
) -> Optional[int]:
    """Stamped 2-hop DISTINCT-endpoints count (see csr_builder.cpp); None
    when the native lib is unavailable or the grouped-akeys precondition
    fails (callers keep the device path)."""
    lib = get_lib()
    if lib is None:
        return None
    if not use_a and not use_c:
        # the kernel counts one hit per frontier ROW in this mode while the
        # device path would count at most one GLOBAL row — reject rather
        # than silently diverge (ADVICE r4)
        return None
    ak = np.ascontiguousarray(akeys, dtype=np.int64)
    if not _grouped(ak):
        return None  # stamping needs contiguous per-source row groups
    fr = np.ascontiguousarray(frontier, dtype=np.int64)
    rp1, ci1 = _csr32(rp1, ci1)
    rp2, ci2 = _csr32(rp2, ci2)
    m1, m2 = _mask_u8(mask1), _mask_u8(mask2)
    return int(
        lib.two_hop_distinct(
            _p32(rp1), _p32(ci1), _p32(rp2), _p32(ci2), _p64(fr), _p64(ak),
            len(fr), int(n), int(use_a), int(use_c), _pm(m1), _pm(m2),
        )
    )


def two_hop_close_count_native(
    rp1, ci1, rp2, ci2, rpc, cic, frontier, akeys, n, mask1, mask2
) -> Optional[int]:
    """Stamped 2-hop + close-probe count (see csr_builder.cpp); None when
    unavailable or equal akeys are not contiguous."""
    lib = get_lib()
    if lib is None:
        return None
    ak = np.ascontiguousarray(akeys, dtype=np.int64)
    if not _grouped(ak):
        return None
    fr = np.ascontiguousarray(frontier, dtype=np.int64)
    rp1, ci1 = _csr32(rp1, ci1)
    rp2, ci2 = _csr32(rp2, ci2)
    rpc, cic = _csr32(rpc, cic)
    m1, m2 = _mask_u8(mask1), _mask_u8(mask2)
    return int(
        lib.two_hop_close_count(
            _p32(rp1), _p32(ci1), _p32(rp2), _p32(ci2), _p32(rpc), _p32(cic),
            _p64(fr), _p64(ak), len(fr), int(n), _pm(m1), _pm(m2),
        )
    )


def varlen_count_native(
    rp, ci, eo, frontier, lo, hi, far_mask, forbid=None
) -> Optional[int]:
    """Bounded var-length walk count via the DFS kernel (see
    csr_builder.cpp); None when the native lib is unavailable or the bound
    is out of the kernel's stack range. ``forbid``: optional [nf, k] int64
    canonical scan rows each frontier row's walks must avoid (-1 pads)."""
    lib = get_lib()
    if lib is None:
        return None
    rp, ci = _csr32(rp, ci)
    eo = np.ascontiguousarray(eo, dtype=np.int64)
    fr = np.ascontiguousarray(frontier, dtype=np.int64)
    m = _mask_u8(far_mask)
    if forbid is not None:
        fb = np.ascontiguousarray(forbid, dtype=np.int64)
        if fb.ndim != 2 or fb.shape[0] != len(fr):
            return None
        got = int(
            lib.varlen_count_forbid(
                _p32(rp), _p32(ci), _p64(eo), _p64(fr),
                len(fr), int(lo), int(hi), _pm(m),
                _p64(fb), int(fb.shape[1]),
            )
        )
        return None if got < 0 else got
    got = int(
        lib.varlen_count(
            _p32(rp), _p32(ci), _p64(eo), _p64(fr),
            len(fr), int(lo), int(hi), _pm(m),
        )
    )
    return None if got < 0 else got


def build_csr_native(
    node_ids: np.ndarray, src: np.ndarray, dst: np.ndarray
) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]]:
    """(unique_ids, row_ptr, col_idx, src_idx) lexsorted by (src, dst), or
    None if the native lib is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    ids = np.ascontiguousarray(node_ids, dtype=np.int64)
    uniq = np.empty(len(ids), dtype=np.int64)
    n = lib.unique_sorted(_p64(ids), len(ids), _p64(uniq))
    uniq = uniq[:n].copy()
    s = np.ascontiguousarray(src, dtype=np.int64)
    d = np.ascontiguousarray(dst, dtype=np.int64)
    e = len(s)
    row_ptr = np.empty(n + 1, dtype=np.int32)
    col_idx = np.empty(e, dtype=np.int32)
    src_idx = np.empty(e, dtype=np.int32)
    rc = lib.build_csr(
        _p64(uniq), n, _p64(s), _p64(d), e, _p32(row_ptr), _p32(col_idx), _p32(src_idx)
    )
    if rc != 0:
        raise ValueError("Edge endpoint id not present in node_ids")
    return uniq, row_ptr, col_idx, src_idx

"""Shared analysis substrate: one parse + one scope-resolution pass per file.

Every rule consumes the same ``FileContext``: the AST with parent links, an
enclosing-function index, a per-function assignment table (for one-level
value chasing: "was this name bound from a device-producing call?"), the
pre-extracted call list, and the parsed suppression comments. Building
these once per file — instead of once per rule per file — is what lets the
whole engine lint in seconds (six rules over ~100 modules is one parse,
not six).
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

# ---------------------------------------------------------------------------
# findings
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Finding:
    """One rule violation at one source location. ``path`` is normalized to
    a posix-style path relative to the analysis root so baselines and JSON
    output are machine-portable."""

    rule: str
    path: str
    line: int
    col: int
    message: str

    def location(self) -> str:
        return f"{self.path}:{self.line}:{self.col}"

    def to_json(self) -> Dict[str, object]:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
        }

    def baseline_key(self) -> Tuple[str, str, str]:
        # line numbers drift with unrelated edits; (rule, path, message)
        # is stable as long as the offending construct survives
        return (self.rule, self.path, self.message)


# ---------------------------------------------------------------------------
# suppressions: `# tpulint: allow[rule-a,rule-b] reason=...`
# ---------------------------------------------------------------------------

_SUPPRESS_RE = re.compile(
    r"#\s*tpulint:\s*allow\[(?P<rules>[^\]]*)\]\s*(?:reason=(?P<reason>.*))?$"
)
_TPULINT_RE = re.compile(r"#\s*tpulint:")


@dataclass
class Suppression:
    line: int  # the line the comment sits on
    rules: Tuple[str, ...]
    reason: str
    covers: Tuple[int, ...] = ()  # lines this suppression applies to


def _parse_suppressions(lines: Sequence[str]) -> Tuple[List[Suppression], List[Finding]]:
    """A suppression covers the line it shares with code; a comment-only
    line covers the next line instead (the ``# noqa``-above style). The
    reason is MANDATORY — an allow without one is reported as a
    ``suppression`` finding and ignored, as is any malformed ``tpulint:``
    comment (a typo must not silently stop suppressing)."""
    sups: List[Suppression] = []
    bad: List[Finding] = []
    for i, text in enumerate(lines, start=1):
        if "tpulint" not in text:
            continue
        m = _SUPPRESS_RE.search(text)
        if not m:
            if _TPULINT_RE.search(text):
                bad.append(
                    Finding(
                        "suppression",
                        "",
                        i,
                        max(text.find("#"), 0),
                        "malformed tpulint comment (expected "
                        "'# tpulint: allow[rule-id] reason=...')",
                    )
                )
            continue
        rules = tuple(
            r.strip() for r in m.group("rules").split(",") if r.strip()
        )
        reason = (m.group("reason") or "").strip()
        comment_only = text[: m.start()].strip() == ""
        covered = (i + 1,) if comment_only else (i,)
        if not rules or not reason:
            bad.append(
                Finding(
                    "suppression",
                    "",
                    i,
                    max(text.find("#"), 0),
                    "suppression without a %s — every allow must name its "
                    "rule(s) and carry reason=<why this site is exempt>"
                    % ("reason" if rules else "rule id"),
                )
            )
            continue
        sups.append(Suppression(i, rules, reason, covered))
    return sups, bad


# ---------------------------------------------------------------------------
# file context
# ---------------------------------------------------------------------------

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)


def dotted_name(node: ast.AST) -> str:
    """``jnp.nonzero`` / ``os.environ.get`` / ``fault_point`` as a dotted
    string; '' when the expression is not a plain name/attribute chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


class FileContext:
    """Parsed AST + indexes for one source file. Raises ``SyntaxError`` on
    unparsable input (the runner reports it as a ``parse`` finding)."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath.replace("\\", "/")
        self.source = source
        self.lines: List[str] = source.splitlines()
        self.tree = ast.parse(source)

        self.parent: Dict[ast.AST, ast.AST] = {}
        self.functions: List[ast.AST] = []
        self.calls: List[ast.Call] = []
        self._enclosing: Dict[ast.AST, Optional[ast.AST]] = {}
        self._func_assigns: Dict[Optional[ast.AST], Dict[str, List[ast.expr]]] = {}
        self._func_calls: Dict[Optional[ast.AST], List[ast.Call]] = {}

        self._index()
        self.suppressions, self.suppression_findings = _parse_suppressions(
            self.lines
        )
        self._allow: Dict[int, Dict[str, str]] = {}
        for s in self.suppressions:
            for ln in s.covers:
                slot = self._allow.setdefault(ln, {})
                for r in s.rules:
                    slot[r] = s.reason

    # -- construction -------------------------------------------------------

    def _index(self) -> None:
        stack: List[ast.AST] = []

        def visit(node: ast.AST) -> None:
            fn = stack[-1] if stack else None
            self._enclosing[node] = fn
            if isinstance(node, ast.Call):
                self.calls.append(node)
                self._func_calls.setdefault(fn, []).append(node)
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = node.targets[0]
                if isinstance(t, ast.Name):
                    self._func_assigns.setdefault(fn, {}).setdefault(
                        t.id, []
                    ).append(node.value)
            is_fn = isinstance(node, _FUNC_NODES)
            if is_fn:
                self.functions.append(node)
                # decorators, parameter defaults, and annotations evaluate
                # in the ENCLOSING scope — visit them before pushing
                outer_children = list(node.decorator_list) + [
                    d for d in node.args.defaults if d is not None
                ] + [d for d in node.args.kw_defaults if d is not None]
                if node.returns is not None:
                    outer_children.append(node.returns)
                for child in outer_children:
                    self.parent[child] = node
                    visit(child)
                stack.append(node)
                for child in node.body:
                    self.parent[child] = node
                    visit(child)
                stack.pop()
                return
            for child in ast.iter_child_nodes(node):
                self.parent[child] = node
                visit(child)

        visit(self.tree)

    # -- queries ------------------------------------------------------------

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        """Innermost FunctionDef/AsyncFunctionDef containing ``node`` (None
        at module scope)."""
        return self._enclosing.get(node)

    def calls_in(self, fn: Optional[ast.AST]) -> List[ast.Call]:
        """Calls whose innermost enclosing function is ``fn`` — NOT
        transitive into nested defs (a nested closure is its own scope)."""
        return self._func_calls.get(fn, [])

    def calls_under(self, fn: ast.AST) -> Iterator[ast.Call]:
        """All calls lexically under ``fn``, including nested defs."""
        for n in ast.walk(fn):
            if isinstance(n, ast.Call):
                yield n

    def assignments(self, fn: Optional[ast.AST], name: str) -> List[ast.expr]:
        """Every ``name = <expr>`` value bound in ``fn``'s own scope."""
        return self._func_assigns.get(fn, {}).get(name, [])

    def param_names(self, fn: ast.AST) -> List[str]:
        a = fn.args
        names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
        if a.vararg:
            names.append(a.vararg.arg)
        if a.kwarg:
            names.append(a.kwarg.arg)
        return names

    def decorators(self, fn: ast.AST) -> List[str]:
        """Dotted names of ``fn``'s decorators; a ``partial(jax.jit, ...)``
        decorator contributes ``jax.jit`` (the wrapped callable is what
        matters for tracing semantics)."""
        out: List[str] = []
        for dec in fn.decorator_list:
            if isinstance(dec, ast.Call):
                name = dotted_name(dec.func)
                if name.split(".")[-1] == "partial" and dec.args:
                    inner = dotted_name(dec.args[0])
                    if inner:
                        out.append(inner)
                        continue
                out.append(name)
            else:
                out.append(dotted_name(dec))
        return [n for n in out if n]

    def is_jitted(self, fn: ast.AST) -> bool:
        return any(
            d in ("jax.jit", "jit") or d.endswith(".jit")
            for d in self.decorators(fn)
        )

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def allowed(self, lineno: int, rule: str) -> Optional[str]:
        """The suppression reason covering (line, rule), or None."""
        slot = self._allow.get(lineno)
        if not slot:
            return None
        return slot.get(rule)

    def finding(
        self, rule: str, node: ast.AST, message: str
    ) -> Finding:
        return Finding(
            rule,
            self.relpath,
            getattr(node, "lineno", 0),
            getattr(node, "col_offset", 0),
            message,
        )


# ---------------------------------------------------------------------------
# rule base
# ---------------------------------------------------------------------------


class Rule:
    """One invariant. ``check`` yields findings for a single file; cross-
    file facts (the config registry's declared names, dispatch's registered
    impls) come in via the ``ProjectContext`` built by the runner."""

    id: str = ""
    title: str = ""
    rationale: str = ""

    def check(self, ctx: FileContext, project: "ProjectContext"):  # noqa: F821
        raise NotImplementedError
        yield  # pragma: no cover

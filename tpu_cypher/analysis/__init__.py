"""Engine-aware static analysis: machine-check the invariants the engine's
correctness rests on.

The execution model (PR 1-4) created invariants that no general-purpose
linter knows about: size-changing materializes must round through the
bucket lattice (docs/pad-invariants.md), device syncs must sit behind a
``fault_point`` so the ladder and the deadline see them, ``TPU_CYPHER_*``
configuration must flow through the typed registry in ``utils.config``,
broad excepts in the TPU backend must re-raise device faults, and every
kernel launch / counter emission must go through obs. Before this package
those invariants lived in ad-hoc AST walkers duplicated across three test
files — exactly the invariant-drift failure mode EmptyHeaded (arxiv
1503.02368) describes when one algebra is lowered through many specialized
code paths: the paths diverge silently until a query is wrong or slow.

This package is the real static-analysis pass:

* one parsed-AST + scope-resolution pass per file (``core.FileContext``),
  shared by every rule, so the whole engine lints in seconds;
* a rule registry (``rules.ALL_RULES``) with six engine-grounded rules —
  see ``docs/static-analysis.md`` for the rule table;
* inline suppressions ``# tpulint: allow[rule-id] reason=...`` with the
  reason MANDATORY (an allow without a reason is itself a finding);
* a committed baseline (``analysis/baseline.json``) for grandfathered
  findings — kept EMPTY: new debt needs an inline reason, not a baseline
  entry;
* a CLI: ``python -m tpu_cypher.analysis [--format text|json]
  [--baseline FILE] [paths...]`` — exit 0 only when every finding is
  fixed, suppressed-with-reason, or baselined.

The three legacy test walkers (test_obs / test_fault_ladder /
test_pallas_dispatch) are reimplemented as framework rules; the tests now
invoke the framework (``check_engine``) so test-time and lint-time enforce
the SAME predicate.
"""

from __future__ import annotations

from .core import FileContext, Finding, Rule
from .runner import (
    ENGINE_ROOT,
    check_engine,
    engine_is_clean,
    engine_lint_summary,
    run_paths,
)
from .rules import ALL_RULES

__all__ = [
    "ALL_RULES",
    "ENGINE_ROOT",
    "FileContext",
    "Finding",
    "Rule",
    "check_engine",
    "engine_is_clean",
    "engine_lint_summary",
    "run_paths",
]

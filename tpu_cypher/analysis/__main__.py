"""CLI: ``python -m tpu_cypher.analysis [options] [paths...]``.

Exit codes: 0 clean, 1 findings, 2 usage/internal error.
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from .baseline import save as save_baseline
from .runner import (
    DEFAULT_BASELINE,
    ENGINE_ROOT,
    format_report,
    run_paths,
)
from .rules import ALL_RULES, RULES_BY_ID


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tpu_cypher.analysis",
        description=(
            "Engine-aware static analysis: tracer-safety, pad, sync, and "
            "config invariants (docs/static-analysis.md)."
        ),
    )
    p.add_argument(
        "paths",
        nargs="*",
        help="files or directories to lint (default: the tpu_cypher package)",
    )
    p.add_argument(
        "--format",
        choices=("text", "json"),
        default="text",
        help="report format (default: text)",
    )
    p.add_argument(
        "--baseline",
        default=DEFAULT_BASELINE,
        help="grandfathered-findings file (default: the committed, empty "
        "analysis/baseline.json); pass an empty string for no baseline",
    )
    p.add_argument(
        "--write-baseline",
        action="store_true",
        help="write all current blocking findings to --baseline and exit 0 "
        "(the adoption ratchet)",
    )
    p.add_argument(
        "--rules",
        default="",
        help="comma-separated rule ids to run (default: all)",
    )
    p.add_argument(
        "--list-rules",
        action="store_true",
        help="print the rule table and exit",
    )
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_rules:
        for rule in ALL_RULES:
            print(f"{rule.id:20s} {rule.title}")
        return 0

    rule_ids = None
    if args.rules:
        rule_ids = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in rule_ids if r not in RULES_BY_ID]
        if unknown:
            print(f"unknown rule id(s): {', '.join(unknown)}", file=sys.stderr)
            return 2

    paths = args.paths or [ENGINE_ROOT]
    baseline = args.baseline or None

    try:
        report = run_paths(paths, rules=rule_ids, baseline_path=baseline)
    except ValueError as exc:  # malformed baseline
        print(str(exc), file=sys.stderr)
        return 2

    if args.write_baseline:
        if not baseline:
            print("--write-baseline needs --baseline", file=sys.stderr)
            return 2
        save_baseline(baseline, report.blocking)
        print(
            f"wrote {len(report.blocking)} finding(s) to {baseline}"
        )
        return 0

    print(format_report(report, args.format))
    return 0 if report.clean else 1


if __name__ == "__main__":
    sys.exit(main())

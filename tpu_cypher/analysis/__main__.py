"""CLI: ``python -m tpu_cypher.analysis [options] [paths...]``.

Exit codes: 0 clean, 1 findings, 2 usage/internal error.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
from typing import List, Optional

from .baseline import save as save_baseline
from .runner import (
    DEFAULT_BASELINE,
    ENGINE_ROOT,
    format_report,
    run_paths,
)
from .rules import ALL_RULES, RULES_BY_ID


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tpu_cypher.analysis",
        description=(
            "Engine-aware static analysis: tracer-safety, pad, sync, and "
            "config invariants (docs/static-analysis.md)."
        ),
    )
    p.add_argument(
        "paths",
        nargs="*",
        help="files or directories to lint (default: the tpu_cypher package)",
    )
    p.add_argument(
        "--format",
        choices=("text", "json"),
        default="text",
        help="report format (default: text)",
    )
    p.add_argument(
        "--baseline",
        default=DEFAULT_BASELINE,
        help="grandfathered-findings file (default: the committed, empty "
        "analysis/baseline.json); pass an empty string for no baseline",
    )
    p.add_argument(
        "--write-baseline",
        action="store_true",
        help="write all current blocking findings to --baseline and exit 0 "
        "(the adoption ratchet)",
    )
    p.add_argument(
        "--rules",
        default="",
        help="comma-separated rule ids to run (default: all)",
    )
    p.add_argument(
        "--list-rules",
        action="store_true",
        help="print the rule table and exit",
    )
    p.add_argument(
        "--facts-out",
        default="",
        metavar="PATH",
        help="write the shape interpreter's facts (schema-versioned "
        "per-operator padded-shape formulas plus every classified size "
        "site) as JSON to PATH — the cost-model feedstock",
    )
    p.add_argument(
        "--changed-only",
        action="store_true",
        help="check only files git reports modified/untracked; the whole "
        "tree is still parsed so cross-module (interprocedural) facts stay "
        "complete",
    )
    return p


def _git_changed_files() -> Optional[List[str]]:
    """Absolute paths of the .py files git reports changed (staged,
    unstaged, or untracked) in the repo containing the engine package.
    None when git is unavailable or this is not a work tree."""
    cwd = os.path.dirname(ENGINE_ROOT)
    try:
        top = subprocess.run(
            ["git", "rev-parse", "--show-toplevel"],
            cwd=cwd, capture_output=True, text=True, timeout=10,
        )
        if top.returncode != 0:
            return None
        root = top.stdout.strip()
        st = subprocess.run(
            ["git", "status", "--porcelain"],
            cwd=root, capture_output=True, text=True, timeout=10,
        )
        if st.returncode != 0:
            return None
    except (OSError, subprocess.SubprocessError):
        return None
    changed: List[str] = []
    for line in st.stdout.splitlines():
        if len(line) < 4:
            continue
        path = line[3:]
        if " -> " in path:  # rename: lint the new name
            path = path.split(" -> ", 1)[1]
        path = path.strip().strip('"')
        if path.endswith(".py"):
            changed.append(os.path.join(root, path))
    return changed


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_rules:
        for rule in ALL_RULES:
            print(f"{rule.id:20s} {rule.title}")
        return 0

    rule_ids = None
    if args.rules:
        rule_ids = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in rule_ids if r not in RULES_BY_ID]
        if unknown:
            print(f"unknown rule id(s): {', '.join(unknown)}", file=sys.stderr)
            return 2

    paths = args.paths or [ENGINE_ROOT]
    baseline = args.baseline or None

    restrict = None
    if args.changed_only:
        restrict = _git_changed_files()
        if restrict is None:
            print("--changed-only needs a git work tree", file=sys.stderr)
            return 2

    try:
        report = run_paths(
            paths, rules=rule_ids, baseline_path=baseline, restrict_to=restrict
        )
    except ValueError as exc:  # malformed baseline
        print(str(exc), file=sys.stderr)
        return 2

    if args.write_baseline:
        if not baseline:
            print("--write-baseline needs --baseline", file=sys.stderr)
            return 2
        save_baseline(baseline, report.blocking)
        print(
            f"wrote {len(report.blocking)} finding(s) to {baseline}"
        )
        return 0

    if args.facts_out:
        import json as _json

        from .shapes import collect_facts

        facts = collect_facts(report.project)
        with open(args.facts_out, "w") as f:
            _json.dump(facts, f, indent=2, sort_keys=True)
            f.write("\n")

    print(format_report(report, args.format))
    return 0 if report.clean else 1


if __name__ == "__main__":
    sys.exit(main())

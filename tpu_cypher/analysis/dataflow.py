"""Interprocedural dataflow: the device-value taint lattice and the
blocking-call summaries.

Two analyses share the call graph, both computed as fixpoints over
per-function summaries (classic bottom-up summary propagation — each
function is summarized once, call sites consume summaries, iteration
continues until nothing changes):

**Device taint** — "does this expression hold a traced device array?"
The lattice is ``device > unknown > host`` with one refinement: a
function whose return value depends only on its parameters gets a
PASSTHROUGH summary naming them, so call sites classify the actual
arguments (``helper(x)`` is device-valued exactly when ``x`` is). Taint
enters at the jax/jnp/lax/J intrinsics and at ``dispatch.launch``, flows
through single-target assignments, arithmetic, subscripts, returns, and
call sites (both directions: returns flow OUT to callers, argument taint
flows IN to parameters), and dies at shape/dtype metadata. This is what
makes the ``host-sync`` rule semantic: ``int(helper(x))`` flags when
``helper`` returns a traced array from two files away, and a helper that
syncs its own parameter flags when ANY caller passes it a device value.

**Blocking summaries** — "can a call to this function block the thread?"
Seeded at the blocking intrinsics (``time.sleep``, socket/subprocess
ops, ``session.cypher``, device syncs — which reuse the device taint),
propagated along call edges, with each summary carrying the CHAIN of
calls that reaches the intrinsic so the ``async-blocking`` finding can
say *why* (``handler -> helper -> time.sleep``). Calls inside lambdas do
not propagate (a deferred body is not executed by its lexical encloser);
callables handed to ``run_in_executor``/``to_thread`` are the sanctioned
escape hatch and never taint the async def that awaits them.

Everything is conservative in the direction that avoids false positives:
an unresolvable call is UNKNOWN (not device, not blocking), a parameter
with no resolved caller is UNKNOWN, and UNKNOWN never fires a rule.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple, Union

from .callgraph import CallGraph, FunctionInfo
from .core import FileContext, dotted_name

# -- the taint lattice -------------------------------------------------------

DEVICE = "device"
HOST = "host"
UNKNOWN = "unknown"

# a summary is a fixed verdict or ("passthrough", frozenset(param names)):
# the return taint equals the join of those arguments' taints at the site
Summary = Union[str, Tuple[str, frozenset]]

# dotted-prefix spelling of "this call returns a device value" in this
# codebase: jax/jnp/lax directly, J (the jit_ops alias), pl (pallas)
_DEVICE_PREFIXES = ("jnp.", "jax.", "lax.", "J.", "pl.")
_DEVICE_EXACT = ("dispatch.launch", "launch")
# dtype/shape introspection: host-side metadata, not device values
_METADATA_FUNCS = ("iinfo", "finfo", "dtype", "result_type", "ndim", "shape")
_HOST_ATTRS = ("shape", "ndim", "size", "dtype")
_HOST_BUILTINS = ("len", "range", "enumerate", "zip", "sorted", "repr", "str")


def is_device_intrinsic(name: str) -> bool:
    if not name:
        return False
    if name in _DEVICE_EXACT:
        return True
    if name.startswith("jax.device_put") or ".shape" in name:
        return False
    if name.split(".")[-1] in _METADATA_FUNCS:
        return False
    return name.startswith(_DEVICE_PREFIXES)


def _join(verdicts) -> str:
    out = HOST
    saw = False
    for v in verdicts:
        saw = True
        if v == DEVICE:
            return DEVICE
        if v != HOST:
            out = UNKNOWN
    return out if saw else UNKNOWN


class DeviceTaint:
    """Per-function return summaries + per-parameter taints, to fixpoint."""

    def __init__(self, graph: CallGraph):
        self.graph = graph
        self.returns: Dict[ast.AST, Summary] = {}
        self.params: Dict[Tuple[ast.AST, str], str] = {}
        # per-round inputs precomputed once: re-walking every function AST
        # and re-resolving every call site each fixpoint round dominated
        # the analyzer's runtime before being hoisted here
        self._returns_of: Dict[ast.AST, List[ast.AST]] = {}
        self._callee_sites: Dict[ast.AST, list] = {}
        for info in graph.infos.values():
            self._returns_of[info.node] = [
                n.value
                for n in ast.walk(info.node)
                if isinstance(n, ast.Return)
                and n.value is not None
                and info.ctx.enclosing_function(n) is info.node
            ]
            sites = [
                (site, targets)
                for site, targets in graph.callees(info)
                if targets
            ]
            if sites:
                self._callee_sites[info.node] = sites
        self._solve()

    # -- public -------------------------------------------------------------

    def classify(
        self, ctx: FileContext, fn: Optional[ast.AST], expr: ast.AST
    ) -> str:
        """'device' | 'host' | 'unknown' for an expression at a rule's
        query site, with parameters resolved through the computed
        cross-call taints."""
        v = self._classify(ctx, fn, expr, 0, symbolic=False)
        return v if isinstance(v, str) else UNKNOWN

    def return_summary(self, node: ast.AST) -> Summary:
        return self.returns.get(node, UNKNOWN)

    # -- fixpoint -----------------------------------------------------------

    def _solve(self, max_rounds: int = 8) -> None:
        infos = list(self.graph.infos.values())
        for _ in range(max_rounds):
            changed = False
            for info in infos:
                new = self._summarize(info)
                if self.returns.get(info.node) != new:
                    self.returns[info.node] = new
                    changed = True
            changed |= self._flow_params(infos)
            if not changed:
                return

    def _summarize(self, info: FunctionInfo) -> Summary:
        ctx, fn = info.ctx, info.node
        verdicts: List[str] = []
        passthrough: Set[str] = set()
        for ret in self._returns_of.get(fn, ()):
            v = self._classify(ctx, fn, ret, 0, symbolic=True)
            if isinstance(v, tuple) and v[0] == "param":
                passthrough.add(v[1])
            else:
                verdicts.append(v)
        if DEVICE in verdicts:
            return DEVICE
        if passthrough:
            # host-valued alternate returns don't break passthrough — the
            # call site join handles them
            if all(v == HOST for v in verdicts) or not verdicts:
                return ("passthrough", frozenset(passthrough))
            return UNKNOWN
        if verdicts and all(v == HOST for v in verdicts):
            return HOST
        return UNKNOWN

    def _flow_params(self, infos: Sequence[FunctionInfo]) -> bool:
        """Argument taint -> parameter taint, joined over every resolved
        call site. A parameter nobody is seen calling stays UNKNOWN."""
        incoming: Dict[Tuple[ast.AST, str], List[str]] = {}
        for info in infos:
            for site, targets in self._callee_sites.get(info.node, ()):
                arg_taints = [
                    self._arg_taint(site.ctx, info.node, a)
                    for a in site.call.args
                ]
                kw_taints = {
                    kw.arg: self._arg_taint(site.ctx, info.node, kw.value)
                    for kw in site.call.keywords
                    if kw.arg is not None
                }
                for tgt in targets:
                    names = tgt.ctx.param_names(tgt.node)
                    if names and names[0] == "self":
                        names = names[1:]
                    for i, t in enumerate(arg_taints):
                        if i < len(names):
                            incoming.setdefault(
                                (tgt.node, names[i]), []
                            ).append(t)
                    for k, t in kw_taints.items():
                        if k in names:
                            incoming.setdefault((tgt.node, k), []).append(t)
        changed = False
        for key, taints in incoming.items():
            new = _join(taints)
            if self.params.get(key, UNKNOWN) != new:
                self.params[key] = new
                changed = True
        return changed

    def _arg_taint(
        self, ctx: FileContext, fn: Optional[ast.AST], expr: ast.AST
    ) -> str:
        v = self._classify(ctx, fn, expr, 0, symbolic=False)
        return v if isinstance(v, str) else UNKNOWN

    # -- the expression classifier ------------------------------------------

    def _classify(
        self,
        ctx: FileContext,
        fn: Optional[ast.AST],
        expr: ast.AST,
        depth: int,
        symbolic: bool,
    ):
        """-> DEVICE | HOST | UNKNOWN | ("param", name) (symbolic mode
        keeps parameters symbolic for passthrough summaries; query mode
        resolves them through the cross-call taints)."""
        if depth > 6:
            return UNKNOWN
        if isinstance(expr, ast.Constant):
            return HOST
        if isinstance(expr, (ast.JoinedStr, ast.FormattedValue, ast.Dict,
                             ast.DictComp, ast.Lambda)):
            return HOST
        if isinstance(expr, ast.Attribute):
            if expr.attr in _HOST_ATTRS:
                return HOST
            return self._classify(ctx, fn, expr.value, depth + 1, symbolic)
        if isinstance(expr, ast.Subscript):
            return self._classify(ctx, fn, expr.value, depth + 1, symbolic)
        if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
            vs = [
                self._classify(ctx, fn, e, depth + 1, symbolic)
                for e in expr.elts
            ]
            if DEVICE in vs:
                return DEVICE  # a container OF device values syncs too
            return HOST
        if isinstance(expr, ast.Call):
            return self._classify_call(ctx, fn, expr, depth, symbolic)
        if isinstance(expr, (ast.BinOp, ast.BoolOp, ast.Compare, ast.UnaryOp)):
            if isinstance(expr, ast.BinOp):
                sides = [expr.left, expr.right]
            elif isinstance(expr, ast.BoolOp):
                sides = list(expr.values)
            elif isinstance(expr, ast.Compare):
                sides = [expr.left] + list(expr.comparators)
            else:
                sides = [expr.operand]
            vs = [
                self._classify(ctx, fn, s, depth + 1, symbolic)
                for s in sides
            ]
            if DEVICE in vs:
                return DEVICE
            if any(isinstance(v, tuple) for v in vs):
                # arithmetic ON a param is still param-shaped
                name = next(v[1] for v in vs if isinstance(v, tuple))
                return ("param", name)
            return _join(v for v in vs if isinstance(v, str))
        if isinstance(expr, ast.IfExp):
            vs = [
                self._classify(ctx, fn, s, depth + 1, symbolic)
                for s in (expr.body, expr.orelse)
            ]
            if DEVICE in vs:
                return DEVICE
            return _join(v if isinstance(v, str) else UNKNOWN for v in vs)
        if isinstance(expr, ast.Name):
            return self._classify_name(ctx, fn, expr.id, depth, symbolic)
        return UNKNOWN

    def _classify_name(
        self,
        ctx: FileContext,
        fn: Optional[ast.AST],
        name: str,
        depth: int,
        symbolic: bool,
    ):
        if fn is not None and name in ctx.param_names(fn):
            # parameter: symbolic for summaries, cross-call taint for rules
            assigns = ctx.assignments(fn, name)
            if not assigns:
                if symbolic:
                    return ("param", name)
                return self.params.get((fn, name), UNKNOWN)
        verdicts = []
        for v in ctx.assignments(fn, name):
            verdicts.append(self._classify(ctx, fn, v, depth + 1, symbolic))
        if DEVICE in verdicts:
            return DEVICE
        params = [v for v in verdicts if isinstance(v, tuple)]
        if params:
            return params[0]
        if verdicts:
            return _join(verdicts)
        return UNKNOWN

    def _classify_call(
        self,
        ctx: FileContext,
        fn: Optional[ast.AST],
        call: ast.Call,
        depth: int,
        symbolic: bool,
    ):
        name = dotted_name(call.func)
        if name in _HOST_BUILTINS or ".shape" in name:
            return HOST
        if is_device_intrinsic(name):
            return DEVICE
        if name in ("int", "float", "bool"):
            return HOST  # the sync itself produces a host scalar
        # metadata calls and .item() RETURN host values regardless of the
        # receiver (the host-sync rule looks at .item()'s receiver itself)
        if isinstance(call.func, ast.Attribute):
            leaf = call.func.attr
            if leaf in _METADATA_FUNCS or leaf == "item":
                return HOST
        targets = self.graph.resolve_call(ctx, call)
        if targets:
            vs: List[str] = []
            for tgt in targets:
                summary = self.returns.get(tgt.node, UNKNOWN)
                if isinstance(summary, tuple):
                    vs.append(
                        self._passthrough_at_site(
                            ctx, fn, call, tgt, summary[1], depth, symbolic
                        )
                    )
                else:
                    vs.append(summary)
            if DEVICE in vs:
                return DEVICE
            return _join(vs)
        if isinstance(call.func, ast.Attribute):
            recv = self._classify(
                ctx, fn, call.func.value, depth + 1, symbolic
            )
            if recv == DEVICE:
                return DEVICE
        return UNKNOWN

    def _passthrough_at_site(
        self,
        ctx: FileContext,
        fn: Optional[ast.AST],
        call: ast.Call,
        tgt: FunctionInfo,
        param_names: frozenset,
        depth: int,
        symbolic: bool,
    ) -> str:
        names = tgt.ctx.param_names(tgt.node)
        if names and names[0] == "self":
            names = names[1:]
        taints: List[str] = []
        for i, arg in enumerate(call.args):
            if i < len(names) and names[i] in param_names:
                v = self._classify(ctx, fn, arg, depth + 1, symbolic)
                taints.append(v if isinstance(v, str) else UNKNOWN)
        for kw in call.keywords:
            if kw.arg in param_names:
                v = self._classify(ctx, fn, kw.value, depth + 1, symbolic)
                taints.append(v if isinstance(v, str) else UNKNOWN)
        if DEVICE in taints:
            return DEVICE
        return _join(taints) if taints else UNKNOWN


# -- blocking summaries ------------------------------------------------------

# calls that block the calling thread outright, by dotted name or prefix
_BLOCKING_EXACT = {
    "time.sleep": "time.sleep",
    "os.system": "os.system",
    "socket.create_connection": "socket.create_connection",
    "jax.device_get": "jax.device_get (device sync)",
    "device_get": "jax.device_get (device sync)",
}
_BLOCKING_PREFIXES = (
    ("subprocess.", "subprocess"),
    ("requests.", "requests network I/O"),
    ("urllib.request.", "urllib network I/O"),
)
# attribute leaves that block when called on anything: the engine's own
# synchronous query entry, raw device syncs, and thread-pool waits
_BLOCKING_ATTRS = {
    "cypher": "session.cypher (synchronous engine execution)",
    "block_until_ready": "block_until_ready (device sync)",
    "warmup": "warmup (compiles synchronously)",
}


class BlockingInfo:
    """Why a function blocks: the call chain down to the intrinsic."""

    __slots__ = ("chain",)

    def __init__(self, chain: Tuple[str, ...]):
        self.chain = chain

    def via(self, hop: str) -> "BlockingInfo":
        return BlockingInfo((hop,) + self.chain)

    def render(self) -> str:
        return " -> ".join(self.chain)


def blocking_intrinsic(call: ast.Call) -> Optional[str]:
    """The human-readable reason this call blocks the thread, or None."""
    name = dotted_name(call.func)
    if name in _BLOCKING_EXACT:
        return _BLOCKING_EXACT[name]
    for prefix, why in _BLOCKING_PREFIXES:
        if name.startswith(prefix):
            return why
    if isinstance(call.func, ast.Attribute):
        leaf = call.func.attr
        if leaf in _BLOCKING_ATTRS:
            return _BLOCKING_ATTRS[leaf]
        # sock.recv/accept/connect: only when the receiver LOOKS like a
        # socket (named sock/socket/conn_sock) — keeps asyncio writers out
        if leaf in ("recv", "accept", "connect", "sendall"):
            recv_name = dotted_name(call.func.value)
            if "sock" in recv_name.split(".")[-1]:
                return f"socket.{leaf}"
    return None


class BlockingSummaries:
    """Transitive can-block verdicts for every project function."""

    def __init__(self, graph: CallGraph, taint: DeviceTaint):
        self.graph = graph
        self.taint = taint
        self.blocks: Dict[ast.AST, BlockingInfo] = {}
        self._solve()

    def direct_reason(
        self, info: FunctionInfo, site_call: ast.Call
    ) -> Optional[str]:
        """The reason this ONE call blocks the calling thread (an intrinsic
        or a taint-resolved device sync), or None. Shared with the
        async-blocking rule so both agree on what 'blocking' means."""
        reason = blocking_intrinsic(site_call)
        if reason is not None:
            return reason
        # a device sync (int/float/bool/np.asarray of a device value,
        # .item() on one) blocks on the device stream
        name = dotted_name(site_call.func)
        ctx, fn = info.ctx, info.node
        if name in ("int", "float", "bool") and len(site_call.args) == 1:
            if self.taint.classify(ctx, fn, site_call.args[0]) == DEVICE:
                return f"{name}(<device value>) (device sync)"
        if name in ("np.asarray", "numpy.asarray") and site_call.args:
            if self.taint.classify(ctx, fn, site_call.args[0]) == DEVICE:
                return "np.asarray(<device value>) (device sync)"
        if (
            isinstance(site_call.func, ast.Attribute)
            and site_call.func.attr == "item"
            and not site_call.args
        ):
            if self.taint.classify(ctx, fn, site_call.func.value) != HOST:
                return ".item() (device sync)"
        return None

    def _solve(self, max_rounds: int = 12) -> None:
        infos = list(self.graph.infos.values())
        # seed: direct intrinsics (never through a lambda — deferred)
        for info in infos:
            for site, _targets in self.graph.callees(info):
                if site.in_lambda:
                    continue
                reason = self.direct_reason(info, site.call)
                if reason is not None and info.node not in self.blocks:
                    self.blocks[info.node] = BlockingInfo((reason,))
        for _ in range(max_rounds):
            changed = False
            for info in infos:
                if info.node in self.blocks or info.is_async:
                    # an async def never blocks its CALLER by being called
                    # (calling it just builds a coroutine)
                    continue
                for site, targets in self.graph.callees(info):
                    if site.in_lambda:
                        continue
                    for tgt in targets:
                        if tgt.is_async:
                            continue
                        sub = self.blocks.get(tgt.node)
                        if sub is not None:
                            self.blocks[info.node] = sub.via(
                                f"{tgt.qualname}()"
                            )
                            changed = True
                            break
                    if info.node in self.blocks:
                        break
            if not changed:
                return

    def blocking_reason(self, node: ast.AST) -> Optional[BlockingInfo]:
        return self.blocks.get(node)

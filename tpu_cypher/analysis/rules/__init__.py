"""The rule registry: six engine-grounded invariants, one shared pass.

Adding a rule = subclass ``core.Rule``, give it a kebab-case ``id``, and
list an instance here. Rules are documented (id, rationale, fixture pair)
in ``docs/static-analysis.md``; every rule must ship a known-bad and a
known-clean fixture under ``tests/lint_fixtures/``.
"""

from __future__ import annotations

from typing import Dict, List

from ..core import Rule
from .env_registry import EnvVarRegistryRule
from .exception_hygiene import ExceptionHygieneRule
from .host_sync import HostSyncRule
from .obs_emission import ObsEmissionRule
from .pad_invariant import PadInvariantRule
from .recompile import RecompileHazardRule

ALL_RULES: List[Rule] = [
    HostSyncRule(),
    RecompileHazardRule(),
    PadInvariantRule(),
    EnvVarRegistryRule(),
    ExceptionHygieneRule(),
    ObsEmissionRule(),
]

RULES_BY_ID: Dict[str, Rule] = {r.id: r for r in ALL_RULES}

"""The rule registry: nine engine-grounded invariants, one shared pass.

Adding a rule = subclass ``core.Rule``, give it a kebab-case ``id``, and
list an instance here. Rules are documented (id, rationale, fixture pair)
in ``docs/static-analysis.md``; every rule must ship a known-bad and a
known-clean fixture under ``tests/lint_fixtures/``.

Six rules are per-file; ``host-sync`` and the concurrency pack
(``async-blocking``, ``contextvar-discipline``, ``shared-state-race``)
additionally consume the interprocedural substrate (``callgraph.py`` /
``dataflow.py``) the ``ProjectContext`` builds lazily on first use.
"""

from __future__ import annotations

from typing import Dict, List

from ..core import Rule
from .async_blocking import AsyncBlockingRule
from .contextvar_discipline import ContextvarDisciplineRule
from .env_registry import EnvVarRegistryRule
from .exception_hygiene import ExceptionHygieneRule
from .host_sync import HostSyncRule
from .obs_emission import ObsEmissionRule
from .pad_invariant import PadInvariantRule
from .recompile import RecompileHazardRule
from .shared_state_race import SharedStateRaceRule

ALL_RULES: List[Rule] = [
    HostSyncRule(),
    RecompileHazardRule(),
    PadInvariantRule(),
    EnvVarRegistryRule(),
    ExceptionHygieneRule(),
    ObsEmissionRule(),
    AsyncBlockingRule(),
    ContextvarDisciplineRule(),
    SharedStateRaceRule(),
]

RULES_BY_ID: Dict[str, Rule] = {r.id: r for r in ALL_RULES}

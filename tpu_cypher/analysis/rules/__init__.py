"""The rule registry: twelve engine-grounded invariants, one shared pass.

Adding a rule = subclass ``core.Rule``, give it a kebab-case ``id``, and
list an instance here. Rules are documented (id, rationale, fixture pair)
in ``docs/static-analysis.md``; every rule must ship a known-bad and a
known-clean fixture under ``tests/lint_fixtures/``.

Six rules are per-file; ``host-sync`` and the concurrency pack
(``async-blocking``, ``contextvar-discipline``, ``shared-state-race``)
consume the interprocedural substrate (``callgraph.py`` / ``dataflow.py``)
the ``ProjectContext`` builds lazily on first use; the shape pack
(``shape-stability``, ``pad-mask-discipline``, ``bucket-cardinality``)
rides the abstract shape interpreter (``shapes.py``) on the same call
graph — the semantic generation above the lexical pad/recompile rules.
"""

from __future__ import annotations

from typing import Dict, List

from ..core import Rule
from .async_blocking import AsyncBlockingRule
from .bucket_cardinality import BucketCardinalityRule
from .contextvar_discipline import ContextvarDisciplineRule
from .env_registry import EnvVarRegistryRule
from .exception_hygiene import ExceptionHygieneRule
from .host_sync import HostSyncRule
from .obs_emission import ObsEmissionRule
from .pad_invariant import PadInvariantRule
from .pad_mask import PadMaskRule
from .recompile import RecompileHazardRule
from .shape_stability import ShapeStabilityRule
from .shared_state_race import SharedStateRaceRule

ALL_RULES: List[Rule] = [
    HostSyncRule(),
    RecompileHazardRule(),
    PadInvariantRule(),
    EnvVarRegistryRule(),
    ExceptionHygieneRule(),
    ObsEmissionRule(),
    AsyncBlockingRule(),
    ContextvarDisciplineRule(),
    SharedStateRaceRule(),
    ShapeStabilityRule(),
    PadMaskRule(),
    BucketCardinalityRule(),
]

RULES_BY_ID: Dict[str, Rule] = {r.id: r for r in ALL_RULES}

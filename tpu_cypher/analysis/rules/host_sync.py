"""host-sync: device→host syncs sit behind a ``fault_point``.

A ``int(device_scalar)`` / ``.item()`` / ``jax.device_get`` blocks the
host on the device stream: it is exactly where an OOM/DeviceLost surfaces,
where the query deadline must be checked, and where the trace spans stamp
their sync points. The engine's contract (PR 2/4) is that every such sync
happens inside a function that passes through a named ``fault_point`` —
that is what makes the fault-injection matrix exhaustive and the ladder's
retry windows deterministic. A sync outside a fault-pointed function is
invisible to injection, to the deadline, and to obs.

Detection is scope-resolved, not textual: ``int(x)`` is only a sync when
``x`` (after chasing single-target assignments in the same function) comes
from a device-producing call (``jnp.*`` / ``jax.*`` / ``lax.*`` / jit-op
aliases); ``int(x.shape[0])`` and host arithmetic never flag.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from ..core import FileContext, Finding, Rule, dotted_name
from ..project import ProjectContext

SCOPE_DIRS = ("backend/tpu/", "parallel/")

# dotted-prefix spelling of "this call returns a device value" in this
# codebase: jax/jnp/lax directly, J (the jit_ops alias), dispatch.launch
_DEVICE_PREFIXES = ("jnp.", "jax.", "lax.", "J.", "pl.")
_DEVICE_EXACT = ("dispatch.launch", "launch")
_SYNC_BUILTINS = ("int", "float", "bool")


# dtype/shape metadata: host-side introspection, not device values
_METADATA_FUNCS = ("iinfo", "finfo", "dtype", "result_type", "ndim", "shape")


def _is_device_call(name: str) -> bool:
    if not name:
        return False
    if name in _DEVICE_EXACT:
        return True
    if name.startswith("jax.device_put") or ".shape" in name:
        return False
    if name.split(".")[-1] in _METADATA_FUNCS:
        return False
    return name.startswith(_DEVICE_PREFIXES)


class HostSyncRule(Rule):
    id = "host-sync"
    title = "device syncs happen at fault_point-wrapped sites"
    rationale = (
        "a sync outside a fault-pointed function is invisible to fault "
        "injection, the query deadline, and the span sync-point telemetry"
    )

    def check(
        self, ctx: FileContext, project: ProjectContext
    ) -> Iterator[Finding]:
        if not any(d in ctx.relpath for d in SCOPE_DIRS):
            return
        for call in ctx.calls:
            fn = ctx.enclosing_function(call)
            if fn is None:
                continue  # module scope: import-time constants, not syncs
            sync = self._sync_kind(ctx, fn, call)
            if sync is None:
                continue
            if self._under_fault_point(ctx, fn):
                continue
            yield ctx.finding(
                self.id,
                call,
                f"{sync} forces a device->host sync in a function with no "
                "fault_point — wrap the sync site (or suppress with the "
                "reason it cannot fault)",
            )

    # -- sync detection -----------------------------------------------------

    def _sync_kind(
        self, ctx: FileContext, fn: ast.AST, call: ast.Call
    ) -> Optional[str]:
        name = dotted_name(call.func)
        if name in ("jax.device_get", "device_get"):
            return "jax.device_get"
        if (
            isinstance(call.func, ast.Attribute)
            and call.func.attr == "item"
            and not call.args
        ):
            if self._classify(ctx, fn, call.func.value, 0) != "host":
                return ".item()"
            return None
        if name in _SYNC_BUILTINS and len(call.args) == 1:
            if self._classify(ctx, fn, call.args[0], 0) == "device":
                return f"{name}(<device value>)"
            return None
        if name in ("np.asarray", "numpy.asarray") and call.args:
            if self._classify(ctx, fn, call.args[0], 0) == "device":
                return "np.asarray(<device value>)"
        return None

    def _classify(
        self, ctx: FileContext, fn: ast.AST, expr: ast.AST, depth: int
    ) -> str:
        """'device' | 'host' | 'unknown' for one expression, chasing
        single-target assignments in the same function up to 4 hops."""
        if depth > 4:
            return "unknown"
        if isinstance(expr, ast.Constant):
            return "host"
        if isinstance(expr, ast.Attribute):
            if expr.attr in ("shape", "ndim", "size", "dtype"):
                return "host"
            return self._classify(ctx, fn, expr.value, depth + 1)
        if isinstance(expr, ast.Subscript):
            return self._classify(ctx, fn, expr.value, depth + 1)
        if isinstance(expr, ast.Call):
            name = dotted_name(expr.func)
            if name == "len" or ".shape" in name:
                return "host"
            if _is_device_call(name):
                return "device"
            return "unknown"
        if isinstance(expr, ast.BinOp):
            sides = {
                self._classify(ctx, fn, expr.left, depth + 1),
                self._classify(ctx, fn, expr.right, depth + 1),
            }
            if "device" in sides:
                return "device"
            if sides == {"host"}:
                return "host"
            return "unknown"
        if isinstance(expr, ast.Name):
            verdicts = {
                self._classify(ctx, fn, v, depth + 1)
                for v in ctx.assignments(fn, expr.id)
            }
            if "device" in verdicts:
                return "device"
            if verdicts == {"host"}:
                return "host"
            return "unknown"
        return "unknown"

    # -- fault_point containment --------------------------------------------

    @staticmethod
    def _under_fault_point(ctx: FileContext, fn: ast.AST) -> bool:
        """True when ``fn`` or any lexically enclosing function makes a
        direct ``fault_point(..)`` call in its own scope."""
        node: Optional[ast.AST] = fn
        while node is not None:
            for call in ctx.calls_in(node):
                if dotted_name(call.func).split(".")[-1] == "fault_point":
                    return True
            node = ctx.enclosing_function(node)
        return False

"""host-sync: device→host syncs sit behind a ``fault_point``.

A ``int(device_scalar)`` / ``.item()`` / ``jax.device_get`` blocks the
host on the device stream: it is exactly where an OOM/DeviceLost surfaces,
where the query deadline must be checked, and where the trace spans stamp
their sync points. The engine's contract (PR 2/4) is that every such sync
happens inside a function that passes through a named ``fault_point`` —
that is what makes the fault-injection matrix exhaustive and the ladder's
retry windows deterministic. A sync outside a fault-pointed function is
invisible to injection, to the deadline, and to obs.

Detection is SEMANTIC, not textual: the project-wide device-taint lattice
(``analysis/dataflow.py``) decides whether a value is device-array-valued,
chasing assignments, returns, and call sites across modules. ``int(x)``
flags when ``x`` came from ``helper(rows)`` and ``helper`` returns
``jnp.cumsum(...)`` two files away — or when ``helper`` is a passthrough
and the ARGUMENT was device-valued; ``int(x.shape[0])``, host arithmetic,
and metadata never flag. Containment stays lexical on purpose: the
contract is that the sync site's OWN function (or a lexical encloser)
passes through ``fault_point`` — a fault-pointed caller three frames up
does not make the sync observable at the right site name.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from ..core import FileContext, Finding, Rule, dotted_name
from ..project import ProjectContext
from ..dataflow import DEVICE, HOST

SCOPE_DIRS = ("backend/tpu/", "parallel/")

_SYNC_BUILTINS = ("int", "float", "bool")


class HostSyncRule(Rule):
    id = "host-sync"
    title = "device syncs happen at fault_point-wrapped sites"
    rationale = (
        "a sync outside a fault-pointed function is invisible to fault "
        "injection, the query deadline, and the span sync-point telemetry"
    )

    def check(
        self, ctx: FileContext, project: ProjectContext
    ) -> Iterator[Finding]:
        if not any(d in ctx.relpath for d in SCOPE_DIRS):
            return
        taint = project.device_taint
        for call in ctx.calls:
            fn = ctx.enclosing_function(call)
            if fn is None:
                continue  # module scope: import-time constants, not syncs
            sync = self._sync_kind(taint, ctx, fn, call)
            if sync is None:
                continue
            if self._under_fault_point(ctx, fn):
                continue
            yield ctx.finding(
                self.id,
                call,
                f"{sync} forces a device->host sync in a function with no "
                "fault_point — wrap the sync site (or suppress with the "
                "reason it cannot fault)",
            )

    # -- sync detection -----------------------------------------------------

    @staticmethod
    def _sync_kind(
        taint, ctx: FileContext, fn: ast.AST, call: ast.Call
    ) -> Optional[str]:
        name = dotted_name(call.func)
        if name in ("jax.device_get", "device_get"):
            return "jax.device_get"
        if (
            isinstance(call.func, ast.Attribute)
            and call.func.attr == "item"
            and not call.args
        ):
            if taint.classify(ctx, fn, call.func.value) != HOST:
                return ".item()"
            return None
        if name in _SYNC_BUILTINS and len(call.args) == 1:
            if taint.classify(ctx, fn, call.args[0]) == DEVICE:
                return f"{name}(<device value>)"
            return None
        if name in ("np.asarray", "numpy.asarray") and call.args:
            if taint.classify(ctx, fn, call.args[0]) == DEVICE:
                return "np.asarray(<device value>)"
        return None

    # -- fault_point containment --------------------------------------------

    @staticmethod
    def _under_fault_point(ctx: FileContext, fn: ast.AST) -> bool:
        """True when ``fn`` or any lexically enclosing function makes a
        direct ``fault_point(..)`` call in its own scope."""
        node: Optional[ast.AST] = fn
        while node is not None:
            for call in ctx.calls_in(node):
                if dotted_name(call.func).split(".")[-1] == "fault_point":
                    return True
            node = ctx.enclosing_function(node)
        return False

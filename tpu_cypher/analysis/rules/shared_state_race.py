"""shared-state-race: annotated shared objects mutate only under their owner.

The serving tier shares a handful of objects between the asyncio event
loop and the ``SessionPool`` worker lanes: the admission scheduler's
queues, the batcher's open windows, the metrics registry every lane
writes through. Each such class declares its OWNER with a comment
annotation on (or directly above) the ``class`` line:

    # shared-by: loop
    class AdmissionScheduler: ...          # only the event loop mutates

    class MetricsRegistry:  # shared-by: lanes
        ...                                # lanes mutate, under the lock

The rule derives the check from the annotation:

* ``loop`` — the object is loop-owned (the scheduler/batcher design:
  "everything here runs on the event loop, no locks"). Mutating methods
  must be ``async def`` (they can only run on the loop) or sync methods
  that are NOT reachable from any worker lane in the call graph. A
  lane-reachable sync method mutating loop-owned state is the race.
* ``lanes`` — the object is mutated from worker threads; every mutation
  of ``self.<attr>`` outside ``__init__`` must sit lexically inside a
  ``with <...lock...>:`` block (an attribute chain containing "lock" —
  ``self._lock``, ``self._reg._lock`` both qualify).

"Mutation" is an assignment/augmented assignment to ``self.<attr>`` or
``self.<attr>[..]``, or a mutator-method call on it (``append``, ``pop``,
``update``, ...). ``__init__``/``__post_init__`` are exempt — construction
happens-before sharing. Unannotated classes are not checked: the
annotation is the opt-in contract, and ``docs/serving.md`` lists which
serving classes carry it.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, List, Optional, Set, Tuple

from ..core import FileContext, Finding, Rule, dotted_name
from ..project import ProjectContext

_ANNOT_RE = re.compile(r"#\s*shared-by:\s*(?P<owner>\S+)")
_OWNERS = ("lanes", "loop")
_MUTATORS = (
    "append", "add", "update", "pop", "remove", "clear", "extend",
    "setdefault", "popitem", "insert", "discard", "appendleft",
)
_EXEMPT_METHODS = ("__init__", "__post_init__")


def _annotation(ctx: FileContext, cls: ast.ClassDef) -> Optional[Tuple[str, int]]:
    """(owner, comment line) from the class line or the line above."""
    for ln in (cls.lineno, cls.lineno - 1):
        m = _ANNOT_RE.search(ctx.line_text(ln))
        if m:
            return m.group("owner"), ln
    return None


def _self_attr_target(node: ast.expr) -> Optional[str]:
    """``self.X`` / ``self.X[..]`` as a mutation target -> ``X``."""
    if isinstance(node, ast.Subscript):
        node = node.value
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _under_lock(ctx: FileContext, node: ast.AST, fn: ast.AST) -> bool:
    cur = ctx.parent.get(node)
    while cur is not None and cur is not fn:
        if isinstance(cur, ast.With):
            for item in cur.items:
                if "lock" in dotted_name(item.context_expr).lower():
                    return True
        cur = ctx.parent.get(cur)
    return False


class SharedStateRaceRule(Rule):
    id = "shared-state-race"
    title = "shared serving objects mutate only under their declared owner"
    rationale = (
        "the scheduler/batcher run lock-free BECAUSE only the loop touches "
        "them, and the metrics registry survives lanes BECAUSE of its "
        "lock — an ownership violation is a silent data race"
    )

    def check(
        self, ctx: FileContext, project: ProjectContext
    ) -> Iterator[Finding]:
        classes = [
            n for n in ast.walk(ctx.tree) if isinstance(n, ast.ClassDef)
        ]
        if not classes:
            return
        graph = None
        lane: Set[ast.AST] = set()
        for cls in classes:
            annot = _annotation(ctx, cls)
            if annot is None:
                continue
            owner, _ln = annot
            if owner not in _OWNERS:
                yield ctx.finding(
                    self.id,
                    cls,
                    f"unknown ownership '{owner}' on class '{cls.name}' — "
                    "the annotation must be '# shared-by: lanes' or "
                    "'# shared-by: loop'",
                )
                continue
            if graph is None:
                graph = project.callgraph
                lane = graph.lane_reachable()
            for meth in cls.body:
                if not isinstance(
                    meth, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    continue
                if meth.name in _EXEMPT_METHODS:
                    continue
                for mut_node, attr in self._mutations(ctx, meth):
                    if owner == "lanes":
                        if not _under_lock(ctx, mut_node, meth):
                            yield ctx.finding(
                                self.id,
                                mut_node,
                                f"'{cls.name}' is shared-by: lanes but "
                                f"'{meth.name}' mutates self.{attr} outside "
                                "a 'with <lock>:' block — lane-shared state "
                                "mutates only under the owning lock",
                            )
                    else:  # loop
                        if isinstance(meth, ast.AsyncFunctionDef):
                            continue  # coroutines only ever run on the loop
                        if meth in lane:
                            yield ctx.finding(
                                self.id,
                                mut_node,
                                f"'{cls.name}' is shared-by: loop but sync "
                                f"method '{meth.name}' (reachable from a "
                                f"worker lane) mutates self.{attr} — "
                                "loop-owned state mutates only on the "
                                "event loop",
                            )
                            break  # one finding per lane-reachable method

    @staticmethod
    def _mutations(
        ctx: FileContext, meth: ast.AST
    ) -> Iterator[Tuple[ast.AST, str]]:
        """(node, attr) for every self-attribute mutation lexically in
        ``meth`` (excluding nested defs — their execution context is their
        own problem)."""
        for node in ast.walk(meth):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if node is not meth:
                    continue
            if ctx.enclosing_function(node) is not meth:
                continue
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    attr = _self_attr_target(t)
                    if attr is not None:
                        yield node, attr
            elif isinstance(node, ast.AugAssign):
                attr = _self_attr_target(node.target)
                if attr is not None:
                    yield node, attr
            elif isinstance(node, ast.Call):
                name = dotted_name(node.func)
                parts = name.split(".")
                if (
                    len(parts) >= 3
                    and parts[0] == "self"
                    and parts[-1] in _MUTATORS
                ):
                    yield node, parts[1]

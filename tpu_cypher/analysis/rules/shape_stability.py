"""shape-stability: no DATA_DEPENDENT shape may reach a compile boundary.

The semantic upgrade of the lexical ``pad-invariant``/``recompile-hazard``
pair: the abstract shape interpreter (``analysis.shapes``) classifies
every size expression, and this rule fires where a provably
data-dependent extent reaches a point that bakes it into an XLA program —
a sized-materialize kwarg (``size=``, ``total_repeat_length=``,
``num_segments=``), an unsized value-dependent materialize inside a
jitted function (a guaranteed trace error or silent full-length fallback),
or an array whose leading dim is data-dependent flowing into a
``pl.pallas_call`` / ``dispatch.launch`` boundary. Each such site means
one fresh compile per distinct runtime count: the recompile storm the
bucket lattice exists to prevent.

Lines carrying an existing ``allow[pad-invariant]`` suppression are
declared exact-size sites (the compact primitive itself, the ladder's
bucket-exact rung); the semantic rules honor those declarations rather
than re-litigating them.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..core import FileContext, Finding, Rule, dotted_name
from .. import shapes as S

_UNSIZED_VALUE_DEP = ("nonzero", "unique")
_BOUNDARY_LEAVES = ("pallas_call",)
_BOUNDARY_DOTTED = ("dispatch.launch",)


def _declared_exact(ctx: FileContext, line: int) -> bool:
    return ctx.allowed(line, "pad-invariant") is not None


class ShapeStabilityRule(Rule):
    id = "shape-stability"
    title = "data-dependent shape reaches a compile boundary"
    rationale = (
        "An extent the interpreter proves data-dependent (a synced "
        "reduction, an unsized nonzero) that reaches a jit boundary, a "
        "sized-materialize kwarg, or a pallas_call compiles one program "
        "per distinct runtime value. Route it through bucketing.round_size "
        "so the compile cache stays warm."
    )

    def check(self, ctx: FileContext, project) -> Iterator[Finding]:
        if not S.in_scope(ctx.relpath):
            return
        ana = project.shapes
        graph = project.callgraph
        for call in ctx.calls:
            line = getattr(call, "lineno", 0)
            if _declared_exact(ctx, line):
                continue
            fn = ctx.enclosing_function(call)
            name = dotted_name(call.func)
            leaf = name.split(".")[-1] if name else ""
            device = name.startswith(S._DEVICE_PREFIXES)

            # (a) a sized-materialize kwarg fed a data-dependent count
            if device:
                for kw in call.keywords:
                    if kw.arg not in S.SIZE_KWARGS:
                        continue
                    v = ana.classify_size(ctx, fn, kw.value)
                    if v.kind == S.DATA_KIND:
                        yield ctx.finding(
                            self.id,
                            kw.value,
                            f"{name}({kw.arg}=...) receives a "
                            f"data-dependent count ({v.render()}): one "
                            f"compile per distinct value. Round it via "
                            f"bucketing.round_size first.",
                        )

                # (b) an unsized value-dependent materialize under jit
                if (
                    leaf in _UNSIZED_VALUE_DEP
                    and not any(kw.arg in S.SIZE_KWARGS for kw in call.keywords)
                    and fn is not None
                    and ctx.is_jitted(fn)
                ):
                    yield ctx.finding(
                        self.id,
                        call,
                        f"unsized {name} inside a jitted function: the "
                        f"result extent is data-dependent, which cannot "
                        f"trace. Pass size= (bucketed) or hoist out of jit.",
                    )

            # (c) a data-dependent array shape crossing a kernel boundary
            if leaf in _BOUNDARY_LEAVES or any(
                name.endswith(d) for d in _BOUNDARY_DOTTED
            ):
                for arg in call.args:
                    v = ana.classify_array(ctx, fn, arg)
                    if v.kind == S.DATA_KIND:
                        yield ctx.finding(
                            self.id,
                            arg,
                            f"array with data-dependent leading dim "
                            f"({v.render()}) crosses the {name} boundary: "
                            f"every distinct extent compiles a fresh "
                            f"kernel. Pad to the bucket lattice first.",
                        )
                continue

            # (c') a data-dependent array traced into a project jit boundary
            targets = graph.resolve_call(ctx, call)
            jitted = [t for t in targets if t.ctx.is_jitted(t.node)]
            if not jitted:
                continue
            for tgt in jitted:
                statics = S.jit_static_argnames(tgt.node)
                names = tgt.ctx.param_names(tgt.node)
                if names and names[0] == "self":
                    names = names[1:]
                for i, arg in enumerate(call.args):
                    pname = names[i] if i < len(names) else ""
                    if pname in statics:
                        continue  # static args are bucket-cardinality's beat
                    v = ana.classify_array(ctx, fn, arg)
                    if v.kind == S.DATA_KIND:
                        yield ctx.finding(
                            self.id,
                            arg,
                            f"array with data-dependent leading dim "
                            f"({v.render()}) traced into jitted "
                            f"{tgt.qualname}(): one compile per distinct "
                            f"extent. Pad to the bucket lattice before "
                            f"the boundary.",
                        )
                break  # one target's param view is enough per call

"""exception-hygiene: a broad except may not silently swallow a device
fault.

Generalizes the backend/tpu walker that used to live in
``tests/test_fault_ladder.py`` to the WHOLE engine: any bare ``except`` /
``except Exception`` / ``except BaseException`` must either re-raise (a
typed ``tpu_cypher.errors`` class or the original), route device faults on
through ``errors.reraise_if_device``, or carry an explicit ``fault-ok``
annotation on the except line stating why the handler is host-side-only.
Without one of the three, a real DeviceLost/OOM can be eaten by a
convenience fallback and the degrade-and-retry ladder never sees it.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..core import FileContext, Finding, Rule, dotted_name
from ..project import ProjectContext

_RERAISE_NAMES = ("reraise_if_device", "_reraise_if_device")


class ExceptionHygieneRule(Rule):
    id = "exception-hygiene"
    title = "broad excepts re-raise device faults or are marked fault-ok"
    rationale = (
        "a broad handler that neither re-raises nor routes through "
        "errors.reraise_if_device can swallow DeviceLost/OOM and starve "
        "the retry ladder"
    )

    def check(
        self, ctx: FileContext, project: ProjectContext
    ) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            broad = node.type is None or (
                isinstance(node.type, ast.Name)
                and node.type.id in ("Exception", "BaseException")
            )
            if not broad:
                continue
            reraises = any(
                isinstance(n, ast.Raise) for n in ast.walk(node)
            ) or any(
                isinstance(n, ast.Call)
                and dotted_name(n.func).split(".")[-1] in _RERAISE_NAMES
                for n in ast.walk(node)
            )
            annotated = "fault-ok" in ctx.line_text(node.lineno)
            if not (reraises or annotated):
                yield ctx.finding(
                    self.id,
                    node,
                    "broad except neither re-raises, routes through "
                    "errors.reraise_if_device, nor carries a '# fault-ok: "
                    "<why host-side-only>' annotation",
                )

"""bucket-cardinality: every jitted call site needs a static bound on
its distinct bucket signatures.

Each distinct value of a ``static_argnames`` parameter at a jitted call
site is one entry in the compile cache. The abstract shape interpreter
gives every size expression a cardinality bound: STATIC takes exactly one
value, BUCKETED takes at most the lattice's rung count
(``shapes.BUCKET_BOUNDS``), DATA_DEPENDENT is unbounded — an unrounded
count threaded into a static argument grows the compile cache without
limit, which is this rule's finding. UNKNOWN makes no claim and never
fires.

The per-site bounds (including the bounded ones) are exported through
``--facts-out`` as the compile-cache-growth facts the cost model
consumes. Lines carrying an ``allow[pad-invariant]`` suppression are
declared exact-size sites and stay out of scope here too.
"""

from __future__ import annotations

from typing import Iterator

from ..core import FileContext, Finding, Rule, dotted_name
from .. import shapes as S


class BucketCardinalityRule(Rule):
    id = "bucket-cardinality"
    title = "unbounded bucket signatures at a jitted call site"
    rationale = (
        "A static_argnames parameter keys the compile cache: a "
        "data-dependent (unrounded) value there admits unboundedly many "
        "signatures — compile-cache growth proportional to distinct "
        "runtime counts. Round through the bucket lattice to cap it at "
        "the lattice's rung count."
    )

    def check(self, ctx: FileContext, project) -> Iterator[Finding]:
        if not S.in_scope(ctx.relpath):
            return
        ana = project.shapes
        graph = project.callgraph
        for call in ctx.calls:
            line = getattr(call, "lineno", 0)
            if ctx.allowed(line, "pad-invariant") is not None:
                continue  # declared exact-size site
            fn = ctx.enclosing_function(call)
            for tgt in graph.resolve_call(ctx, call):
                if not tgt.ctx.is_jitted(tgt.node):
                    continue
                statics = S.jit_static_argnames(tgt.node)
                if not statics:
                    continue
                names = tgt.ctx.param_names(tgt.node)
                if names and names[0] == "self":
                    names = names[1:]
                bound_exprs = []
                for i, arg in enumerate(call.args):
                    if i < len(names) and names[i] in statics:
                        bound_exprs.append((names[i], arg))
                for kw in call.keywords:
                    if kw.arg in statics:
                        bound_exprs.append((kw.arg, kw.value))
                for pname, expr in bound_exprs:
                    v = ana.classify_size(ctx, fn, expr)
                    if v.kind == S.DATA_KIND:
                        yield ctx.finding(
                            self.id,
                            expr,
                            f"static arg {pname}= of jitted "
                            f"{tgt.qualname}() is data-dependent "
                            f"({v.render()}): unbounded bucket signatures "
                            f"at this call site. Round via "
                            f"bucketing.round_size to bound the compile "
                            f"cache.",
                        )
                break  # one jitted target's signature view per call

"""pad-invariant: size-static materializes round through the bucket
lattice.

Every data-dependent output size in the TPU backend is baked STATIC into
its jitted materialize program (``jnp.nonzero(size=..)``,
``total_repeat_length=..`` — docs/pad-invariants.md). A size that reaches
one of those without passing ``bucketing.round_size`` (or the pow2 /
multiple helpers) compiles one XLA program PER DISTINCT COUNT: correct
output, quadratic compile bill, invisible until a BENCH delta. The
sanctioned shapes are exactly two — the size is a (static) parameter of a
jitted ``*_counted``-style primitive, or the size expression routes
through a ``bucketing`` rounding helper before being passed down.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from ..core import FileContext, Finding, Rule, dotted_name
from ..project import ProjectContext

SCOPE_DIRS = ("backend/tpu/", "parallel/")
_SIZE_KWARGS = ("size", "total_repeat_length")
_ROUNDERS = (
    "round_size",
    "round_up_pow2",
    "round_up_multiple",
    "bucket_pad_host",
)
_BUCKETING_SUFFIX = "backend/tpu/bucketing.py"


def _mentions_rounder(expr: ast.AST) -> bool:
    for n in ast.walk(expr):
        if isinstance(n, ast.Call) and dotted_name(n.func).split(".")[
            -1
        ] in _ROUNDERS:
            return True
    return False


class PadInvariantRule(Rule):
    id = "pad-invariant"
    title = "size-static materializes route through bucketing.round_size"
    rationale = (
        "an unrounded data-dependent size compiles one XLA program per "
        "distinct count — the recompile storm bucketing exists to kill"
    )

    def check(
        self, ctx: FileContext, project: ProjectContext
    ) -> Iterator[Finding]:
        if not any(d in ctx.relpath for d in SCOPE_DIRS):
            return
        if ctx.relpath.endswith(_BUCKETING_SUFFIX):
            return  # the lattice itself
        for call in ctx.calls:
            name = dotted_name(call.func)
            size_kw = next(
                (kw for kw in call.keywords if kw.arg in _SIZE_KWARGS), None
            )
            if size_kw is None:
                # the classic trap: an UNSIZED jnp.nonzero is value-
                # dependent — it can't live under jit and host-syncs outside
                if name == "jnp.nonzero":
                    yield ctx.finding(
                        self.id,
                        call,
                        "unsized jnp.nonzero — value-dependent output "
                        "shape; use the sized form with a bucketed size "
                        "(jit_ops.mask_nonzero / *_counted variants)",
                    )
                continue
            fn = ctx.enclosing_function(call)
            if self._size_sanctioned(ctx, fn, size_kw.value, 0):
                continue
            yield ctx.finding(
                self.id,
                call,
                f"{name or 'call'}({size_kw.arg}=..) with a size that "
                "neither routes through bucketing.round_size/round_up_* "
                "nor is a static parameter of the enclosing primitive — "
                "every data-dependent materialize size must round the "
                "bucket lattice (docs/pad-invariants.md)",
            )

    def _size_sanctioned(
        self,
        ctx: FileContext,
        fn: Optional[ast.AST],
        expr: ast.AST,
        depth: int,
    ) -> bool:
        if depth > 4:
            return False
        if _mentions_rounder(expr):
            return True
        if isinstance(expr, ast.Constant):
            return True  # a literal size is one fixed program
        if isinstance(expr, ast.Attribute) or (
            isinstance(expr, ast.Subscript)
            and isinstance(expr.value, ast.Attribute)
        ):
            # shape-derived sizes (x.shape[0], self._cap) are already
            # padded/static by the time they are attributes
            return True
        if isinstance(expr, ast.Call):
            name = dotted_name(expr.func)
            if name in ("len", "min", "max", "int"):
                return all(
                    self._size_sanctioned(ctx, fn, a, depth + 1)
                    for a in expr.args
                )
            return False
        if isinstance(expr, ast.BinOp):
            return self._size_sanctioned(
                ctx, fn, expr.left, depth + 1
            ) and self._size_sanctioned(ctx, fn, expr.right, depth + 1)
        if isinstance(expr, ast.Name):
            if fn is not None and expr.id in ctx.param_names(fn):
                # a parameter: the caller computed (and rounded) the size —
                # this is the jitted *_counted primitive shape
                return True
            assigns = ctx.assignments(fn, expr.id)
            return bool(assigns) and any(
                self._size_sanctioned(ctx, fn, v, depth + 1)
                for v in assigns
            )
        return False

"""contextvar-discipline: every ``ContextVar.set`` balances its token.

The engine's per-query state — guard deadline, ladder rung, trace span,
metric scopes, scoped fault schedules — is all ``contextvars``. The
serving tier multiplexes 100 clients onto one process by running each
query in a FRESH ``contextvars.Context`` (``SessionPool._isolated``), so
a ``set`` inside a lane dies with the query. Everywhere else, an
unbalanced ``set`` leaks state into the next query sharing that context:
the classic "deadline from request A kills request B" bug.

The rule identifies ContextVars by RESOLUTION, not by name: a module-level
``X = ContextVar(..)`` / ``X: ContextVar[..] = ContextVar(..)`` binding
(local or imported) is a ContextVar; ``bucketing.MODE.set(..)`` — a
``ConfigOption`` with its own override stack — never matches. On every
resolved ``X.set(..)``:

* module scope: flagged outright (an import-time ``set`` poisons every
  context that ever forks from the main thread's).
* the returned token must be kept: a bare ``X.set(..)`` expression
  statement discards the only handle that can restore the previous value.
* a token kept in a local must be ``X.reset(tok)`` inside a ``finally``
  in the same function (the only construct that runs on ALL exit paths).
* a token kept on ``self`` (the ``__enter__``/``__exit__`` idiom every
  engine scope uses: ``guard.activate``, ``guard.request_deadline``,
  ``faults.scoped_spec``, ``obs.trace.activate``/``span``,
  ``obs.metrics`` scopes) needs SOME method of the same class calling
  ``X.reset(self.<attr>)``.
* functions that only ever run on a pool lane (``lane_reachable`` in the
  call graph) are exempt — their context is born and dies with the query.

Declarations are checked too: a mutable default (``default=[]``) is
shared across every context that never ``set`` — mutation through one
context is visible to all of them.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ..callgraph import module_path
from ..core import FileContext, Finding, Rule, dotted_name
from ..project import ProjectContext

_MUTABLE_CALLS = ("list", "dict", "set", "defaultdict", "deque")


def _is_contextvar_decl(expr: ast.expr) -> bool:
    if not isinstance(expr, ast.Call):
        return False
    name = dotted_name(expr.func)
    return name in ("ContextVar", "contextvars.ContextVar")


def _mutable_default(expr: ast.Call) -> Optional[ast.expr]:
    for kw in expr.keywords:
        if kw.arg != "default":
            continue
        v = kw.value
        if isinstance(v, (ast.List, ast.Dict, ast.Set)):
            return v
        if (
            isinstance(v, ast.Call)
            and dotted_name(v.func).split(".")[-1] in _MUTABLE_CALLS
        ):
            return v
    return None


class ContextvarDisciplineRule(Rule):
    id = "contextvar-discipline"
    title = "ContextVar.set keeps and resets its token on all exit paths"
    rationale = (
        "an unbalanced set leaks one query's deadline/rung/trace into the "
        "next query sharing the context; a mutable default is shared "
        "across every context"
    )

    def check(
        self, ctx: FileContext, project: ProjectContext
    ) -> Iterator[Finding]:
        graph = project.callgraph
        mod = graph.modules.get(module_path(ctx.relpath))
        if mod is None:
            return
        cvars = _project_contextvars(project)
        local = cvars.get(mod.path, {})

        # declaration hygiene: no mutable defaults
        for name, decl in local.items():
            bad = _mutable_default(decl)
            if bad is not None:
                yield ctx.finding(
                    self.id,
                    bad,
                    f"ContextVar '{name}' has a MUTABLE default — the "
                    "default object is shared by every context that never "
                    "set(); use an immutable sentinel and copy on write",
                )

        lane = graph.lane_reachable()
        for call in ctx.calls:
            target = self._resolved_set(graph, mod, cvars, call)
            if target is None:
                continue
            var_name = target
            fn = ctx.enclosing_function(call)
            if fn is None:
                yield ctx.finding(
                    self.id,
                    call,
                    f"module-scope {var_name}.set() poisons every context "
                    "forked after import — set per-query state inside a "
                    "scope object (enter/exit) instead",
                )
                continue
            if fn in lane:
                continue  # fresh-Context lane: state dies with the query
            parent = ctx.parent.get(call)
            if isinstance(parent, ast.Expr):
                yield ctx.finding(
                    self.id,
                    call,
                    f"{var_name}.set() discards its token — keep it and "
                    "reset() on all exit paths, or the previous value is "
                    "unrestorable",
                )
                continue
            if isinstance(parent, ast.Assign) and len(parent.targets) == 1:
                t = parent.targets[0]
                if isinstance(t, ast.Name):
                    if not self._reset_in_finally(ctx, fn, var_name, t.id):
                        yield ctx.finding(
                            self.id,
                            call,
                            f"token of {var_name}.set() is not reset in a "
                            f"finally block of this function — "
                            f"'{var_name}.reset({t.id})' must run on ALL "
                            "exit paths",
                        )
                    continue
                if (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    if not self._reset_in_class(
                        graph, ctx, fn, var_name, t.attr
                    ):
                        yield ctx.finding(
                            self.id,
                            call,
                            f"token of {var_name}.set() is stored on "
                            f"self.{t.attr} but no method of this class "
                            f"calls {var_name}.reset(self.{t.attr}) — the "
                            "scope has no exit path",
                        )
                    continue
            yield ctx.finding(
                self.id,
                call,
                f"token of {var_name}.set() is not kept in a resettable "
                "binding (local or self attribute) — the previous value "
                "is unrestorable",
            )

    # -- resolution ----------------------------------------------------------

    @staticmethod
    def _resolved_set(graph, mod, cvars, call: ast.Call) -> Optional[str]:
        """The spelled receiver name when ``call`` is ``X.set(..)`` on a
        resolved ContextVar, else None."""
        name = dotted_name(call.func)
        if not name.endswith(".set") or name.count(".") > 2:
            return None
        recv = name[: -len(".set")]
        parts = recv.split(".")
        if len(parts) == 1:
            if parts[0] in cvars.get(mod.path, {}):
                return recv
            imp = mod.imports.get(parts[0])
            if imp is not None and imp[1] is not None:
                target = graph._find_module(imp[0])  # noqa: SLF001
                if target is not None and imp[1] in cvars.get(
                    target.path, {}
                ):
                    return recv
            return None
        # mod_alias.X.set(..): the head must be an imported module
        imp = mod.imports.get(parts[0])
        if imp is None:
            return None
        target_path = imp[0] if imp[1] is None else f"{imp[0]}.{imp[1]}"
        target = graph._find_module(target_path)  # noqa: SLF001
        if target is not None and parts[1] in cvars.get(target.path, {}):
            return recv
        return None

    # -- token discipline -----------------------------------------------------

    @staticmethod
    def _reset_in_finally(
        ctx: FileContext, fn: ast.AST, var_name: str, token: str
    ) -> bool:
        leaf = var_name.split(".")[-1]
        for node in ast.walk(fn):
            if not isinstance(node, ast.Try) or not node.finalbody:
                continue
            for stmt in node.finalbody:
                for sub in ast.walk(stmt):
                    if not isinstance(sub, ast.Call):
                        continue
                    name = dotted_name(sub.func)
                    if not name.endswith(".reset"):
                        continue
                    if name[: -len(".reset")].split(".")[-1] != leaf:
                        continue
                    if any(
                        isinstance(a, ast.Name) and a.id == token
                        for a in sub.args
                    ):
                        return True
        return False

    @staticmethod
    def _reset_in_class(
        graph, ctx: FileContext, fn: ast.AST, var_name: str, attr: str
    ) -> bool:
        leaf = var_name.split(".")[-1]
        cls = _enclosing_classdef(ctx, fn)
        if cls is None:
            return False
        for sub in ast.walk(cls):
            if not isinstance(sub, ast.Call):
                continue
            name = dotted_name(sub.func)
            if not name.endswith(".reset"):
                continue
            if name[: -len(".reset")].split(".")[-1] != leaf:
                continue
            for a in sub.args:
                if (
                    isinstance(a, ast.Attribute)
                    and a.attr == attr
                    and isinstance(a.value, ast.Name)
                    and a.value.id == "self"
                ):
                    return True
        return False


# -- shared helpers ----------------------------------------------------------


def _enclosing_classdef(ctx: FileContext, fn: ast.AST) -> Optional[ast.AST]:
    node = ctx.parent.get(fn)
    while node is not None:
        if isinstance(node, ast.ClassDef):
            return node
        node = ctx.parent.get(node)
    return None


def _project_contextvars(
    project: ProjectContext,
) -> Dict[str, Dict[str, ast.Call]]:
    """module path -> {name: declaration Call} for every module-level
    ContextVar in the analyzed set. Cached on the project (one pass)."""
    cached = getattr(project, "_contextvar_index", None)
    if cached is not None:
        return cached
    out: Dict[str, Dict[str, ast.Call]] = {}
    for path, mod in project.callgraph.modules.items():
        found: Dict[str, ast.Call] = {}
        for name, exprs in mod.globals.items():
            for e in exprs:
                if _is_contextvar_decl(e):
                    found[name] = e
        if found:
            out[path] = found
    project._contextvar_index = out  # noqa: SLF001
    return out

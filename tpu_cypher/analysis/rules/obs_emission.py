"""obs-emission: telemetry flows through the unified registry and every
kernel launch goes through dispatch.

Three sub-checks, replacing the walkers that lived in ``tests/test_obs.py``
and ``tests/test_pallas_dispatch.py``:

* no module-global ``NAME = {"k": 0, ...}`` counter dicts outside ``obs/``
  — the shape all four pre-obs counters had; counters belong to the
  registry where scopes, export, and reset work;
* a raw ``pl.pallas_call`` may only appear inside a function registered as
  a kernel impl via ``dispatch.register(name, site, impls=(..))`` and only
  under ``backend/tpu/pallas/`` — no kernel may bypass eligibility,
  broken-once fallback, fault sites, or use counters;
* the two chokepoint files keep their emission contracts:
  ``runtime/faults.py``'s ``fault_point`` counts through a registry
  counter, and ``pallas/dispatch.py``'s ``_count`` feeds the launch
  counter while ``launch`` opens a kernel span.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from ..core import FileContext, Finding, Rule, dotted_name
from ..project import ProjectContext

_PALLAS_DIR = "backend/tpu/pallas/"
_FAULTS_SUFFIX = "runtime/faults.py"
_DISPATCH_SUFFIX = "backend/tpu/pallas/dispatch.py"


def _assigned_from_counter(ctx: FileContext, var: str) -> bool:
    for node in ast.walk(ctx.tree):
        if (
            isinstance(node, ast.Assign)
            and any(
                isinstance(t, ast.Name) and t.id == var
                for t in node.targets
            )
            and isinstance(node.value, ast.Call)
            and isinstance(node.value.func, ast.Attribute)
            and node.value.func.attr == "counter"
        ):
            return True
    return False


def _func(ctx: FileContext, name: str) -> Optional[ast.AST]:
    for fn in ctx.functions:
        if fn.name == name:
            return fn
    return None


def _calls_inc_on(ctx: FileContext, fn: ast.AST, var: str) -> bool:
    for call in ctx.calls_under(fn):
        f = call.func
        if (
            isinstance(f, ast.Attribute)
            and f.attr == "inc"
            and isinstance(f.value, ast.Name)
            and f.value.id == var
        ):
            return True
    return False


class ObsEmissionRule(Rule):
    id = "obs-emission"
    title = "counters live in the obs registry; kernels launch via dispatch"
    rationale = (
        "module-global counter dicts escape scopes/export/reset; a raw "
        "pallas_call outside a registered impl bypasses eligibility, "
        "fallback, fault sites, and use counters"
    )

    def check(
        self, ctx: FileContext, project: ProjectContext
    ) -> Iterator[Finding]:
        yield from self._check_counter_dicts(ctx)
        yield from self._check_pallas_calls(ctx, project)
        if ctx.relpath.endswith(_FAULTS_SUFFIX):
            yield from self._check_faults_chokepoint(ctx)
        if ctx.relpath.endswith(_DISPATCH_SUFFIX):
            yield from self._check_dispatch_chokepoint(ctx)

    def _check_counter_dicts(self, ctx: FileContext) -> Iterator[Finding]:
        if "obs/" in ctx.relpath:
            return  # the registry itself
        for node in ctx.tree.body:  # module level only
            if not (
                isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Dict)
            ):
                continue
            vals = node.value.values
            if vals and all(
                isinstance(v, ast.Constant) and v.value == 0 for v in vals
            ):
                names = ", ".join(
                    t.id
                    for t in node.targets
                    if isinstance(t, ast.Name)
                )
                yield ctx.finding(
                    self.id,
                    node,
                    f"module-global counter dict {names or '<target>'} — "
                    "counters belong to the obs registry "
                    "(REGISTRY.counter(..)), not module state",
                )

    def _check_pallas_calls(
        self, ctx: FileContext, project: ProjectContext
    ) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not (
                isinstance(node, ast.Attribute)
                and node.attr == "pallas_call"
            ):
                continue
            fn = ctx.enclosing_function(node)
            fn_name = fn.name if fn is not None else "<module>"
            if (
                _PALLAS_DIR not in ctx.relpath
                or fn_name not in project.dispatch_impls
            ):
                yield ctx.finding(
                    self.id,
                    node,
                    f"pl.pallas_call in {fn_name}() outside a dispatch-"
                    "registered impl — every kernel must launch through "
                    "backend.tpu.pallas.dispatch.launch",
                )

    def _check_faults_chokepoint(self, ctx: FileContext) -> Iterator[Finding]:
        if not _assigned_from_counter(ctx, "FAULT_SITE_HITS"):
            yield Finding(
                self.id,
                ctx.relpath,
                1,
                0,
                "FAULT_SITE_HITS is not a registry counter — fault-site "
                "telemetry must be served by the unified obs registry",
            )
        fp = _func(ctx, "fault_point")
        if fp is None or not _calls_inc_on(ctx, fp, "FAULT_SITE_HITS"):
            yield Finding(
                self.id,
                ctx.relpath,
                fp.lineno if fp is not None else 1,
                0,
                "fault_point must count every site invocation through the "
                "obs registry (FAULT_SITE_HITS.inc(..))",
            )

    def _check_dispatch_chokepoint(
        self, ctx: FileContext
    ) -> Iterator[Finding]:
        if not _assigned_from_counter(ctx, "PALLAS_LAUNCH"):
            yield Finding(
                self.id,
                ctx.relpath,
                1,
                0,
                "PALLAS_LAUNCH is not a registry counter — kernel-tier "
                "telemetry must be served by the unified obs registry",
            )
        cnt = _func(ctx, "_count")
        if cnt is None or not _calls_inc_on(ctx, cnt, "PALLAS_LAUNCH"):
            yield Finding(
                self.id,
                ctx.relpath,
                cnt.lineno if cnt is not None else 1,
                0,
                "dispatch._count must feed PALLAS_LAUNCH.inc(..) — every "
                "launch outcome is a registry series",
            )
        launch = _func(ctx, "launch")
        opens_span = launch is not None and any(
            isinstance(c.func, ast.Attribute) and c.func.attr == "span"
            for c in ctx.calls_under(launch)
        )
        if not opens_span:
            yield Finding(
                self.id,
                ctx.relpath,
                launch.lineno if launch is not None else 1,
                0,
                "dispatch.launch must open a kernel trace span "
                "(obs.trace.span) so kernel tiers appear in profiles",
            )

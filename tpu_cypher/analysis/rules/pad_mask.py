"""pad-mask-discipline: bucketed arrays must be masked before
pad-sensitive consumers.

The semantic version of docs/pad-invariants.md: once an extent rounds the
bucket lattice, lanes past the true count hold garbage, and any
*pad-sensitive* consumer — a reduction (pads pollute the total), a sort
(pads interleave with live keys unless forced last via the ID_SENTINEL
discipline), or a ``searchsorted`` over the padded table (pads shift
every rank) — must see the array only after a mask against the true
count. The interpreter tracks that proof as the ``masked`` bit on the
BUCKETED lattice point: a 3-arg ``jnp.where`` selection, a comparison
against an ``arange`` iota (the ``_live_lanes`` idiom), or multiplication
/ conjunction with an already-masked mask all establish it; ``jnp.pad``,
``cumsum``, gathers, and boolean negation forfeit it.

A ``where=`` (or ``initial=``) kwarg on the reduction itself is the
sanctioned in-place form. Lines carrying an ``allow[pad-invariant]``
suppression are declared exact-size sites — nothing there is padded.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..core import FileContext, Finding, Rule, dotted_name
from .. import shapes as S

_SCOPE = ("backend/tpu/", "parallel/")

# reductions whose result a single garbage lane corrupts
_REDUCERS = S._REDUCERS
_SORTS = S._SORTS


class PadMaskRule(Rule):
    id = "pad-mask-discipline"
    title = "bucketed array reaches a pad-sensitive op unmasked"
    rationale = (
        "Past the true count, a bucket-padded array holds garbage lanes. "
        "A reduction, sort, or searchsorted that consumes it without a "
        "mask against the true count (jnp.where against a liveness mask, "
        "an arange-vs-count comparison, or the where= kwarg) computes "
        "over that garbage."
    )

    def check(self, ctx: FileContext, project) -> Iterator[Finding]:
        if not any(d in ctx.relpath for d in _SCOPE):
            return
        if not S.in_scope(ctx.relpath):
            return
        ana = project.shapes
        for call in ctx.calls:
            line = getattr(call, "lineno", 0)
            if ctx.allowed(line, "pad-invariant") is not None:
                continue  # declared exact-size site
            name = dotted_name(call.func)
            if not name.startswith(S._DEVICE_PREFIXES):
                continue
            leaf = name.split(".")[-1]
            fn = ctx.enclosing_function(call)

            if leaf in _REDUCERS:
                if any(kw.arg in ("where", "initial") for kw in call.keywords):
                    continue  # sanctioned in-place mask
                if not call.args:
                    continue
                v = ana.classify_array(ctx, fn, call.args[0])
                if v.kind == S.BUCKETED_KIND and not v.masked:
                    yield ctx.finding(
                        self.id,
                        call,
                        f"{name} reduces a bucket-padded array "
                        f"({v.render()}) with no mask against its true "
                        f"count: pad lanes pollute the result. Mask via "
                        f"jnp.where(live, x, neutral) or pass where=.",
                    )
            elif leaf in _SORTS:
                ops = (
                    call.args[0].elts
                    if (
                        leaf == "lexsort"
                        and call.args
                        and isinstance(call.args[0], (ast.Tuple, ast.List))
                    )
                    else call.args[:1]
                )
                for op_expr in ops:
                    v = ana.classify_array(ctx, fn, op_expr)
                    if v.kind == S.BUCKETED_KIND and not v.masked:
                        yield ctx.finding(
                            self.id,
                            call,
                            f"{name} sorts a bucket-padded array "
                            f"({v.render()}) whose pad lanes are not "
                            f"forced last: garbage keys interleave with "
                            f"live rows. Apply the ID_SENTINEL discipline "
                            f"(where(live, keys, sentinel)) first.",
                        )
                        break
            elif leaf == "searchsorted" and call.args:
                v = ana.classify_array(ctx, fn, call.args[0])
                if v.kind == S.BUCKETED_KIND and not v.masked:
                    yield ctx.finding(
                        self.id,
                        call,
                        f"{name} searches a bucket-padded table "
                        f"({v.render()}) whose pad lanes were never "
                        f"masked to the sentinel: padded keys shift every "
                        f"rank. Mask pads to ID_SENTINEL before building "
                        f"the sorted table.",
                    )

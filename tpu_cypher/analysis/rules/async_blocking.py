"""async-blocking: nothing reachable from an ``async def`` body blocks.

The serving tier multiplexes every client onto ONE asyncio event loop
(``serve/``): a single ``time.sleep``, socket op, synchronous
``session.cypher``, or device sync (``jax.device_get``, ``int(<device
value>)``, ``.block_until_ready()``) executed on the loop stalls every
connected client for its full duration — the whole point of the
``SessionPool`` lane design is that blocking engine work happens on
worker threads.

The check is interprocedural: the blocking summaries
(``analysis/dataflow.py``) propagate "can block the calling thread"
bottom-up through the call graph, so an ``async def`` that calls a sync
helper that calls ``session.cypher`` three modules away flags AT THE
AWAITABLE'S CALL SITE, with the full chain in the message. The sanctioned
escape hatches stay silent by construction:

* ``await pool.run(lambda: self._execute(..))`` — a call inside a
  ``lambda`` is DEFERRED; the call graph marks the site ``in_lambda`` and
  neither the direct check nor the summaries attribute it to the
  enclosing coroutine (the lambda body executes on the worker lane).
* ``run_in_executor(ex, fn)`` / ``to_thread(fn)`` — ``fn`` is passed by
  reference, never called on the loop; no call edge exists.
* awaiting another ``async def`` — calling a coroutine function only
  builds the coroutine; its body is the loop scheduler's business and is
  checked on its own.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..core import FileContext, Finding, Rule
from ..project import ProjectContext


class AsyncBlockingRule(Rule):
    id = "async-blocking"
    title = "async def bodies never block the event loop"
    rationale = (
        "one blocking call on the loop stalls every connected client; "
        "blocking engine work belongs on the pool's worker lanes "
        "(run_in_executor / to_thread)"
    )

    def check(
        self, ctx: FileContext, project: ProjectContext
    ) -> Iterator[Finding]:
        graph = project.callgraph
        blocking = project.blocking
        for fn in ctx.functions:
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            info = graph.info_for(fn)
            if info is None:
                continue
            for site, targets in graph.callees(info):
                if site.in_lambda:
                    continue  # deferred body: executes on a worker lane
                reason = blocking.direct_reason(info, site.call)
                if reason is not None:
                    yield ctx.finding(
                        self.id,
                        site.call,
                        f"async '{fn.name}' blocks the event loop: {reason} "
                        "— move the blocking work to a worker lane "
                        "(run_in_executor / to_thread)",
                    )
                    continue
                for tgt in targets:
                    if tgt.is_async:
                        continue  # a coroutine call only builds the coroutine
                    sub = blocking.blocking_reason(tgt.node)
                    if sub is not None:
                        yield ctx.finding(
                            self.id,
                            site.call,
                            f"async '{fn.name}' blocks the event loop via "
                            f"{tgt.qualname}() -> {sub.render()} — move the "
                            "blocking work to a worker lane "
                            "(run_in_executor / to_thread)",
                        )
                        break

"""recompile-hazard: nothing on the query path may manufacture fresh XLA
programs per call.

PR 1's entire win — shape-bucketed, compile-once execution — dies quietly
if someone (a) builds a ``jax.jit`` wrapper INSIDE a function (every call
makes a new callable with its own cache), (b) reads ``os.environ`` or a
config option inside a jitted body (the value is baked into the trace;
changing it silently does nothing, and conditioning a Python branch on it
re-traces), or (c) declares a ``static_argnames`` parameter whose default
is an unhashable literal (first call with the default raises deep inside
jax). None of these break tests on day one; all of them show up as BENCH
compile-count regressions weeks later. Catch them at lint time.
"""

from __future__ import annotations

import ast
from typing import Iterator, List

from ..core import FileContext, Finding, Rule, dotted_name
from ..project import ProjectContext

_ENV_READS = ("os.environ.get", "os.getenv", "os.environ.setdefault")


def _jit_target(call: ast.Call) -> bool:
    """Is this Call expression ``jax.jit(..)`` or ``partial(jax.jit, ..)``?"""
    name = dotted_name(call.func)
    if name in ("jax.jit", "jit") or name.endswith(".jit"):
        return True
    if name.split(".")[-1] == "partial" and call.args:
        inner = dotted_name(call.args[0])
        return inner in ("jax.jit", "jit") or inner.endswith(".jit")
    return False


def _static_names(call: ast.Call) -> List[str]:
    for kw in call.keywords:
        if kw.arg == "static_argnames" and isinstance(
            kw.value, (ast.Tuple, ast.List)
        ):
            return [
                el.value
                for el in kw.value.elts
                if isinstance(el, ast.Constant) and isinstance(el.value, str)
            ]
    return []


class RecompileHazardRule(Rule):
    id = "recompile-hazard"
    title = "no per-call jit wrappers, traced env reads, or unhashable statics"
    rationale = (
        "per-call jax.jit wrappers and value-varying reads inside jitted "
        "bodies defeat the compile cache; unhashable static defaults raise "
        "at the first defaulted call"
    )

    @staticmethod
    def _stores_into_cache(fn: ast.AST) -> bool:
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign):
                continue
            for t in node.targets:
                if (
                    isinstance(t, ast.Subscript)
                    and isinstance(t.value, ast.Name)
                    and t.value.id.isupper()
                ):
                    return True
        return False

    def check(
        self, ctx: FileContext, project: ProjectContext
    ) -> Iterator[Finding]:
        # (a) jax.jit constructed inside a function body — EXCEPT the
        # engine's memoized-factory idiom, where the function stores the
        # jitted callable into a module-level cache (an ALL_CAPS dict
        # subscript store, e.g. _MESH_CHAIN_CACHE[(mesh, axis)] = run):
        # those compile once per key, which is the whole point
        for call in ctx.calls:
            if not _jit_target(call):
                continue
            fn = ctx.enclosing_function(call)
            if fn is not None and not self._stores_into_cache(fn):
                yield ctx.finding(
                    self.id,
                    call,
                    f"jax.jit constructed inside {fn.name}() — a fresh "
                    "jitted callable (and compile cache) per call; hoist "
                    "to module scope or memoize in a module-level cache",
                )

        for fn in ctx.functions:
            jitted = ctx.is_jitted(fn)
            # (c) unhashable defaults on static_argnames params
            for dec in fn.decorator_list:
                if not (isinstance(dec, ast.Call) and _jit_target(dec)):
                    continue
                statics = set(_static_names(dec))
                if not statics:
                    continue
                args = fn.args
                pos = args.posonlyargs + args.args
                for name_node, default in list(
                    zip(pos[len(pos) - len(args.defaults):], args.defaults)
                ) + [
                    (a, d)
                    for a, d in zip(args.kwonlyargs, args.kw_defaults)
                    if d is not None
                ]:
                    if name_node.arg in statics and isinstance(
                        default, (ast.List, ast.Dict, ast.Set)
                    ):
                        yield ctx.finding(
                            self.id,
                            default,
                            f"static arg {name_node.arg!r} of {fn.name}() "
                            "has an unhashable default — jit hashes static "
                            "args; use a tuple or None",
                        )
            if not jitted:
                continue
            # (b) value-varying reads inside a jitted body
            for call in ctx.calls_under(fn):
                name = dotted_name(call.func)
                if name in _ENV_READS:
                    yield ctx.finding(
                        self.id,
                        call,
                        f"os.environ read inside jitted {fn.name}() — the "
                        "value is baked into the trace at first call; read "
                        "it outside and pass it in (static or operand)",
                    )
                elif (
                    isinstance(call.func, ast.Attribute)
                    and call.func.attr == "get"
                    and isinstance(call.func.value, ast.Name)
                    and call.func.value.id.isupper()
                    and not call.args
                ):
                    # CONFIG_OPTION.get() inside a jitted body: same bake-in
                    yield ctx.finding(
                        self.id,
                        call,
                        f"config option {call.func.value.id}.get() inside "
                        f"jitted {fn.name}() — the flag value is traced in; "
                        "resolve it at the call site instead",
                    )
            for node in ast.walk(fn):
                if isinstance(node, ast.Subscript) and dotted_name(
                    node.value
                ) == "os.environ":
                    yield ctx.finding(
                        self.id,
                        node,
                        f"os.environ subscript inside jitted {fn.name}() — "
                        "the value is baked into the trace at first call",
                    )

"""env-var-registry: every ``TPU_CYPHER_*`` knob flows through the typed
registry in ``utils/config.py``.

A raw ``os.environ.get("TPU_CYPHER_X")`` is invisible configuration: no
type, no default policy, no in-process override for tests, no single place
an operator can enumerate the engine's knobs — and the same var drifts to
different defaults in different modules (the ``TPU_CYPHER_PRINT_TIMINGS``
duplication that motivated this rule). Declarations themselves must live
in the config module: a ``ConfigOption`` constructed elsewhere is a
declaration the registry cannot see.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..core import FileContext, Finding, Rule, dotted_name
from ..project import CONFIG_MODULE_SUFFIX, ProjectContext

ENV_PREFIX = "TPU_CYPHER_"
_CTOR_NAMES = ("ConfigOption", "ConfigFlag")


def _env_key(node: ast.AST):
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


class EnvVarRegistryRule(Rule):
    id = "env-var-registry"
    title = "TPU_CYPHER_* reads go through the typed config registry"
    rationale = (
        "raw env reads have no type, default policy, or test override; "
        "declarations outside utils/config.py are invisible to the registry"
    )

    def check(
        self, ctx: FileContext, project: ProjectContext
    ) -> Iterator[Finding]:
        in_config = ctx.relpath.endswith(CONFIG_MODULE_SUFFIX)
        for call in ctx.calls:
            name = dotted_name(call.func)
            # raw reads: os.environ.get / os.getenv / os.environ.setdefault
            if name in ("os.environ.get", "os.getenv", "os.environ.setdefault"):
                key = _env_key(call.args[0]) if call.args else None
                if key and key.startswith(ENV_PREFIX) and not in_config:
                    yield ctx.finding(
                        self.id,
                        call,
                        f"raw env read of {key!r} — declare it in "
                        "utils/config.py and read through the typed option",
                    )
                continue
            # declarations outside the registry module
            last = name.split(".")[-1]
            if last in _CTOR_NAMES and not in_config:
                key = _env_key(call.args[0]) if call.args else None
                label = f" for {key!r}" if key else ""
                yield ctx.finding(
                    self.id,
                    call,
                    f"{last} constructed{label} outside utils/config.py — "
                    "declare the option in the registry and import it",
                )
        # raw subscript reads: os.environ["TPU_CYPHER_X"]
        if in_config:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Subscript):
                continue
            if dotted_name(node.value) != "os.environ":
                continue
            key = _env_key(node.slice)
            if key and key.startswith(ENV_PREFIX):
                yield ctx.finding(
                    self.id,
                    node,
                    f"raw env subscript of {key!r} — declare it in "
                    "utils/config.py and read through the typed option",
                )
        # reads through the registry of names nobody declared (typo guard);
        # only when the config module is part of the analyzed set
        if project.declared_env_vars is None:
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                v = node.value
                if (
                    v.startswith(ENV_PREFIX)
                    and v != ENV_PREFIX
                    and "=" not in v
                    and " " not in v
                    and v.rstrip("*") == v
                    and v not in project.declared_env_vars
                ):
                    yield ctx.finding(
                        self.id,
                        node,
                        f"env var literal {v!r} is not declared in the "
                        "utils/config.py registry",
                    )

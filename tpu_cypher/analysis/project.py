"""Cross-file facts rules need: the config registry's declared env vars and
dispatch's registered kernel impls.

Both are extracted STATICALLY from the already-parsed ``FileContext``s (no
engine import, no runtime registry): the analyzer must be able to lint a
broken tree, and the fixture corpus must be lintable without being
importable as the real package.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, Optional, Set

from .core import FileContext, dotted_name

CONFIG_MODULE_SUFFIX = "utils/config.py"
_DECLARE_FUNCS = ("declare", "declare_flag", "ConfigOption", "ConfigFlag")


class ProjectContext:
    """Facts visible only across files.

    ``declared_env_vars`` — env var names declared in the typed registry
    (``utils/config.py``); ``None`` when no config module is among the
    analyzed files (fixture corpora), in which case declaration-existence
    checks are skipped but raw-read checks still apply.

    ``dispatch_impls`` — function names registered as kernel impls via
    ``dispatch.register(name, site, impls=(..))`` anywhere in the analyzed
    set: the allowlist for raw ``pl.pallas_call`` sites.

    The interprocedural substrate — ``callgraph``, ``device_taint``,
    ``blocking`` — is built LAZILY on first access and shared by every
    rule in the run: rules that stay per-file never pay for it, and the
    fixpoints run at most once per analysis invocation.
    """

    def __init__(self, contexts: Iterable[FileContext]):
        self.contexts = list(contexts)
        self.declared_env_vars: Optional[Set[str]] = None
        self.dispatch_impls: Set[str] = set()
        self.by_relpath: Dict[str, FileContext] = {}
        self._callgraph = None
        self._device_taint = None
        self._blocking = None
        self._shapes = None
        for ctx in self.contexts:
            self.by_relpath[ctx.relpath] = ctx
            if ctx.relpath.endswith(CONFIG_MODULE_SUFFIX):
                declared = self._collect_declared(ctx)
                if self.declared_env_vars is None:
                    self.declared_env_vars = set()
                self.declared_env_vars |= declared
            self.dispatch_impls |= self._collect_impls(ctx)

    # -- the interprocedural substrate (lazy, shared across rules) ----------

    @property
    def callgraph(self):
        if self._callgraph is None:
            from .callgraph import CallGraph

            self._callgraph = CallGraph(self.contexts, self.dispatch_impls)
        return self._callgraph

    @property
    def device_taint(self):
        if self._device_taint is None:
            from .dataflow import DeviceTaint

            self._device_taint = DeviceTaint(self.callgraph)
        return self._device_taint

    @property
    def blocking(self):
        if self._blocking is None:
            from .dataflow import BlockingSummaries

            self._blocking = BlockingSummaries(self.callgraph, self.device_taint)
        return self._blocking

    @property
    def shapes(self):
        if self._shapes is None:
            from .shapes import analysis_for

            self._shapes, self.shape_summary_cache_hit = analysis_for(self)
        return self._shapes

    @staticmethod
    def _collect_declared(ctx: FileContext) -> Set[str]:
        out: Set[str] = set()
        for call in ctx.calls:
            name = dotted_name(call.func).split(".")[-1]
            if name not in _DECLARE_FUNCS:
                continue
            for arg in list(call.args[:1]) + [
                kw.value for kw in call.keywords if kw.arg == "name"
            ]:
                if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                    out.add(arg.value)
        return out

    @staticmethod
    def _collect_impls(ctx: FileContext) -> Set[str]:
        out: Set[str] = set()
        for call in ctx.calls:
            if dotted_name(call.func).split(".")[-1] != "register":
                continue
            impl_args = [kw.value for kw in call.keywords if kw.arg == "impls"]
            if not impl_args and len(call.args) >= 3:
                impl_args = [call.args[2]]
            for node in impl_args:
                if isinstance(node, (ast.Tuple, ast.List)):
                    for el in node.elts:
                        if isinstance(el, ast.Constant) and isinstance(
                            el.value, str
                        ):
                            out.add(el.value)
        return out

"""Orchestration: collect files, build contexts once, run every rule,
apply suppressions and the baseline, format the report.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from . import baseline as baseline_mod
from .core import FileContext, Finding
from .project import ProjectContext
from .rules import ALL_RULES, RULES_BY_ID

# the engine package root (…/tpu_cypher) — what check_engine lints
ENGINE_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DEFAULT_BASELINE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "baseline.json"
)

_SKIP_DIRS = {"__pycache__", ".git", "node_modules"}


@dataclass
class Report:
    """Everything one analysis run produced. ``blocking`` is what fails
    CI; suppressed and baselined findings are carried for the report so a
    reader can audit the debt."""

    blocking: List[Finding] = field(default_factory=list)
    suppressed: List[Finding] = field(default_factory=list)
    baselined: List[Finding] = field(default_factory=list)
    suppress_reasons: Dict[Finding, str] = field(default_factory=dict)
    files_checked: int = 0
    rules_run: int = 0

    @property
    def clean(self) -> bool:
        return not self.blocking

    def to_json(self) -> Dict[str, object]:
        return {
            "clean": self.clean,
            "files_checked": self.files_checked,
            "rules_run": self.rules_run,
            "findings": [f.to_json() for f in self.blocking],
            "suppressed": [
                {**f.to_json(), "reason": self.suppress_reasons.get(f, "")}
                for f in self.suppressed
            ],
            "baselined": [f.to_json() for f in self.baselined],
        }

    def render_text(self) -> str:
        out: List[str] = []
        for f in self.blocking:
            out.append(f"{f.location()}: [{f.rule}] {f.message}")
        out.append(
            f"{len(self.blocking)} finding(s) "
            f"({len(self.suppressed)} suppressed, "
            f"{len(self.baselined)} baselined) across "
            f"{self.files_checked} file(s)"
        )
        return "\n".join(out)


def _collect_files(paths: Sequence[str]) -> List[str]:
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, fnames in os.walk(p):
                dirnames[:] = sorted(
                    d
                    for d in dirnames
                    if d not in _SKIP_DIRS and not d.startswith(".")
                )
                for fname in sorted(fnames):
                    if fname.endswith(".py"):
                        files.append(os.path.join(dirpath, fname))
        elif p.endswith(".py"):
            files.append(p)
    # dedupe, stable order
    seen = set()
    out = []
    for f in files:
        a = os.path.abspath(f)
        if a not in seen:
            seen.add(a)
            out.append(f)
    return out


def _relpath(path: str) -> str:
    a = os.path.abspath(path)
    rel = os.path.relpath(a, os.getcwd())
    chosen = a if rel.startswith("..") else rel
    return chosen.replace(os.path.sep, "/")


def run_paths(
    paths: Sequence[str],
    rules: Optional[Sequence[str]] = None,
    baseline_path: Optional[str] = None,
) -> Report:
    """Analyze ``paths`` (files or directories). ``rules`` limits to a
    subset of rule ids; ``baseline_path`` points at a grandfather file
    (None = no baseline). Raises ``KeyError`` on an unknown rule id."""
    active = (
        ALL_RULES
        if rules is None
        else [RULES_BY_ID[r] for r in rules]
    )
    report = Report(rules_run=len(active))

    contexts: List[FileContext] = []
    for path in _collect_files(paths):
        rel = _relpath(path)
        try:
            with open(path, "r") as f:
                source = f.read()
            ctx = FileContext(path, rel, source)
        except (SyntaxError, UnicodeDecodeError, OSError) as exc:
            report.blocking.append(
                Finding(
                    "parse",
                    rel,
                    getattr(exc, "lineno", 0) or 0,
                    0,
                    f"unparsable file: {exc}",
                )
            )
            continue
        contexts.append(ctx)
    report.files_checked = len(contexts)

    project = ProjectContext(contexts)

    raw: List[Finding] = []
    for ctx in contexts:
        # malformed / reason-less suppressions are findings themselves
        for f in ctx.suppression_findings:
            raw.append(
                Finding(f.rule, ctx.relpath, f.line, f.col, f.message)
            )
        for rule in active:
            for f in rule.check(ctx, project):
                reason = ctx.allowed(f.line, f.rule)
                if reason is not None:
                    report.suppressed.append(f)
                    report.suppress_reasons[f] = reason
                else:
                    raw.append(f)

    raw.sort(key=lambda f: (f.path, f.line, f.col, f.rule, f.message))

    if baseline_path is not None:
        base = baseline_mod.load(baseline_path)
        blocking, grandfathered = baseline_mod.split(raw, base)
        report.blocking.extend(blocking)
        report.baselined.extend(grandfathered)
    else:
        report.blocking.extend(raw)

    report.blocking.sort(
        key=lambda f: (f.path, f.line, f.col, f.rule, f.message)
    )
    return report


def check_engine(
    rules: Optional[Sequence[str]] = None,
    baseline_path: Optional[str] = DEFAULT_BASELINE,
) -> Report:
    """Lint the installed ``tpu_cypher`` package — the thin invocation the
    test suite (and bench.py's ``lint_clean``) uses."""
    return run_paths([ENGINE_ROOT], rules=rules, baseline_path=baseline_path)


def engine_is_clean() -> bool:
    """True when the engine lints clean. Never raises — bench.py records
    this on its one guaranteed JSON line even mid-incident."""
    try:
        return check_engine().clean
    except Exception:  # fault-ok: a lint crash must not fail the bench line
        return False


def format_report(report: Report, fmt: str) -> str:
    if fmt == "json":
        return json.dumps(report.to_json(), indent=2)
    return report.render_text()

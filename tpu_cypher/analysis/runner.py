"""Orchestration: collect files, build contexts once, run every rule,
apply suppressions and the baseline, format the report.

Parsing is cached process-wide keyed by ``(abspath, mtime_ns, size)`` —
repeated ``run_paths`` calls in one process (the test suite runs the
analyzer dozens of times) re-parse only files that actually changed.
``restrict_to`` narrows which files RULES run on while still parsing the
whole tree, so the interprocedural substrate (call graph, taint) sees
every definition even when only a git-changed subset is being checked.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from . import baseline as baseline_mod
from .core import FileContext, Finding
from .project import ProjectContext
from .rules import ALL_RULES, RULES_BY_ID

# the engine package root (…/tpu_cypher) — what check_engine lints
ENGINE_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DEFAULT_BASELINE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "baseline.json"
)

# version stamp on the ``suppressions`` section of --format json output
SUPPRESSION_SCHEMA_VERSION = 1

_SKIP_DIRS = {"__pycache__", ".git", "node_modules"}

# (abspath) -> ((mtime_ns, size, relpath), FileContext): one parse per
# file VERSION per process, shared across run_paths calls
_PARSE_CACHE: Dict[str, Tuple[Tuple[int, int, str], FileContext]] = {}


@dataclass
class Report:
    """Everything one analysis run produced. ``blocking`` is what fails
    CI; suppressed and baselined findings are carried for the report so a
    reader can audit the debt."""

    blocking: List[Finding] = field(default_factory=list)
    suppressed: List[Finding] = field(default_factory=list)
    baselined: List[Finding] = field(default_factory=list)
    suppress_reasons: Dict[Finding, str] = field(default_factory=dict)
    # every well-formed suppression seen, fired or not — the auditable
    # debt ledger ``--format json`` exports as the ``suppressions`` section
    suppression_entries: List[Dict[str, object]] = field(default_factory=list)
    files_checked: int = 0
    rules_run: int = 0
    # per-run cache traffic: parse cache + shape summary cache hit/miss
    cache_stats: Dict[str, int] = field(default_factory=dict)
    # the ProjectContext the run was checked against (not serialized):
    # what --facts-out hands to shapes.collect_facts
    project: Optional[object] = None

    @property
    def clean(self) -> bool:
        return not self.blocking

    def counts_by_rule(self) -> Dict[str, int]:
        """{rule id: blocking finding count} — the bench.py lint field."""
        out: Dict[str, int] = {}
        for f in self.blocking:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out

    def to_json(self) -> Dict[str, object]:
        return {
            "clean": self.clean,
            "files_checked": self.files_checked,
            "rules_run": self.rules_run,
            "findings": [f.to_json() for f in self.blocking],
            "suppressed": [
                {**f.to_json(), "reason": self.suppress_reasons.get(f, "")}
                for f in self.suppressed
            ],
            "baselined": [f.to_json() for f in self.baselined],
            "suppressions": {
                "schema_version": SUPPRESSION_SCHEMA_VERSION,
                "entries": self.suppression_entries,
            },
            "caches": dict(self.cache_stats),
        }

    def render_text(self) -> str:
        out: List[str] = []
        for f in self.blocking:
            out.append(f"{f.location()}: [{f.rule}] {f.message}")
        out.append(
            f"{len(self.blocking)} finding(s) "
            f"({len(self.suppressed)} suppressed, "
            f"{len(self.baselined)} baselined) across "
            f"{self.files_checked} file(s)"
        )
        return "\n".join(out)


def _collect_files(paths: Sequence[str]) -> List[str]:
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, fnames in os.walk(p):
                dirnames[:] = sorted(
                    d
                    for d in dirnames
                    if d not in _SKIP_DIRS and not d.startswith(".")
                )
                for fname in sorted(fnames):
                    if fname.endswith(".py"):
                        files.append(os.path.join(dirpath, fname))
        elif p.endswith(".py"):
            files.append(p)
    # dedupe, stable order
    seen = set()
    out = []
    for f in files:
        a = os.path.abspath(f)
        if a not in seen:
            seen.add(a)
            out.append(f)
    return out


def _relpath(path: str) -> str:
    a = os.path.abspath(path)
    rel = os.path.relpath(a, os.getcwd())
    chosen = a if rel.startswith("..") else rel
    return chosen.replace(os.path.sep, "/")


def _load_context(path: str, rel: str) -> Tuple[FileContext, bool]:
    """Parse ``path`` into a FileContext, reusing the process-wide cache
    when (mtime_ns, size, relpath) are unchanged. FileContext is immutable
    after construction, so sharing one across runs is safe. Returns
    ``(ctx, cache_hit)`` so the caller can report per-run cache traffic
    without module-level counters."""
    a = os.path.abspath(path)
    try:
        st = os.stat(a)
        key = (st.st_mtime_ns, st.st_size, rel)
    except OSError:
        key = None
    if key is not None:
        hit = _PARSE_CACHE.get(a)
        if hit is not None and hit[0] == key:
            return hit[1], True
    with open(path, "r") as f:
        source = f.read()
    ctx = FileContext(path, rel, source)
    if key is not None:
        _PARSE_CACHE[a] = (key, ctx)
    return ctx, False


def run_paths(
    paths: Sequence[str],
    rules: Optional[Sequence[str]] = None,
    baseline_path: Optional[str] = None,
    restrict_to: Optional[Iterable[str]] = None,
) -> Report:
    """Analyze ``paths`` (files or directories). ``rules`` limits to a
    subset of rule ids; ``baseline_path`` points at a grandfather file
    (None = no baseline). ``restrict_to`` (paths) narrows which files the
    RULES check and report on — the whole tree is still parsed so the
    interprocedural substrate stays complete (``--changed-only``). Raises
    ``KeyError`` on an unknown rule id."""
    active = (
        ALL_RULES
        if rules is None
        else [RULES_BY_ID[r] for r in rules]
    )
    active_ids = {r.id for r in active}
    report = Report(rules_run=len(active))
    parse_hits = parse_misses = 0

    restrict = (
        None
        if restrict_to is None
        else {os.path.abspath(p) for p in restrict_to}
    )

    contexts: List[FileContext] = []
    for path in _collect_files(paths):
        rel = _relpath(path)
        in_scope = restrict is None or os.path.abspath(path) in restrict
        try:
            ctx, was_hit = _load_context(path, rel)
            parse_hits += 1 if was_hit else 0
            parse_misses += 0 if was_hit else 1
        except (SyntaxError, UnicodeDecodeError, OSError) as exc:
            if in_scope:
                report.blocking.append(
                    Finding(
                        "parse",
                        rel,
                        getattr(exc, "lineno", 0) or 0,
                        0,
                        f"unparsable file: {exc}",
                    )
                )
            continue
        contexts.append(ctx)

    checked = [
        c
        for c in contexts
        if restrict is None or os.path.abspath(c.path) in restrict
    ]
    report.files_checked = len(checked)

    project = ProjectContext(contexts)

    raw: List[Finding] = []
    for ctx in checked:
        # malformed / reason-less suppressions are findings themselves
        for f in ctx.suppression_findings:
            raw.append(
                Finding(f.rule, ctx.relpath, f.line, f.col, f.message)
            )
        for rule in active:
            for f in rule.check(ctx, project):
                reason = ctx.allowed(f.line, f.rule)
                if reason is not None:
                    report.suppressed.append(f)
                    report.suppress_reasons[f] = reason
                else:
                    raw.append(f)

    # suppression inventory + stale detection: an allow whose named rules
    # ALL ran this pass but suppressed nothing marks a site that is clean
    # now — the comment itself becomes the finding. Suppressions naming
    # any rule OUTSIDE the active set are skipped (a restricted run cannot
    # know whether the other rule still fires there).
    for ctx in checked:
        for s in ctx.suppressions:
            if not any(r in RULES_BY_ID for r in s.rules):
                # syntax examples in docstrings (allow[rule-id] ...) parse
                # as suppressions for nonexistent rules; they suppress
                # nothing and don't belong in the inventory
                continue
            fired = any(
                f.path == ctx.relpath
                and f.rule in s.rules
                and f.line in s.covers
                for f in report.suppressed
            )
            report.suppression_entries.append(
                {
                    "path": ctx.relpath,
                    "line": s.line,
                    "rules": list(s.rules),
                    "reason": s.reason,
                    "active": fired,
                }
            )
            if not fired and all(r in active_ids for r in s.rules):
                raw.append(
                    Finding(
                        "suppression",
                        ctx.relpath,
                        s.line,
                        0,
                        "stale suppression: allow[%s] matched no finding "
                        "this run — the site is clean now; delete the "
                        "comment" % ",".join(s.rules),
                    )
                )

    raw.sort(key=lambda f: (f.path, f.line, f.col, f.rule, f.message))

    if baseline_path is not None:
        base = baseline_mod.load(baseline_path)
        blocking, grandfathered = baseline_mod.split(raw, base)
        report.blocking.extend(blocking)
        report.baselined.extend(grandfathered)
    else:
        report.blocking.extend(raw)

    report.blocking.sort(
        key=lambda f: (f.path, f.line, f.col, f.rule, f.message)
    )
    # shape-summary cache traffic: at most one lookup per run (the lazy
    # ProjectContext.shapes property records whether it hit)
    built = project._shapes is not None
    hit = bool(getattr(project, "shape_summary_cache_hit", False))
    report.cache_stats = {
        "parse_hits": parse_hits,
        "parse_misses": parse_misses,
        "summary_hits": 1 if (built and hit) else 0,
        "summary_misses": 1 if (built and not hit) else 0,
    }
    report.project = project
    return report


def check_engine(
    rules: Optional[Sequence[str]] = None,
    baseline_path: Optional[str] = DEFAULT_BASELINE,
) -> Report:
    """Lint the installed ``tpu_cypher`` package — the thin invocation the
    test suite (and bench.py's ``lint_clean``) uses."""
    return run_paths([ENGINE_ROOT], rules=rules, baseline_path=baseline_path)


def engine_is_clean() -> bool:
    """True when the engine lints clean. Never raises — bench.py records
    this on its one guaranteed JSON line even mid-incident."""
    try:
        return check_engine().clean
    except Exception:  # fault-ok: a lint crash must not fail the bench line
        return False


def engine_lint_summary() -> Dict[str, object]:
    """The bench.py ``lint_clean`` payload: verdict plus per-rule blocking
    finding counts, so a regressed invariant names itself on the JSON line
    instead of flipping an opaque boolean. Never raises — an analyzer
    crash reports ``{"clean": False, "error": ...}``."""
    try:
        report = check_engine()
        return {
            "clean": report.clean,
            "findings_by_rule": report.counts_by_rule(),
            "suppressed": len(report.suppressed),
            "files_checked": report.files_checked,
        }
    except Exception as exc:  # fault-ok: a lint crash must not fail the bench line
        return {"clean": False, "findings_by_rule": {}, "error": str(exc)[:200]}


def format_report(report: Report, fmt: str) -> str:
    if fmt == "json":
        return json.dumps(report.to_json(), indent=2)
    return report.render_text()

"""Baseline: grandfathered findings that don't fail the run.

The committed baseline (``analysis/baseline.json``) is kept EMPTY — the
acceptance bar for this engine is that every finding is fixed or carries
an inline reason. The mechanism still exists (and is tested) because a
downstream consumer adopting a new rule over a large tree needs a ratchet:
baseline today's debt, fail anything NEW, burn the file down over time.

Matching is by ``(rule, path, message)`` with multiplicity — line numbers
drift with unrelated edits, but if a file grows a SECOND identical
violation the new one still fails.
"""

from __future__ import annotations

import json
from collections import Counter
from typing import Iterable, List, Tuple

from .core import Finding

BASELINE_VERSION = 1


def load(path: str) -> Counter:
    """Baseline file -> multiset of finding keys. A missing file is an
    empty baseline; a malformed one raises (a corrupt ratchet must not
    silently allow everything)."""
    try:
        with open(path) as f:
            data = json.load(f)
    except FileNotFoundError:
        return Counter()
    if not isinstance(data, dict) or "findings" not in data:
        raise ValueError(f"baseline {path!r}: expected {{'findings': [..]}}")
    keys: Counter = Counter()
    for entry in data["findings"]:
        keys[(entry["rule"], entry["path"], entry["message"])] += 1
    return keys


def save(path: str, findings: Iterable[Finding]) -> None:
    entries = sorted(
        (
            {"rule": f.rule, "path": f.path, "message": f.message}
            for f in findings
        ),
        key=lambda e: (e["path"], e["rule"], e["message"]),
    )
    with open(path, "w") as f:
        json.dump(
            {"version": BASELINE_VERSION, "findings": entries}, f, indent=2
        )
        f.write("\n")


def split(
    findings: List[Finding], baseline: Counter
) -> Tuple[List[Finding], List[Finding]]:
    """-> (blocking, baselined). Consumes baseline multiplicity in file
    order, so N baselined + 1 new identical findings block exactly once."""
    remaining = Counter(baseline)
    blocking: List[Finding] = []
    grandfathered: List[Finding] = []
    for f in findings:
        key = f.baseline_key()
        if remaining[key] > 0:
            remaining[key] -= 1
            grandfathered.append(f)
        else:
            blocking.append(f)
    return blocking, grandfathered

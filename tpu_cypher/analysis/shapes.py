"""Abstract shape interpretation: the semantic layer under the shape rules.

The engine's two deepest invariants — every traced shape rounds the bucket
lattice (compile-cache stability) and every padded lane is masked before a
pad-sensitive consumer — were until now only *lexically* checked
(``pad-invariant`` matches ``size=`` kwargs, ``recompile-hazard`` matches
``jax.jit`` call shapes). This module interprets the array-manipulating
code of ``backend/tpu/``, ``parallel/``, and ``relational/`` over an
abstract shape lattice instead:

* ``STATIC(n)`` — a compile-time-fixed extent (a literal, a shape of an
  already-padded array, a static jit parameter);
* ``BUCKETED(lattice, origin)`` — an extent that routes through one of the
  ``bucketing`` rounding helpers, so it takes at most a bounded number of
  distinct values (one compiled program per lattice rung, not per count).
  ``masked`` additionally records that the pad lanes past the true count
  have been proven neutral (a 3-arg ``jnp.where`` against a liveness mask,
  or a comparison against an ``arange`` iota);
* ``DATA_DEPENDENT`` — an unrounded data-dependent count (a synced
  reduction, an unsized ``jnp.nonzero``): one XLA program per distinct
  value if it ever reaches a compile boundary;
* ``UNKNOWN`` — the conservative top. Like the device-taint lattice,
  UNKNOWN never fires a rule: every sharp verdict requires positive
  evidence.

Two classification *facets* share one recursive evaluator: the SIZE facet
("what count does this integer expression hold?") and the ARRAY facet
("what is the leading-dim extent of this array expression?"). They differ
exactly where arrays and counts diverge — a reduction is a STATIC scalar
as an array but a DATA_DEPENDENT value as a size.

Function boundaries reuse the PR 7 call graph unchanged: per-function
return summaries (fixed verdict or parameter passthrough, mirroring
``dataflow.DeviceTaint``) solved to fixpoint, with argument shape classes
flowing into parameter shape classes across every resolved call site.

The interpreter also exports its facts (``collect_facts``) as a
schema-versioned JSON artifact: the per-operator padded-shape transfer
catalog plus every classified size site — the cost-model feedstock for
the ROADMAP item 2 optimizer, whose padded-lattice cost model needs
exactly "what padded shape does this operator run at, as a function of
its lattice inputs". ``predict_padded`` is the pure (engine-import-free)
re-implementation of ``bucketing.round_size`` that makes static
predictions comparable against the padded-vs-true pairs obs spans stamp
at runtime; a test pins the two lattices equal so they cannot drift.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field, replace
from typing import Dict, FrozenSet, Iterable, List, Optional, Tuple, Union

from .core import FileContext, dotted_name

# directories whose array code the interpreter covers (relational/ is in
# scope for compile-boundary rules; the pad-mask rule narrows further)
SCOPE_DIRS = ("backend/tpu/", "parallel/", "relational/")
_BUCKETING_SUFFIX = "backend/tpu/bucketing.py"

FACTS_SCHEMA_VERSION = 1

# the smallest nonzero bucket — mirrors bucketing._BUCKET_FLOOR; pinned
# equal by tests/test_shape_facts.py so the pure predictor cannot drift
BUCKET_FLOOR = 32

# ---------------------------------------------------------------------------
# the abstract domain
# ---------------------------------------------------------------------------

STATIC_KIND = "static"
BUCKETED_KIND = "bucketed"
DATA_KIND = "data"
UNKNOWN_KIND = "unknown"

_RANK = {STATIC_KIND: 0, BUCKETED_KIND: 1, DATA_KIND: 2, UNKNOWN_KIND: 3}

# static upper bound on distinct lattice rungs a bucketed size can take
# (counts up to 2^40 rows — far past any single-device graph): the
# bucket-cardinality bound exported per site
BUCKET_BOUNDS = {
    "pow2": 36,       # pow2 rungs from the floor to 2^40
    "1.25": 112,      # 1.25-ratio rungs over the same range
    "mode": 112,      # round_size: whichever lattice MODE selects
    "multiple": 64,   # round_up_multiple: bounded by the padded axis cap
    "derived": 160,   # concatenations/sums of bucketed extents
}


@dataclass(frozen=True)
class ShapeVal:
    """One point of the abstract shape lattice."""

    kind: str
    n: Optional[int] = None       # known extent (STATIC only)
    lattice: Optional[str] = None  # pow2 | 1.25 | mode | multiple | derived
    origin: str = ""              # where the class was introduced
    masked: bool = False          # pad lanes proven neutral (BUCKETED)
    iota: bool = False            # an arange over the axis (compare => mask)

    def render(self) -> str:
        if self.kind == STATIC_KIND:
            return f"static({self.n})" if self.n is not None else "static"
        if self.kind == BUCKETED_KIND:
            m = ", masked" if self.masked else ""
            return f"bucketed({self.lattice}{m})"
        if self.kind == DATA_KIND:
            o = f": {self.origin}" if self.origin else ""
            return f"data-dependent{o}"
        return "unknown"


def STATIC(n: Optional[int] = None, **kw) -> ShapeVal:
    return ShapeVal(STATIC_KIND, n=n, **kw)


def BUCKETED(lattice: str, origin: str = "", masked: bool = False) -> ShapeVal:
    return ShapeVal(BUCKETED_KIND, lattice=lattice, origin=origin, masked=masked)


def DATA(origin: str = "") -> ShapeVal:
    return ShapeVal(DATA_KIND, origin=origin)


UNKNOWN_SHAPE = ShapeVal(UNKNOWN_KIND)


def join(vals: Iterable[ShapeVal], masked_any: bool = False) -> ShapeVal:
    """Lattice join. UNKNOWN absorbs everything (conservative: a rule
    never fires on a join it did not fully understand); DATA absorbs
    BUCKETED absorbs STATIC. ``masked_any`` selects the mask-combining
    policy: AND by default (every contributor must be proven neutral),
    OR for operators that force pads dead when ANY operand does
    (``x & live``, ``x * live``)."""
    vals = list(vals)
    if not vals:
        return UNKNOWN_SHAPE
    top = max(vals, key=lambda v: _RANK[v.kind])
    if top.kind == UNKNOWN_KIND:
        return UNKNOWN_SHAPE
    if top.kind == DATA_KIND:
        return top
    if top.kind == BUCKETED_KIND:
        bucketed = [v for v in vals if v.kind == BUCKETED_KIND]
        lattices = {v.lattice for v in bucketed}
        lattice = lattices.pop() if len(lattices) == 1 else "derived"
        if masked_any:
            masked = any(v.masked for v in vals)
        else:
            masked = all(v.masked for v in vals)
        return BUCKETED(lattice, origin=bucketed[0].origin, masked=masked)
    ns = {v.n for v in vals}
    return STATIC(ns.pop() if len(ns) == 1 else None,
                  iota=any(v.iota for v in vals))


# ---------------------------------------------------------------------------
# the pure padded-shape predictor (no engine import: the agreement test
# pins it equal to bucketing.round_size so the two can never drift)
# ---------------------------------------------------------------------------


def predict_padded(n: int, mode: str = "pow2") -> int:
    """The padded extent ``bucketing.round_size`` produces for a true
    count ``n`` under lattice ``mode`` — re-derived from the lattice
    definition alone. ``n <= 0`` stays 0 (the empty case keeps its own
    trivially-cheap program); ``off`` is identity."""
    n = int(n)
    if n <= 0:
        return 0
    if mode == "off":
        return n
    if mode == "1.25":
        rung = BUCKET_FLOOR
        while rung < n:
            rung = max(rung + 1, int(rung * 1.25))
        return rung
    # pow2: smallest power of two >= max(n, floor)
    m = max(n, BUCKET_FLOOR)
    return 1 << (m - 1).bit_length() if m > 1 else 1


# ---------------------------------------------------------------------------
# the transfer catalog: how each primitive the engine uses maps input
# shape classes to its padded output shape. This table IS the per-operator
# facts payload; the evaluator's call transfer consults the same leaf sets.
# ---------------------------------------------------------------------------

# leaf names of array-producing calls with an explicit static size kwarg
SIZE_KWARGS = ("size", "total_repeat_length", "num_segments")

_REDUCERS = frozenset(
    "sum prod mean min max amin amax any all argmin argmax count_nonzero "
    "nanmin nanmax nansum median average".split()
)
_SORTS = frozenset("sort argsort lexsort".split())
_ELEMENTWISE = frozenset(
    "abs clip astype asarray minimum maximum logical_and logical_or "
    "logical_not isnan isfinite sign negative add subtract multiply "
    "floor_divide mod equal not_equal less less_equal greater "
    "greater_equal bitwise_and bitwise_or invert where_keep exp log".split()
)
_PRESERVING = frozenset("reshape ravel flatten copy block_until_ready".split())
_ROUNDER_LATTICE = {
    "round_size": "mode",
    "round_up_pow2": "pow2",
    "round_up_multiple": "multiple",
    "bucket_pad_host": "mode",
}
_DEVICE_PREFIXES = ("jnp.", "jax.", "lax.", "J.", "np.", "numpy.")

# the exported per-operator padded-shape formulas, as functions of the
# abstract inputs. ``padded_shape`` is the leading-dim extent of the
# result; ``class`` names the transfer family the evaluator applies.
OPERATOR_FORMULAS: List[Dict[str, str]] = [
    {"op": "jnp.nonzero", "class": "sized_materialize",
     "padded_shape": "size (DATA_DEPENDENT when the size kwarg is absent)"},
    {"op": "jnp.repeat", "class": "sized_materialize",
     "padded_shape": "total_repeat_length (DATA_DEPENDENT when absent and "
                     "repeats is traced)"},
    {"op": "jnp.unique", "class": "sized_materialize",
     "padded_shape": "size (DATA_DEPENDENT when the size kwarg is absent)"},
    {"op": "jax.ops.segment_sum", "class": "sized_materialize",
     "padded_shape": "num_segments"},
    {"op": "jnp.where", "class": "select",
     "padded_shape": "join(x, y); masked=True (3-arg form); "
                     "DATA_DEPENDENT (1-arg form)"},
    {"op": "jnp.arange", "class": "iota",
     "padded_shape": "stop; iota=True (a compare against it is a "
                     "liveness mask)"},
    {"op": "jnp.zeros|ones|full|empty", "class": "alloc",
     "padded_shape": "shape[0]"},
    {"op": "jnp.concatenate|hstack|append", "class": "concat",
     "padded_shape": "sum(parts) -> bucketed(derived) when any part is "
                     "bucketed"},
    {"op": "jnp.stack", "class": "concat",
     "padded_shape": "len(parts) along the new axis; parts join"},
    {"op": "jnp.reshape|ravel", "class": "preserve",
     "padded_shape": "input (total extent preserved)"},
    {"op": "jnp.pad", "class": "pad",
     "padded_shape": "input + pad_width; masked=False (fresh pad lanes "
                     "are live garbage until masked)"},
    {"op": "jnp.sort|argsort|lexsort|lax.sort", "class": "sort",
     "padded_shape": "input (pad-sensitive consumer: pads must sort last "
                     "via the ID_SENTINEL discipline)"},
    {"op": "jnp.searchsorted", "class": "search",
     "padded_shape": "shape(v); the sorted operand is the pad-sensitive "
                     "side"},
    {"op": "jnp.cumsum", "class": "scan",
     "padded_shape": "input; masked=False (pad lanes absorb the running "
                     "total)"},
    {"op": "jnp.take|take_along_axis", "class": "gather",
     "padded_shape": "shape(indices); masked=False (pad lanes gather "
                     "duplicate payload)"},
    {"op": "jnp.sum|max|min|any|all|argmin|argmax|count_nonzero",
     "class": "reduction",
     "padded_shape": "scalar as an array; DATA_DEPENDENT as a size"},
    {"op": "lax.top_k", "class": "sized_materialize", "padded_shape": "k"},
    {"op": "lax.dynamic_slice_in_dim", "class": "sized_materialize",
     "padded_shape": "slice_size"},
    {"op": "jnp.dot|matmul", "class": "contraction",
     "padded_shape": "shape(lhs)[0]"},
    {"op": "bucketing.round_size", "class": "rounder",
     "padded_shape": "bucketed(mode): next rung of the active lattice "
                     "(pow2 floor 32 | 1.25 ratio from 32)"},
    {"op": "bucketing.round_up_pow2", "class": "rounder",
     "padded_shape": "bucketed(pow2): 1 << ceil(log2(max(n, floor)))"},
    {"op": "bucketing.round_up_multiple", "class": "rounder",
     "padded_shape": "bucketed(multiple): ceil(n / m) * m"},
    {"op": "bucketing.bucket_pad_host", "class": "rounder",
     "padded_shape": "bucketed(mode): host tail-pad up to round_size"},
    {"op": "int|float|bool", "class": "sync",
     "padded_shape": "preserves the size class of the synced operand "
                     "(a synced DATA_DEPENDENT count stays DATA_DEPENDENT)"},
    # the factorized run-decompress family (backend/tpu/factorized.py):
    # lane-extent prefix programs plus the bucketed flat-extent decode
    {"op": "factorized._runs_weights", "class": "run_prefix",
     "padded_shape": "lane extent (input); per-lane run products cumsum "
                     "into exclusive prefixes masked to ID_SENTINEL past "
                     "the live lanes (the pad-mask discipline cumsum "
                     "otherwise forfeits)"},
    {"op": "factorized._decode_runs", "class": "run_decode",
     "padded_shape": "size (bucketed: round_size(chunk or total) passed "
                     "static); searchsorted over the sentinel-masked "
                     "prefix then mixed-radix positions at the same "
                     "extent"},
    {"op": "factorized._gather_decoded", "class": "gather",
     "padded_shape": "shape(i) (the decoded flat extent); pad lanes "
                     "gather duplicate payload and stay dead via the "
                     "decode's live mask"},
]


def jit_static_argnames(fn: ast.AST) -> FrozenSet[str]:
    """The ``static_argnames`` a ``jax.jit``/``partial(jax.jit, ..)``
    decorator declares on ``fn`` — the compile-cache-keyed parameters a
    bucket-cardinality bound must exist for."""
    names: set = set()
    for dec in fn.decorator_list:
        if not isinstance(dec, ast.Call):
            continue
        d = dotted_name(dec.func)
        inner, kwsrc = d, dec.keywords
        if d.split(".")[-1] == "partial" and dec.args:
            inner = dotted_name(dec.args[0])
        if not (inner in ("jax.jit", "jit") or inner.endswith(".jit")):
            continue
        for kw in kwsrc:
            if kw.arg != "static_argnames":
                continue
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                names.add(v.value)
            elif isinstance(v, (ast.Tuple, ast.List)):
                for el in v.elts:
                    if isinstance(el, ast.Constant) and isinstance(el.value, str):
                        names.add(el.value)
    return frozenset(names)


def in_scope(relpath: str) -> bool:
    if relpath.endswith(_BUCKETING_SUFFIX):
        return False  # the lattice itself
    return any(d in relpath for d in SCOPE_DIRS)


# ---------------------------------------------------------------------------
# the interprocedural analysis
# ---------------------------------------------------------------------------

SIZE = "size"
ARRAY = "array"

# a symbolic summary component: ("param", name, masked_through)
_Param = Tuple[str, str, bool]
# per-(function, facet) return summary
Summary = Union[ShapeVal, Tuple[str, FrozenSet[str], bool]]


class ShapeAnalysis:
    """Per-function shape summaries + parameter shape classes, solved to
    fixpoint over the call graph — ``dataflow.DeviceTaint`` shaped, with
    ShapeVal as the lattice and two facets per function."""

    def __init__(self, graph):
        self.graph = graph
        self.infos = [
            info for info in graph.infos.values() if in_scope(info.ctx.relpath)
        ]
        self._scope_nodes = {info.node for info in self.infos}
        # (fn node, facet) -> Summary; (fn node, param, facet) -> ShapeVal
        self.returns: Dict[Tuple[ast.AST, str], Summary] = {}
        self.params: Dict[Tuple[ast.AST, str, str], ShapeVal] = {}
        # post-fixpoint query memo: (expr node, facet) -> ShapeVal
        self._memo: Dict[Tuple[ast.AST, str], ShapeVal] = {}
        # precomputed per-round inputs: walking every function AST each
        # fixpoint round is what would blow the <5s budget
        self._returns_of: Dict[ast.AST, List[ast.AST]] = {}
        for info in self.infos:
            self._returns_of[info.node] = [
                n.value
                for n in ast.walk(info.node)
                if isinstance(n, ast.Return)
                and n.value is not None
                and info.ctx.enclosing_function(n) is info.node
            ]
        self._callee_sites = {}
        for info in self.infos:
            sites = []
            for site, targets in graph.callees(info):
                tgts = [t for t in targets if t.node in self._scope_nodes]
                if tgts:
                    sites.append((site, tgts))
            if sites:
                self._callee_sites[info.node] = sites
        self._solve()

    # -- public --------------------------------------------------------------

    def classify_size(
        self, ctx: FileContext, fn: Optional[ast.AST], expr: ast.AST
    ) -> ShapeVal:
        """The abstract class of an integer count expression."""
        return self._query(ctx, fn, expr, SIZE)

    def classify_array(
        self, ctx: FileContext, fn: Optional[ast.AST], expr: ast.AST
    ) -> ShapeVal:
        """The abstract leading-dim extent of an array expression."""
        return self._query(ctx, fn, expr, ARRAY)

    def _query(self, ctx, fn, expr, facet) -> ShapeVal:
        key = (expr, facet)
        hit = self._memo.get(key)
        if hit is None:
            v = self._eval(ctx, fn, expr, facet, 0, symbolic=False)
            hit = v if isinstance(v, ShapeVal) else UNKNOWN_SHAPE
            self._memo[key] = hit
        return hit

    # -- fixpoint ------------------------------------------------------------

    def _solve(self, max_rounds: int = 8) -> None:
        for _ in range(max_rounds):
            changed = False
            for info in self.infos:
                for facet in (SIZE, ARRAY):
                    new = self._summarize(info, facet)
                    key = (info.node, facet)
                    if self.returns.get(key) != new:
                        self.returns[key] = new
                        changed = True
            changed |= self._flow_params()
            if not changed:
                return

    def _summarize(self, info, facet: str) -> Summary:
        ctx, fn = info.ctx, info.node
        verdicts: List[ShapeVal] = []
        passthrough: set = set()
        masked_through = False
        for ret in self._returns_of.get(fn, ()):
            v = self._eval(ctx, fn, ret, facet, 0, symbolic=True)
            if isinstance(v, tuple):
                passthrough.add(v[1])
                masked_through |= v[2]
            else:
                verdicts.append(v)
        sharp = [v for v in verdicts if v.kind in (DATA_KIND, BUCKETED_KIND)]
        if sharp:
            # any data/bucketed return dominates: report the join of the
            # sharp returns (a mixed passthrough demotes masked)
            out = join(sharp)
            if passthrough and out.kind == BUCKETED_KIND and not masked_through:
                out = replace(out, masked=False)
            return out
        if passthrough:
            return ("passthrough", frozenset(passthrough), masked_through)
        if verdicts:
            return join(verdicts)
        return UNKNOWN_SHAPE

    def _flow_params(self) -> bool:
        incoming: Dict[Tuple[ast.AST, str, str], List[ShapeVal]] = {}
        for info in self.infos:
            for site, targets in self._callee_sites.get(info.node, ()):
                for facet in (SIZE, ARRAY):
                    arg_vals = [
                        self._arg_val(site.ctx, info.node, a, facet)
                        for a in site.call.args
                    ]
                    kw_vals = {
                        kw.arg: self._arg_val(site.ctx, info.node, kw.value, facet)
                        for kw in site.call.keywords
                        if kw.arg is not None
                    }
                    for tgt in targets:
                        names = tgt.ctx.param_names(tgt.node)
                        if names and names[0] == "self":
                            names = names[1:]
                        for i, v in enumerate(arg_vals):
                            if i < len(names):
                                incoming.setdefault(
                                    (tgt.node, names[i], facet), []
                                ).append(v)
                        for k, v in kw_vals.items():
                            if k in names:
                                incoming.setdefault(
                                    (tgt.node, k, facet), []
                                ).append(v)
        changed = False
        for key, vals in incoming.items():
            new = join(vals)
            if self.params.get(key, UNKNOWN_SHAPE) != new:
                self.params[key] = new
                changed = True
        return changed

    def _arg_val(self, ctx, fn, expr, facet) -> ShapeVal:
        v = self._eval(ctx, fn, expr, facet, 0, symbolic=False)
        return v if isinstance(v, ShapeVal) else UNKNOWN_SHAPE

    # -- the evaluator -------------------------------------------------------

    def _eval(self, ctx, fn, expr, facet, depth, symbolic):
        """-> ShapeVal | ("param", name, masked_through). Depth-capped,
        UNKNOWN on anything not understood."""
        if depth > 6:
            return UNKNOWN_SHAPE
        if isinstance(expr, ast.Constant):
            if facet == SIZE and isinstance(expr.value, int):
                return STATIC(int(expr.value))
            return STATIC()
        if isinstance(expr, ast.Name):
            return self._eval_name(ctx, fn, expr.id, facet, depth, symbolic)
        if isinstance(expr, ast.Call):
            return self._eval_call(ctx, fn, expr, facet, depth, symbolic)
        if isinstance(expr, ast.Subscript):
            # x.shape[0] / x.shape[axis]: the array facet of x, as a size
            if (
                isinstance(expr.value, ast.Attribute)
                and expr.value.attr == "shape"
            ):
                return self._eval(
                    ctx, fn, expr.value.value, ARRAY, depth + 1, symbolic
                )
            # plain subscripts/slices approximately preserve the class
            return self._eval(ctx, fn, expr.value, facet, depth + 1, symbolic)
        if isinstance(expr, ast.Attribute):
            if expr.attr in ("size", "shape"):
                return self._eval(ctx, fn, expr.value, ARRAY, depth + 1, symbolic)
            # other attributes (self._cap, table.nrows): precomputed state,
            # already padded/static by the time it is an attribute — but not
            # provably, so stay at the non-firing top
            return UNKNOWN_SHAPE
        if isinstance(expr, ast.BinOp):
            vs = [
                self._eval(ctx, fn, s, facet, depth + 1, symbolic)
                for s in (expr.left, expr.right)
            ]
            return self._combine(
                vs, masked_any=isinstance(expr.op, (ast.Mult, ast.BitAnd))
            )
        if isinstance(expr, ast.Compare):
            sides = [expr.left] + list(expr.comparators)
            vs = [
                self._eval(ctx, fn, s, ARRAY, depth + 1, symbolic)
                for s in sides
            ]
            iota = any(isinstance(v, ShapeVal) and v.iota for v in vs)
            out = self._combine(vs, masked_any=iota)
            if iota and isinstance(out, ShapeVal):
                # lane < nvalid over an iota: THE liveness-mask idiom — pad
                # lanes are False by construction
                out = replace(out, masked=True, iota=False)
            return out
        if isinstance(expr, ast.BoolOp):
            vs = [
                self._eval(ctx, fn, s, facet, depth + 1, symbolic)
                for s in expr.values
            ]
            return self._combine(vs, masked_any=isinstance(expr.op, ast.And))
        if isinstance(expr, ast.UnaryOp):
            v = self._eval(ctx, fn, expr.operand, facet, depth + 1, symbolic)
            if isinstance(v, ShapeVal) and isinstance(expr.op, ast.Not):
                # ~live flips pad lanes True: the mask proof does not survive
                return replace(v, masked=False)
            return v
        if isinstance(expr, ast.IfExp):
            vs = [
                self._eval(ctx, fn, s, facet, depth + 1, symbolic)
                for s in (expr.body, expr.orelse)
            ]
            return self._combine(vs)
        if isinstance(expr, (ast.Tuple, ast.List)):
            vs = [
                self._eval(ctx, fn, e, facet, depth + 1, symbolic)
                for e in expr.elts
            ]
            return self._combine(vs)
        if isinstance(expr, ast.Starred):
            return self._eval(ctx, fn, expr.value, facet, depth + 1, symbolic)
        return UNKNOWN_SHAPE

    def _combine(self, vs, masked_any: bool = False):
        params = [v for v in vs if isinstance(v, tuple)]
        shapes = [v for v in vs if isinstance(v, ShapeVal)]
        if params:
            # an op OVER a param is still param-shaped for the summary;
            # record whether a mask-forcing op was part of the chain
            masked = any(p[2] for p in params) or (
                masked_any and any(s.masked for s in shapes)
            )
            return ("param", params[0][1], masked)
        return join(shapes, masked_any=masked_any)

    def _eval_name(self, ctx, fn, name, facet, depth, symbolic):
        if fn is not None and name in ctx.param_names(fn):
            if not ctx.assignments(fn, name):
                if symbolic:
                    return ("param", name, False)
                return self.params.get((fn, name, facet), UNKNOWN_SHAPE)
        vals = [
            self._eval(ctx, fn, v, facet, depth + 1, symbolic)
            for v in ctx.assignments(fn, name)
        ]
        if not vals:
            return UNKNOWN_SHAPE
        return self._combine(vals)

    def _eval_call(self, ctx, fn, call, facet, depth, symbolic):
        name = dotted_name(call.func)
        leaf = name.split(".")[-1] if name else ""
        line = getattr(call, "lineno", 0)

        # -- rounders: the lattice entry points -----------------------------
        if leaf in _ROUNDER_LATTICE:
            return BUCKETED(
                _ROUNDER_LATTICE[leaf], origin=f"{leaf}@{ctx.relpath}:{line}"
            )

        # -- host syncs / casts preserve the size class ---------------------
        if leaf in ("int", "float", "bool") and len(call.args) == 1 and not name.count("."):
            if facet == SIZE:
                return self._eval(ctx, fn, call.args[0], SIZE, depth + 1, symbolic)
            return STATIC()  # a synced scalar has no leading dim
        if leaf == "len" and len(call.args) == 1 and name == "len":
            return self._eval(ctx, fn, call.args[0], ARRAY, depth + 1, symbolic)
        if name in ("min", "max", "abs") and call.args:
            vs = [
                self._eval(ctx, fn, a, facet, depth + 1, symbolic)
                for a in call.args
            ]
            return self._combine(vs)

        device = name.startswith(_DEVICE_PREFIXES)

        # -- .item() and reductions: scalar arrays, data-dependent sizes ----
        if isinstance(call.func, ast.Attribute) and leaf == "item" and not call.args:
            if facet == SIZE:
                return self._eval(
                    ctx, fn, call.func.value, SIZE, depth + 1, symbolic
                )
            return STATIC()
        if leaf in _REDUCERS and (device or isinstance(call.func, ast.Attribute)):
            if facet == SIZE:
                return DATA(f"{name or leaf}@{ctx.relpath}:{line}")
            return STATIC()  # reduced away: scalar (or trailing-axes) result

        # -- the array-op transfer catalog ----------------------------------
        if device:
            v = self._transfer_device(ctx, fn, call, leaf, facet, depth, symbolic)
            if v is not None:
                return v

        # -- project calls: consume the fixpoint summaries ------------------
        targets = self.graph.resolve_call(ctx, call)
        scope_targets = [t for t in targets if t.node in self._scope_nodes]
        if scope_targets:
            vs = []
            for tgt in scope_targets:
                summary = self.returns.get((tgt.node, facet), UNKNOWN_SHAPE)
                if isinstance(summary, tuple):
                    vs.append(
                        self._passthrough_at_site(
                            ctx, fn, call, tgt, summary, facet, depth, symbolic
                        )
                    )
                else:
                    vs.append(summary)
            return self._combine(vs)
        return UNKNOWN_SHAPE

    def _transfer_device(self, ctx, fn, call, leaf, facet, depth, symbolic):
        """The jnp/lax transfer functions. Returns None for ops the
        catalog does not model (the caller falls through to UNKNOWN)."""
        size_kw = next(
            (kw for kw in call.keywords if kw.arg in SIZE_KWARGS), None
        )
        line = getattr(call, "lineno", 0)

        if leaf in ("nonzero", "unique"):
            if size_kw is not None:
                return self._size_as_shape(ctx, fn, size_kw.value, depth, symbolic)
            return DATA(f"jnp.{leaf} (unsized)@{ctx.relpath}:{line}")
        if leaf == "repeat":
            if size_kw is not None:
                return self._size_as_shape(ctx, fn, size_kw.value, depth, symbolic)
            if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant):
                # static repeats: extent scales by a constant, class preserved
                return self._eval(ctx, fn, call.args[0], ARRAY, depth + 1, symbolic)
            return DATA(f"jnp.repeat (unsized)@{ctx.relpath}:{line}")
        if leaf == "where":
            if len(call.args) == 1:
                return DATA(f"jnp.where (1-arg)@{ctx.relpath}:{line}")
            if len(call.args) == 3:
                vs = [
                    self._eval(ctx, fn, a, ARRAY, depth + 1, symbolic)
                    for a in call.args[1:3]
                ]
                out = self._combine(vs)
                if isinstance(out, ShapeVal):
                    return replace(out, masked=True)
                return ("param", out[1], True)
            return UNKNOWN_SHAPE
        if leaf == "arange":
            v = self._size_as_shape(
                ctx, fn, call.args[-1] if call.args else call, depth, symbolic
            )
            if isinstance(v, ShapeVal):
                return replace(v, iota=True)
            return v
        if leaf in ("zeros", "ones", "full", "empty"):
            shape_arg = call.args[0] if call.args else None
            if isinstance(shape_arg, (ast.Tuple, ast.List)) and shape_arg.elts:
                shape_arg = shape_arg.elts[0]
            if shape_arg is not None:
                return self._size_as_shape(ctx, fn, shape_arg, depth, symbolic)
            return UNKNOWN_SHAPE
        if leaf in ("concatenate", "hstack", "append", "stack"):
            parts = call.args[0].elts if (
                call.args and isinstance(call.args[0], (ast.Tuple, ast.List))
            ) else call.args
            vs = [
                self._eval(ctx, fn, p, ARRAY, depth + 1, symbolic)
                for p in parts
            ]
            out = self._combine(vs)
            if isinstance(out, ShapeVal) and out.kind == BUCKETED_KIND:
                # a concat of bucketed extents leaves the source lattice
                return replace(out, lattice="derived")
            return out
        if leaf in _PRESERVING:
            src = (
                call.func.value
                if isinstance(call.func, ast.Attribute)
                else (call.args[0] if call.args else None)
            )
            if src is None:
                return UNKNOWN_SHAPE
            return self._eval(ctx, fn, src, ARRAY, depth + 1, symbolic)
        if leaf == "pad":
            v = self._eval(
                ctx, fn, call.args[0] if call.args else call, ARRAY, depth + 1,
                symbolic,
            )
            if isinstance(v, ShapeVal):
                # fresh pad lanes are live garbage until masked
                return replace(v, masked=False,
                               lattice="derived" if v.kind == BUCKETED_KIND
                               else v.lattice)
            return v
        if leaf in _SORTS:
            ops = call.args[0].elts if (
                leaf == "lexsort"
                and call.args
                and isinstance(call.args[0], (ast.Tuple, ast.List))
            ) else call.args[:1]
            vs = [
                self._eval(ctx, fn, o, ARRAY, depth + 1, symbolic) for o in ops
            ]
            return self._combine(vs)
        if leaf == "searchsorted":
            if len(call.args) >= 2:
                return self._eval(ctx, fn, call.args[1], ARRAY, depth + 1, symbolic)
            return UNKNOWN_SHAPE
        if leaf in ("cumsum", "cummax"):
            v = self._eval(
                ctx, fn, call.args[0] if call.args else call, ARRAY, depth + 1,
                symbolic,
            )
            if isinstance(v, ShapeVal):
                return replace(v, masked=False)  # pads absorb the running total
            return v
        if leaf in ("take", "take_along_axis"):
            idx = (
                call.args[1]
                if len(call.args) >= 2
                else next(
                    (kw.value for kw in call.keywords if kw.arg == "indices"),
                    None,
                )
            )
            if idx is None:
                return UNKNOWN_SHAPE
            v = self._eval(ctx, fn, idx, ARRAY, depth + 1, symbolic)
            if isinstance(v, ShapeVal):
                return replace(v, masked=False, iota=False)
            return v
        if leaf == "top_k" and len(call.args) >= 2:
            return self._size_as_shape(ctx, fn, call.args[1], depth, symbolic)
        if leaf == "dynamic_slice_in_dim" and len(call.args) >= 3:
            return self._size_as_shape(ctx, fn, call.args[2], depth, symbolic)
        if leaf in ("dot", "matmul"):
            v = self._eval(
                ctx, fn, call.args[0] if call.args else call, ARRAY, depth + 1,
                symbolic,
            )
            if isinstance(v, ShapeVal):
                return replace(v, masked=False)
            return v
        if leaf.startswith("segment_"):
            if size_kw is not None:
                return self._size_as_shape(ctx, fn, size_kw.value, depth, symbolic)
            return UNKNOWN_SHAPE
        if leaf in _ELEMENTWISE:
            src = (
                call.func.value
                if isinstance(call.func, ast.Attribute)
                and not dotted_name(call.func).startswith(_DEVICE_PREFIXES)
                else (call.args[0] if call.args else None)
            )
            if src is None:
                return UNKNOWN_SHAPE
            return self._eval(ctx, fn, src, ARRAY, depth + 1, symbolic)
        return None

    def _size_as_shape(self, ctx, fn, size_expr, depth, symbolic):
        """A materialize whose leading dim IS a size expression: the
        array-facet result takes the size facet's class."""
        v = self._eval(ctx, fn, size_expr, SIZE, depth + 1, symbolic)
        return v

    def _passthrough_at_site(
        self, ctx, fn, call, tgt, summary, facet, depth, symbolic
    ):
        _tag, param_names, masked_through = summary
        names = tgt.ctx.param_names(tgt.node)
        if names and names[0] == "self":
            names = names[1:]
        vals = []
        for i, arg in enumerate(call.args):
            if i < len(names) and names[i] in param_names:
                vals.append(self._eval(ctx, fn, arg, facet, depth + 1, symbolic))
        for kw in call.keywords:
            if kw.arg in param_names:
                vals.append(self._eval(ctx, fn, kw.value, facet, depth + 1, symbolic))
        out = self._combine(vals) if vals else UNKNOWN_SHAPE
        if masked_through and isinstance(out, ShapeVal):
            out = replace(out, masked=True)
        return out


# ---------------------------------------------------------------------------
# the process-wide summary cache
# ---------------------------------------------------------------------------

# [(contexts tuple, ShapeAnalysis)] — identity-keyed: the runner's parse
# cache hands back the SAME FileContext objects for unchanged files, so
# repeated engine runs in one process (the test suite runs the analyzer
# dozens of times) solve the fixpoint once. Strong refs, tiny LRU.
_SUMMARY_CACHE: List[Tuple[Tuple[FileContext, ...], ShapeAnalysis]] = []
_SUMMARY_CACHE_MAX = 4


def analysis_for(project) -> Tuple[ShapeAnalysis, bool]:
    """The ShapeAnalysis for a ProjectContext, cached by the identity of
    the analyzed file set (every context, not just the in-scope ones —
    resolution can cross the scope boundary). Returns ``(analysis,
    cache_hit)`` so the runner can report per-run cache traffic."""
    key = tuple(project.contexts)
    for i, (ctxs, ana) in enumerate(_SUMMARY_CACHE):
        if len(ctxs) == len(key) and all(a is b for a, b in zip(ctxs, key)):
            if i != 0:
                _SUMMARY_CACHE.insert(0, _SUMMARY_CACHE.pop(i))
            return ana, True
    ana = ShapeAnalysis(project.callgraph)
    _SUMMARY_CACHE.insert(0, (key, ana))
    del _SUMMARY_CACHE[_SUMMARY_CACHE_MAX:]
    return ana, False


# ---------------------------------------------------------------------------
# facts export: the cost-model feedstock
# ---------------------------------------------------------------------------


def _bucket_bound(v: ShapeVal) -> Optional[int]:
    if v.kind == STATIC_KIND:
        return 1
    if v.kind == BUCKETED_KIND:
        return BUCKET_BOUNDS.get(v.lattice or "derived", BUCKET_BOUNDS["derived"])
    return None  # data-dependent: unbounded; unknown: no claim


def collect_facts(project) -> Dict[str, object]:
    """Everything the interpreter statically knows, as one JSON-stable
    artifact: the lattice definition, the per-operator padded-shape
    transfer catalog, and every classified size site (sized materializes
    and static args of jitted primitives) with its abstract class and
    bucket-signature bound."""
    shapes = project.shapes
    graph = project.callgraph
    sites: List[Dict[str, object]] = []
    for ctx in project.contexts:
        if not in_scope(ctx.relpath):
            continue
        for call in ctx.calls:
            fn = ctx.enclosing_function(call)
            name = dotted_name(call.func)
            args: List[Dict[str, object]] = []
            for kw in call.keywords:
                if kw.arg in SIZE_KWARGS:
                    v = shapes.classify_size(ctx, fn, kw.value)
                    args.append(
                        {"name": kw.arg, "shape": v.render(),
                         "bucket_bound": _bucket_bound(v)}
                    )
            for tgt in graph.resolve_call(ctx, call):
                statics = jit_static_argnames(tgt.node)
                if not statics:
                    continue
                names = tgt.ctx.param_names(tgt.node)
                if names and names[0] == "self":
                    names = names[1:]
                for i, a in enumerate(call.args):
                    if i < len(names) and names[i] in statics:
                        v = shapes.classify_size(ctx, fn, a)
                        args.append(
                            {"name": names[i], "shape": v.render(),
                             "bucket_bound": _bucket_bound(v)}
                        )
                for kw in call.keywords:
                    if kw.arg in statics and kw.arg not in SIZE_KWARGS:
                        v = shapes.classify_size(ctx, fn, kw.value)
                        args.append(
                            {"name": kw.arg, "shape": v.render(),
                             "bucket_bound": _bucket_bound(v)}
                        )
            if not args:
                continue
            bounds = [a["bucket_bound"] for a in args]
            verdict = (
                "unbounded"
                if any(a["shape"].startswith("data") for a in args)
                else ("bounded" if all(b is not None for b in bounds) else "unknown")
            )
            sites.append(
                {
                    "path": ctx.relpath,
                    "line": getattr(call, "lineno", 0),
                    "op": name or "<call>",
                    "args": args,
                    "verdict": verdict,
                }
            )
    sites.sort(key=lambda s: (s["path"], s["line"], s["op"]))
    data_sites = sum(1 for s in sites if s["verdict"] == "unbounded")
    bucketed_sites = sum(
        1
        for s in sites
        if any(str(a["shape"]).startswith("bucketed") for a in s["args"])
    )
    return {
        "schema_version": FACTS_SCHEMA_VERSION,
        "lattice": {
            "floor": BUCKET_FLOOR,
            "modes": {
                "off": "n (identity)",
                "pow2": "1 << ceil(log2(max(n, floor))) for n > 0; 0 stays 0",
                "1.25": "first rung >= n of [floor, max(prev+1, "
                        "int(prev*1.25)), ...]; 0 stays 0",
            },
            "bounds": dict(BUCKET_BOUNDS),
        },
        "operators": [dict(f) for f in OPERATOR_FORMULAS],
        "sites": sites,
        "summary": {
            "facts_emitted": len(OPERATOR_FORMULAS) + len(sites),
            "data_dependent_sites": data_sites,
            "bucketed_sites": bucketed_sites,
        },
    }


def engine_shape_summary() -> Dict[str, object]:
    """The bench.py ``shape_facts`` payload: the facts summary over the
    installed engine. Never raises — a crash reports itself on the line."""
    try:
        from .runner import ENGINE_ROOT, run_paths

        report = run_paths([ENGINE_ROOT], rules=[])
        facts = collect_facts(report.project)
        return dict(facts["summary"])
    except Exception as exc:  # fault-ok: a facts crash must not fail the bench line
        return {
            "facts_emitted": 0,
            "data_dependent_sites": -1,
            "bucketed_sites": -1,
            "error": str(exc)[:200],
        }

"""Project-wide call graph: who can call whom, across files.

PR 5's rules were per-file: a device sync one helper call away, or a
blocking dispatch two modules from the ``async def`` that reaches it,
passed silently. The call graph is the substrate that makes those rules
semantic. It is built STATICALLY from the already-parsed ``FileContext``s
(like ``ProjectContext`` — no engine import, no runtime registry), so a
broken tree and the fixture corpora both resolve.

Resolution covers the shapes this codebase actually uses:

* module-level defs, called bare (``helper(x)``) or through an import
  alias (``G.check_deadline`` after ``from ..runtime import guard as G``);
* methods, through ``self.meth()``, ``ClassName.meth``, instances bound in
  the same scope (``s = CypherSession(); s.cypher(..)``), module-level
  singletons (``REGISTRY = MetricsRegistry(); REGISTRY.counter(..)`` —
  also through an imported alias), and class-attribute chasing
  (``self.pool.run`` resolves through ``self.pool = SessionPool(..)`` in
  ``__init__``);
* relative and absolute imports, matched against the analyzed file set by
  dotted-path suffix, so the graph is exact whether the analyzer runs from
  the repo root or on a fixture corpus that mirrors the package layout;
* the dispatch-registry indirection ``ProjectContext`` already indexes: a
  ``dispatch.launch(..)`` call fans out to every statically registered
  kernel impl.

Unresolvable calls (builtins, third-party, higher-order params) resolve to
the empty tuple — every consumer must treat "no edge" as UNKNOWN, never as
safe/clean, or as definitely-blocking. The graph also records, per call
site, whether the call sits inside a ``lambda`` (a deferred body is not
executed by its lexical encloser — the async-blocking and shared-state
rules need exactly that distinction).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .core import FileContext, dotted_name

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)

# callable-argument sinks that move execution onto a worker lane (a thread
# or a fresh contextvars.Context) — the roots of "lane code" for the
# shared-state-race rule, and the sanctioned escape hatch for the
# async-blocking rule
LANE_SINKS = ("run_in_executor", "to_thread", "submit", "run")


def module_path(relpath: str) -> str:
    """``tpu_cypher/serve/server.py`` -> ``tpu_cypher.serve.server``;
    ``pkg/__init__.py`` -> ``pkg``. Leading path junk survives as extra
    dotted segments — resolution matches by SUFFIX, so it never matters."""
    p = relpath[:-3] if relpath.endswith(".py") else relpath
    parts = [x for x in p.split("/") if x and x != "."]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


@dataclass(frozen=True)
class FunctionInfo:
    """One function or method in the analyzed set."""

    ctx: FileContext
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    module: str
    qualname: str  # "func" | "Class.method" | "outer.<nested>"
    cls: Optional[str] = None  # owning class name, if a method

    @property
    def name(self) -> str:
        return self.node.name

    @property
    def is_async(self) -> bool:
        return isinstance(self.node, ast.AsyncFunctionDef)

    def __repr__(self) -> str:  # compact for finding messages
        return f"{self.module}.{self.qualname}"


@dataclass
class ClassInfo:
    name: str
    node: ast.ClassDef
    ctx: FileContext
    methods: Dict[str, FunctionInfo] = field(default_factory=dict)
    bases: Tuple[str, ...] = ()
    # self.<attr> = <expr> bindings collected from every method
    attr_exprs: Dict[str, List[ast.expr]] = field(default_factory=dict)


@dataclass
class ModuleIndex:
    path: str
    ctx: FileContext
    defs: Dict[str, FunctionInfo] = field(default_factory=dict)
    classes: Dict[str, ClassInfo] = field(default_factory=dict)
    # local binding -> (target module dotted path, symbol | None)
    imports: Dict[str, Tuple[str, Optional[str]]] = field(default_factory=dict)
    # module-level NAME = <expr> (singleton instances, aliases)
    globals: Dict[str, List[ast.expr]] = field(default_factory=dict)


@dataclass(frozen=True)
class CallSite:
    caller: Optional[FunctionInfo]  # None at module scope
    call: ast.Call
    ctx: FileContext
    in_lambda: bool  # lexically inside a lambda: deferred, not executed here


class CallGraph:
    """The resolved graph over one analyzed file set."""

    def __init__(self, contexts: Sequence[FileContext], dispatch_impls: Set[str]):
        self.modules: Dict[str, ModuleIndex] = {}
        self.infos: Dict[ast.AST, FunctionInfo] = {}
        self._by_suffix: Dict[str, List[str]] = {}
        for ctx in contexts:
            self._index_module(ctx)
        for mod in self.modules.values():
            for seg_start in range(len(mod.path.split("."))):
                suffix = ".".join(mod.path.split(".")[seg_start:])
                self._by_suffix.setdefault(suffix, []).append(mod.path)
        # dispatch indirection targets: registered impl names -> infos
        self._dispatch_targets: Tuple[FunctionInfo, ...] = tuple(
            info
            for info in self.infos.values()
            if info.name in dispatch_impls
        )
        # resolved edges
        self._callees: Dict[ast.AST, List[Tuple[CallSite, Tuple[FunctionInfo, ...]]]] = {}
        self._callers: Dict[ast.AST, List[CallSite]] = {}
        self._build_edges()

    # -- indexing -----------------------------------------------------------

    def _index_module(self, ctx: FileContext) -> None:
        mod = ModuleIndex(module_path(ctx.relpath), ctx)
        self.modules[mod.path] = mod
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    mod.imports[a.asname or a.name.split(".")[0]] = (
                        a.name,
                        None,
                    )
            elif isinstance(node, ast.ImportFrom):
                base = self._import_base(mod.path, node)
                for a in node.names:
                    if a.name == "*":
                        continue
                    mod.imports[a.asname or a.name] = (base, a.name)
        for stmt in ctx.tree.body:
            if isinstance(stmt, _FUNC_NODES):
                info = FunctionInfo(ctx, stmt, mod.path, stmt.name)
                mod.defs[stmt.name] = info
                self.infos[stmt] = info
            elif isinstance(stmt, ast.ClassDef):
                self._index_class(mod, stmt)
            elif isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        mod.globals.setdefault(t.id, []).append(stmt.value)
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                if isinstance(stmt.target, ast.Name):
                    mod.globals.setdefault(stmt.target.id, []).append(
                        stmt.value
                    )
        # nested defs: resolvable by bare name from their encloser only
        for fn in ctx.functions:
            if fn in self.infos:
                continue
            encl = ctx.enclosing_function(fn)
            qual = (
                f"{encl.name}.<{fn.name}>" if encl is not None else fn.name
            )
            self.infos[fn] = FunctionInfo(ctx, fn, mod.path, qual)

    def _index_class(self, mod: ModuleIndex, node: ast.ClassDef) -> None:
        ci = ClassInfo(
            node.name,
            node,
            mod.ctx,
            bases=tuple(
                dotted_name(b) for b in node.bases if dotted_name(b)
            ),
        )
        mod.classes[node.name] = ci
        for stmt in node.body:
            if isinstance(stmt, _FUNC_NODES):
                info = FunctionInfo(
                    mod.ctx, stmt, mod.path,
                    f"{node.name}.{stmt.name}", cls=node.name,
                )
                ci.methods[stmt.name] = info
                self.infos[stmt] = info
        # self.<attr> = <expr> anywhere in the class: attribute chasing
        for meth in ci.methods.values():
            for sub in ast.walk(meth.node):
                if not isinstance(sub, (ast.Assign, ast.AnnAssign)):
                    continue
                targets = (
                    sub.targets if isinstance(sub, ast.Assign) else [sub.target]
                )
                value = sub.value
                if value is None:
                    continue
                for t in targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        ci.attr_exprs.setdefault(t.attr, []).append(value)

    @staticmethod
    def _import_base(importer: str, node: ast.ImportFrom) -> str:
        if not node.level:
            return node.module or ""
        parts = importer.split(".")
        # a module's package is its path minus the leaf; each extra level
        # climbs one more package
        base = parts[: max(len(parts) - node.level, 0)]
        if node.module:
            base.append(node.module)
        return ".".join(base)

    # -- module / class resolution ------------------------------------------

    def _find_module(self, dotted: str) -> Optional[ModuleIndex]:
        if not dotted:
            return None
        if dotted in self.modules:
            return self.modules[dotted]
        hits = self._by_suffix.get(dotted)
        if hits:
            return self.modules[sorted(hits)[0]]
        return None

    def _resolve_symbol(
        self, mod: ModuleIndex, name: str, _depth: int = 0
    ):
        """A bare name in ``mod``'s namespace -> FunctionInfo | ClassInfo |
        ModuleIndex | ('global', exprs, mod) | None."""
        if _depth > 4:
            return None
        if name in mod.defs:
            return mod.defs[name]
        if name in mod.classes:
            return mod.classes[name]
        if name in mod.imports:
            target_mod, symbol = mod.imports[name]
            target = self._find_module(
                f"{target_mod}.{symbol}" if symbol else target_mod
            )
            if target is not None and symbol:
                # `from pkg import submodule` where submodule is a module
                return target
            target = self._find_module(target_mod)
            if target is None:
                return None
            if symbol is None:
                return target
            return self._resolve_symbol(target, symbol, _depth + 1)
        if name in mod.globals:
            return ("global", mod.globals[name], mod)
        return None

    def _class_of_expr(
        self, mod: ModuleIndex, expr: ast.expr, _depth: int = 0
    ) -> Optional[ClassInfo]:
        """The class an expression instantiates, if statically evident:
        ``ClassName(..)``, an alias of one, or a name bound to one."""
        if _depth > 4:
            return None
        if isinstance(expr, ast.Call):
            resolved = self._resolve_symbol(mod, dotted_name(expr.func))
            if resolved is None and isinstance(expr.func, ast.Attribute):
                # Mod.Class(..) through an import alias
                owner = self._resolve_symbol(
                    mod, dotted_name(expr.func.value)
                )
                if isinstance(owner, ModuleIndex):
                    resolved = owner.classes.get(expr.func.attr)
            if isinstance(resolved, ClassInfo):
                return resolved
        elif isinstance(expr, ast.Name):
            resolved = self._resolve_symbol(mod, expr.id)
            if isinstance(resolved, tuple) and resolved[0] == "global":
                for v in resolved[1]:
                    ci = self._class_of_expr(resolved[2], v, _depth + 1)
                    if ci is not None:
                        return ci
        return None

    def class_methods(self, ci: ClassInfo) -> Dict[str, FunctionInfo]:
        """``ci``'s methods, including ones inherited from project-local
        bases (single chase per base, no MRO subtleties needed here)."""
        out: Dict[str, FunctionInfo] = {}
        mod = self.modules.get(module_path(ci.ctx.relpath))
        for base in ci.bases:
            resolved = (
                self._resolve_symbol(mod, base) if mod is not None else None
            )
            if isinstance(resolved, ClassInfo) and resolved is not ci:
                out.update(resolved.methods)
        out.update(ci.methods)
        return out

    # -- call resolution ----------------------------------------------------

    def resolve_call(
        self, ctx: FileContext, call: ast.Call
    ) -> Tuple[FunctionInfo, ...]:
        """Every project function this call site can enter (empty = UNKNOWN,
        never 'safe'). Memoized per call node — the graph is immutable once
        built and several passes (taint, blocking, shapes) resolve the same
        sites."""
        memo = getattr(self, "_resolve_memo", None)
        if memo is None:
            memo = self._resolve_memo = {}
        hit = memo.get(call)
        if hit is not None:
            return hit
        out = self._resolve_call_uncached(ctx, call)
        memo[call] = out
        return out

    def _resolve_call_uncached(
        self, ctx: FileContext, call: ast.Call
    ) -> Tuple[FunctionInfo, ...]:
        mod = self.modules.get(module_path(ctx.relpath))
        if mod is None:
            return ()
        name = dotted_name(call.func)
        if not name:
            return ()
        # the dispatch-registry indirection: launch(name, ..) enters every
        # registered kernel impl
        if name in ("dispatch.launch", "launch") and self._dispatch_targets:
            direct = self._resolve_dotted(mod, ctx, call, name)
            return tuple(direct) + self._dispatch_targets
        return tuple(self._resolve_dotted(mod, ctx, call, name))

    def _resolve_dotted(
        self, mod: ModuleIndex, ctx: FileContext, call: ast.Call, name: str
    ) -> List[FunctionInfo]:
        parts = name.split(".")
        fn = ctx.enclosing_function(call)
        if len(parts) == 1:
            # nested def in the same scope shadows module names
            if fn is not None:
                for cand in ctx.functions:
                    if (
                        cand.name == parts[0]
                        and ctx.enclosing_function(cand) is fn
                    ):
                        return [self.infos[cand]]
            resolved = self._resolve_symbol(mod, parts[0])
            if isinstance(resolved, FunctionInfo):
                return [resolved]
            if isinstance(resolved, ClassInfo):
                init = self.class_methods(resolved).get("__init__")
                return [init] if init is not None else []
            return []
        head, rest = parts[0], parts[1:]
        if head == "self" and fn is not None:
            ci = self._enclosing_class(ctx, fn)
            if ci is None:
                return []
            if len(rest) == 1:
                meth = self.class_methods(ci).get(rest[0])
                return [meth] if meth is not None else []
            # self.attr.meth(): chase the attribute's bound class
            attr_ci = self._attr_class(mod, ci, rest[0])
            if attr_ci is not None and len(rest) == 2:
                meth = self.class_methods(attr_ci).get(rest[1])
                return [meth] if meth is not None else []
            return []
        resolved = self._resolve_symbol(mod, head)
        # obj.meth() where obj is bound in this scope: chase the binding
        if resolved is None or isinstance(resolved, tuple):
            exprs: List[ast.expr] = []
            if fn is not None:
                exprs.extend(ctx.assignments(fn, head))
            if isinstance(resolved, tuple):
                exprs.extend(resolved[1])
            for v in exprs:
                ci = self._class_of_expr(mod, v)
                if ci is not None and len(rest) == 1:
                    meth = self.class_methods(ci).get(rest[0])
                    return [meth] if meth is not None else []
            return []
        for seg in rest[:-1]:
            if isinstance(resolved, ModuleIndex):
                resolved = self._resolve_symbol(resolved, seg)
            elif isinstance(resolved, ClassInfo):
                resolved = self.class_methods(resolved).get(seg)
            else:
                return []
            if resolved is None:
                return []
        leaf = rest[-1]
        if isinstance(resolved, ModuleIndex):
            final = self._resolve_symbol(resolved, leaf)
            if isinstance(final, FunctionInfo):
                return [final]
            if isinstance(final, ClassInfo):
                init = self.class_methods(final).get("__init__")
                return [init] if init is not None else []
            if isinstance(final, tuple):
                # imported singleton instance: its class's methods? no —
                # leaf IS the global; a call on a global is handled below
                pass
            return []
        if isinstance(resolved, ClassInfo):
            meth = self.class_methods(resolved).get(leaf)
            return [meth] if meth is not None else []
        return []

    def _enclosing_class(
        self, ctx: FileContext, fn: ast.AST
    ) -> Optional[ClassInfo]:
        mod = self.modules.get(module_path(ctx.relpath))
        if mod is None:
            return None
        node = ctx.parent.get(fn)
        while node is not None:
            if isinstance(node, ast.ClassDef):
                return mod.classes.get(node.name)
            node = ctx.parent.get(node)
        return None

    def _attr_class(
        self, mod: ModuleIndex, ci: ClassInfo, attr: str
    ) -> Optional[ClassInfo]:
        for expr in ci.attr_exprs.get(attr, []):
            found = self._class_of_expr(mod, expr)
            if found is not None:
                return found
        return None

    # -- edges --------------------------------------------------------------

    def _build_edges(self) -> None:
        for info in list(self.infos.values()):
            ctx = info.ctx
            sites: List[Tuple[CallSite, Tuple[FunctionInfo, ...]]] = []
            for call in ctx.calls_in(info.node):
                site = CallSite(
                    info, call, ctx, self._in_lambda(ctx, call, info.node)
                )
                targets = self.resolve_call(ctx, call)
                sites.append((site, targets))
                for tgt in targets:
                    self._callers.setdefault(tgt.node, []).append(site)
            self._callees[info.node] = sites

    @staticmethod
    def _in_lambda(ctx: FileContext, node: ast.AST, stop: ast.AST) -> bool:
        cur = ctx.parent.get(node)
        while cur is not None and cur is not stop:
            if isinstance(cur, ast.Lambda):
                return True
            cur = ctx.parent.get(cur)
        return False

    def callees(
        self, info: FunctionInfo
    ) -> List[Tuple[CallSite, Tuple[FunctionInfo, ...]]]:
        return self._callees.get(info.node, [])

    def callers(self, info: FunctionInfo) -> List[CallSite]:
        return self._callers.get(info.node, [])

    def info_for(self, node: ast.AST) -> Optional[FunctionInfo]:
        return self.infos.get(node)

    # -- lane analysis ------------------------------------------------------

    def lane_roots(self) -> Set[ast.AST]:
        """Function nodes handed to a worker lane by reference: arguments
        of ``run_in_executor`` / ``to_thread`` / ``submit`` /
        ``Context().run`` / ``Thread(target=..)`` / ``Process(target=..)``
        sinks, plus the call targets inside lambdas passed to those sinks
        (the lambda body runs ON the lane). A ``multiprocessing.Process``
        target is a lane like any other for race purposes: bound-method
        targets drag ``self`` across the spawn boundary, so loop-affine
        state reached from one is just as suspect as from a thread.
        Cached — the graph is immutable once built."""
        cached = getattr(self, "_lane_roots", None)
        if cached is not None:
            return cached
        roots: Set[ast.AST] = set()
        for mod in self.modules.values():
            ctx = mod.ctx
            for call in ctx.calls:
                name = dotted_name(call.func)
                leaf = name.split(".")[-1] if name else ""
                cand_args: List[ast.expr] = []
                if leaf in LANE_SINKS:
                    cand_args = list(call.args) + [
                        kw.value for kw in call.keywords
                    ]
                elif leaf in ("Thread", "Process"):
                    cand_args = [
                        kw.value for kw in call.keywords if kw.arg == "target"
                    ]
                for arg in cand_args:
                    roots.update(self._callable_targets(ctx, call, arg))
        self._lane_roots = roots
        return roots

    def _callable_targets(
        self, ctx: FileContext, call: ast.Call, arg: ast.expr
    ) -> Set[ast.AST]:
        out: Set[ast.AST] = set()
        if isinstance(arg, ast.Lambda):
            for sub in ast.walk(arg.body):
                if isinstance(sub, ast.Call):
                    for tgt in self.resolve_call(ctx, sub):
                        out.add(tgt.node)
            return out
        name = dotted_name(arg)
        if not name:
            return out
        # a bare function/method REFERENCE: resolve it like a call to it
        fake = ast.Call(func=arg, args=[], keywords=[])
        ast.copy_location(fake, call)
        # reuse the enclosing-function index of the sink call
        ctx._enclosing[fake] = ctx.enclosing_function(call)  # noqa: SLF001
        for tgt in self.resolve_call(ctx, fake):
            out.add(tgt.node)
        return out

    def lane_reachable(self) -> Set[ast.AST]:
        """Closure of ``lane_roots`` over call edges: every function that
        can execute on a worker lane (thread / fresh context), as opposed
        to the asyncio event loop. Cached — the graph is immutable."""
        cached = getattr(self, "_lane_reachable", None)
        if cached is not None:
            return cached
        seen: Set[ast.AST] = set()
        frontier = list(self.lane_roots())
        while frontier:
            node = frontier.pop()
            if node in seen:
                continue
            seen.add(node)
            info = self.infos.get(node)
            if info is None:
                continue
            for _site, targets in self.callees(info):
                for tgt in targets:
                    if tgt.node not in seen:
                        frontier.append(tgt.node)
        self._lane_reachable = seen
        return seen

"""Stored-pattern vocabulary: the scan shapes a graph can answer.

Re-design of the reference's ``Pattern`` family
(``okapi-api/.../api/graph/Pattern.scala:135-182``): a graph's element
tables may store composite patterns — a node co-stored with its outgoing
relationships (``NodeRelPattern``) or a full (source, rel, target) triplet
(``TripletPattern``) — and ``find_mapping`` embeds a search pattern into a
stored one (same shape; each search element type a supertype of the stored
element type, or equal under ``exact``). The logical optimizer uses this to
collapse Expand cascades into single ``PatternScan``s
(``LogicalOptimizer.scala:67``)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Tuple

from . import types as T

# canonical entity names inside a stored pattern (reference DEFAULT_NODE_NAME
# / "source_"/"target_" prefixes, Pattern.scala:135-182)
NODE_ENTITY = "node"
REL_ENTITY = "rel"
SOURCE_ENTITY = "source_node"
TARGET_ENTITY = "target_node"


def _node_subtype(search: T.CTNodeType, stored: T.CTNodeType) -> bool:
    """search ⊒ stored: every stored row satisfies the search type — i.e.
    the search label set is a subset of the stored labels."""
    return frozenset(search.labels) <= frozenset(stored.labels)


def _rel_subtype(search: T.CTRelationshipType, stored: T.CTRelationshipType) -> bool:
    if not search.types:  # untyped search matches any stored types
        return True
    if not stored.types:  # stored any-type cannot be guaranteed to satisfy
        return False
    return frozenset(stored.types) <= frozenset(search.types)


@dataclass(frozen=True)
class GraphPattern:
    """Base class; subclasses define ``entities`` (name -> CypherType)."""

    def entities(self) -> Dict[str, T.CypherType]:
        raise NotImplementedError

    def find_mapping(
        self, search: "GraphPattern", exact: bool = False
    ) -> Optional[Dict[str, str]]:
        """Embed ``search`` into this STORED pattern: same shape, pairwise
        type embedding. Returns {search entity -> stored entity} or None
        (reference ``Pattern.findMapping``)."""
        if type(search) is not type(self):
            return None
        pairs = list(zip(search.entities().items(), self.entities().items()))
        for (sn, st), (on, ot) in pairs:
            if exact:
                if st != ot:
                    return None
            elif isinstance(st, T.CTNodeType) and isinstance(ot, T.CTNodeType):
                if not _node_subtype(st, ot):
                    return None
            elif isinstance(st, T.CTRelationshipType) and isinstance(
                ot, T.CTRelationshipType
            ):
                if not _rel_subtype(st, ot):
                    return None
            else:
                return None
        return {sn: on for (sn, _), (on, _) in pairs}


@dataclass(frozen=True)
class NodePattern(GraphPattern):
    node_type: T.CTNodeType

    def entities(self) -> Dict[str, T.CypherType]:
        return {NODE_ENTITY: self.node_type}


@dataclass(frozen=True)
class RelationshipPattern(GraphPattern):
    rel_type: T.CTRelationshipType

    def entities(self) -> Dict[str, T.CypherType]:
        return {REL_ENTITY: self.rel_type}


@dataclass(frozen=True)
class NodeRelPattern(GraphPattern):
    """A node co-stored with one of its OUTGOING relationships."""

    node_type: T.CTNodeType
    rel_type: T.CTRelationshipType

    def entities(self) -> Dict[str, T.CypherType]:
        return {NODE_ENTITY: self.node_type, REL_ENTITY: self.rel_type}


@dataclass(frozen=True)
class TripletPattern(GraphPattern):
    """(source)-[rel]->(target) stored in one table."""

    source_type: T.CTNodeType
    rel_type: T.CTRelationshipType
    target_type: T.CTNodeType

    def entities(self) -> Dict[str, T.CypherType]:
        return {
            SOURCE_ENTITY: self.source_type,
            REL_ENTITY: self.rel_type,
            TARGET_ENTITY: self.target_type,
        }

"""Cypher runtime values.

TPU-native re-design of the reference's boxed ``CypherValue`` hierarchy
(``okapi-api/src/main/scala/org/opencypher/okapi/api/value/CypherValue.scala:139``):
instead of boxing everything we use Python natives (None/bool/int/float/str/
Decimal/date/datetime/list/dict) plus dedicated classes for graph elements
(``Node`` ≈ ``CypherValue.scala:382``, ``Relationship`` ≈ ``:428``), ``Duration``
and row maps (``CypherMap`` ≈ ``:301``).

Two notions of sameness (reference distinguishes equality vs equivalence):

* ``cypher_equals(a, b)`` — ternary Cypher ``=``: returns None when either side
  is null (or a list/map containing null compares inconclusively).
* ``cypher_equivalent(a, b)`` — boolean, null ≡ null, NaN ≡ NaN; used for
  DISTINCT, grouping and test-bag comparison.
"""

from __future__ import annotations

import datetime as _dt
import math
from decimal import Decimal
from typing import Any, Dict, Iterable, List, Mapping, Optional, Tuple


class Duration:
    """Cypher duration: months / days / seconds / microseconds components.

    Mirrors ``okapi-api/.../impl/temporal/Duration.scala`` — calendar-aware
    (months and days don't normalize into seconds).
    """

    __slots__ = ("months", "days", "seconds", "microseconds")

    def __init__(self, months: int = 0, days: int = 0, seconds: int = 0, microseconds: int = 0):
        # normalize micros into seconds, keep months/days separate
        extra_s, us = divmod(microseconds, 1_000_000)
        self.months = int(months)
        self.days = int(days)
        self.seconds = int(seconds + extra_s)
        self.microseconds = int(us)

    @staticmethod
    def of(
        years: float = 0,
        months: float = 0,
        weeks: float = 0,
        days: float = 0,
        hours: float = 0,
        minutes: float = 0,
        seconds: float = 0,
        milliseconds: float = 0,
        microseconds: float = 0,
        nanoseconds: float = 0,
    ) -> "Duration":
        total_months = years * 12 + months
        whole_months = int(total_months)
        frac_month_days = (total_months - whole_months) * 30.4375  # avg month
        total_days = weeks * 7 + days + frac_month_days
        whole_days = int(total_days)
        frac_day_secs = (total_days - whole_days) * 86400
        total_secs = hours * 3600 + minutes * 60 + seconds + frac_day_secs
        whole_secs = int(total_secs)
        total_us = (
            (total_secs - whole_secs) * 1e6
            + milliseconds * 1000
            + microseconds
            + nanoseconds / 1000
        )
        return Duration(whole_months, whole_days, whole_secs, round(total_us))

    # total microseconds treating a month as 30.4375 days? Reference compares
    # durations by their components; we expose a canonical tuple instead.
    def _key(self) -> Tuple[int, int, int, int]:
        return (self.months, self.days, self.seconds, self.microseconds)

    def total_seconds_approx(self) -> float:
        return (
            self.months * 30.4375 * 86400
            + self.days * 86400
            + self.seconds
            + self.microseconds / 1e6
        )

    def __eq__(self, other) -> bool:
        return isinstance(other, Duration) and self._key() == other._key()

    def __hash__(self) -> int:
        return hash(("Duration",) + self._key())

    def __add__(self, other: "Duration") -> "Duration":
        if not isinstance(other, Duration):
            return NotImplemented
        return Duration(
            self.months + other.months,
            self.days + other.days,
            self.seconds + other.seconds,
            self.microseconds + other.microseconds,
        )

    def __sub__(self, other: "Duration") -> "Duration":
        if not isinstance(other, Duration):
            return NotImplemented
        return Duration(
            self.months - other.months,
            self.days - other.days,
            self.seconds - other.seconds,
            self.microseconds - other.microseconds,
        )

    def __neg__(self) -> "Duration":
        return Duration(-self.months, -self.days, -self.seconds, -self.microseconds)

    def __repr__(self) -> str:
        return f"Duration(months={self.months}, days={self.days}, seconds={self.seconds}, microseconds={self.microseconds})"

    def cypher_str(self) -> str:
        """ISO-8601-ish rendering, e.g. P1Y2M3DT4H5M6.007S.

        Components carry their own sign (Neo4j-style): months, days and the
        time part are each rendered signed, truncating toward zero.
        """
        y = int(self.months / 12) if self.months else 0
        mo = self.months - 12 * y
        out = "P"
        if y:
            out += f"{y}Y"
        if mo:
            out += f"{mo}M"
        if self.days:
            out += f"{self.days}D"
        us_total = self.seconds * 1_000_000 + self.microseconds
        if us_total:
            neg = "-" if us_total < 0 else ""
            a = abs(us_total)
            h, rem = divmod(a, 3_600_000_000)
            m, rem = divmod(rem, 60_000_000)
            s, us = divmod(rem, 1_000_000)
            out += "T"
            if h:
                out += f"{neg}{h}H"
            if m:
                out += f"{neg}{m}M"
            if s or us:
                if us:
                    frac = f"{us / 1e6:.6f}".split(".")[1].rstrip("0")
                    out += f"{neg}{s}.{frac}S"
                else:
                    out += f"{neg}{s}S"
        if out == "P":
            out = "PT0S"
        return out


class Element:
    """Common base for Node / Relationship (reference ``CypherElement``)."""

    __slots__ = ("id", "properties")

    def __init__(self, id: int, properties: Optional[Mapping[str, Any]] = None):
        self.id = id
        self.properties = dict(properties or {})


class Node(Element):
    """Reference: ``CypherValue.scala:382`` (id-typed; here int64 ids)."""

    __slots__ = ("labels",)

    def __init__(self, id: int, labels: Iterable[str] = (), properties: Optional[Mapping[str, Any]] = None):
        super().__init__(id, properties)
        self.labels = frozenset(labels)

    def __eq__(self, other) -> bool:
        return isinstance(other, Node) and other.id == self.id

    def __hash__(self) -> int:
        return hash(("Node", self.id))

    def __repr__(self) -> str:
        lbl = "".join(f":{l}" for l in sorted(self.labels))
        props = ", ".join(f"{k}: {to_cypher_string(v)}" for k, v in sorted(self.properties.items()))
        inner = " ".join(x for x in [lbl, "{" + props + "}" if props else ""] if x)
        return f"({inner})"


class Relationship(Element):
    """Reference: ``CypherValue.scala:428``."""

    __slots__ = ("start", "end", "rel_type")

    def __init__(
        self,
        id: int,
        start: int,
        end: int,
        rel_type: str,
        properties: Optional[Mapping[str, Any]] = None,
    ):
        super().__init__(id, properties)
        self.start = start
        self.end = end
        self.rel_type = rel_type

    def __eq__(self, other) -> bool:
        return isinstance(other, Relationship) and other.id == self.id

    def __hash__(self) -> int:
        return hash(("Relationship", self.id))

    def __repr__(self) -> str:
        props = ", ".join(f"{k}: {to_cypher_string(v)}" for k, v in sorted(self.properties.items()))
        inner = ":" + self.rel_type + (" {" + props + "}" if props else "")
        return f"[{inner}]"


class Path:
    __slots__ = ("elements",)

    def __init__(self, elements: Iterable[Element]):
        self.elements = tuple(elements)

    def __eq__(self, other) -> bool:
        return isinstance(other, Path) and self.elements == other.elements

    def __hash__(self) -> int:
        return hash(("Path", self.elements))

    def __repr__(self) -> str:
        # TCK-style: <(:A)-[:R]->(:B)>; arrow orientation from the stored
        # relationship endpoints relative to the previous node in the walk
        out = []
        prev_node_id = None
        for e in self.elements:
            if isinstance(e, Relationship):
                if prev_node_id is not None and e.start == prev_node_id:
                    out.append(f"-{e!r}->")
                    prev_node_id = None
                else:
                    out.append(f"<-{e!r}-")
                    prev_node_id = None
            else:
                out.append(repr(e))
                prev_node_id = e.id
        return "<" + "".join(out) + ">"


class CypherMap(dict):
    """A row of named Cypher values (reference ``CypherMap``, ``:301``).

    Hash/eq use *equivalence* so CypherMaps can live in Bags (multisets).
    """

    def __hash__(self) -> int:  # type: ignore[override]
        return hash(tuple(sorted((k, _equiv_key(v)) for k, v in self.items())))

    def __eq__(self, other) -> bool:  # type: ignore[override]
        if not isinstance(other, Mapping) or set(self.keys()) != set(other.keys()):
            return False
        return all(cypher_equivalent(self[k], other[k]) for k in self)

    def __ne__(self, other) -> bool:  # type: ignore[override]
        return not self.__eq__(other)

    def __repr__(self) -> str:
        return "{" + ", ".join(f"{k}: {to_cypher_string(v)}" for k, v in self.items()) + "}"


# ---------------------------------------------------------------------------
# Equality / equivalence / ordering
# ---------------------------------------------------------------------------


def cypher_equals(a, b) -> Optional[bool]:
    """Ternary Cypher ``=``; None means unknown (null semantics)."""
    if a is None or b is None:
        return None
    if isinstance(a, bool) != isinstance(b, bool):
        return False
    if isinstance(a, bool):
        return a == b
    if isinstance(a, (int, float, Decimal)) and isinstance(b, (int, float, Decimal)):
        if isinstance(a, float) and math.isnan(a):
            return False
        if isinstance(b, float) and math.isnan(b):
            return False
        # Python's cross-type numeric == is exact — no float64 collapse of
        # ints beyond 2**53 (graph-tagged element ids live at 2**54+)
        return a == b
    if isinstance(a, (list, tuple)) and isinstance(b, (list, tuple)):
        if len(a) != len(b):
            return False
        saw_null = False
        for x, y in zip(a, b):
            r = cypher_equals(x, y)
            if r is False:
                return False
            if r is None:
                saw_null = True
        return None if saw_null else True
    if (
        isinstance(a, Mapping)
        and isinstance(b, Mapping)
        and not isinstance(a, Element)
        and not isinstance(b, Element)
    ):
        if set(a.keys()) != set(b.keys()):
            return False
        saw_null = False
        for k in a:
            r = cypher_equals(a[k], b[k])
            if r is False:
                return False
            if r is None:
                saw_null = True
        return None if saw_null else True
    if type(a) is not type(b) and not (
        isinstance(a, Element) and isinstance(b, Element)
    ):
        if isinstance(a, (str,)) and isinstance(b, (str,)):
            pass
        else:
            return False
    return a == b


def cypher_equivalent(a, b) -> bool:
    """Equivalence: null ≡ null, NaN ≡ NaN. Used for DISTINCT/grouping/tests."""
    if a is None and b is None:
        return True
    if a is None or b is None:
        return False
    if isinstance(a, bool) != isinstance(b, bool):
        return False
    if isinstance(a, (int, float, Decimal)) and isinstance(b, (int, float, Decimal)):
        a_nan = _num_is_nan(a)
        b_nan = _num_is_nan(b)
        if a_nan or b_nan:
            return a_nan and b_nan
        return a == b  # exact cross-type numeric equality
    if isinstance(a, (list, tuple)) and isinstance(b, (list, tuple)):
        return len(a) == len(b) and all(cypher_equivalent(x, y) for x, y in zip(a, b))
    if (
        isinstance(a, Mapping)
        and isinstance(b, Mapping)
        and not isinstance(a, Element)
        and not isinstance(b, Element)
    ):
        return set(a.keys()) == set(b.keys()) and all(
            cypher_equivalent(a[k], b[k]) for k in a
        )
    return a == b


def _num_is_nan(x) -> bool:
    return (isinstance(x, float) and math.isnan(x)) or (
        isinstance(x, Decimal) and x.is_nan()
    )


def _equiv_key(v) -> Any:
    """A hashable key st. equivalence-equal values share a key — must agree
    with :func:`cypher_equivalent` (used for DISTINCT/grouping/hash joins)."""
    if v is None:
        return ("null",)
    if isinstance(v, bool):
        return ("bool", v)
    if isinstance(v, (int, float, Decimal)):
        # ints/Decimals exactly representable in float64 share the float's
        # key (Cypher equivalence: 1 = 1.0); beyond 2**53 the float would
        # collapse distinct ids (graph-tagged element ids live at 2**54+),
        # so non-representable values key on their exact value
        if isinstance(v, int):
            try:
                f = float(v)
            except OverflowError:  # ints >= ~1.8e308
                return ("num", v)
            if not math.isinf(f) and int(f) == v:
                return ("num", f)
            return ("num", v)
        if isinstance(v, Decimal):
            if v.is_nan():
                return ("nan",)
            if v.is_infinite():
                return ("num", math.inf if v > 0 else -math.inf)
            try:
                f = float(v)
            except OverflowError:
                f = math.inf if v > 0 else -math.inf
            if not math.isinf(f) and Decimal(f) == v:
                return ("num", f)  # exactly representable: shares float key
            if v == v.to_integral_value():
                return ("num", int(v))  # exact integral beyond float range
            return ("num", "dec", str(v.normalize()))  # exact non-integral
        f = v  # plain float
        if math.isnan(f):
            return ("nan",)
        return ("num", f)
    if isinstance(v, (list, tuple)):
        return ("list", tuple(_equiv_key(x) for x in v))
    if isinstance(v, Element):
        return ("elem", v.id)
    if isinstance(v, Mapping):
        return ("map", tuple(sorted((k, _equiv_key(x)) for k, x in v.items())))
    return ("v", v)


_TYPE_ORDER = {
    # Cypher global sort order (descending per openCypher): MAP > NODE > REL >
    # LIST > PATH > STRING > BOOLEAN > NUMBER > VOID(null last in ASC)
    "map": 0,
    "node": 1,
    "relationship": 2,
    "list": 3,
    "path": 4,
    "string": 5,
    "boolean": 6,
    "number": 7,
    # temporal instants fall in the default "other" class (8; ISO strings
    # order chronologically); durations get their own slot with an
    # average-length key (below)
    "duration": 9,
}

# duration order key basis: average-length microseconds with a month of
# 30.4375 days (the reference compares CalendarIntervals by their converted
# java.time.Duration, TemporalUdafs.scala; same constants as the device key
# in backend/tpu/column.py). Ties are resolved by stability (first
# occurrence) on BOTH backends, never by value.
_DUR_MONTH_US = 2_629_800_000_000
_DUR_DAY_US = 86_400_000_000


def duration_order_us(v: "Duration") -> int:
    return (
        v.months * _DUR_MONTH_US
        + v.days * _DUR_DAY_US
        + v.seconds * 1_000_000
        + v.microseconds
    )


def _order_class(v) -> str:
    if isinstance(v, Duration):
        return "duration"
    if isinstance(v, Node):
        return "node"
    if isinstance(v, Relationship):
        return "relationship"
    if isinstance(v, Path):
        return "path"
    if isinstance(v, bool):
        return "boolean"
    if isinstance(v, (int, float, Decimal)):
        return "number"
    if isinstance(v, str):
        return "string"
    if isinstance(v, (list, tuple)):
        return "list"
    if isinstance(v, Mapping):
        return "map"
    return "other"


def order_key(v):
    """Total-order sort key implementing Cypher's orderability.

    Nulls sort last ascending (caller appends null flag first).
    """
    if v is None:
        return (1, 0, 0)
    cls = _order_class(v)
    o = _TYPE_ORDER.get(cls, 8)
    if cls == "number":
        if isinstance(v, int):
            # keep ints exact: float64 would collapse ids beyond 2**53
            # (Python orders int vs float exactly, so mixing is safe)
            key = (False, v)
        else:
            f = float(v)
            key = (math.isnan(f), f)  # NaN greater than all numbers
    elif cls == "boolean":
        key = v
    elif cls == "string":
        key = v
    elif cls in ("node", "relationship"):
        key = v.id
    elif cls == "list":
        key = tuple(order_key(x) for x in v)
    elif cls == "map":
        key = tuple(sorted((k, order_key(x)) for k, x in v.items()))
    elif cls == "duration":
        key = duration_order_us(v)
    else:
        key = str(v)
    return (0, o, key)


# ---------------------------------------------------------------------------
# Formatting
# ---------------------------------------------------------------------------


def to_cypher_string(v) -> str:
    """Render a value the way Cypher would print it."""
    if v is None:
        return "null"
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float):
        if math.isnan(v):
            return "NaN"
        if math.isinf(v):
            return "Infinity" if v > 0 else "-Infinity"
        if v == int(v) and abs(v) < 1e15:
            return f"{v:.1f}"
        return repr(v)
    if isinstance(v, int):
        return str(v)
    if isinstance(v, str):
        return "'" + v.replace("\\", "\\\\").replace("'", "\\'") + "'"
    if isinstance(v, Duration):
        return f"'{v.cypher_str()}'"
    if isinstance(v, _dt.datetime):
        return f"'{v.isoformat()}'"
    if isinstance(v, _dt.date):
        return f"'{v.isoformat()}'"
    if isinstance(v, (Node, Relationship)):
        return repr(v)
    if isinstance(v, (list, tuple)):
        return "[" + ", ".join(to_cypher_string(x) for x in v) + "]"
    if isinstance(v, Mapping):
        return "{" + ", ".join(f"{k}: {to_cypher_string(x)}" for k, x in v.items()) + "}"
    if isinstance(v, Decimal):
        return str(v)
    return str(v)


def format_utc_offset(total_seconds: int) -> str:
    """'+HH:MM' (':SS' only when nonzero) — ONE formatter for zone offsets,
    shared by the oracle accessors and the device column metadata."""
    sign = "+" if total_seconds >= 0 else "-"
    h, rem = divmod(abs(int(total_seconds)), 3600)
    m, sec = divmod(rem, 60)
    base = f"{sign}{h:02d}:{m:02d}"
    return base + (f":{sec:02d}" if sec else "")

"""Cypher structural type lattice.

TPU-native re-design of the reference's ``CypherType`` system
(``okapi-api/src/main/scala/org/opencypher/okapi/api/types/CypherType.scala:32``):
a structural lattice with ``CTNode(labels)`` / ``CTRelationship(types)`` element
types, ``CTList``/``CTMap`` containers, union types (``CTUnion``, reference
``CypherType.scala:284``), and nullability modelled as union-with-``CTNull``.

Unlike the JVM reference this module is deliberately *hashable-frozen-dataclass*
flavoured so types can key dictionaries (RecordHeader) and be compared
structurally. The lattice operations are ``subtype_of``, ``join`` (least upper
bound) and ``meet`` (greatest lower bound).
"""

from __future__ import annotations

from typing import FrozenSet, Iterable, Mapping, Optional


import re as _re

_IDENT = _re.compile(r"[A-Za-z_][A-Za-z0-9_]*\Z")


def _esc(name: str) -> str:
    """Backtick-escape names that aren't plain identifiers (parser round-trip)."""
    return name if _IDENT.match(name) else f"`{name}`"


class CypherType:
    """Base class for all Cypher types. Immutable, hashable."""

    __slots__ = ()

    # -- nullability ------------------------------------------------------

    @property
    def is_nullable(self) -> bool:
        return False

    @property
    def nullable(self) -> "CypherType":
        """This type or null."""
        if self.is_nullable:
            return self
        return CTUnion.of(self, CTNull)

    @property
    def material(self) -> "CypherType":
        """This type without null."""
        return self

    # -- lattice ----------------------------------------------------------

    def subtype_of(self, other: "CypherType") -> bool:
        if self == other:
            return True
        # ANY is the *material* top: it does not include null
        if isinstance(other, CTAnyType) and not self.is_nullable:
            return True
        if isinstance(other, CTUnion):
            return any(self.subtype_of(a) for a in other.alternatives)
        return self._subtype_of_material(other)

    def _subtype_of_material(self, other: "CypherType") -> bool:
        return False

    def supertype_of(self, other: "CypherType") -> bool:
        return other.subtype_of(self)

    def join(self, other: "CypherType") -> "CypherType":
        """Least upper bound."""
        if self.subtype_of(other):
            return other
        if other.subtype_of(self):
            return self
        special = self._join_special(other) or other._join_special(self)
        if special is not None:
            return special
        return CTUnion.of(self, other)

    def _join_special(self, other: "CypherType") -> Optional["CypherType"]:
        return None

    def meet(self, other: "CypherType") -> "CypherType":
        """Greatest lower bound."""
        if self.subtype_of(other):
            return self
        if other.subtype_of(self):
            return other
        special = self._meet_special(other) or other._meet_special(self)
        if special is not None:
            return special
        return CTVoid

    def _meet_special(self, other: "CypherType") -> Optional["CypherType"]:
        return None

    def couldBe(self, other: "CypherType") -> bool:
        return self.meet(other) != CTVoid

    # -- misc --------------------------------------------------------------

    @property
    def name(self) -> str:
        return repr(self)

    def __repr__(self) -> str:  # pragma: no cover - overridden
        return self.__class__.__name__


# ---------------------------------------------------------------------------
# Leaf / singleton types
# ---------------------------------------------------------------------------


class _Singleton(CypherType):
    __slots__ = ()
    _NAME = "?"

    def __repr__(self) -> str:
        return self._NAME

    def __eq__(self, other) -> bool:
        return type(self) is type(other)

    def __hash__(self) -> int:
        return hash(type(self).__name__)


class CTAnyType(_Singleton):
    """Top of the material lattice (does not include null)."""

    _NAME = "ANY"

    def _subtype_of_material(self, other: CypherType) -> bool:
        return isinstance(other, CTAnyType)


class CTVoidType(_Singleton):
    """Bottom (no value)."""

    _NAME = "VOID"

    def subtype_of(self, other: CypherType) -> bool:
        return True


class CTNullType(_Singleton):
    _NAME = "NULL"

    @property
    def is_nullable(self) -> bool:
        return True

    @property
    def material(self) -> CypherType:
        return CTVoid

    def _subtype_of_material(self, other: CypherType) -> bool:
        return other.is_nullable


class CTBooleanType(_Singleton):
    _NAME = "BOOLEAN"


class CTStringType(_Singleton):
    _NAME = "STRING"


class CTIntegerType(_Singleton):
    _NAME = "INTEGER"

    def _subtype_of_material(self, other: CypherType) -> bool:
        return isinstance(other, CTNumberType)


class CTFloatType(_Singleton):
    _NAME = "FLOAT"

    def _subtype_of_material(self, other: CypherType) -> bool:
        return isinstance(other, CTNumberType)


class CTNumberType(_Singleton):
    """Supertype of INTEGER and FLOAT (reference: CTNumber = union)."""

    _NAME = "NUMBER"


class CTDateType(_Singleton):
    _NAME = "DATE"


class CTLocalDateTimeType(_Singleton):
    _NAME = "LOCALDATETIME"


class CTDateTimeType(_Singleton):
    """Zoned datetime (instant + zone offset) — reference CTDateTime; its
    ``TemporalUdfs.scala:40`` warns on timezone loss, we keep the offset."""

    _NAME = "DATETIME"


class CTLocalTimeType(_Singleton):
    _NAME = "LOCALTIME"


class CTTimeType(_Singleton):
    """Zoned time-of-day (local micros + zone offset) — reference CTTime."""

    _NAME = "TIME"


class CTDurationType(_Singleton):
    _NAME = "DURATION"


class CTBigDecimalType(CypherType):
    """BIGDECIMAL(precision, scale) — reference CTBigDecimal."""

    __slots__ = ("precision", "scale")

    def __init__(self, precision: int = 38, scale: int = 18):
        object.__setattr__(self, "precision", precision)
        object.__setattr__(self, "scale", scale)

    def __repr__(self) -> str:
        return f"BIGDECIMAL({self.precision},{self.scale})"

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, CTBigDecimalType)
            and self.precision == other.precision
            and self.scale == other.scale
        )

    def __hash__(self) -> int:
        return hash(("BIGDECIMAL", self.precision, self.scale))

    def _subtype_of_material(self, other: CypherType) -> bool:
        return isinstance(other, CTNumberType)


class CTPathType(_Singleton):
    _NAME = "PATH"


class CTElementIdType(_Singleton):
    """Internal: an element id column type (int64 on device)."""

    _NAME = "ELEMENTID"


# ---------------------------------------------------------------------------
# Element types
# ---------------------------------------------------------------------------


class CTNodeType(CypherType):
    """Node with *at least* the given labels: more labels = more specific.

    Reference: ``CypherType.scala:222`` — ``CTNode(labels)``; subtyping is
    label-superset.
    """

    __slots__ = ("labels",)

    def __init__(self, labels: Iterable[str] = ()):  # noqa: D401
        object.__setattr__(self, "labels", frozenset(labels))

    def __repr__(self) -> str:
        if not self.labels:
            return "NODE"
        return "NODE(" + ":".join(_esc(l) for l in sorted(self.labels)) + ")"

    def __eq__(self, other) -> bool:
        return isinstance(other, CTNodeType) and self.labels == other.labels

    def __hash__(self) -> int:
        return hash(("NODE", self.labels))

    def _subtype_of_material(self, other: CypherType) -> bool:
        return isinstance(other, CTNodeType) and other.labels <= self.labels

    def _join_special(self, other: CypherType) -> Optional[CypherType]:
        if isinstance(other, CTNodeType):
            return CTNodeType(self.labels & other.labels)
        return None

    def _meet_special(self, other: CypherType) -> Optional[CypherType]:
        if isinstance(other, CTNodeType):
            return CTNodeType(self.labels | other.labels)
        return None


class CTRelationshipType(CypherType):
    """Relationship with type in the given set (empty = any type).

    Reference: ``CypherType.scala:242`` — ``CTRelationship(types)``; a
    relationship has exactly one type, so *fewer* alternatives = more specific.
    """

    __slots__ = ("types",)

    def __init__(self, types: Iterable[str] = ()):  # noqa: D401
        object.__setattr__(self, "types", frozenset(types))

    def __repr__(self) -> str:
        if not self.types:
            return "RELATIONSHIP"
        return "RELATIONSHIP(" + "|".join(_esc(t) for t in sorted(self.types)) + ")"

    def __eq__(self, other) -> bool:
        return isinstance(other, CTRelationshipType) and self.types == other.types

    def __hash__(self) -> int:
        return hash(("RELATIONSHIP", self.types))

    def _subtype_of_material(self, other: CypherType) -> bool:
        if not isinstance(other, CTRelationshipType):
            return False
        if not other.types:
            return True
        return bool(self.types) and self.types <= other.types

    def _join_special(self, other: CypherType) -> Optional[CypherType]:
        if isinstance(other, CTRelationshipType):
            if not self.types or not other.types:
                return CTRelationshipType()
            return CTRelationshipType(self.types | other.types)
        return None

    def _meet_special(self, other: CypherType) -> Optional[CypherType]:
        if isinstance(other, CTRelationshipType):
            if not self.types:
                return other
            if not other.types:
                return self
            inter = self.types & other.types
            return CTRelationshipType(inter) if inter else CTVoid
        return None


# ---------------------------------------------------------------------------
# Container types
# ---------------------------------------------------------------------------


class CTListType(CypherType):
    __slots__ = ("inner",)

    def __init__(self, inner: CypherType):
        object.__setattr__(self, "inner", inner)

    def __repr__(self) -> str:
        return f"LIST({self.inner!r})"

    def __eq__(self, other) -> bool:
        return isinstance(other, CTListType) and self.inner == other.inner

    def __hash__(self) -> int:
        return hash(("LIST", self.inner))

    def _subtype_of_material(self, other: CypherType) -> bool:
        return isinstance(other, CTListType) and self.inner.subtype_of(other.inner)

    def _join_special(self, other: CypherType) -> Optional[CypherType]:
        if isinstance(other, CTListType):
            return CTListType(self.inner.join(other.inner))
        return None

    def _meet_special(self, other: CypherType) -> Optional[CypherType]:
        if isinstance(other, CTListType):
            return CTListType(self.inner.meet(other.inner))
        return None


class CTMapType(CypherType):
    """Map with known fields (width subtyping) or CTMapType(None) = any map."""

    __slots__ = ("fields",)

    def __init__(self, fields: Optional[Mapping[str, CypherType]] = None):
        object.__setattr__(
            self,
            "fields",
            None if fields is None else tuple(sorted(fields.items())),
        )

    @property
    def fields_dict(self) -> Optional[dict]:
        return None if self.fields is None else dict(self.fields)

    def __repr__(self) -> str:
        if self.fields is None:
            return "MAP"
        inner = ", ".join(f"{_esc(k)}: {v!r}" for k, v in self.fields)
        return f"MAP({inner})"

    def __eq__(self, other) -> bool:
        return isinstance(other, CTMapType) and self.fields == other.fields

    def __hash__(self) -> int:
        return hash(("MAP", self.fields))

    def _subtype_of_material(self, other: CypherType) -> bool:
        if not isinstance(other, CTMapType):
            return False
        if other.fields is None:
            return True
        if self.fields is None:
            return False
        mine = dict(self.fields)
        theirs = dict(other.fields)
        # every key of ours must be known to `other`; keys we lack must be
        # nullable there (join marks one-sided keys nullable, keeping join
        # an upper bound)
        if not set(mine) <= set(theirs):
            return False
        return all(
            mine[k].subtype_of(theirs[k]) if k in mine else theirs[k].is_nullable
            for k in theirs
        )

    def _join_special(self, other: CypherType) -> Optional[CypherType]:
        if isinstance(other, CTMapType):
            if self.fields is None or other.fields is None:
                return CTMapType(None)
            mine = dict(self.fields)
            theirs = dict(other.fields)
            out = {}
            for k in set(mine) | set(theirs):
                if k in mine and k in theirs:
                    out[k] = mine[k].join(theirs[k])
                else:
                    out[k] = (mine.get(k) or theirs.get(k)).nullable
            return CTMapType(out)
        return None


# ---------------------------------------------------------------------------
# Union types
# ---------------------------------------------------------------------------


class CTUnion(CypherType):
    """Union of alternatives; nullability is CTNull-membership.

    Reference: ``CypherType.scala:284``.
    """

    __slots__ = ("alternatives",)

    def __init__(self, alternatives: FrozenSet[CypherType]):
        object.__setattr__(self, "alternatives", frozenset(alternatives))

    @staticmethod
    def of(*types: CypherType) -> CypherType:
        """Construct a simplified union."""
        flat: set = set()

        def add(t: CypherType):
            if isinstance(t, CTUnion):
                for a in t.alternatives:
                    add(a)
            elif isinstance(t, CTVoidType):
                pass
            else:
                flat.add(t)

        for t in types:
            add(t)
        if not flat:
            return CTVoid
        # drop alternatives subsumed by others
        pruned = {
            t
            for t in flat
            if not any(o is not t and t != o and t.subtype_of(o) for o in flat)
        }
        # INTEGER | FLOAT -> NUMBER
        if CTInteger in pruned and CTFloat in pruned:
            pruned -= {CTInteger, CTFloat}
            pruned.add(CTNumber)
        if len(pruned) == 1:
            return next(iter(pruned))
        return CTUnion(frozenset(pruned))

    @property
    def is_nullable(self) -> bool:
        return any(a.is_nullable for a in self.alternatives)

    @property
    def material(self) -> CypherType:
        return CTUnion.of(*[a for a in self.alternatives if a != CTNull])

    def subtype_of(self, other: CypherType) -> bool:
        if self == other:
            return True
        return all(a.subtype_of(other) for a in self.alternatives)

    def _join_special(self, other: CypherType) -> Optional[CypherType]:
        return CTUnion.of(*self.alternatives, other)

    def _meet_special(self, other: CypherType) -> Optional[CypherType]:
        met = [a.meet(other) for a in self.alternatives]
        return CTUnion.of(*met)

    def __repr__(self) -> str:
        mat = self.material
        if self.is_nullable and not isinstance(mat, CTUnion) and mat != CTVoid:
            return f"{mat!r}?"
        return "UNION(" + ", ".join(sorted(repr(a) for a in self.alternatives)) + ")"

    def __eq__(self, other) -> bool:
        return isinstance(other, CTUnion) and self.alternatives == other.alternatives

    def __hash__(self) -> int:
        return hash(("UNION", self.alternatives))


# ---------------------------------------------------------------------------
# Singletons & helpers
# ---------------------------------------------------------------------------

CTAny = CTAnyType()
CTVoid = CTVoidType()
CTNull = CTNullType()
CTBoolean = CTBooleanType()
CTString = CTStringType()
CTInteger = CTIntegerType()
CTFloat = CTFloatType()
CTNumber = CTNumberType()
CTDate = CTDateType()
CTLocalDateTime = CTLocalDateTimeType()
CTDateTime = CTDateTimeType()
CTLocalTime = CTLocalTimeType()
CTTime = CTTimeType()
CTDuration = CTDurationType()
CTPath = CTPathType()
CTElementId = CTElementIdType()


def CTNode(*labels: str) -> CTNodeType:
    if len(labels) == 1 and not isinstance(labels[0], str):
        return CTNodeType(labels[0])
    return CTNodeType(labels)


def CTRelationship(*types: str) -> CTRelationshipType:
    if len(types) == 1 and not isinstance(types[0], str):
        return CTRelationshipType(types[0])
    return CTRelationshipType(types)


def CTList(inner: CypherType) -> CTListType:
    return CTListType(inner)


def CTMap(fields: Optional[Mapping[str, CypherType]] = None) -> CTMapType:
    return CTMapType(fields)


CTAnyNullable = CTAny.nullable


def join_types(types: Iterable[CypherType]) -> CypherType:
    out: CypherType = CTVoid
    for t in types:
        out = out.join(t)
    return out


# -- value -> type inference -------------------------------------------------


def type_of_value(value) -> CypherType:
    """Infer the CypherType of a Python-represented Cypher value."""
    from . import values as _v
    import datetime as _dt
    from decimal import Decimal

    if value is None:
        return CTNull
    if isinstance(value, bool):
        return CTBoolean
    if isinstance(value, int):
        return CTInteger
    if isinstance(value, float):
        return CTFloat
    if isinstance(value, str):
        return CTString
    if isinstance(value, Decimal):
        return CTBigDecimalType()
    if isinstance(value, _v.Node):
        return CTNodeType(value.labels)
    if isinstance(value, _v.Relationship):
        return CTRelationshipType([value.rel_type])
    if isinstance(value, _v.Duration):
        return CTDuration
    if isinstance(value, _v.Path):
        return CTPath
    if isinstance(value, _dt.datetime):
        return CTDateTime if value.tzinfo is not None else CTLocalDateTime
    if isinstance(value, _dt.date):
        return CTDate
    if isinstance(value, _dt.time):
        return CTTime if value.tzinfo is not None else CTLocalTime
    if isinstance(value, (list, tuple)):
        return CTListType(join_types(type_of_value(v) for v in value))
    if isinstance(value, Mapping):
        return CTMapType({k: type_of_value(v) for k, v in value.items()})
    raise TypeError(f"No CypherType for value {value!r} ({type(value)})")


# -- parsing (schema JSON round-trip) ----------------------------------------


def parse_type(s: str) -> CypherType:
    """Parse the textual form produced by ``repr``.

    Mirrors the reference's ``CypherTypeParser``
    (``okapi-api/.../impl/types/CypherTypeParser.scala``).
    """
    from .type_parser import parse_cypher_type

    return parse_cypher_type(s)

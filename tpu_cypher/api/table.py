"""Table — the backend SPI.

Re-design of the reference's backend contract
(``okapi-relational/.../api/table/Table.scala:43-178``): the relational
algebra a backend must provide. Two implementations exist:
``backend.local.LocalTable`` (pure-Python columnar; correctness oracle and
TCK runner) and ``backend.tpu.TpuTable`` (sharded JAX arrays; the TPU path).

Differences from the reference signature: expression-bearing ops take
``(header, parameters)`` explicitly (the reference passes them implicitly),
and ``explode`` (UNWIND) and ``rename`` are first-class (the reference
backends implement them via engine-specific functions)."""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

from .types import CypherType

JoinType = str  # "inner" | "left_outer" | "right_outer" | "full_outer" | "cross"


class Table(ABC):
    """Abstract columnar table (reference ``Table[T]``)."""

    # -- metadata ---------------------------------------------------------

    @property
    @abstractmethod
    def physical_columns(self) -> List[str]:
        ...

    @abstractmethod
    def column_type(self, col: str) -> CypherType:
        ...

    @property
    @abstractmethod
    def size(self) -> int:
        ...

    @abstractmethod
    def rows(self) -> Iterator[Dict[str, Any]]:
        """Iterate rows as {column: python value} (null = None)."""
        ...

    @classmethod
    def from_arrays(cls, cols: Dict[str, Any]) -> "Table":
        """Bulk construction from mixed numpy arrays / value lists (the
        IO/bench ingestion SPI). Default decodes arrays to value lists and
        delegates to ``from_columns``; backends override with a zero-decode
        fast path (``TpuTable.from_arrays`` -> one H2D copy per numeric
        column)."""
        return cls.from_columns(
            {
                c: (v.tolist() if hasattr(v, "tolist") else list(v))
                for c, v in cols.items()
            }
        )

    def column_values(self, col: str) -> List[Any]:
        """One column as host Python values (null = None). Backends override
        with a columnar read; the default goes through ``rows``."""
        return [r[col] for r in self.rows()]

    def distinct_count(self, cols: Sequence[str]) -> Optional[int]:
        """Number of distinct rows over ``cols`` without materializing them,
        or None when this backend has no cheaper path than ``distinct()``
        (count-over-distinct aggregate pushdown)."""
        return None

    # -- algebra ----------------------------------------------------------

    @abstractmethod
    def select(self, cols: Sequence[str]) -> "Table":
        ...

    @abstractmethod
    def rename(self, mapping: Dict[str, str]) -> "Table":
        ...

    @abstractmethod
    def drop(self, cols: Sequence[str]) -> "Table":
        ...

    @abstractmethod
    def filter(self, expr, header, parameters) -> "Table":
        ...

    @abstractmethod
    def join(
        self,
        other: "Table",
        kind: JoinType,
        join_cols: Sequence[Tuple[str, str]],
    ) -> "Table":
        ...

    @abstractmethod
    def union_all(self, other: "Table") -> "Table":
        ...

    @abstractmethod
    def order_by(self, items: Sequence[Tuple[str, bool]]) -> "Table":
        """items: (column, ascending)."""
        ...

    @abstractmethod
    def skip(self, n: int) -> "Table":
        ...

    @abstractmethod
    def limit(self, n: int) -> "Table":
        ...

    @abstractmethod
    def distinct(self, cols: Optional[Sequence[str]] = None) -> "Table":
        ...

    @abstractmethod
    def group(
        self,
        by: Sequence[str],
        aggregations: Sequence[Tuple[str, Any]],  # (output col, typed Agg expr)
        header,
        parameters,
    ) -> "Table":
        ...

    @abstractmethod
    def with_columns(
        self,
        items: Sequence[Tuple[Any, str]],  # (typed expr, output col)
        header,
        parameters,
    ) -> "Table":
        ...

    @abstractmethod
    def explode(self, expr, col: str, header, parameters) -> "Table":
        """One output row per element of the evaluated list expr (UNWIND)."""
        ...

    def project(self, pairs: Sequence[Tuple[str, str]]) -> "Table":
        """Project (source column, output column) pairs; unlike select+rename
        a source column may appear multiple times (e.g. a self-loop relationship
        whose start and end map to the same physical column)."""
        raise NotImplementedError

    @abstractmethod
    def with_row_index(self, col: str) -> "Table":
        """Append a 0..n-1 int64 row-index column (id generation for new
        elements — the analog of the reference's partitioned id assignment,
        ``MorpheusFunctions.scala:76`` / ``TableOps.scala:217``)."""
        ...

    def cache(self) -> "Table":
        return self

    def show(self, n: int = 20) -> str:
        from ..utils.printer import format_table

        return format_table(self, n)

"""Parser for the textual CypherType syntax emitted by ``repr(CypherType)``.

Mirrors ``okapi-api/src/main/scala/org/opencypher/okapi/impl/types/CypherTypeParser.scala``
for schema JSON round-trips.
"""

from __future__ import annotations

import re
from typing import List, Tuple

from . import types as T

_TOKEN = re.compile(
    r"\s*(?:(?P<lparen>\()|(?P<rparen>\))|(?P<comma>,)|(?P<colon>:)"
    r"|(?P<qmark>\?)|(?P<pipe>\|)|(?P<num>\d+)"
    r"|(?P<word>[A-Za-z_][A-Za-z0-9_]*)|(?P<str>`[^`]*`))"
)


def _tokenize(s: str) -> List[Tuple[str, str]]:
    out = []
    pos = 0
    while pos < len(s):
        m = _TOKEN.match(s, pos)
        if not m:
            if s[pos:].strip() == "":
                break
            raise ValueError(f"Cannot tokenize type string at {s[pos:]!r}")
        pos = m.end()
        for name, val in m.groupdict().items():
            if val is not None:
                out.append((name, val.strip()))
                break
    return out


class _Parser:
    def __init__(self, tokens: List[Tuple[str, str]]):
        self.tokens = tokens
        self.i = 0

    def peek(self):
        return self.tokens[self.i] if self.i < len(self.tokens) else (None, None)

    def next(self):
        tok = self.peek()
        self.i += 1
        return tok

    def expect(self, kind):
        k, v = self.next()
        if k != kind:
            raise ValueError(f"Expected {kind}, got {k}:{v}")
        return v

    def parse(self) -> T.CypherType:
        t = self.parse_one()
        k, _ = self.peek()
        if k == "qmark":
            self.next()
            t = t.nullable
        return t

    def _name(self) -> str:
        k, v = self.next()
        if k == "word":
            return v
        if k == "str":
            return v[1:-1]
        raise ValueError(f"Expected name, got {k}:{v}")

    def parse_one(self) -> T.CypherType:
        k, v = self.next()
        if k != "word":
            raise ValueError(f"Expected type name, got {k}:{v}")
        u = v.upper()
        simple = {
            "ANY": T.CTAny,
            "VOID": T.CTVoid,
            "NOTHING": T.CTVoid,
            "NULL": T.CTNull,
            "BOOLEAN": T.CTBoolean,
            "STRING": T.CTString,
            "INTEGER": T.CTInteger,
            "FLOAT": T.CTFloat,
            "NUMBER": T.CTNumber,
            "DATE": T.CTDate,
            "LOCALDATETIME": T.CTLocalDateTime,
            "DATETIME": T.CTDateTime,
            "LOCALTIME": T.CTLocalTime,
            "TIME": T.CTTime,
            "DURATION": T.CTDuration,
            "PATH": T.CTPath,
            "ELEMENTID": T.CTElementId,
        }
        if u in simple:
            return simple[u]
        if u == "NODE":
            labels = []
            if self.peek()[0] == "lparen":
                self.next()
                while self.peek()[0] != "rparen":
                    if self.peek()[0] == "colon":
                        self.next()
                        continue
                    labels.append(self._name())
                self.expect("rparen")
            return T.CTNodeType(labels)
        if u == "RELATIONSHIP":
            types = []
            if self.peek()[0] == "lparen":
                self.next()
                while self.peek()[0] != "rparen":
                    if self.peek()[0] in ("pipe", "colon"):
                        self.next()
                        continue
                    types.append(self._name())
                self.expect("rparen")
            return T.CTRelationshipType(types)
        if u == "LIST":
            self.expect("lparen")
            inner = self.parse()
            self.expect("rparen")
            return T.CTListType(inner)
        if u == "MAP":
            if self.peek()[0] != "lparen":
                return T.CTMapType(None)
            self.next()
            fields = {}
            while self.peek()[0] != "rparen":
                if self.peek()[0] == "comma":
                    self.next()
                    continue
                key = self._name()
                self.expect("colon")
                fields[key] = self.parse()
            self.expect("rparen")
            return T.CTMapType(fields)
        if u == "BIGDECIMAL":
            if self.peek()[0] != "lparen":
                return T.CTBigDecimalType()
            self.next()
            prec = int(self.expect("num"))
            self.expect("comma")
            scale = int(self.expect("num"))
            self.expect("rparen")
            return T.CTBigDecimalType(prec, scale)
        if u == "UNION":
            self.expect("lparen")
            alts = []
            while self.peek()[0] != "rparen":
                if self.peek()[0] == "comma":
                    self.next()
                    continue
                alts.append(self.parse())
            self.expect("rparen")
            return T.CTUnion.of(*alts)
        raise ValueError(f"Unknown type name {v!r}")


def parse_cypher_type(s: str) -> T.CypherType:
    return _Parser(_tokenize(s)).parse()

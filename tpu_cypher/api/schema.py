"""Property graph schema.

Re-design of ``okapi-api/src/main/scala/org/opencypher/okapi/api/schema/PropertyGraphSchema.scala:62``
and its impl (``impl/schema/PropertyGraphSchemaImpl.scala``, ``ImpliedLabels.scala``,
``LabelCombinations.scala``): maps *label combinations* (the exact set of labels on a
node) to property keys/types, and relationship types to property keys/types; tracks
schema patterns (which (srcLabels, relType, dstLabels) triplets exist, used for
pattern-scan recognition) and supports merge (``++``/union), restriction
(``for_node`` / ``for_relationship``) and JSON round-trip.
"""

from __future__ import annotations

import json
from typing import Dict, FrozenSet, Iterable, List, Mapping, Optional, Set, Tuple

from . import types as T
from .types import CypherType

LabelCombo = FrozenSet[str]
PropertyKeys = Dict[str, CypherType]


def _merge_keys(a: PropertyKeys, b: PropertyKeys) -> PropertyKeys:
    """Join property keys: shared keys join types; one-sided keys become nullable."""
    out: PropertyKeys = {}
    for k in set(a) | set(b):
        if k in a and k in b:
            out[k] = a[k].join(b[k])
        else:
            out[k] = (a.get(k) or b.get(k)).nullable
    return out


class SchemaPattern:
    """A (source labels, rel type, target labels) triplet known to the schema.

    Reference: ``PropertyGraphSchema.scala`` schema patterns / ``SchemaPattern``.
    """

    __slots__ = ("source_labels", "rel_type", "target_labels")

    def __init__(self, source_labels: Iterable[str], rel_type: str, target_labels: Iterable[str]):
        self.source_labels = frozenset(source_labels)
        self.rel_type = rel_type
        self.target_labels = frozenset(target_labels)

    def _key(self):
        return (self.source_labels, self.rel_type, self.target_labels)

    def __eq__(self, other):
        return isinstance(other, SchemaPattern) and self._key() == other._key()

    def __hash__(self):
        return hash(("SchemaPattern",) + tuple(map(hash, self._key())))

    def __repr__(self):
        s = ":".join(sorted(self.source_labels))
        t = ":".join(sorted(self.target_labels))
        return f"(:{s})-[:{self.rel_type}]->(:{t})"


class PropertyGraphSchema:
    __slots__ = ("_node_keys", "_rel_keys", "_patterns")

    def __init__(
        self,
        node_keys: Optional[Mapping[LabelCombo, PropertyKeys]] = None,
        rel_keys: Optional[Mapping[str, PropertyKeys]] = None,
        patterns: Optional[Iterable[SchemaPattern]] = None,
    ):
        self._node_keys: Dict[LabelCombo, PropertyKeys] = {
            frozenset(k): dict(v) for k, v in (node_keys or {}).items()
        }
        self._rel_keys: Dict[str, PropertyKeys] = {k: dict(v) for k, v in (rel_keys or {}).items()}
        self._patterns: Set[SchemaPattern] = set(patterns or ())

    # -- constructors -----------------------------------------------------

    @staticmethod
    def empty() -> "PropertyGraphSchema":
        return PropertyGraphSchema()

    def with_node_combination(
        self, labels: Iterable[str], keys: Optional[Mapping[str, CypherType]] = None
    ) -> "PropertyGraphSchema":
        combo = frozenset(labels)
        nk = {k: dict(v) for k, v in self._node_keys.items()}
        if combo in nk:
            nk[combo] = _merge_keys(nk[combo], dict(keys or {}))
        else:
            nk[combo] = dict(keys or {})
        return PropertyGraphSchema(nk, self._rel_keys, self._patterns)

    def with_relationship_type(
        self, rel_type: str, keys: Optional[Mapping[str, CypherType]] = None
    ) -> "PropertyGraphSchema":
        rk = {k: dict(v) for k, v in self._rel_keys.items()}
        if rel_type in rk:
            rk[rel_type] = _merge_keys(rk[rel_type], dict(keys or {}))
        else:
            rk[rel_type] = dict(keys or {})
        return PropertyGraphSchema(self._node_keys, rk, self._patterns)

    def with_schema_patterns(self, *patterns: SchemaPattern) -> "PropertyGraphSchema":
        return PropertyGraphSchema(
            self._node_keys, self._rel_keys, self._patterns | set(patterns)
        )

    # -- accessors --------------------------------------------------------

    @property
    def labels(self) -> FrozenSet[str]:
        out: Set[str] = set()
        for combo in self._node_keys:
            out |= combo
        return frozenset(out)

    @property
    def label_combinations(self) -> FrozenSet[LabelCombo]:
        return frozenset(self._node_keys.keys())

    @property
    def relationship_types(self) -> FrozenSet[str]:
        return frozenset(self._rel_keys.keys())

    @property
    def schema_patterns(self) -> FrozenSet[SchemaPattern]:
        return frozenset(self._patterns)

    def combinations_for(self, labels: Iterable[str]) -> FrozenSet[LabelCombo]:
        """All stored combos that contain all the given labels."""
        want = frozenset(labels)
        return frozenset(c for c in self._node_keys if want <= c)

    def node_property_keys(self, combo: Iterable[str]) -> PropertyKeys:
        """Exact-combination property keys."""
        return dict(self._node_keys.get(frozenset(combo), {}))

    def node_property_keys_for_combinations(
        self, combos: Iterable[LabelCombo]
    ) -> PropertyKeys:
        out: Optional[PropertyKeys] = None
        for c in combos:
            keys = self._node_keys.get(frozenset(c), {})
            out = dict(keys) if out is None else _merge_keys(out, keys)
        return out or {}

    def node_property_keys_for_labels(self, labels: Iterable[str]) -> PropertyKeys:
        """Keys a node known to have (at least) ``labels`` may have."""
        return self.node_property_keys_for_combinations(self.combinations_for(labels))

    def relationship_property_keys(self, rel_type: str) -> PropertyKeys:
        return dict(self._rel_keys.get(rel_type, {}))

    def relationship_property_keys_for_types(self, types: Iterable[str]) -> PropertyKeys:
        ts = list(types) or list(self._rel_keys)
        out: Optional[PropertyKeys] = None
        for t in ts:
            keys = self._rel_keys.get(t, {})
            out = dict(keys) if out is None else _merge_keys(out, keys)
        return out or {}

    @property
    def implied_labels(self) -> Dict[str, FrozenSet[str]]:
        """label -> labels implied by it (present in every combo containing it).

        Reference: ``ImpliedLabels.scala``.
        """
        out: Dict[str, FrozenSet[str]] = {}
        for label in self.labels:
            combos = [c for c in self._node_keys if label in c]
            if combos:
                implied = frozenset.intersection(*combos) - {label}
                out[label] = implied
        return out

    # -- type helpers -----------------------------------------------------

    def node_type(self, *labels: str) -> T.CTNodeType:
        return T.CTNodeType(labels)

    def to_node_type(self, combo: LabelCombo) -> T.CTNodeType:
        return T.CTNodeType(combo)

    # -- combination -------------------------------------------------------

    def union(self, other: "PropertyGraphSchema") -> "PropertyGraphSchema":
        """Reference ``++`` (PropertyGraphSchema.scala join)."""
        nk = {k: dict(v) for k, v in self._node_keys.items()}
        for combo, keys in other._node_keys.items():
            nk[combo] = _merge_keys(nk[combo], keys) if combo in nk else dict(keys)
        rk = {k: dict(v) for k, v in self._rel_keys.items()}
        for t, keys in other._rel_keys.items():
            rk[t] = _merge_keys(rk[t], keys) if t in rk else dict(keys)
        return PropertyGraphSchema(nk, rk, self._patterns | other._patterns)

    __add__ = union

    def for_node(self, labels: Iterable[str]) -> "PropertyGraphSchema":
        """Restrict to combos matching a scan on ``labels``."""
        labels = frozenset(labels)
        combos = self.combinations_for(labels) if labels else self.label_combinations
        nk = {c: self._node_keys[c] for c in combos}
        return PropertyGraphSchema(nk, {}, set())

    def for_relationship(self, rel: T.CTRelationshipType) -> "PropertyGraphSchema":
        types = rel.types or self.relationship_types
        rk = {t: self._rel_keys[t] for t in types if t in self._rel_keys}
        return PropertyGraphSchema({}, rk, set())

    # -- equality / repr ---------------------------------------------------

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, PropertyGraphSchema)
            and self._node_keys == other._node_keys
            and self._rel_keys == other._rel_keys
            and self._patterns == other._patterns
        )

    def __hash__(self) -> int:
        return hash(
            (
                frozenset((c, frozenset(k.items())) for c, k in self._node_keys.items()),
                frozenset((t, frozenset(k.items())) for t, k in self._rel_keys.items()),
                frozenset(self._patterns),
            )
        )

    def __repr__(self) -> str:
        lines = ["PropertyGraphSchema:"]
        for combo in sorted(self._node_keys, key=lambda c: sorted(c)):
            keys = ", ".join(
                f"{k}: {v!r}" for k, v in sorted(self._node_keys[combo].items())
            )
            lines.append(f"  (:{':'.join(sorted(combo)) or ''}) {{{keys}}}")
        for t in sorted(self._rel_keys):
            keys = ", ".join(f"{k}: {v!r}" for k, v in sorted(self._rel_keys[t].items()))
            lines.append(f"  [:{t}] {{{keys}}}")
        for p in sorted(self._patterns, key=repr):
            lines.append(f"  {p!r}")
        return "\n".join(lines)

    # -- JSON round trip (reference JsonSerialization) ---------------------

    def to_json(self) -> str:
        doc = {
            "version": 1,
            "nodes": [
                {
                    "labels": sorted(combo),
                    "properties": {k: repr(v) for k, v in keys.items()},
                }
                for combo, keys in sorted(
                    self._node_keys.items(), key=lambda kv: sorted(kv[0])
                )
            ],
            "relationships": [
                {
                    "type": t,
                    "properties": {k: repr(v) for k, v in keys.items()},
                }
                for t, keys in sorted(self._rel_keys.items())
            ],
            "patterns": [
                {
                    "sourceLabels": sorted(p.source_labels),
                    "relType": p.rel_type,
                    "targetLabels": sorted(p.target_labels),
                }
                for p in sorted(self._patterns, key=repr)
            ],
        }
        return json.dumps(doc, indent=2)

    @staticmethod
    def from_json(s: str) -> "PropertyGraphSchema":
        doc = json.loads(s)
        nk = {
            frozenset(n["labels"]): {
                k: T.parse_type(v) for k, v in n.get("properties", {}).items()
            }
            for n in doc.get("nodes", [])
        }
        rk = {
            r["type"]: {k: T.parse_type(v) for k, v in r.get("properties", {}).items()}
            for r in doc.get("relationships", [])
        }
        patterns = {
            SchemaPattern(p["sourceLabels"], p["relType"], p["targetLabels"])
            for p in doc.get("patterns", [])
        }
        return PropertyGraphSchema(nk, rk, patterns)

"""Element mappings: table columns -> graph elements.

Re-design of the reference's ``ElementMapping`` builders
(``okapi-api/.../io/conversion/ElementMapping.scala:53``,
``NodeMappingBuilder``, ``RelationshipMappingBuilder``): declarative mapping
from a table's columns onto a node/relationship element — id column, implied
labels (or optional per-label boolean columns), start/end columns, property
key -> column renames — with validation."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, FrozenSet, Optional, Tuple

from .schema import PropertyGraphSchema
from .types import CypherType


class MappingError(Exception):
    pass


@dataclass(frozen=True)
class NodeMapping:
    id_key: str
    implied_labels: FrozenSet[str]
    optional_labels: Tuple[Tuple[str, str], ...] = ()  # (label, bool column)
    property_mapping: Tuple[Tuple[str, str], ...] = ()  # (property key, column)

    @property
    def all_columns(self) -> Tuple[str, ...]:
        return (
            (self.id_key,)
            + tuple(c for _, c in self.optional_labels)
            + tuple(c for _, c in self.property_mapping)
        )

    def pattern(self):
        from .graph_pattern import NodePattern
        from .types import CTNodeType

        return NodePattern(CTNodeType(frozenset(self.implied_labels)))


@dataclass(frozen=True)
class RelationshipMapping:
    id_key: str
    source_key: str
    target_key: str
    rel_type: str
    property_mapping: Tuple[Tuple[str, str], ...] = ()

    @property
    def all_columns(self) -> Tuple[str, ...]:
        return (self.id_key, self.source_key, self.target_key) + tuple(
            c for _, c in self.property_mapping
        )

    def pattern(self):
        from .graph_pattern import RelationshipPattern
        from .types import CTRelationshipType

        return RelationshipPattern(CTRelationshipType(frozenset({self.rel_type})))


class NodeMappingBuilder:
    """``NodeMappingBuilder.on("id").withImpliedLabel("Person")
    .withPropertyKey("name", "name_col").build()``"""

    def __init__(self, id_key: str):
        self._id = id_key
        self._implied: set = set()
        self._optional: Dict[str, str] = {}
        self._props: Dict[str, str] = {}

    @staticmethod
    def on(id_key: str) -> "NodeMappingBuilder":
        return NodeMappingBuilder(id_key)

    def with_implied_label(self, *labels: str) -> "NodeMappingBuilder":
        self._implied.update(labels)
        return self

    def with_optional_label(self, label: str, column: Optional[str] = None) -> "NodeMappingBuilder":
        self._optional[label] = column or label
        return self

    def with_property_key(self, key: str, column: Optional[str] = None) -> "NodeMappingBuilder":
        self._props[key] = column or key
        return self

    def with_property_keys(self, *keys: str) -> "NodeMappingBuilder":
        for k in keys:
            self.with_property_key(k)
        return self

    def build(self) -> NodeMapping:
        m = NodeMapping(
            self._id,
            frozenset(self._implied),
            tuple(sorted(self._optional.items())),
            tuple(sorted(self._props.items())),
        )
        validate_node_mapping(m)
        return m


class RelationshipMappingBuilder:
    def __init__(self, id_key: str):
        self._id = id_key
        self._source: Optional[str] = None
        self._target: Optional[str] = None
        self._type: Optional[str] = None
        self._props: Dict[str, str] = {}

    @staticmethod
    def on(id_key: str) -> "RelationshipMappingBuilder":
        return RelationshipMappingBuilder(id_key)

    def from_(self, source_key: str) -> "RelationshipMappingBuilder":
        self._source = source_key
        return self

    def to(self, target_key: str) -> "RelationshipMappingBuilder":
        self._target = target_key
        return self

    def with_relationship_type(self, rel_type: str) -> "RelationshipMappingBuilder":
        self._type = rel_type
        return self

    def with_property_key(self, key: str, column: Optional[str] = None) -> "RelationshipMappingBuilder":
        self._props[key] = column or key
        return self

    def with_property_keys(self, *keys: str) -> "RelationshipMappingBuilder":
        for k in keys:
            self.with_property_key(k)
        return self

    def build(self) -> RelationshipMapping:
        if self._source is None or self._target is None:
            raise MappingError("Relationship mapping requires from_() and to()")
        if not self._type:
            raise MappingError("Relationship mapping requires a relationship type")
        m = RelationshipMapping(
            self._id,
            self._source,
            self._target,
            self._type,
            tuple(sorted(self._props.items())),
        )
        validate_relationship_mapping(m)
        return m


def validate_node_mapping(m: NodeMapping):
    cols = list(m.all_columns)
    if len(set(cols)) != len(cols):
        raise MappingError(f"Duplicate columns in node mapping: {cols}")
    if not m.implied_labels and not m.optional_labels:
        raise MappingError("Node mapping requires at least one label")
    overlap = m.implied_labels & {l for l, _ in m.optional_labels}
    if overlap:
        raise MappingError(f"Labels both implied and optional: {overlap}")


def validate_relationship_mapping(m: RelationshipMapping):
    ids = {m.id_key, m.source_key, m.target_key}
    if len(ids) != 3:
        raise MappingError("id/source/target columns must be distinct")
    prop_cols = [c for _, c in m.property_mapping]
    if set(prop_cols) & ids:
        raise MappingError("Property columns overlap id/source/target columns")


# -- composite (stored-pattern) mappings ------------------------------------
#
# Reference: ``ElementMapping`` generalized over a ``Pattern``
# (``ElementMapping.scala:53`` + ``Pattern.scala:135-182``). A composite
# table stores several elements per row: NodeRel = a node plus one of its
# outgoing relationships; Triplet = (source node, relationship, target node).


@dataclass(frozen=True)
class NodeRelMapping:
    """One table row = one (node, outgoing relationship) pair."""

    node: NodeMapping
    relationship: RelationshipMapping

    @property
    def all_columns(self) -> Tuple[str, ...]:
        seen = dict.fromkeys(self.node.all_columns + self.relationship.all_columns)
        return tuple(seen)

    def pattern(self):
        from .graph_pattern import NodeRelPattern
        from .types import CTNodeType, CTRelationshipType

        return NodeRelPattern(
            CTNodeType(frozenset(self.node.implied_labels)),
            CTRelationshipType(frozenset({self.relationship.rel_type})),
        )


@dataclass(frozen=True)
class TripletMapping:
    """One table row = one full (source)-[rel]->(target) triplet."""

    source: NodeMapping
    relationship: RelationshipMapping
    target: NodeMapping

    @property
    def all_columns(self) -> Tuple[str, ...]:
        seen = dict.fromkeys(
            self.source.all_columns
            + self.relationship.all_columns
            + self.target.all_columns
        )
        return tuple(seen)

    def pattern(self):
        from .graph_pattern import TripletPattern
        from .types import CTNodeType, CTRelationshipType

        return TripletPattern(
            CTNodeType(frozenset(self.source.implied_labels)),
            CTRelationshipType(frozenset({self.relationship.rel_type})),
            CTNodeType(frozenset(self.target.implied_labels)),
        )


def validate_node_rel_mapping(m: NodeRelMapping):
    if m.relationship.source_key != m.node.id_key:
        raise MappingError(
            "NodeRel mapping: the relationship's source column must be the "
            f"node id column ({m.relationship.source_key!r} != {m.node.id_key!r})"
        )


def validate_triplet_mapping(m: TripletMapping):
    if m.relationship.source_key != m.source.id_key:
        raise MappingError(
            "Triplet mapping: relationship source column must be the source "
            f"node id column ({m.relationship.source_key!r} != {m.source.id_key!r})"
        )
    if m.relationship.target_key != m.target.id_key:
        raise MappingError(
            "Triplet mapping: relationship target column must be the target "
            f"node id column ({m.relationship.target_key!r} != {m.target.id_key!r})"
        )
    if m.source.id_key == m.target.id_key:
        raise MappingError("Triplet mapping: source and target id columns collide")


def node_rel_mapping(node: NodeMapping, relationship: RelationshipMapping) -> NodeRelMapping:
    m = NodeRelMapping(node, relationship)
    validate_node_rel_mapping(m)
    return m


def triplet_mapping(
    source: NodeMapping, relationship: RelationshipMapping, target: NodeMapping
) -> TripletMapping:
    m = TripletMapping(source, relationship, target)
    validate_triplet_mapping(m)
    return m

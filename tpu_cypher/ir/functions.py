"""Scalar function registry: type signatures + reference semantics.

The reference models ~70 functions as individual ``Expr`` case classes
(``okapi-ir/.../api/expr/Expr.scala``) with per-backend SQL translations
(``FlinkSQLExprMapper.scala:48`` / ``SparkSQLExprMapper.scala``). Here each
function is one table entry: a result-type rule plus a pure-Python reference
implementation (the local backend's evaluator and the oracle for the TPU
kernels; the TPU backend overrides the hot ones with jnp equivalents).

``null_prop`` functions return null when any argument is null (the default
Cypher convention); exceptions (coalesce, toString variants…) opt out.
"""

from __future__ import annotations

import datetime as _dt
import math
import re
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence

from ..api import types as T
from ..api.types import CypherType
from ..api.values import Duration, Node, Path, Relationship, to_cypher_string


class CypherTypeError(Exception):
    pass


@dataclass
class FunctionDef:
    name: str
    min_args: int
    max_args: int  # -1 = varargs
    result_type: Callable[[List[CypherType]], CypherType]
    fn: Callable
    null_prop: bool = True


def _const(t: CypherType):
    return lambda args: t


def _nullable(t: CypherType):
    return lambda args: t.nullable


FUNCTIONS: Dict[str, FunctionDef] = {}


def _register(
    name: str,
    fn: Callable,
    result_type,
    min_args: int = 1,
    max_args: Optional[int] = None,
    null_prop: bool = True,
):
    if isinstance(result_type, CypherType):
        result_type = _const(result_type)
    FUNCTIONS[name] = FunctionDef(
        name,
        min_args,
        min_args if max_args is None else max_args,
        result_type,
        fn,
        null_prop,
    )


# ---------------------------------------------------------------------------
# element functions
# ---------------------------------------------------------------------------


def _f_id(v):
    if isinstance(v, (Node, Relationship)):
        return v.id
    raise CypherTypeError(f"id() expects an element, got {type(v).__name__}")


def _f_labels(v):
    if isinstance(v, Node):
        return sorted(v.labels)
    raise CypherTypeError("labels() expects a node")


def _f_type(v):
    if isinstance(v, Relationship):
        return v.rel_type
    raise CypherTypeError("type() expects a relationship")


def _f_keys(v):
    if isinstance(v, (Node, Relationship)):
        return sorted(k for k, p in v.properties.items() if p is not None)
    if isinstance(v, dict):
        return sorted(v.keys())
    raise CypherTypeError("keys() expects an element or map")


def _f_properties(v):
    if isinstance(v, (Node, Relationship)):
        return dict(v.properties)
    if isinstance(v, dict):
        return dict(v)
    raise CypherTypeError("properties() expects an element or map")


_register("id", _f_id, T.CTInteger)
_register("labels", _f_labels, T.CTList(T.CTString))
_register("type", _f_type, T.CTString)
_register("keys", _f_keys, T.CTList(T.CTString))
_register("properties", _f_properties, T.CTMap(None))
_register(
    "startnode",
    lambda r: r.start if isinstance(r, Relationship) else None,
    T.CTNode(),
)
_register(
    "endnode", lambda r: r.end if isinstance(r, Relationship) else None, T.CTNode()
)


# ---------------------------------------------------------------------------
# scalar / list functions
# ---------------------------------------------------------------------------


def _f_size(v):
    if isinstance(v, (list, tuple, str)):
        return len(v)
    raise CypherTypeError("size() expects a list or string")


def _f_length(v):
    if isinstance(v, Path):
        return sum(1 for e in v.elements if isinstance(e, Relationship))
    if isinstance(v, (list, tuple, str)):
        return len(v)
    raise CypherTypeError("length() expects a path, list or string")


def _f_nodes(v):
    if isinstance(v, Path):
        return [e for e in v.elements if isinstance(e, Node)]
    raise CypherTypeError("nodes() expects a path")


def _f_relationships(v):
    if isinstance(v, Path):
        return [e for e in v.elements if isinstance(e, Relationship)]
    raise CypherTypeError("relationships() expects a path")


def _f_range(*args):
    start, end = args[0], args[1]
    step = args[2] if len(args) > 2 else 1
    if step == 0:
        raise CypherTypeError("range() step must not be zero")
    out = list(range(start, end + (1 if step > 0 else -1), step))
    return out


def _f_coalesce(*args):
    for a in args:
        if a is not None:
            return a
    return None


def _f_head(v):
    return v[0] if v else None


def _f_last(v):
    return v[-1] if v else None


def _f_tail(v):
    return list(v[1:])


def _list_inner(args: List[CypherType]) -> CypherType:
    if args and isinstance(args[0].material, T.CTListType):
        return args[0].material.inner.nullable
    return T.CTAny.nullable


_register("size", _f_size, T.CTInteger)
_register("length", _f_length, T.CTInteger)
_register("nodes", _f_nodes, T.CTList(T.CTNode()))
_register("relationships", _f_relationships, T.CTList(T.CTRelationship()))
_register("range", _f_range, T.CTList(T.CTInteger), min_args=2, max_args=3)
_register(
    "coalesce",
    _f_coalesce,
    lambda args: T.join_types(a for a in args),
    min_args=1,
    max_args=-1,
    null_prop=False,
)
_register("head", _f_head, _list_inner)
_register("last", _f_last, _list_inner)
_register(
    "tail",
    _f_tail,
    lambda args: args[0].material if isinstance(args[0].material, T.CTListType) else T.CTList(T.CTAny),
)
_register("reverse", lambda v: v[::-1], lambda args: args[0])
_register("exists", lambda v: v is not None, T.CTBoolean, null_prop=False)


# ---------------------------------------------------------------------------
# conversions
# ---------------------------------------------------------------------------


def _f_tointeger(v):
    if isinstance(v, bool):
        raise CypherTypeError("toInteger() on boolean")
    if isinstance(v, (int, float)):
        return int(v)
    if isinstance(v, str):
        try:
            return int(v)
        except ValueError:
            try:
                return int(float(v))
            except ValueError:
                return None
    raise CypherTypeError("toInteger() expects number or string")


def _f_tofloat(v):
    if isinstance(v, bool):
        raise CypherTypeError("toFloat() on boolean")
    if isinstance(v, (int, float)):
        return float(v)
    if isinstance(v, str):
        try:
            return float(v)
        except ValueError:
            return None
    raise CypherTypeError("toFloat() expects number or string")


def _f_toboolean(v):
    if isinstance(v, bool):
        return v
    if isinstance(v, str):
        low = v.lower()
        if low == "true":
            return True
        if low == "false":
            return False
        return None
    raise CypherTypeError("toBoolean() expects boolean or string")


def _f_tostring(v):
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float):
        return to_cypher_string(v)
    if isinstance(v, (int, str)):
        return str(v)
    if isinstance(v, (_dt.date, _dt.datetime)):
        return v.isoformat()
    if isinstance(v, Duration):
        return v.cypher_str()
    return str(v)


_register("tointeger", _f_tointeger, _nullable(T.CTInteger))
_register("tofloat", _f_tofloat, _nullable(T.CTFloat))
_register("toboolean", _f_toboolean, _nullable(T.CTBoolean))
_register("tostring", _f_tostring, T.CTString)


# ---------------------------------------------------------------------------
# strings
# ---------------------------------------------------------------------------


def _f_substring(s, start, length=None):
    if length is None:
        return s[start:]
    return s[start : start + length]


def _f_split(s, sep):
    return s.split(sep)


_register("touppercase", str.upper, T.CTString)
_register("toupper", str.upper, T.CTString)
_register("tolowercase", str.lower, T.CTString)
_register("tolower", str.lower, T.CTString)
_register("trim", str.strip, T.CTString)
_register("ltrim", str.lstrip, T.CTString)
_register("rtrim", str.rstrip, T.CTString)
_register("substring", _f_substring, T.CTString, min_args=2, max_args=3)
_register("left", lambda s, n: s[:n], T.CTString, min_args=2)
_register("right", lambda s, n: s[-n:] if n > 0 else "", T.CTString, min_args=2)
_register("replace", lambda s, a, b: s.replace(a, b), T.CTString, min_args=3)
_register("split", _f_split, T.CTList(T.CTString), min_args=2)


# ---------------------------------------------------------------------------
# math
# ---------------------------------------------------------------------------


def _numeric_result(args: List[CypherType]) -> CypherType:
    t = args[0].material if args else T.CTNumber
    if t == T.CTInteger:
        return T.CTInteger
    if t == T.CTFloat:
        return T.CTFloat
    return T.CTNumber


def _f_abs(v):
    return abs(v)


def _f_round(v):
    # Cypher rounds half away from zero
    return float(math.floor(v + 0.5)) if v >= 0 else float(math.ceil(v - 0.5))


def _f_sign(v):
    return (v > 0) - (v < 0)


_register("abs", _f_abs, _numeric_result)
_register("ceil", lambda v: float(math.ceil(v)), T.CTFloat)
_register("floor", lambda v: float(math.floor(v)), T.CTFloat)
_register("round", _f_round, T.CTFloat)
_register("sqrt", lambda v: math.sqrt(v), T.CTFloat)
_register("sign", _f_sign, T.CTInteger)
_register("exp", math.exp, T.CTFloat)
_register("log", lambda v: math.log(v) if v > 0 else None, _nullable(T.CTFloat))
_register("log10", lambda v: math.log10(v) if v > 0 else None, _nullable(T.CTFloat))
_register("sin", math.sin, T.CTFloat)
_register("cos", math.cos, T.CTFloat)
_register("tan", math.tan, T.CTFloat)
_register("cot", lambda v: 1.0 / math.tan(v), T.CTFloat)
_register("asin", math.asin, T.CTFloat)
_register("acos", math.acos, T.CTFloat)
_register("atan", math.atan, T.CTFloat)
_register("atan2", math.atan2, T.CTFloat, min_args=2)
_register("degrees", math.degrees, T.CTFloat)
_register("radians", math.radians, T.CTFloat)
_register("haversin", lambda v: (1 - math.cos(v)) / 2, T.CTFloat)
_register("pi", lambda: math.pi, T.CTFloat, min_args=0, max_args=0)
_register("e", lambda: math.e, T.CTFloat, min_args=0, max_args=0)

import random as _random

_register("rand", lambda: _random.random(), T.CTFloat, min_args=0, max_args=0)


# ---------------------------------------------------------------------------
# temporal
# ---------------------------------------------------------------------------

_DATE_RE = re.compile(r"(\d{4})-?(\d{2})?-?(\d{2})?")


def _f_date(v=None):
    if v is None:
        return _dt.date.today()
    if isinstance(v, str):
        m = _DATE_RE.match(v)
        if not m:
            raise CypherTypeError(f"Cannot parse date {v!r}")
        y, mo, d = int(m.group(1)), int(m.group(2) or 1), int(m.group(3) or 1)
        return _dt.date(y, mo, d)
    if isinstance(v, dict):
        return _dt.date(int(v.get("year", 1)), int(v.get("month", 1)), int(v.get("day", 1)))
    raise CypherTypeError("date() expects a string or map")


def _f_localdatetime(v=None):
    if v is None:
        return _dt.datetime.now()
    if isinstance(v, str):
        return _dt.datetime.fromisoformat(v)
    if isinstance(v, dict):
        return _dt.datetime(
            int(v.get("year", 1)),
            int(v.get("month", 1)),
            int(v.get("day", 1)),
            int(v.get("hour", 0)),
            int(v.get("minute", 0)),
            int(v.get("second", 0)),
            int(v.get("millisecond", 0)) * 1000 + int(v.get("microsecond", 0)),
        )
    raise CypherTypeError("localdatetime() expects a string or map")


def _tzinfo_of(spec: str) -> _dt.tzinfo:
    """'+01:00' / 'Z' fixed offsets, else an IANA name via zoneinfo (the
    reference resolves zone ids on the JVM; ``TemporalUdfs.scala:40``)."""
    s = spec.strip()
    if s in ("Z", "z", "UTC"):
        return _dt.timezone.utc
    if s and s[0] in "+-":
        t = _dt.datetime.fromisoformat(f"2000-01-01T00:00:00{s}")
        return t.tzinfo
    from zoneinfo import ZoneInfo

    return ZoneInfo(s)


def _f_datetime(v=None):
    """Zoned datetime (reference CTDateTime / TemporalUdfs): ISO strings
    with offsets, 'Z', or a bracketed zone name; maps with a ``timezone``
    key (DST-correct via zoneinfo); epoch selectors."""
    if v is None:
        return _dt.datetime.now(_dt.timezone.utc)
    if isinstance(v, _dt.datetime):
        return v if v.tzinfo is not None else v.replace(tzinfo=_dt.timezone.utc)
    if isinstance(v, str):
        s = v.strip()
        zone = None
        if s.endswith("]") and "[" in s:
            s, _, z = s.rpartition("[")
            zone = _tzinfo_of(z[:-1])
        if s.endswith(("Z", "z")):
            s = s[:-1] + "+00:00"
        out = _dt.datetime.fromisoformat(s)
        if zone is not None:
            if out.tzinfo is None:
                out = out.replace(tzinfo=zone)
            else:
                out = out.astimezone(zone)
        elif out.tzinfo is None:
            out = out.replace(tzinfo=_dt.timezone.utc)
        return out
    if isinstance(v, dict):
        v = {k.lower(): x for k, x in v.items()}
        tz = _tzinfo_of(str(v.get("timezone", "UTC")))
        if "epochseconds" in v or "epochmillis" in v:
            us = int(v.get("epochseconds", 0)) * 1_000_000
            us += int(v.get("epochmillis", 0)) * 1000
            # integer timedelta arithmetic: a float detour (us / 1e6)
            # rounds at microsecond granularity for large epoch magnitudes
            epoch = _dt.datetime(1970, 1, 1, tzinfo=_dt.timezone.utc)
            return (epoch + _dt.timedelta(microseconds=us)).astimezone(tz)
        return _dt.datetime(
            int(v.get("year", 1)),
            int(v.get("month", 1)),
            int(v.get("day", 1)),
            int(v.get("hour", 0)),
            int(v.get("minute", 0)),
            int(v.get("second", 0)),
            int(v.get("millisecond", 0)) * 1000 + int(v.get("microsecond", 0)),
            tzinfo=tz,
        )
    raise CypherTypeError("datetime() expects a string or map")


def _parse_time_body(s: str) -> _dt.time:
    if len(s) == 2:
        s += ":00"
    elif len(s) == 4 and ":" not in s:
        s = s[:2] + ":" + s[2:]
    elif len(s) == 6 and ":" not in s:
        s = s[:2] + ":" + s[2:4] + ":" + s[4:]
    return _dt.time.fromisoformat(s)


def _f_time(v=None):
    if v is None:
        return _dt.datetime.now(_dt.timezone.utc).timetz()
    if isinstance(v, _dt.time):
        return v if v.tzinfo is not None else v.replace(tzinfo=_dt.timezone.utc)
    if isinstance(v, str):
        s = v.strip()
        if s.endswith(("Z", "z")):
            s = s[:-1] + "+00:00"
        out = _parse_time_body(s)
        if out.tzinfo is None:
            out = out.replace(tzinfo=_dt.timezone.utc)
        return out
    if isinstance(v, dict):
        v = {k.lower(): x for k, x in v.items()}
        tz = _tzinfo_of(str(v.get("timezone", "UTC")))
        # named zones resolve their offset against the CURRENT instant (the
        # Neo4j rule) — via an AWARE UTC now: feeding a naive machine-local
        # wall time to utcoffset() would read it as zone-local, making the
        # result depend on the host's timezone (and wrong near DST edges)
        off = _dt.datetime.now(_dt.timezone.utc).astimezone(tz).utcoffset()
        return _dt.time(
            int(v.get("hour", 0)),
            int(v.get("minute", 0)),
            int(v.get("second", 0)),
            int(v.get("millisecond", 0)) * 1000 + int(v.get("microsecond", 0)),
            tzinfo=_dt.timezone(off),
        )
    raise CypherTypeError("time() expects a string or map")


def _f_localtime(v=None):
    if v is None:
        return _dt.datetime.now().time()
    if isinstance(v, _dt.time):
        return v.replace(tzinfo=None)
    if isinstance(v, str):
        return _parse_time_body(v.strip())
    if isinstance(v, dict):
        v = {k.lower(): x for k, x in v.items()}
        return _dt.time(
            int(v.get("hour", 0)),
            int(v.get("minute", 0)),
            int(v.get("second", 0)),
            int(v.get("millisecond", 0)) * 1000 + int(v.get("microsecond", 0)),
        )
    raise CypherTypeError("localtime() expects a string or map")


def _f_datetime_truncate(unit, v):
    if not isinstance(v, _dt.datetime) or v.tzinfo is None:
        raise CypherTypeError("datetime.truncate() expects a zoned datetime")
    tz = v.tzinfo
    out = _truncate_temporal(unit, v.replace(tzinfo=None), allow_sub_day=True)
    return out.replace(tzinfo=tz)


def _f_duration(v):
    if isinstance(v, str):
        return _parse_iso_duration(v)
    if isinstance(v, dict):
        return Duration.of(**{k: v for k, v in v.items()})
    raise CypherTypeError("duration() expects a string or map")


_ISO_DUR = re.compile(
    r"^(?P<sign>-)?P(?:(?P<y>-?[\d.]+)Y)?(?:(?P<mo>-?[\d.]+)M)?(?:(?P<w>-?[\d.]+)W)?"
    r"(?:(?P<d>-?[\d.]+)D)?(?:T(?:(?P<h>-?[\d.]+)H)?(?:(?P<mi>-?[\d.]+)M)?"
    r"(?:(?P<s>-?[\d.]+)S)?)?$"
)


def _parse_iso_duration(s: str) -> Duration:
    m = _ISO_DUR.match(s.strip())
    if not m or s.strip() in ("P", "-P"):
        raise CypherTypeError(f"Cannot parse duration {s!r}")
    g = {k: float(v) if v else 0.0 for k, v in m.groupdict().items() if k != "sign"}
    d = Duration.of(
        years=g["y"], months=g["mo"], weeks=g["w"], days=g["d"],
        hours=g["h"], minutes=g["mi"], seconds=g["s"],
    )
    if m.group("sign"):
        d = -d
    return d


def _add_months(d: _dt.datetime, months: int) -> _dt.datetime:
    y, m = divmod(d.year * 12 + (d.month - 1) + months, 12)
    import calendar

    day = min(d.day, calendar.monthrange(y, m + 1)[1])
    return d.replace(year=y, month=m + 1, day=day)


def _f_duration_between(a, b):
    """Calendar-aware decomposition (Neo4j ``duration.between``): whole
    months truncated toward zero, then whole days, then the time remainder —
    NOT a flat day count, and NOT swap-and-negate. Month-end clamping makes
    those differ: between(2020-03-31, 2020-02-28) anchors at 2020-02-29
    (leap year) giving P-1M-1D, where swap-and-negate would give -(P1M3D);
    in a non-leap year the anchor clamps to Feb 28 exactly, giving P-1M."""
    if isinstance(a, _dt.date) and not isinstance(a, _dt.datetime):
        a = _dt.datetime(a.year, a.month, a.day)
    if isinstance(b, _dt.date) and not isinstance(b, _dt.datetime):
        b = _dt.datetime(b.year, b.month, b.day)
    months = (b.year - a.year) * 12 + (b.month - a.month)
    # pull the month anchor back toward a if it overshot b
    if months > 0 and _add_months(a, months) > b:
        months -= 1
    elif months < 0 and _add_months(a, months) < b:
        months += 1
    anchor = _add_months(a, months)
    delta = b - anchor
    total_us = (delta.days * 86400 + delta.seconds) * 1_000_000 + delta.microseconds
    sign_t = 1 if total_us >= 0 else -1
    day_us = 86400 * 1_000_000
    days = sign_t * (abs(total_us) // day_us)
    rem = total_us - days * day_us
    secs = sign_t * (abs(rem) // 1_000_000)
    us = rem - secs * 1_000_000
    return Duration(months=months, days=days, seconds=secs, microseconds=us)


_TRUNC_UNITS = (
    "millennium", "century", "decade", "year", "quarter", "month", "week",
    "day", "hour", "minute", "second", "millisecond", "microsecond",
)


_SUB_DAY_UNITS = ("hour", "minute", "second", "millisecond", "microsecond")


def _truncate_temporal(unit: str, v, allow_sub_day: bool):
    """Shared truncation core (Neo4j ``<type>.truncate(unit, temporal)``):
    returns a datetime at the start of the requested unit. ``allow_sub_day``
    is False for ``date.truncate`` — a date cannot carry time fields, so
    sub-day units are an error regardless of the input's type."""
    u = str(unit).lower()
    if u not in _TRUNC_UNITS:
        raise CypherTypeError(f"Unknown truncation unit {unit!r}")
    if u in _SUB_DAY_UNITS and not allow_sub_day:
        raise CypherTypeError(f"Unit {unit!r} is too small to truncate a date to")
    if isinstance(v, _dt.datetime):
        y, mo, d = v.year, v.month, v.day
        h, mi, s, us = v.hour, v.minute, v.second, v.microsecond
    elif isinstance(v, _dt.date):
        y, mo, d = v.year, v.month, v.day
        h = mi = s = us = 0
        if u in _SUB_DAY_UNITS:
            raise CypherTypeError(f"Cannot truncate a date to {unit!r}")
    else:
        raise CypherTypeError("truncate() expects a temporal value")

    def year_start(yy: int) -> _dt.datetime:
        if yy < _dt.MINYEAR:  # proleptic range floor (year 0 unrepresentable)
            raise CypherTypeError(
                f"Cannot truncate year {y} to {unit!r}: start of unit is out of range"
            )
        return _dt.datetime(yy, 1, 1)

    if u == "millennium":
        return year_start(y - y % 1000)
    if u == "century":
        return year_start(y - y % 100)
    if u == "decade":
        return year_start(y - y % 10)
    if u == "year":
        return _dt.datetime(y, 1, 1)
    if u == "quarter":
        return _dt.datetime(y, 3 * ((mo - 1) // 3) + 1, 1)
    if u == "month":
        return _dt.datetime(y, mo, 1)
    if u == "week":
        monday = _dt.date(y, mo, d) - _dt.timedelta(
            days=_dt.date(y, mo, d).isoweekday() - 1
        )
        return _dt.datetime(monday.year, monday.month, monday.day)
    if u == "day":
        return _dt.datetime(y, mo, d)
    if u == "hour":
        return _dt.datetime(y, mo, d, h)
    if u == "minute":
        return _dt.datetime(y, mo, d, h, mi)
    if u == "second":
        return _dt.datetime(y, mo, d, h, mi, s)
    if u == "millisecond":
        return _dt.datetime(y, mo, d, h, mi, s, us - us % 1000)
    return _dt.datetime(y, mo, d, h, mi, s, us)


def _f_date_truncate(unit, v):
    return _truncate_temporal(unit, v, allow_sub_day=False).date()


def _f_ldt_truncate(unit, v):
    return _truncate_temporal(unit, v, allow_sub_day=True)


_US_PER_DAY = 86_400 * 1_000_000


def _between_micros(a, b) -> int:
    if isinstance(a, _dt.date) and not isinstance(a, _dt.datetime):
        a = _dt.datetime(a.year, a.month, a.day)
    if isinstance(b, _dt.date) and not isinstance(b, _dt.datetime):
        b = _dt.datetime(b.year, b.month, b.day)
    delta = b - a
    return (delta.days * 86400 + delta.seconds) * 1_000_000 + delta.microseconds


def _f_duration_inmonths(a, b):
    """Whole months between (days/seconds dropped — Neo4j duration.inMonths)."""
    d = _f_duration_between(a, b)
    return Duration(months=d.months, days=0, seconds=0, microseconds=0)


def _f_duration_indays(a, b):
    """Whole days between, no month component (Neo4j duration.inDays)."""
    us = _between_micros(a, b)
    sign = 1 if us >= 0 else -1
    return Duration(months=0, days=sign * (abs(us) // _US_PER_DAY), seconds=0, microseconds=0)


def _f_duration_inseconds(a, b):
    """Exact seconds+microseconds between (Neo4j duration.inSeconds);
    ``Duration`` normalizes the raw microsecond count itself."""
    return Duration(microseconds=_between_micros(a, b))


_register("date", _f_date, T.CTDate, min_args=0, max_args=1)
_register("localdatetime", _f_localdatetime, T.CTLocalDateTime, min_args=0, max_args=1)
_register("datetime", _f_datetime, T.CTDateTime, min_args=0, max_args=1)
_register("time", _f_time, T.CTTime, min_args=0, max_args=1)
_register("localtime", _f_localtime, T.CTLocalTime, min_args=0, max_args=1)
_register("date.truncate", _f_date_truncate, T.CTDate, min_args=2)
_register(
    "localdatetime.truncate", _f_ldt_truncate, T.CTLocalDateTime, min_args=2
)
_register("datetime.truncate", _f_datetime_truncate, T.CTDateTime, min_args=2)
_register("duration", _f_duration, T.CTDuration)
_register("duration.between", _f_duration_between, T.CTDuration, min_args=2)
_register("duration.inmonths", _f_duration_inmonths, T.CTDuration, min_args=2)
_register("duration.indays", _f_duration_indays, T.CTDuration, min_args=2)
_register("duration.inseconds", _f_duration_inseconds, T.CTDuration, min_args=2)


# temporal accessors used via property syntax (d.year, d.month, ...)
TEMPORAL_ACCESSORS: Dict[str, Callable] = {
    "year": lambda d: d.year,
    "month": lambda d: d.month,
    "day": lambda d: d.day,
    "week": lambda d: d.isocalendar()[1],
    "weekyear": lambda d: d.isocalendar()[0],
    "dayofweek": lambda d: d.isoweekday(),
    "ordinalday": lambda d: d.timetuple().tm_yday,
    "quarter": lambda d: (d.month - 1) // 3 + 1,
    "dayofquarter": lambda d: (d - _quarter_start(d)).days + 1,
    "hour": lambda d: d.hour,
    "minute": lambda d: d.minute,
    "second": lambda d: d.second,
    "millisecond": lambda d: d.microsecond // 1000,
    "microsecond": lambda d: d.microsecond,
    # zone accessors (aware datetime/time only — zoneless values raise a
    # typed CypherTypeError, never a raw AttributeError)
    "timezone": lambda d: _zone_name(d),
    "offset": lambda d: _offset_str(d),
    "offsetminutes": lambda d: _offset_total_seconds(d) // 60,
    "offsetseconds": lambda d: _offset_total_seconds(d),
    "epochseconds": lambda d: _epoch_micros(d) // 1_000_000,
    "epochmillis": lambda d: _epoch_micros(d) // 1000,
}


def _offset_total_seconds(d) -> int:
    off = getattr(d, "utcoffset", lambda: None)()
    if off is None:
        raise CypherTypeError(
            f"offset accessor on a zoneless temporal {d!r}"
        )
    return int(off.total_seconds())


def _epoch_micros(d) -> int:
    # aware datetimes ONLY: a naive value's timestamp() would silently
    # read the HOST machine's timezone — nondeterministic across machines
    if not isinstance(d, _dt.datetime) or d.tzinfo is None:
        raise CypherTypeError(
            f"epoch accessor on a non-zoned temporal {d!r}"
        )
    delta = d - _dt.datetime(1970, 1, 1, tzinfo=_dt.timezone.utc)
    return (delta.days * 86400 + delta.seconds) * 1_000_000 + delta.microseconds


def _offset_str(d) -> str:
    from ..api.values import format_utc_offset

    return format_utc_offset(_offset_total_seconds(d))


def _zone_name(d) -> str:
    tz = getattr(d, "tzinfo", None)
    if tz is None:
        raise CypherTypeError("timezone accessor on a zoneless temporal")
    key = getattr(tz, "key", None)  # zoneinfo.ZoneInfo region name
    return key if key is not None else _offset_str(d)

DURATION_ACCESSORS: Dict[str, Callable] = {
    "years": lambda d: d.months // 12,
    "months": lambda d: d.months,
    "monthsofyear": lambda d: d.months % 12,
    "weeks": lambda d: d.days // 7,
    "days": lambda d: d.days,
    "hours": lambda d: d.seconds // 3600,
    "minutes": lambda d: d.seconds // 60,
    "seconds": lambda d: d.seconds,
    "milliseconds": lambda d: d.seconds * 1000 + d.microseconds // 1000,
    "microseconds": lambda d: d.seconds * 1_000_000 + d.microseconds,
}


def _quarter_start(d):
    q_month = 3 * ((d.month - 1) // 3) + 1
    if isinstance(d, _dt.datetime):
        return _dt.datetime(d.year, q_month, 1)
    return _dt.date(d.year, q_month, 1)


# ---------------------------------------------------------------------------
# big decimal
# ---------------------------------------------------------------------------

from decimal import Decimal


def _f_bigdecimal(v, precision=38, scale=18):
    if isinstance(v, bool):
        raise CypherTypeError("bigdecimal() on boolean")
    q = Decimal(str(v)).quantize(Decimal(1).scaleb(-int(scale)))
    return q


_register(
    "bigdecimal",
    _f_bigdecimal,
    lambda args: T.CTBigDecimalType(),
    min_args=1,
    max_args=3,
)


def lookup(name: str) -> FunctionDef:
    f = FUNCTIONS.get(name)
    if f is None:
        raise CypherTypeError(f"Unknown function: {name}")
    return f

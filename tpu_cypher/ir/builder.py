"""IR builder: frontend AST -> typed block pipeline.

Re-design of the reference's eff-monad IR builder
(``okapi-ir/.../impl/IRBuilder.scala:51``, clause match at ``:71-690``) plus its
``ExpressionConverter``/``PatternConverter`` and incremental typer
(``impl/typer/TypeTracker.scala``): a single pass that

* converts patterns to :class:`~tpu_cypher.ir.pattern.IRPattern` (fresh names
  for anonymous entities, property maps lowered to equality predicates —
  matching the reference's pattern conversion),
* converts + types expressions against the scope environment and graph schema
  (label info refines ``CTNode`` types; property lookups consult the schema),
* performs aggregation isolation (reference ``isolateAggregation`` rewriter):
  projection items containing aggregators are split into an AggregationBlock
  over extracted aggregates plus a post-projection,
* tracks the WITH/RETURN horizon discipline via Select blocks,
* handles multiple-graph clauses (FROM GRAPH switching the schema context,
  CONSTRUCT, RETURN GRAPH) and CATALOG statements.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field as dc_field
from typing import Any, Dict, List, Optional, Tuple

from ..api import types as T
from ..api.schema import PropertyGraphSchema
from ..api.types import CypherType
from ..frontend import ast as A
from ..frontend.lexer import CypherSyntaxError
from . import blocks as B
from . import expr as E
from .functions import CypherTypeError, lookup as lookup_function
from .pattern import BOTH, INCOMING, OUTGOING, Connection, IRPattern


class IRBuildError(Exception):
    pass


# clauses that make a single query a WRITE query (docs/mutation.md)
_WRITE_CLAUSES = (A.CreateClause, A.MergeClause, A.SetClause, A.DeleteClause)


class UnsupportedFeatureError(IRBuildError):
    """A feature the grammar accepts but the engine does not execute
    (procedure calls). The reference's analog: its frontend parses CALL and
    the backends blacklist ProcedureCallAcceptance at TCK level."""


@dataclass
class IRBuilderContext:
    schema: PropertyGraphSchema
    parameters: Dict[str, Any] = dc_field(default_factory=dict)
    catalog_schemas: Dict[str, PropertyGraphSchema] = dc_field(default_factory=dict)
    working_graph: str = "session.ambient"
    # driving-table input fields (session.cypher(query, drivingTable))
    input_fields: Dict[str, CypherType] = dc_field(default_factory=dict)


class IRBuilder:
    def __init__(self, ctx: IRBuilderContext):
        self.ctx = ctx
        self.schema = ctx.schema
        self._fresh = itertools.count()

    # ------------------------------------------------------------------
    def fresh_name(self, prefix: str = "a") -> str:
        return f"__{prefix}{next(self._fresh)}"

    def build(self, stmt: A.Statement):
        if isinstance(stmt, A.SingleQuery):
            if any(isinstance(c, _WRITE_CLAUSES) for c in stmt.clauses):
                return self._build_update(stmt)
            return self._build_single(stmt)
        if isinstance(stmt, A.UnionQuery):
            irs = [self._build_single(q) for q in stmt.queries]
            cols = irs[0].returns
            for ir in irs[1:]:
                if ir.returns != cols:
                    raise IRBuildError(
                        f"UNION requires same return columns: {cols} vs {ir.returns}"
                    )
            return B.UnionIR(tuple(irs), all=stmt.all, returns=cols)
        if isinstance(stmt, A.CreateGraphStatement):
            inner = IRBuilder(self.ctx).build(stmt.inner)
            if isinstance(inner, B.UpdateIR):
                raise IRBuildError(
                    "CREATE GRAPH inner queries cannot contain write "
                    "clauses (use FROM/CONSTRUCT/RETURN GRAPH)"
                )
            return B.CreateGraphIR(stmt.qgn, inner)
        if isinstance(stmt, A.CreateViewStatement):
            return B.CreateViewIR(stmt.name, stmt.params, stmt.inner_text)
        if isinstance(stmt, A.DropGraphStatement):
            return B.DropGraphIR(stmt.qgn, stmt.view)
        raise IRBuildError(f"Unsupported statement {type(stmt).__name__}")

    # ------------------------------------------------------------------

    def _build_single(self, q: A.SingleQuery) -> B.QueryIR:
        env: Dict[str, CypherType] = dict(self.ctx.input_fields)
        blocks: List[B.Block] = []
        returns: Optional[Tuple[str, ...]] = None
        clauses = list(q.clauses)
        i = 0
        saw_return = False
        while i < len(clauses):
            c = clauses[i]
            if isinstance(c, A.Match):
                blocks.extend(self._convert_match(c, env))
            elif isinstance(c, A.Unwind):
                lst = self.convert_expr(c.expr, env)
                inner = self._list_inner_type(lst.cypher_type)
                blocks.append(B.UnwindBlock(lst, c.var))
                env[c.var] = inner
            elif isinstance(c, (A.With, A.Return)) and not isinstance(c, A.ReturnGraph):
                is_return = isinstance(c, A.Return)
                new_env, seg = self._convert_projection(c, env)
                blocks.extend(seg)
                env = new_env
                if is_return:
                    returns = tuple(env.keys())
                    blocks.append(B.ResultBlock(returns))
                    saw_return = True
            elif isinstance(c, A.FromGraph):
                if c.args:
                    # view invocations are expanded by the session BEFORE IR
                    # building; reaching here means the caller skipped
                    # CypherSession._expand_views
                    raise IRBuildError(
                        f"Unresolved view invocation {c.graph_name}(...) — "
                        "views resolve at the session level"
                    )
                qgn = self._resolve_qgn(c.graph_name)
                if qgn not in self.ctx.catalog_schemas:
                    raise IRBuildError(f"Unknown graph {qgn!r}")
                self.schema = self.ctx.catalog_schemas[qgn]
                blocks.append(B.FromGraphBlock(qgn))
            elif isinstance(c, A.ConstructClause):
                blocks.append(self._convert_construct(c, env))
            elif isinstance(c, A.ReturnGraph):
                blocks.append(B.GraphResultBlock())
                saw_return = True
            elif isinstance(c, _WRITE_CLAUSES):
                # single-query writes route through _build_update; reaching
                # here means a UNION branch or view body carries a write
                raise IRBuildError(
                    f"{type(c).__name__}: write clauses are only supported "
                    "in top-level single queries"
                )
            elif isinstance(c, A.CallClause):
                raise UnsupportedFeatureError(
                    f"CALL {c.procedure}: procedure calls are not supported"
                )
            else:
                raise IRBuildError(f"Unsupported clause {type(c).__name__}")
            i += 1
        if not saw_return:
            raise IRBuildError("Query must end in RETURN")
        return B.QueryIR(tuple(blocks), returns, self.ctx.working_graph)

    # ------------------------------------------------------------------
    # write queries (docs/mutation.md)
    # ------------------------------------------------------------------

    def _build_update(self, q: A.SingleQuery) -> B.UpdateIR:
        """Split a write query at its first write clause: the read prefix
        becomes a normal QueryIR returning every in-scope field (planned
        and executed on the pinned snapshot), the write suffix becomes
        host-evaluated write ops (relational/mutate.py)."""
        clauses = list(q.clauses)
        first = next(
            i for i, c in enumerate(clauses) if isinstance(c, _WRITE_CLAUSES)
        )
        reads, writes = clauses[:first], clauses[first:]
        env: Dict[str, CypherType] = dict(self.ctx.input_fields)
        blocks: List[B.Block] = []
        for c in reads:
            if isinstance(c, A.Match):
                blocks.extend(self._convert_match(c, env))
            elif isinstance(c, A.Unwind):
                lst = self.convert_expr(c.expr, env)
                blocks.append(B.UnwindBlock(lst, c.var))
                env[c.var] = self._list_inner_type(lst.cypher_type)
            elif isinstance(c, A.With) and not isinstance(c, A.Return):
                new_env, seg = self._convert_projection(c, env)
                blocks.extend(seg)
                env = new_env
            else:
                raise IRBuildError(
                    f"{type(c).__name__} cannot precede a write clause"
                )
        read_ir: Optional[B.QueryIR] = None
        if blocks:
            fields = tuple(n for n in env if not n.startswith("__"))
            blocks.append(B.ResultBlock(fields))
            read_ir = B.QueryIR(tuple(blocks), fields, self.ctx.working_graph)
        ops: List[B.Block] = []
        for c in writes:
            if isinstance(c, A.CreateClause):
                nodes, rels = self._convert_write_pattern(c.pattern, env)
                ops.append(B.CreateOp(nodes, rels))
            elif isinstance(c, A.MergeClause):
                ops.append(self._convert_merge(c, env))
            elif isinstance(c, A.SetClause):
                ops.append(
                    B.SetOp(
                        tuple(self._convert_set_item(it, env) for it in c.items)
                    )
                )
            elif isinstance(c, A.DeleteClause):
                ops.append(self._convert_delete(c, env))
            else:
                raise IRBuildError(
                    f"{type(c).__name__} cannot follow a write clause — "
                    "write queries end at their writes (RETURN after a "
                    "write is not supported; they return write counters)"
                )
        return B.UpdateIR(read_ir, tuple(ops), self.ctx.working_graph)

    def _convert_write_pattern(
        self, pattern: A.Pattern, env: Dict[str, CypherType]
    ) -> Tuple[Tuple[B.NodeTemplate, ...], Tuple[B.RelTemplate, ...]]:
        nodes: List[B.NodeTemplate] = []
        rels: List[B.RelTemplate] = []
        for part in pattern.parts:
            if part.path_var:
                raise IRBuildError("path variables are not allowed in writes")
            elems = part.elements
            prev = self._convert_write_node(elems[0], env, nodes)
            for j in range(1, len(elems), 2):
                rp: A.RelPattern = elems[j]
                nxt = self._convert_write_node(elems[j + 1], env, nodes)
                if len(rp.types) != 1:
                    raise IRBuildError(
                        "created relationships need exactly one type"
                    )
                if rp.direction == A.BOTH:
                    raise IRBuildError(
                        "created relationships need a direction"
                    )
                if rp.var and rp.var in env:
                    raise IRBuildError(
                        f"relationship variable {rp.var!r} already bound"
                    )
                var = rp.var or self.fresh_name("wr")
                props = self._convert_write_props(rp.properties, env)
                src, dst = (
                    (nxt, prev) if rp.direction == A.INCOMING else (prev, nxt)
                )
                rels.append(
                    B.RelTemplate(var, rp.types[0], src, dst, props)
                )
                env[var] = T.CTRelationshipType((rp.types[0],))
                prev = nxt
        return tuple(nodes), tuple(rels)

    def _convert_write_node(
        self, np: A.NodePattern, env: Dict[str, CypherType], out: List
    ) -> str:
        if np.var and np.var in env:
            m = env[np.var].material
            if not isinstance(m, T.CTNodeType):
                raise IRBuildError(f"{np.var!r} is not a node")
            if np.labels or np.properties is not None:
                raise IRBuildError(
                    f"bound variable {np.var!r} cannot carry labels or "
                    "properties in a write pattern"
                )
            out.append(B.NodeTemplate(np.var, bound=True))
            return np.var
        var = np.var or self.fresh_name("wn")
        props = self._convert_write_props(np.properties, env)
        out.append(
            B.NodeTemplate(var, bound=False, labels=tuple(np.labels), props=props)
        )
        env[var] = T.CTNodeType(tuple(np.labels))
        return var

    def _convert_write_props(
        self, properties, env: Dict[str, CypherType]
    ) -> Tuple[Tuple[str, E.Expr], ...]:
        if properties is None:
            return ()
        out = []
        for k, v in zip(properties.keys, properties.values):
            if k.startswith("__"):
                raise IRBuildError(
                    f"property key {k!r} is reserved (double-underscore "
                    "prefix marks system columns)"
                )
            out.append((k, self.convert_expr(v, env)))
        return tuple(out)

    def _convert_merge(
        self, c: A.MergeClause, env: Dict[str, CypherType]
    ) -> B.MergeOp:
        nodes, rels = self._convert_write_pattern(c.pattern, env)
        if len(rels) > 1:
            raise IRBuildError("MERGE supports at most one relationship")
        if rels:
            by_var = {t.var: t for t in nodes}
            for end in (rels[0].src, rels[0].dst):
                if not by_var[end].bound:
                    raise IRBuildError(
                        "MERGE relationship endpoints must be bound "
                        "variables (merge the nodes first)"
                    )
        on_create = tuple(self._convert_set_item(i, env) for i in c.on_create)
        on_match = tuple(self._convert_set_item(i, env) for i in c.on_match)
        return B.MergeOp(nodes, rels, on_create, on_match)

    def _convert_set_item(
        self, item: A.SetItem, env: Dict[str, CypherType]
    ) -> B.SetItemSpec:
        target = item.target
        if isinstance(target, E.Property):
            if not isinstance(target.expr, E.Var):
                raise IRBuildError("SET target must be a variable property")
            var = target.expr.name
            self._check_set_var(var, env)
            if target.key.startswith("__"):
                raise IRBuildError(
                    f"property key {target.key!r} is reserved"
                )
            return B.SetItemSpec(
                var, key=target.key, value=self.convert_expr(item.value, env)
            )
        if isinstance(target, E.Var):
            var = target.name
            self._check_set_var(var, env)
            if item.labels:
                return B.SetItemSpec(var, labels=tuple(item.labels))
            return B.SetItemSpec(var, value=self.convert_expr(item.value, env))
        raise IRBuildError(f"unsupported SET target {target.pretty_expr()}")

    def _check_set_var(self, var: str, env: Dict[str, CypherType]) -> None:
        if var not in env:
            raise IRBuildError(f"SET on unbound variable {var!r}")
        m = env[var].material
        if not isinstance(m, (T.CTNodeType, T.CTRelationshipType)):
            raise IRBuildError(f"SET target {var!r} is not an element")

    def _convert_delete(
        self, c: A.DeleteClause, env: Dict[str, CypherType]
    ) -> B.DeleteOp:
        fields = []
        for e in c.exprs:
            if not isinstance(e, E.Var):
                raise IRBuildError("DELETE takes bound element variables")
            if e.name not in env:
                raise IRBuildError(f"DELETE on unbound variable {e.name!r}")
            m = env[e.name].material
            if not isinstance(m, (T.CTNodeType, T.CTRelationshipType)):
                raise IRBuildError(f"DELETE target {e.name!r} is not an element")
            fields.append(e.name)
        return B.DeleteOp(tuple(fields), c.detach)

    def _resolve_qgn(self, name: str) -> str:
        if "." in name:
            return name
        return f"session.{name}"

    @staticmethod
    def _list_inner_type(t: CypherType) -> CypherType:
        m = t.material
        if isinstance(m, T.CTListType):
            return m.inner
        return T.CTAny.nullable

    # ------------------------------------------------------------------
    # MATCH
    # ------------------------------------------------------------------

    def _convert_match(self, c: A.Match, env: Dict[str, CypherType]) -> List[B.Block]:
        pattern, predicates = self.convert_pattern(c.pattern, env)
        # register new entities into env
        for n, t in pattern.node_types.items():
            env[n] = t
        for r, t in pattern.rel_types.items():
            conn = pattern.topology.get(r)
            if conn is not None and conn.is_var_length:
                env[r] = T.CTListType(t)
            else:
                env[r] = t
        for pname in pattern.paths:
            if pname in env or pname in pattern.node_types or pname in pattern.rel_types:
                raise IRBuildError(f"Path variable {pname!r} already bound")
            env[pname] = T.CTPath
        preds = list(predicates)
        if c.where is not None:
            w = self.convert_expr(c.where, env)
            preds.extend(w.exprs if isinstance(w, E.Ands) else [w])
        # assign target fields to exists-pattern predicates
        preds = [self._assign_exists_targets(p, env) for p in preds]
        return [B.MatchBlock(pattern, tuple(preds), c.optional)]

    def _assign_exists_targets(self, p: E.Expr, env) -> E.Expr:
        def rule(n):
            if isinstance(n, E.ExistsPattern) and n.target_field is None:
                sub_pattern, sub_preds = self.convert_pattern(n.pattern, dict(env))
                target = self.fresh_name("exists")
                clone = E.ExistsPattern(n.pattern, target)
                object.__setattr__(clone, "_ir_pattern", sub_pattern)
                object.__setattr__(clone, "_ir_predicates", tuple(sub_preds))
                object.__setattr__(clone, "_typ", T.CTBoolean)
                return clone
            return n

        return p.rewrite_top_down(rule)

    # ------------------------------------------------------------------
    # Pattern conversion
    # ------------------------------------------------------------------

    def convert_pattern(
        self,
        pattern: A.Pattern,
        env: Dict[str, CypherType],
        rel_uniqueness: bool = True,
    ) -> Tuple[IRPattern, List[E.Expr]]:
        """Frontend pattern -> IRPattern + lowered property predicates.

        ``rel_uniqueness`` adds the openCypher per-MATCH relationship-
        isomorphism predicates ``id(r_i) <> id(r_j)`` for every pair of
        fixed-length relationship variables whose type sets can intersect —
        the rewrite Neo4j's frontend performs (AddUniquenessPredicates)
        before the reference ever sees the query. CONSTRUCT patterns define
        NEW elements and pass False."""
        ir = IRPattern()
        predicates: List[E.Expr] = []

        def node_field(np: A.NodePattern) -> str:
            name = np.var or self.fresh_name("n")
            prev = env.get(name) or ir.node_types.get(name)
            if prev is not None:
                base = prev.material
                if not isinstance(base, T.CTNodeType):
                    raise IRBuildError(
                        f"Variable {name!r} already bound to {base!r}, cannot re-bind as node"
                    )
                labels = base.labels | frozenset(np.labels)
            else:
                labels = frozenset(np.labels)
                # label implication from schema
            t = T.CTNodeType(labels)
            ir.node_types[name] = t
            if np.labels and prev is not None:
                # extra label constraints on a bound var become predicates
                for l in np.labels:
                    predicates.append(
                        E.HasLabel(E.Var(name).with_type(t), l).with_type(T.CTBoolean)
                    )
            if np.properties is not None:
                var = E.Var(name).with_type(t)
                for k, v in zip(np.properties.keys, np.properties.values):
                    lhs = self._type_property(E.Property(var, k), t)
                    rhs = self.convert_expr(v, env)
                    predicates.append(
                        E.Equals(lhs, rhs).with_type(T.CTBoolean.nullable)
                    )
            if np.base_var:
                ir.base_entities[name] = np.base_var
            return name

        for part in pattern.parts:
            elems = part.elements
            prev_node = node_field(elems[0])
            path_fields: List[str] = [prev_node]
            for j in range(1, len(elems), 2):
                rp: A.RelPattern = elems[j]
                nxt = node_field(elems[j + 1])
                rname = rp.var or self.fresh_name("r")
                if rname in ir.rel_types or rname in ir.node_types:
                    # openCypher: a relationship variable cannot be re-bound
                    # within one pattern
                    raise IRBuildError(
                        f"Relationship variable {rname!r} bound more than once"
                    )
                bound_prev = env.get(rname)
                if bound_prev is not None:
                    # pre-bound relationship variable: plan the pattern step
                    # with a hidden fresh variable and JOIN it back on
                    # identity (the reference's bound-relationship planning;
                    # its failing_blacklist VarLengthAcceptance2 marks the
                    # var-length form — here the walked rel LIST must equal
                    # the bound value, [r] for a single pre-bound rel)
                    base = bound_prev.material
                    outer = E.Var(rname).with_type(bound_prev)
                    hidden = self.fresh_name("r")
                    is_varlen = rp.length is not None and rp.length != (1, 1)
                    if isinstance(base, T.CTRelationshipType) and not is_varlen:
                        inner_t = T.CTRelationshipType(rp.types)
                        predicates.append(
                            E.Equals(
                                E.Id(E.Var(hidden).with_type(inner_t)).with_type(
                                    T.CTInteger
                                ),
                                E.Id(outer).with_type(T.CTInteger),
                            ).with_type(T.CTBoolean)
                        )
                    elif isinstance(
                        base, (T.CTRelationshipType, T.CTListType)
                    ) and is_varlen:
                        inner_t = T.CTListType(
                            T.CTRelationshipType(rp.types)
                        )
                        rhs = (
                            E.ListLit((outer,)).with_type(inner_t)
                            if isinstance(base, T.CTRelationshipType)
                            else outer
                        )
                        predicates.append(
                            E.Equals(
                                E.Var(hidden).with_type(inner_t), rhs
                            ).with_type(T.CTBoolean.nullable)
                        )
                    else:
                        raise IRBuildError(
                            f"Variable {rname!r} already bound to {base!r}, "
                            "cannot re-bind as relationship"
                        )
                    rname = hidden
                rt = T.CTRelationshipType(rp.types)
                ir.rel_types[rname] = rt
                if rp.direction == INCOMING:
                    src, dst, direction = nxt, prev_node, OUTGOING
                elif rp.direction == OUTGOING:
                    src, dst, direction = prev_node, nxt, OUTGOING
                else:
                    src, dst, direction = prev_node, nxt, BOTH
                var_syntax = rp.length is not None
                if rp.length is None:
                    lo, hi = 1, 1
                else:
                    # hi None = unbounded '*' — resolved at relational
                    # planning to the matching-edge count (relationship
                    # isomorphism bounds any walk by the number of edges),
                    # with the frontier loop exiting at the empty-frontier
                    # fixpoint. The reference REJECTS unbounded (flink
                    # scenario_blacklist:6-7) — we execute it.
                    lo, hi = rp.length
                ir.topology[rname] = Connection(
                    src, dst, direction, lo, hi, var_syntax
                )
                if rp.properties is not None:
                    var = E.Var(rname).with_type(rt)
                    for k, v in zip(rp.properties.keys, rp.properties.values):
                        lhs = self._type_property(E.Property(var, k), rt)
                        rhs = self.convert_expr(v, env)
                        predicates.append(
                            E.Equals(lhs, rhs).with_type(T.CTBoolean.nullable)
                        )
                if rp.base_var:
                    ir.base_entities[rname] = rp.base_var
                path_fields.append(rname)
                path_fields.append(nxt)
                prev_node = nxt
            if part.path_var:
                if part.path_var in ir.paths:
                    raise IRBuildError(
                        f"Path variable {part.path_var!r} already bound"
                    )
                ir.paths[part.path_var] = tuple(path_fields)
        if rel_uniqueness:
            predicates.extend(self._uniqueness_predicates(ir))
        return ir, predicates

    def _uniqueness_predicates(self, ir: IRPattern) -> List[E.Expr]:
        """openCypher per-MATCH relationship-isomorphism predicates for
        every pair of relationship variables whose type sets can intersect
        (the rewrite Neo4j's frontend performs — AddUniquenessPredicates —
        before the reference ever sees the query; reference
        ``VarLengthExpandPlanner.scala:96,173-186`` additionally filters a
        var-length's edges against every rel element in scope):

        * fixed vs fixed — ``id(r1) <> id(r2)``;
        * fixed vs var-length — ``none(x IN rs WHERE id(x) = id(r))``;
        * var-length vs var-length —
          ``none(x IN rs1 WHERE any(y IN rs2 WHERE id(x) = id(y)))``.
        """
        fixed = [r for r, conn in ir.topology.items() if not conn.is_var_length]
        varlen = [r for r, conn in ir.topology.items() if conn.is_var_length]

        def may_intersect(r1: str, r2: str) -> bool:
            t1 = ir.rel_types[r1].types or None  # None/empty = any
            t2 = ir.rel_types[r2].types or None
            return t1 is None or t2 is None or bool(set(t1) & set(t2))

        def rel_id(r: str) -> E.Expr:
            return E.Id(E.Var(r).with_type(ir.rel_types[r])).with_type(T.CTInteger)

        def local_rel(rs: str) -> E.Var:
            return E.Var(self.fresh_name("uq")).with_type(ir.rel_types[rs])

        def local_id(v: E.Var) -> E.Expr:
            return E.Id(v).with_type(T.CTInteger)

        def list_of(rs: str) -> E.Expr:
            return E.Var(rs).with_type(T.CTListType(ir.rel_types[rs]))

        preds: List[E.Expr] = []
        for i in range(len(fixed)):
            for j in range(i + 1, len(fixed)):
                r1, r2 = fixed[i], fixed[j]
                if not may_intersect(r1, r2):
                    continue
                preds.append(
                    E.Neq(rel_id(r1), rel_id(r2)).with_type(T.CTBoolean)
                )
        for rs in varlen:
            for r in fixed:
                if not may_intersect(rs, r):
                    continue
                x = local_rel(rs)
                preds.append(
                    E.Quantified(
                        "none",
                        x,
                        list_of(rs),
                        E.Equals(local_id(x), rel_id(r)).with_type(T.CTBoolean),
                    ).with_type(T.CTBoolean)
                )
        for i in range(len(varlen)):
            for j in range(i + 1, len(varlen)):
                rs1, rs2 = varlen[i], varlen[j]
                if not may_intersect(rs1, rs2):
                    continue
                x, y = local_rel(rs1), local_rel(rs2)
                inner = E.Quantified(
                    "any",
                    y,
                    list_of(rs2),
                    E.Equals(local_id(x), local_id(y)).with_type(T.CTBoolean),
                ).with_type(T.CTBoolean)
                preds.append(
                    E.Quantified("none", x, list_of(rs1), inner).with_type(
                        T.CTBoolean
                    )
                )
        return preds

    # ------------------------------------------------------------------
    # WITH / RETURN
    # ------------------------------------------------------------------

    def _convert_projection(
        self, c: A.ProjectionClause, env: Dict[str, CypherType]
    ) -> Tuple[Dict[str, CypherType], List[B.Block]]:
        blocks: List[B.Block] = []
        items: List[Tuple[str, E.Expr]] = []
        seen: set = set()
        if c.star:
            for name, t in env.items():
                if name.startswith("__"):
                    continue
                items.append((name, E.Var(name).with_type(t)))
                seen.add(name)
        for it in c.items:
            # convert_expr assigns exists-pattern targets inline, so the
            # projected expression is subquery-ready for the planner's
            # _extract_exists (the reference's pattern-expression rewriter)
            converted = self.convert_expr(it.expr, env)
            name = it.alias or it.name
            if name in seen:
                raise IRBuildError(f"Duplicate return column {name!r}")
            seen.add(name)
            items.append((name, converted))

        has_agg = any(E.has_aggregation(e) for _, e in items)
        if has_agg:
            blocks.extend(self._aggregation_blocks(items, env))
        else:
            blocks.append(B.ProjectBlock(tuple(items), distinct=False))
        # environment after projection (pre-narrowing): old fields + new
        wide_env = dict(env)
        new_env: Dict[str, CypherType] = {}
        for name, e in items:
            t = e.cypher_type
            if E.has_aggregation(e):
                t = self._agg_result_type(e)
            wide_env[name] = t
            new_env[name] = t
        if has_agg:
            # aggregation narrows the horizon immediately
            wide_env = dict(new_env)

        # with DISTINCT the horizon narrows first: WHERE/ORDER BY may only
        # reference the projected items (Neo4j's scoping rule); otherwise the
        # wide pre-narrowing scope is visible
        rest_env = new_env if c.distinct else wide_env

        def convert_rest(ast_expr) -> E.Expr:
            """After aggregation, ORDER BY/WHERE may also reference grouping
            or aggregate EXPRESSIONS (``ORDER BY b.name``, ``ORDER BY
            count(*)``): convert them in the pre-projection scope and
            substitute each projected expression with its output column."""
            try:
                return self.convert_expr(ast_expr, rest_env)
            except IRBuildError:
                if not has_agg:
                    raise
                e = self.convert_expr(ast_expr, env)
                proj_sub = {
                    pe: E.Var(nm).with_type(new_env[nm]) for nm, pe in items
                }
                e = E.substitute(e, proj_sub)
                for node in e.iter_nodes():
                    if isinstance(node, E.Var) and node.name not in rest_env:
                        raise IRBuildError(
                            f"Variable {node.name!r} not visible after aggregation"
                        )
                return e

        where_pred = None
        if c.where is not None:
            where_pred = convert_rest(c.where)

        sort_items = []
        for s in c.order_by:
            sort_items.append(A.SortItem(convert_rest(s.expr), s.ascending))
        skip = self.convert_expr(c.skip, rest_env) if c.skip is not None else None
        limit = self.convert_expr(c.limit, rest_env) if c.limit is not None else None

        if c.distinct:
            blocks.append(B.SelectBlock(tuple(new_env.keys())))
            blocks.append(B.DistinctBlock(tuple(new_env.keys())))
            if where_pred is not None:
                blocks.append(B.FilterBlock(where_pred))
            if sort_items or skip is not None or limit is not None:
                blocks.append(B.OrderAndSliceBlock(tuple(sort_items), skip, limit))
        else:
            if where_pred is not None:
                blocks.append(B.FilterBlock(where_pred))
            if sort_items or skip is not None or limit is not None:
                blocks.append(B.OrderAndSliceBlock(tuple(sort_items), skip, limit))
            blocks.append(B.SelectBlock(tuple(new_env.keys())))
        return new_env, blocks

    def _aggregation_blocks(
        self, items: List[Tuple[str, E.Expr]], env: Dict[str, CypherType]
    ) -> List[B.Block]:
        """Aggregation isolation (reference ``isolateAggregation`` rewriter)."""
        group: List[Tuple[str, E.Expr]] = []
        aggs: List[Tuple[str, E.Agg]] = []
        post: List[Tuple[str, E.Expr]] = []
        needs_post = False

        for name, e in items:
            if not E.has_aggregation(e):
                group.append((name, e))
                post.append((name, E.Var(name).with_type(e.cypher_type)))
                continue
            if isinstance(e, (E.Agg, E.CountStar)):
                agg = self._normalize_agg(e)
                aggs.append((name, agg))
                post.append((name, E.Var(name).with_type(self._agg_result_type(e))))
            else:
                # expression over aggregates: extract each Agg into a fresh field
                mapping: Dict[E.Expr, E.Expr] = {}
                for node in e.iter_nodes():
                    if isinstance(node, (E.Agg, E.CountStar)) and node not in mapping:
                        f = self.fresh_name("agg")
                        aggs.append((f, self._normalize_agg(node)))
                        mapping[node] = E.Var(f).with_type(self._agg_result_type(node))
                rewritten = E.substitute(e, mapping)
                rewritten = self._retype(rewritten, {**env, **{m.name: m.cypher_type for m in mapping.values()}})
                post.append((name, rewritten))
                needs_post = True

        blocks: List[B.Block] = [B.AggregationBlock(tuple(group), tuple(aggs))]
        if needs_post:
            blocks.append(B.ProjectBlock(tuple(post), distinct=False))
            blocks.append(B.SelectBlock(tuple(n for n, _ in post)))
        return blocks

    @staticmethod
    def _normalize_agg(e: E.Expr) -> E.Agg:
        if isinstance(e, E.CountStar):
            return E.Agg("count", None, False)
        assert isinstance(e, E.Agg)
        return e

    @staticmethod
    def _agg_result_type(e: E.Expr) -> CypherType:
        if isinstance(e, E.CountStar):
            return T.CTInteger
        if isinstance(e, E.Agg):
            name = e.name
            at = e.expr.cypher_type.material if e.expr is not None else T.CTAny
            if name == "count":
                return T.CTInteger
            if name == "collect":
                return T.CTListType(at)
            if name in ("min", "max"):
                return at.nullable
            if name == "sum":
                return at if at in (T.CTInteger, T.CTFloat) else T.CTNumber
            if name == "avg":
                return T.CTDuration if at == T.CTDuration else T.CTFloat
            if name in ("stdev", "stdevp"):
                return T.CTFloat
            if name in ("percentilecont",):
                return T.CTFloat.nullable
            if name == "percentiledisc":
                return at.nullable
        # expression over aggregations
        return e.cypher_type

    # ------------------------------------------------------------------
    # CONSTRUCT
    # ------------------------------------------------------------------

    def _convert_construct(self, c: A.ConstructClause, env) -> B.ConstructBlock:
        clones: List[Tuple[str, str]] = []
        for item in c.clones:
            if not isinstance(item.expr, E.Var):
                raise IRBuildError("CLONE items must be variables")
            src = item.expr.name
            if src not in env:
                raise IRBuildError(f"CLONE of unbound variable {src!r}")
            clones.append((item.alias or src, src))
        clone_env = dict(env)
        for new, src in clones:
            clone_env[new] = env[src]
        new_pattern = IRPattern()
        new_props: List[Tuple[str, str, E.Expr]] = []
        cloned = {new for new, _ in clones}
        for pat in c.news:
            ir, preds = self.convert_pattern(pat, clone_env, rel_uniqueness=False)
            for n, base in ir.base_entities.items():
                # a COPY OF target must be a FRESH name: colliding with a
                # bound var, clone alias, or earlier COPY declaration would
                # silently drop one of the two meanings
                if n in clone_env:
                    raise IRBuildError(
                        f"COPY OF target {n!r} is already bound; use a "
                        "fresh variable (CLONE keeps element identity)"
                    )
                prev = new_pattern.base_entities.get(n)
                if prev is not None and prev != base:
                    raise IRBuildError(
                        f"COPY OF target {n!r} declared more than once"
                    )
            for n, t in ir.node_types.items():
                if n in clone_env:
                    # references an existing/cloned entity: an implicit clone
                    # (reference: bound vars in NEW patterns are cloned)
                    if n in env and n not in cloned:
                        clones.append((n, n))
                        cloned.add(n)
                    continue
                prev = new_pattern.node_types.get(n)
                if prev is not None:
                    # the same new node re-referenced by a later NEW clause:
                    # label sets UNION (overwriting would drop the first
                    # declaration's labels)
                    t = T.CTNodeType(
                        prev.material.labels | t.material.labels
                    )
                new_pattern.node_types[n] = t
            for r, t in ir.rel_types.items():
                new_pattern.rel_types[r] = t
            new_pattern.topology.update(ir.topology)
            new_pattern.base_entities.update(ir.base_entities)
            # property map predicates become property settings
            for p in preds:
                if isinstance(p, E.Equals) and isinstance(p.lhs, E.Property):
                    owner = p.lhs.expr
                    assert isinstance(owner, E.Var)
                    new_props.append((owner.name, p.lhs.key, p.rhs))
        # COPY OF targets resolve like their base in SET value expressions
        # (the planner aliases the target's columns to the base's)
        for name, base in new_pattern.base_entities.items():
            if base in clone_env and name not in clone_env:
                clone_env[name] = clone_env[base]
        sets: List[Tuple[str, str, E.Expr]] = []
        set_labels: List[Tuple[str, Tuple[str, ...]]] = []
        for s in c.sets:
            if s.labels:
                assert isinstance(s.target, E.Var)
                set_labels.append((s.target.name, s.labels))
            elif isinstance(s.target, E.Property):
                owner = s.target.expr
                assert isinstance(owner, E.Var)
                sets.append(
                    (owner.name, s.target.key, self.convert_expr(s.value, clone_env))
                )
            else:
                raise IRBuildError("Unsupported SET item in CONSTRUCT")
        on_graphs = tuple(self._resolve_qgn(g) for g in c.on_graphs)
        return B.ConstructBlock(
            on_graphs, tuple(clones), new_pattern, tuple(new_props), tuple(sets), tuple(set_labels)
        )

    # ------------------------------------------------------------------
    # Expressions + typing
    # ------------------------------------------------------------------

    def convert_expr(self, e: E.Expr, env: Dict[str, CypherType]) -> E.Expr:
        return self._retype(e, env)

    def _retype(self, e: E.Expr, env: Dict[str, CypherType]) -> E.Expr:
        conv = self._retype  # shorthand

        if isinstance(e, E.Var):
            if e.name not in env:
                raise IRBuildError(f"Variable {e.name!r} not defined")
            return e.with_type(env[e.name])
        if isinstance(e, E.Param):
            val = self.ctx.parameters.get(e.name)
            t = T.type_of_value(val) if val is not None else T.CTAny.nullable
            return e.with_type(t)
        if isinstance(e, E.Lit):
            return e.with_type(T.type_of_value(e.value))
        if isinstance(e, E.ListLit):
            items = tuple(conv(i, env) for i in e.items)
            inner = T.join_types(i.cypher_type for i in items)
            return E.ListLit(items).with_type(T.CTListType(inner))
        if isinstance(e, E.MapLit):
            vals = tuple(conv(v, env) for v in e.values)
            return E.MapLit(e.keys, vals).with_type(
                T.CTMapType({k: v.cypher_type for k, v in zip(e.keys, vals)})
            )
        if isinstance(e, E.Property):
            owner = conv(e.expr, env)
            return self._type_property(E.Property(owner, e.key), owner.cypher_type)
        if isinstance(e, E.HasLabel):
            return E.HasLabel(conv(e.expr, env), e.label).with_type(T.CTBoolean)
        if isinstance(e, E.HasType):
            return E.HasType(conv(e.expr, env), e.rel_type).with_type(T.CTBoolean)
        if isinstance(e, (E.Id, E.StartNode, E.EndNode)):
            inner = conv(e.expr, env)
            t = T.CTInteger if isinstance(e, E.Id) else T.CTNodeType(())
            return type(e)(inner).with_type(t)
        if isinstance(e, E.Ands):
            return E.Ands(tuple(conv(x, env) for x in e.exprs)).with_type(
                T.CTBoolean.nullable
            )
        if isinstance(e, E.Ors):
            return E.Ors(tuple(conv(x, env) for x in e.exprs)).with_type(
                T.CTBoolean.nullable
            )
        if isinstance(e, (E.Xor,)):
            return E.Xor(conv(e.lhs, env), conv(e.rhs, env)).with_type(
                T.CTBoolean.nullable
            )
        if isinstance(e, E.Not):
            return E.Not(conv(e.expr, env)).with_type(T.CTBoolean.nullable)
        if isinstance(e, (E.IsNull, E.IsNotNull)):
            return type(e)(conv(e.expr, env)).with_type(T.CTBoolean)
        if isinstance(e, E.BinaryPredicate):
            lhs, rhs = conv(e.lhs, env), conv(e.rhs, env)
            return type(e)(lhs, rhs).with_type(T.CTBoolean.nullable)
        if isinstance(e, E.Neg):
            inner = conv(e.expr, env)
            return E.Neg(inner).with_type(inner.cypher_type)
        if isinstance(e, E.ArithmeticExpr):
            lhs, rhs = conv(e.lhs, env), conv(e.rhs, env)
            return type(e)(lhs, rhs).with_type(self._arith_type(type(e), lhs, rhs))
        if isinstance(e, E.FunctionCall):
            return self._type_function(e, env)
        if isinstance(e, E.Agg):
            inner = conv(e.expr, env) if e.expr is not None else None
            extra = tuple(conv(x, env) for x in e.extra)
            out = E.Agg(e.name, inner, e.distinct, extra)
            return out.with_type(self._agg_result_type(out))
        if isinstance(e, E.CountStar):
            return e.with_type(T.CTInteger)
        if isinstance(e, E.CaseExpr):
            operand = conv(e.operand, env) if e.operand is not None else None
            whens = tuple(conv(w, env) for w in e.whens)
            thens = tuple(conv(t, env) for t in e.thens)
            default = conv(e.default, env) if e.default is not None else None
            result = T.join_types(t.cypher_type for t in thens)
            if default is not None:
                result = result.join(default.cypher_type)
            else:
                result = result.nullable
            return E.CaseExpr(operand, whens, thens, default).with_type(result)
        if isinstance(e, E.Index):
            owner = conv(e.expr, env)
            idx = conv(e.index, env)
            m = owner.cypher_type.material
            if isinstance(m, T.CTListType):
                t = m.inner.nullable
            elif isinstance(m, T.CTMapType) and m.fields is not None:
                t = T.join_types(dict(m.fields).values()).nullable
            else:
                t = T.CTAny.nullable
            return E.Index(owner, idx).with_type(t)
        if isinstance(e, E.ListSlice):
            owner = conv(e.expr, env)
            return E.ListSlice(
                owner,
                conv(e.from_, env) if e.from_ is not None else None,
                conv(e.to, env) if e.to is not None else None,
            ).with_type(owner.cypher_type.material.nullable if isinstance(owner.cypher_type.material, T.CTListType) else T.CTListType(T.CTAny).nullable)
        if isinstance(e, E.ListComprehension):
            lst = conv(e.list_expr, env)
            inner_t = self._list_inner_type(lst.cypher_type)
            env2 = {**env, e.var.name: inner_t}
            where = conv(e.where, env2) if e.where is not None else None
            proj = conv(e.projection, env2) if e.projection is not None else None
            out_t = proj.cypher_type if proj is not None else inner_t
            return E.ListComprehension(
                e.var.with_type(inner_t), lst, where, proj
            ).with_type(T.CTListType(out_t))
        if isinstance(e, E.Quantified):
            lst = conv(e.list_expr, env)
            inner_t = self._list_inner_type(lst.cypher_type)
            env2 = {**env, e.var.name: inner_t}
            return E.Quantified(
                e.kind, e.var.with_type(inner_t), lst, conv(e.predicate, env2)
            ).with_type(T.CTBoolean.nullable)
        if isinstance(e, E.Reduce):
            lst = conv(e.list_expr, env)
            inner_t = self._list_inner_type(lst.cypher_type)
            init = conv(e.init, env)
            env2 = {**env, e.var.name: inner_t, e.acc.name: init.cypher_type}
            body = conv(e.expr, env2)
            # widen accumulator
            env2[e.acc.name] = init.cypher_type.join(body.cypher_type)
            body = conv(e.expr, env2)
            return E.Reduce(
                e.acc.with_type(env2[e.acc.name]),
                init,
                e.var.with_type(inner_t),
                lst,
                body,
            ).with_type(body.cypher_type)
        if isinstance(e, E.MapProjection):
            var = conv(e.var, env)
            items = tuple(
                (k, conv(v, env) if v is not None else None) for k, v in e.items
            )
            return E.MapProjection(var, items, e.all_props).with_type(T.CTMapType(None))
        if isinstance(e, E.ExistsPattern):
            return self._assign_exists_targets(e, env)
        if isinstance(e, E.PatternComprehension):
            return self._convert_pattern_comprehension(e, env)
        raise IRBuildError(f"Cannot convert expression {type(e).__name__}")

    def _convert_pattern_comprehension(
        self, e: E.PatternComprehension, env: Dict[str, CypherType]
    ) -> E.PatternComprehension:
        """Convert the comprehension's inner pattern/WHERE/projection in an
        inner scope (outer vars correlated, pattern vars fresh) and attach
        the results for the logical planner's collect-subquery extraction
        (the exists-pattern treatment, ``_assign_exists_targets``)."""
        if e.target_field is not None:
            return e
        inner_env = dict(env)
        sub_pattern, sub_preds = self.convert_pattern(e.pattern, inner_env)
        for n, t in sub_pattern.node_types.items():
            inner_env[n] = t
        for r, t in sub_pattern.rel_types.items():
            conn = sub_pattern.topology.get(r)
            if conn is not None and conn.is_var_length:
                inner_env[r] = T.CTListType(t)
            else:
                inner_env[r] = t
        for pname in sub_pattern.paths:
            inner_env[pname] = T.CTPath
        preds = list(sub_preds)
        if e.where is not None:
            w = self.convert_expr(e.where.value, inner_env)
            preds.extend(w.exprs if isinstance(w, E.Ands) else [w])
        proj = self.convert_expr(e.projection.value, inner_env)
        target = self.fresh_name("pc")
        clone = E.PatternComprehension(
            e.pattern, e.path_var, e.where, E.Opaque(proj), target
        )
        object.__setattr__(clone, "_ir_pattern", sub_pattern)
        object.__setattr__(clone, "_ir_predicates", tuple(preds))
        object.__setattr__(clone, "_ir_projection", proj)
        object.__setattr__(clone, "_typ", T.CTListType(proj.cypher_type))
        return clone

    def _type_property(self, p: E.Property, owner_t: CypherType) -> E.Expr:
        m = owner_t.material
        key = p.key
        if isinstance(m, T.CTNodeType):
            keys = self.schema.node_property_keys_for_labels(m.labels)
            t = keys.get(key, T.CTNull)
        elif isinstance(m, T.CTRelationshipType):
            keys = self.schema.relationship_property_keys_for_types(m.types)
            t = keys.get(key, T.CTNull)
        elif isinstance(m, T.CTMapType):
            if m.fields is None:
                t = T.CTAny.nullable
            else:
                t = dict(m.fields).get(key, T.CTNull)
        elif isinstance(
            m,
            (
                T.CTDateType,
                T.CTLocalDateTimeType,
                T.CTDateTimeType,
                T.CTTimeType,
                T.CTLocalTimeType,
            ),
        ):
            t = (
                T.CTString
                if key.lower() in ("timezone", "offset")
                else T.CTInteger
            )
        elif isinstance(m, T.CTDurationType):
            t = T.CTInteger
        elif isinstance(m, T.CTListType):
            # var-length rel list: properties distribute over elements
            t = T.CTListType(T.CTAny.nullable)
        else:
            t = T.CTAny.nullable
        if owner_t.is_nullable and not t.is_nullable and t != T.CTNull:
            t = t.nullable
        return p.with_type(t)

    @staticmethod
    def _arith_type(op, lhs: E.Expr, rhs: E.Expr) -> CypherType:
        lt, rt = lhs.cypher_type.material, rhs.cypher_type.material
        nullable = lhs.cypher_type.is_nullable or rhs.cypher_type.is_nullable
        out: CypherType
        if op is E.Add:
            if lt == T.CTString or rt == T.CTString:
                out = T.CTString
            elif isinstance(lt, T.CTListType) or isinstance(rt, T.CTListType):
                li = lt.inner if isinstance(lt, T.CTListType) else lt
                ri = rt.inner if isinstance(rt, T.CTListType) else rt
                out = T.CTListType(li.join(ri))
            elif lt == T.CTDuration and rt in (T.CTDate, T.CTLocalDateTime):
                out = rt
            elif rt == T.CTDuration and lt in (T.CTDate, T.CTLocalDateTime, T.CTDuration):
                out = lt
            else:
                out = IRBuilder._numeric_join(lt, rt)
        elif op is E.Subtract:
            if rt == T.CTDuration and lt in (T.CTDate, T.CTLocalDateTime, T.CTDuration):
                out = lt
            else:
                out = IRBuilder._numeric_join(lt, rt)
        elif op is E.Divide:
            if lt == T.CTInteger and rt == T.CTInteger:
                out = T.CTInteger
            else:
                out = IRBuilder._numeric_join(lt, rt)
        elif op is E.Pow:
            out = T.CTFloat
        else:
            out = IRBuilder._numeric_join(lt, rt)
        return out.nullable if nullable else out

    @staticmethod
    def _numeric_join(lt: CypherType, rt: CypherType) -> CypherType:
        if lt == T.CTFloat or rt == T.CTFloat:
            return T.CTFloat
        if lt == T.CTInteger and rt == T.CTInteger:
            return T.CTInteger
        if isinstance(lt, T.CTBigDecimalType) or isinstance(rt, T.CTBigDecimalType):
            if isinstance(lt, T.CTBigDecimalType) and isinstance(rt, T.CTBigDecimalType):
                return T.CTBigDecimalType()
            return T.CTBigDecimalType()
        return T.CTNumber

    def _type_function(self, e: E.FunctionCall, env) -> E.Expr:
        args = tuple(self._retype(a, env) for a in e.args)
        name = e.name
        # element-column rewrites (these ARE physical columns)
        if name == "id" and len(args) == 1:
            return E.Id(args[0]).with_type(T.CTInteger)
        if name == "startnode" and len(args) == 1:
            m = args[0].cypher_type.material
            return E.StartNode(args[0]).with_type(T.CTNodeType(()))
        if name == "endnode" and len(args) == 1:
            return E.EndNode(args[0]).with_type(T.CTNodeType(()))
        f = lookup_function(name)
        if len(args) < f.min_args or (f.max_args >= 0 and len(args) > f.max_args):
            raise IRBuildError(
                f"Wrong number of arguments for {name}(): got {len(args)}"
            )
        t = f.result_type([a.cypher_type for a in args])
        if f.null_prop and any(a.cypher_type.is_nullable for a in args):
            t = t.nullable
        return E.FunctionCall(name, args).with_type(t)


def build_ir(stmt: A.Statement, ctx: IRBuilderContext):
    """Entry point (reference ``IRBuilder.process``)."""
    return IRBuilder(ctx).build(stmt)

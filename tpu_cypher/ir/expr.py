"""The expression tree.

Re-design of the reference's ``Expr`` hierarchy
(``okapi-ir/src/main/scala/org/opencypher/okapi/ir/api/expr/Expr.scala:52-1220``,
~150 case classes). Key differences:

* ONE expression tree is shared by the parser AST, the IR, and the physical
  layer (the reference has a separate Neo4j-frontend AST; we own the parser, so
  a single tree with an optional ``typ`` slot that the typer fills suffices).
* Scalar functions are a single ``FunctionCall`` node resolved against a
  signature table (``tpu_cypher.ir.functions``) instead of ~70 case classes;
  aggregators are a single ``Agg`` node. Column-level expressions that the
  RecordHeader tracks per element variable (``Id``, ``HasLabel``, ``HasType``,
  ``StartNode``, ``EndNode``, ``Property``) stay dedicated nodes as in the
  reference (``Expr.scala``: ``Id``, ``HasLabel``, ``HasType``, ``StartNode``,
  ``EndNode``, ``Property``) because they key physical columns.

All nodes are frozen dataclasses on the TreeNode substrate, so plan rewrites
(CNF normalization, alias substitution) reuse the generic rewriting machinery.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Any, Optional, Tuple

from ..api import types as CT
from ..api.types import CypherType
from ..trees import TreeNode


@dataclass(frozen=True)
class Expr(TreeNode):
    """Base expression. ``typ`` is None until the typer runs."""

    def __post_init__(self):
        pass

    @property
    def typ(self) -> Optional[CypherType]:
        return getattr(self, "_typ", None)

    def with_type(self, t: CypherType) -> "Expr":
        clone = replace(self)
        object.__setattr__(clone, "_typ", t)
        return clone

    @property
    def cypher_type(self) -> CypherType:
        t = self.typ
        return t if t is not None else CT.CTAny.nullable

    def with_new_children(self, new_children):
        out = super().with_new_children(new_children)
        if out is not self and self.typ is not None and out.typ is None:
            object.__setattr__(out, "_typ", self.typ)
        return out

    def _show_inner(self) -> str:  # pragma: no cover - cosmetic
        return super()._show_inner()

    def __str__(self) -> str:
        return self.pretty_expr()

    def pretty_expr(self) -> str:
        return repr(self)


def _copy_type(src: Expr, dst: Expr) -> Expr:
    t = src.typ
    if t is not None:
        object.__setattr__(dst, "_typ", t)
    return dst


# ---------------------------------------------------------------------------
# Leaves
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Var(Expr):
    """A named binding (reference ``Var``, ``Expr.scala:106``)."""

    name: str

    def pretty_expr(self) -> str:
        return self.name


@dataclass(frozen=True)
class Param(Expr):
    """$parameter (reference ``Param``)."""

    name: str

    def pretty_expr(self) -> str:
        return f"${self.name}"


@dataclass(frozen=True, eq=False)
class Lit(Expr):
    """A literal scalar (int/float/str/bool/None)."""

    value: Any

    # custom eq/hash: Python's 1 == True would conflate Lit(1) and Lit(True)
    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Lit)
            and type(other.value) is type(self.value)
            and other.value == self.value
        )

    def __hash__(self) -> int:
        return hash(("Lit", type(self.value).__name__, self.value))

    def pretty_expr(self) -> str:
        from ..api.values import to_cypher_string

        return to_cypher_string(self.value)


NULL = Lit(None)
TRUE = Lit(True)
FALSE = Lit(False)


@dataclass(frozen=True)
class ListLit(Expr):
    items: Tuple[Expr, ...]

    def pretty_expr(self) -> str:
        return "[" + ", ".join(i.pretty_expr() for i in self.items) + "]"


@dataclass(frozen=True)
class MapLit(Expr):
    keys: Tuple[str, ...]
    values: Tuple[Expr, ...]

    def pretty_expr(self) -> str:
        inner = ", ".join(f"{k}: {v.pretty_expr()}" for k, v in zip(self.keys, self.values))
        return "{" + inner + "}"


# ---------------------------------------------------------------------------
# Column-level element expressions (RecordHeader keys)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Id(Expr):
    """Element id of a var (reference ``Id``)."""

    expr: Expr

    def pretty_expr(self) -> str:
        return f"id({self.expr.pretty_expr()})"


@dataclass(frozen=True)
class StartNode(Expr):
    expr: Expr

    def pretty_expr(self) -> str:
        return f"startNode({self.expr.pretty_expr()})"


@dataclass(frozen=True)
class EndNode(Expr):
    expr: Expr

    def pretty_expr(self) -> str:
        return f"endNode({self.expr.pretty_expr()})"


@dataclass(frozen=True)
class HasLabel(Expr):
    expr: Expr
    label: str

    def pretty_expr(self) -> str:
        return f"{self.expr.pretty_expr()}:{self.label}"


@dataclass(frozen=True)
class HasType(Expr):
    expr: Expr
    rel_type: str

    def pretty_expr(self) -> str:
        return f"type({self.expr.pretty_expr()}) = '{self.rel_type}'"


@dataclass(frozen=True)
class Property(Expr):
    expr: Expr
    key: str

    def pretty_expr(self) -> str:
        return f"{self.expr.pretty_expr()}.{self.key}"


@dataclass(frozen=True)
class AliasExpr(Expr):
    """``expr AS alias`` (reference ``AliasExpr``)."""

    expr: Expr
    alias: Var

    def pretty_expr(self) -> str:
        return f"{self.expr.pretty_expr()} AS {self.alias.name}"


# ---------------------------------------------------------------------------
# Predicates
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Ands(Expr):
    exprs: Tuple[Expr, ...]

    @staticmethod
    def of(*exprs: Expr) -> Expr:
        flat = []
        for e in exprs:
            if isinstance(e, Ands):
                flat.extend(e.exprs)
            else:
                flat.append(e)
        flat = [e for e in flat if e != TRUE]
        if not flat:
            return TRUE
        if len(flat) == 1:
            return flat[0]
        return Ands(tuple(flat))

    def pretty_expr(self) -> str:
        return " AND ".join(f"({e.pretty_expr()})" for e in self.exprs)


@dataclass(frozen=True)
class Ors(Expr):
    exprs: Tuple[Expr, ...]

    @staticmethod
    def of(*exprs: Expr) -> Expr:
        flat = []
        for e in exprs:
            if isinstance(e, Ors):
                flat.extend(e.exprs)
            else:
                flat.append(e)
        if not flat:
            return FALSE
        if len(flat) == 1:
            return flat[0]
        return Ors(tuple(flat))

    def pretty_expr(self) -> str:
        return " OR ".join(f"({e.pretty_expr()})" for e in self.exprs)


@dataclass(frozen=True)
class Xor(Expr):
    lhs: Expr
    rhs: Expr


@dataclass(frozen=True)
class Not(Expr):
    expr: Expr

    def pretty_expr(self) -> str:
        return f"NOT ({self.expr.pretty_expr()})"


class BinaryPredicate(Expr):
    pass


def _binop(name: str, symbol: str):
    @dataclass(frozen=True)
    class _Op(BinaryPredicate):
        lhs: Expr
        rhs: Expr

        def pretty_expr(self) -> str:
            return f"{self.lhs.pretty_expr()} {symbol} {self.rhs.pretty_expr()}"

    _Op.__name__ = _Op.__qualname__ = name
    _Op.symbol = symbol
    return _Op


Equals = _binop("Equals", "=")
Neq = _binop("Neq", "<>")
LessThan = _binop("LessThan", "<")
LessThanOrEqual = _binop("LessThanOrEqual", "<=")
GreaterThan = _binop("GreaterThan", ">")
GreaterThanOrEqual = _binop("GreaterThanOrEqual", ">=")
In = _binop("In", "IN")
StartsWith = _binop("StartsWith", "STARTS WITH")
EndsWith = _binop("EndsWith", "ENDS WITH")
Contains = _binop("Contains", "CONTAINS")
RegexMatch = _binop("RegexMatch", "=~")


@dataclass(frozen=True)
class IsNull(Expr):
    expr: Expr

    def pretty_expr(self) -> str:
        return f"{self.expr.pretty_expr()} IS NULL"


@dataclass(frozen=True)
class IsNotNull(Expr):
    expr: Expr

    def pretty_expr(self) -> str:
        return f"{self.expr.pretty_expr()} IS NOT NULL"


# ---------------------------------------------------------------------------
# Arithmetic
# ---------------------------------------------------------------------------


class ArithmeticExpr(Expr):
    pass


def _arith(name: str, symbol: str):
    @dataclass(frozen=True)
    class _Op(ArithmeticExpr):
        lhs: Expr
        rhs: Expr

        def pretty_expr(self) -> str:
            return f"({self.lhs.pretty_expr()} {symbol} {self.rhs.pretty_expr()})"

    _Op.__name__ = _Op.__qualname__ = name
    _Op.symbol = symbol
    return _Op


Add = _arith("Add", "+")
Subtract = _arith("Subtract", "-")
Multiply = _arith("Multiply", "*")
Divide = _arith("Divide", "/")
Modulo = _arith("Modulo", "%")
Pow = _arith("Pow", "^")


@dataclass(frozen=True)
class Neg(ArithmeticExpr):
    expr: Expr

    def pretty_expr(self) -> str:
        return f"-({self.expr.pretty_expr()})"


# ---------------------------------------------------------------------------
# Functions & aggregators
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class FunctionCall(Expr):
    """A scalar function call, resolved by name against ``ir.functions``."""

    name: str  # canonical lower-case
    args: Tuple[Expr, ...]

    def pretty_expr(self) -> str:
        return f"{self.name}(" + ", ".join(a.pretty_expr() for a in self.args) + ")"


@dataclass(frozen=True)
class Agg(Expr):
    """An aggregator (count/sum/avg/min/max/collect/stDev/stDevP/percentiles).

    Reference: ``Expr.scala`` ``Aggregator`` family.
    """

    name: str
    expr: Optional[Expr]
    distinct: bool = False
    extra: Tuple[Expr, ...] = ()  # e.g. percentile fraction

    def pretty_expr(self) -> str:
        inner = "DISTINCT " if self.distinct else ""
        arg = self.expr.pretty_expr() if self.expr is not None else "*"
        return f"{self.name}({inner}{arg})"


@dataclass(frozen=True)
class CountStar(Expr):
    def pretty_expr(self) -> str:
        return "count(*)"


# ---------------------------------------------------------------------------
# Conditionals / comprehensions
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class CaseExpr(Expr):
    """Both simple (operand != None) and generic CASE."""

    operand: Optional[Expr]
    whens: Tuple[Expr, ...]
    thens: Tuple[Expr, ...]
    default: Optional[Expr]

    def pretty_expr(self) -> str:
        parts = ["CASE"]
        if self.operand is not None:
            parts.append(self.operand.pretty_expr())
        for w, t in zip(self.whens, self.thens):
            parts.append(f"WHEN {w.pretty_expr()} THEN {t.pretty_expr()}")
        if self.default is not None:
            parts.append(f"ELSE {self.default.pretty_expr()}")
        parts.append("END")
        return " ".join(parts)


@dataclass(frozen=True)
class ListComprehension(Expr):
    """[var IN list WHERE pred | proj]"""

    var: Var
    list_expr: Expr
    where: Optional[Expr]
    projection: Optional[Expr]


@dataclass(frozen=True)
class Opaque:
    """Wraps an expression so generic tree traversal does NOT descend into
    it (it is not a TreeNode): sub-expressions scoped to an inner context
    (pattern comprehension bodies) must not be rewritten/extracted against
    the OUTER plan."""

    value: Any


@dataclass(frozen=True)
class PatternComprehension(Expr):
    """[path = (a)-[:R]->(b) WHERE pred | proj] — a correlated subquery
    producing a list per outer row (reference: ``PatternComprehension`` in
    the Neo4j frontend, rewritten by ``extractSubqueryFromPatternExpression``;
    the reference backends blacklist it at TCK level — we execute it).

    Carries the raw frontend pattern and inner expressions (boxed so outer
    traversals skip them); the IR builder attaches the converted inner
    pattern/predicates/projection, and the logical planner extracts it into
    a collect-subquery the way exists-patterns become ``ExistsSubQuery``."""

    pattern: Any  # frontend.ast.Pattern (untyped to avoid import cycle)
    path_var: Optional[str]
    where: Optional[Opaque]
    projection: Opaque
    # filled by IR builder with a fresh target var name
    target_field: Optional[str] = None


@dataclass(frozen=True)
class ListSlice(Expr):
    expr: Expr
    from_: Optional[Expr]
    to: Optional[Expr]

    def pretty_expr(self) -> str:
        f = self.from_.pretty_expr() if self.from_ is not None else ""
        t = self.to.pretty_expr() if self.to is not None else ""
        return f"{self.expr.pretty_expr()}[{f}..{t}]"


@dataclass(frozen=True)
class Index(Expr):
    """container[index] — list index or map key lookup."""

    expr: Expr
    index: Expr

    def pretty_expr(self) -> str:
        return f"{self.expr.pretty_expr()}[{self.index.pretty_expr()}]"


@dataclass(frozen=True)
class Quantified(Expr):
    """any/all/none/single(var IN list WHERE pred)."""

    kind: str  # any|all|none|single
    var: Var
    list_expr: Expr
    predicate: Expr


@dataclass(frozen=True)
class Reduce(Expr):
    """reduce(acc = init, var IN list | expr)"""

    acc: Var
    init: Expr
    var: Var
    list_expr: Expr
    expr: Expr


@dataclass(frozen=True)
class ExistsPattern(Expr):
    """A pattern used as predicate: WHERE (a)-[:R]->(b) / EXISTS(...).

    Carries the raw frontend pattern; the IR builder converts it into an
    exists-subquery (reference ``ExistsPatternExpr``).
    """

    pattern: Any  # frontend.ast.Pattern (untyped to avoid import cycle)
    # filled by IR builder with a fresh target var name
    target_field: Optional[str] = None


@dataclass(frozen=True)
class MapProjection(Expr):
    """map projection: var{.key, .*, key: expr, var}"""

    var: Var
    items: Tuple[Tuple[str, Optional[Expr]], ...]  # (key, None=.key | expr)
    all_props: bool = False


@dataclass(frozen=True)
class PrefixId(Expr):
    """Tag an element id with a graph prefix in the high bits.

    TPU-native replacement for the reference's varint-prefix codegen
    (``AddPrefix.scala:27-60`` / ``EncodeLong.scala:40-100``): ids stay fixed
    width int64 — ``id | (tag << 54)`` is a cheap XLA bitwise op, where the
    reference's byte-array prefixing is hostile to device columns.
    """

    expr: Expr
    tag: int

    def pretty_expr(self) -> str:
        return f"prefix({self.expr.pretty_expr()}, {self.tag})"


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def walk_vars(e: Expr):
    """All Var leaves in an expression."""
    return [n for n in e.iter_nodes() if isinstance(n, Var)]


def substitute(e: Expr, mapping) -> Expr:
    """Replace sub-expressions per ``mapping`` (dict Expr->Expr), preserving types."""

    def rule(n: TreeNode) -> TreeNode:
        if isinstance(n, Expr) and n in mapping:
            return mapping[n]
        return n

    return e.rewrite_top_down(rule)


def has_aggregation(e: Expr) -> bool:
    return any(isinstance(n, (Agg, CountStar)) for n in e.iter_nodes())

"""IR query blocks.

Mirrors the reference's Block DAG (``okapi-ir/.../api/block/*.scala``:
SourceBlock / MatchBlock / ProjectBlock / AggregationBlock /
OrderAndSliceBlock / UnwindBlock / ResultBlock) — here a linear pipeline,
which is what Cypher's clause chaining produces anyway (each WITH starts a
new horizon). Expressions inside blocks are typed ``ir.expr`` trees.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

from ..frontend.ast import SortItem
from .expr import Agg, Expr, Var
from .pattern import IRPattern


class Block:
    pass


@dataclass
class MatchBlock(Block):
    pattern: IRPattern
    predicates: Tuple[Expr, ...] = ()
    optional: bool = False


@dataclass
class ProjectBlock(Block):
    """Bind new fields; keeps existing fields in scope until a SelectBlock."""

    items: Tuple[Tuple[str, Expr], ...]  # (field name, expr)
    distinct: bool = False


@dataclass
class AggregationBlock(Block):
    group: Tuple[Tuple[str, Expr], ...]  # grouping key fields
    aggregations: Tuple[Tuple[str, Agg], ...]


@dataclass
class FilterBlock(Block):
    predicate: Expr


@dataclass
class OrderAndSliceBlock(Block):
    sort_items: Tuple[SortItem, ...] = ()
    skip: Optional[Expr] = None
    limit: Optional[Expr] = None


@dataclass
class UnwindBlock(Block):
    list_expr: Expr
    fld: str


@dataclass
class DistinctBlock(Block):
    fields: Tuple[str, ...]


@dataclass
class SelectBlock(Block):
    """Narrow scope to the named fields (end of a WITH/RETURN horizon)."""

    fields: Tuple[str, ...]


@dataclass
class ResultBlock(Block):
    fields: Tuple[str, ...]


@dataclass
class FromGraphBlock(Block):
    qgn: str


@dataclass
class GraphResultBlock(Block):
    """RETURN GRAPH"""


@dataclass
class ConstructBlock(Block):
    """CONSTRUCT ... — new-graph spec (reference ``LogicalPatternGraph``)."""

    on_graphs: Tuple[str, ...]
    clones: Tuple[Tuple[str, str], ...]  # (new field, source field)
    new_pattern: IRPattern
    new_properties: Tuple[Tuple[str, str, Expr], ...]  # (field, key, value expr)
    sets: Tuple[Tuple[str, str, Expr], ...] = ()  # SET items (field, key, expr)
    set_labels: Tuple[Tuple[str, Tuple[str, ...]], ...] = ()


@dataclass
class QueryIR:
    """A planned single query: linear block pipeline + final field order.

    ``params`` are the parameter names referenced; ``returns`` the output
    field order (None for graph-returning queries).
    """

    blocks: Tuple[Block, ...]
    returns: Optional[Tuple[str, ...]]
    source_graph: str = "session.ambient"

    def pretty(self) -> str:
        lines = []
        for b in self.blocks:
            lines.append(f"  {b!r}")
        return "QueryIR(\n" + "\n".join(lines) + "\n)"


@dataclass
class UnionIR:
    queries: Tuple["QueryIR", ...]
    all: bool = False
    returns: Optional[Tuple[str, ...]] = None


@dataclass
class CreateGraphIR:
    qgn: str
    inner: object  # QueryIR | UnionIR


@dataclass
class CreateViewIR:
    name: str
    params: Tuple[str, ...]
    inner_text: str


@dataclass
class DropGraphIR:
    qgn: str
    view: bool = False


# ---------------------------------------------------------------------------
# write IR (docs/mutation.md): CREATE / MERGE / SET / DELETE against the
# ambient mutable graph. The read prefix is a normal QueryIR (planned on the
# write query's pinned snapshot); the write ops evaluate host-side per
# binding row and commit as one WriteBatch.
# ---------------------------------------------------------------------------


@dataclass
class NodeTemplate:
    """One node element of a CREATE/MERGE pattern."""

    var: str  # binding name (fresh for anonymous nodes)
    bound: bool  # True: var is already bound — reuse, don't create
    labels: Tuple[str, ...] = ()
    props: Tuple[Tuple[str, Expr], ...] = ()


@dataclass
class RelTemplate:
    """One relationship element; endpoints name node templates/bindings."""

    var: str
    rel_type: str
    src: str
    dst: str
    props: Tuple[Tuple[str, Expr], ...] = ()


@dataclass
class SetItemSpec:
    """One SET item: property assign, label add, or whole-map rewrite."""

    var: str
    key: Optional[str] = None  # property key; None for labels / map value
    value: Optional[Expr] = None
    labels: Tuple[str, ...] = ()


@dataclass
class CreateOp(Block):
    nodes: Tuple[NodeTemplate, ...]
    rels: Tuple[RelTemplate, ...]


@dataclass
class MergeOp(Block):
    nodes: Tuple[NodeTemplate, ...]
    rels: Tuple[RelTemplate, ...]
    on_create: Tuple[SetItemSpec, ...] = ()
    on_match: Tuple[SetItemSpec, ...] = ()


@dataclass
class SetOp(Block):
    items: Tuple[SetItemSpec, ...]


@dataclass
class DeleteOp(Block):
    fields: Tuple[str, ...]
    detach: bool = False


@dataclass
class UpdateIR:
    """A write query: optional read prefix + ordered write ops."""

    read: Optional[QueryIR]
    ops: Tuple[Block, ...]
    source_graph: str = "session.ambient"

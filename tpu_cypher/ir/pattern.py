"""IR pattern model.

Mirrors the reference's IR pattern vocabulary
(``okapi-ir/.../api/pattern/Connection.scala:37``, ``Pattern``/``Entity``):
typed node/relationship entities plus a topology of connections. Direction is
kept per-connection; undirected connections are expanded by the planners
(relational planner unions both orientations, ``RelationalPlanner.scala``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, FrozenSet, Optional, Tuple

from ..api import types as T

OUTGOING = ">"
INCOMING = "<"
BOTH = "-"


@dataclass(frozen=True)
class Connection:
    """rel field -> (source node field, target node field, direction).

    For INCOMING the stored source/target are already swapped to the
    canonical outgoing orientation; ``direction`` is then OUTGOING. BOTH is
    preserved (undirected — planner unions orientations).
    """

    source: str
    target: str
    direction: str  # OUTGOING | BOTH
    lower: int = 1
    upper: Optional[int] = 1  # None = unbounded '*'; (1,1) = single hop
    # True when the pattern WROTE var-length syntax: '*1..1' binds a
    # LIST of one relationship, not the relationship itself (openCypher
    # "Handle fixed-length variable length pattern")
    var_syntax: bool = False

    @property
    def is_var_length(self) -> bool:
        return self.var_syntax or not (self.lower == 1 and self.upper == 1)


@dataclass
class IRPattern:
    """All entities bound by one MATCH."""

    node_types: Dict[str, T.CTNodeType] = field(default_factory=dict)
    rel_types: Dict[str, T.CTRelationshipType] = field(default_factory=dict)
    topology: Dict[str, Connection] = field(default_factory=dict)
    # CONSTRUCT support: entity -> base entity (COPY OF)
    base_entities: Dict[str, str] = field(default_factory=dict)
    # named paths: path var -> ordered element fields
    paths: Dict[str, Tuple[str, ...]] = field(default_factory=dict)

    @property
    def fields(self) -> FrozenSet[str]:
        return frozenset(self.node_types) | frozenset(self.rel_types)

    def entity_type(self, name: str):
        if name in self.node_types:
            return self.node_types[name]
        return self.rel_types.get(name)

    def connections_for(self, node_field: str):
        return {
            r: c
            for r, c in self.topology.items()
            if c.source == node_field or c.target == node_field
        }

    def components(self) -> Tuple[FrozenSet[str], ...]:
        """Connected components over node fields (for CartesianProduct planning).

        Mirrors the connected-component analysis in the reference's
        ``LogicalPlanner`` (``LogicalPlanner.scala:93-190``).
        """
        parent: Dict[str, str] = {n: n for n in self.node_types}

        def find(x: str) -> str:
            while parent[x] != x:
                parent[x] = parent[parent[x]]
                x = parent[x]
            return x

        def union(a: str, b: str):
            ra, rb = find(a), find(b)
            if ra != rb:
                parent[ra] = rb

        for c in self.topology.values():
            union(c.source, c.target)
        groups: Dict[str, set] = {}
        for n in self.node_types:
            groups.setdefault(find(n), set()).add(n)
        return tuple(frozenset(g) for g in groups.values())

"""Unified metrics registry: counters, gauges, histograms with labeled series.

One coherent metrics subsystem for the whole engine. Before this module the
instrumentation added by PRs 1-3 lived in four incompatible mechanisms — the
``jax.monitoring`` compile counter (``backend/tpu/bucketing.py``), the
context-local ``FALLBACK_COUNTER`` (``backend/tpu/table.py``), the per-kernel
Pallas use counters (``backend/tpu/pallas/dispatch.py``), and the fault-site
invocation counts (``runtime/faults.py``). All four now emit through the
process-global ``REGISTRY`` here, keeping their existing public read paths
(``compile_snapshot``, ``FALLBACK_COUNTER.snapshot``, ``dispatch.use_counts``,
``faults.counters``) as thin views over the registry.

Design points:

* **Labeled series** — a metric is a family; each distinct label tuple is a
  series. Cardinality is CAPPED per metric (``LABEL_CARDINALITY_CAP``):
  once a family holds that many series, new label tuples collapse into one
  ``__overflow__`` series instead of growing without bound (a production
  registry must never let a runaway label — e.g. a query string — eat the
  host).
* **Context-local scoping** — ``REGISTRY.scope()`` opens a contextvar-carried
  scope that accumulates only the mutations made in THIS context while open
  (threads / asyncio / nested view execution never cross-pollute), the same
  discipline the fallback counter proved. Scopes nest; each sees its own
  copy.
* **Histograms** — count/sum/min/max plus p50/p95 over a bounded window
  (the ``utils/measurement.py`` stage-timing role, folded in here).
* **Export sinks** — Prometheus text format (``prometheus_text`` /
  ``CypherSession.metrics_text()``) and JSON-lines events appended to
  ``TPU_CYPHER_METRICS_FILE`` (one line per query; see ``write_event``).
"""

from __future__ import annotations

import contextvars
import json
import threading
import time
from typing import Any, Callable, Dict, Iterator, List, Mapping, Optional, Sequence, Tuple

# PRINT_TIMINGS: the stage-timing echo flag, ONE declaration shared with
# the session's timing path; METRICS_FILE: the JSON-lines per-query sink.
# Both live in the typed registry (utils/config.py).
from ..utils.config import METRICS_FILE, PRINT_TIMINGS

# schema version stamped on every exported event/snapshot — consumers
# (the bench driver, log scrapers) key parsing off it
EVENT_SCHEMA_VERSION = 1

# max distinct label tuples per metric family before collapse
LABEL_CARDINALITY_CAP = 64
OVERFLOW_LABEL = "__overflow__"

# histogram quantile window (bounded memory per series)
_HIST_WINDOW = 1024


class MetricError(Exception):
    pass


# active scopes in THIS context (a tuple: scopes nest)
_SCOPES: contextvars.ContextVar[Tuple["MetricsScope", ...]] = (
    contextvars.ContextVar("tpu_cypher_metric_scopes", default=())
)


class _HistState:
    __slots__ = ("count", "sum", "min", "max", "window")

    def __init__(self):
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self.window: List[float] = []

    def observe(self, v: float) -> None:
        self.count += 1
        self.sum += v
        self.min = v if self.min is None else min(self.min, v)
        self.max = v if self.max is None else max(self.max, v)
        if len(self.window) >= _HIST_WINDOW:
            # bounded reservoir: overwrite round-robin so old observations
            # age out without an unbounded list
            self.window[self.count % _HIST_WINDOW] = v
        else:
            self.window.append(v)

    def summary(self) -> Dict[str, float]:
        out: Dict[str, float] = {
            "count": self.count,
            "sum": round(self.sum, 9),
            "min": self.min if self.min is not None else 0.0,
            "max": self.max if self.max is not None else 0.0,
        }
        if self.window:
            w = sorted(self.window)
            out["p50"] = w[int(0.50 * (len(w) - 1))]
            out["p95"] = w[int(0.95 * (len(w) - 1))]
        else:
            out["p50"] = 0.0
            out["p95"] = 0.0
        return out


class Metric:  # shared-by: lanes
    """One metric family: (name, help, label names) plus its series map."""

    kind = "untyped"

    def __init__(self, registry: "MetricsRegistry", name: str, help: str,
                 labels: Sequence[str]):
        self._reg = registry
        self.name = name
        self.help = help
        self.label_names = tuple(labels)
        self._series: Dict[Tuple[str, ...], Any] = {}

    def _key_locked(self, labels: Dict[str, Any]) -> Tuple[str, ...]:
        """Series key for a label dict — caller holds the registry lock.
        Applies the cardinality cap: a NEW tuple past the cap collapses to
        the overflow series."""
        if set(labels) != set(self.label_names):
            raise MetricError(
                f"{self.name}: labels {sorted(labels)} != declared "
                f"{sorted(self.label_names)}"
            )
        key = tuple(str(labels[l]) for l in self.label_names)
        if key not in self._series and len(self._series) >= LABEL_CARDINALITY_CAP:
            key = tuple(OVERFLOW_LABEL for _ in self.label_names)
        return key

    def _label_dict(self, key: Tuple[str, ...]) -> Dict[str, str]:
        return dict(zip(self.label_names, key))

    def items(self) -> List[Tuple[Dict[str, str], Any]]:
        """(label dict, value-or-histogram-summary) per series."""
        with self._reg._lock:
            return [
                (self._label_dict(k),
                 v.summary() if isinstance(v, _HistState) else v)
                for k, v in self._series.items()
            ]

    def reset(self, **labels) -> None:
        """Zero matching series (all series when no labels given). Series
        stay registered so zero-valued reads keep working."""
        with self._reg._lock:
            if not labels:
                keys = list(self._series)
            else:
                want = {k: str(v) for k, v in labels.items()}
                keys = [
                    k for k in self._series
                    if all(self._label_dict(k).get(n) == v
                           for n, v in want.items())
                ]
            for k in keys:
                self._series[k] = (
                    _HistState() if isinstance(self._series[k], _HistState)
                    else 0.0
                )


class Counter(Metric):
    kind = "counter"

    def inc(self, amount: float = 1.0, **labels) -> float:
        """Add ``amount`` (>= 0; 0 pre-seeds the series so it exports as an
        explicit zero) and return the NEW cumulative value — an atomic
        inc-and-get, which is what ``runtime/faults.py`` keys occurrence
        windows off."""
        if amount < 0:
            raise MetricError(f"{self.name}: counter increments must be >= 0")
        with self._reg._lock:
            key = self._key_locked(labels)
            v = self._series.get(key, 0.0) + amount
            self._series[key] = v
        if amount:
            for s in _SCOPES.get():
                s._add(self, key, amount)
        return v

    def value(self, **labels) -> float:
        with self._reg._lock:
            if not labels and not self.label_names:
                return self._series.get((), 0.0)
            key = self._key_locked(labels)
            return self._series.get(key, 0.0)


class Gauge(Metric):
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        with self._reg._lock:
            self._series[self._key_locked(labels)] = float(value)

    def value(self, **labels) -> float:
        with self._reg._lock:
            return self._series.get(self._key_locked(labels), 0.0)


class Histogram(Metric):
    kind = "histogram"

    def observe(self, value: float, **labels) -> None:
        value = float(value)
        with self._reg._lock:
            key = self._key_locked(labels)
            st = self._series.get(key)
            if st is None:
                st = self._series[key] = _HistState()
            st.observe(value)
        for s in _SCOPES.get():
            s._observe(self, key, value)

    def summary(self, **labels) -> Dict[str, float]:
        """count / sum / min / max / p50 / p95 for one series (zeros when
        the series has never observed) — the ``utils/measurement.py``
        p50/p95/max histogram, per labeled series."""
        with self._reg._lock:
            st = self._series.get(self._key_locked(labels))
            return st.summary() if st is not None else _HistState().summary()


class MetricsScope:
    """Context-local accumulation of metric deltas: ``with REGISTRY.scope()
    as s:`` — ``s`` fills with only the counter increments and histogram
    observations recorded in THIS context while the scope is open. Readable
    both during and after the ``with`` block."""

    def __init__(self):
        # (metric name, series key) -> delta / (count, sum)
        self._counters: Dict[Tuple[str, Tuple[str, ...]], float] = {}
        self._hists: Dict[Tuple[str, Tuple[str, ...]], Tuple[int, float]] = {}
        self._names: Dict[Tuple[str, Tuple[str, ...]], Tuple[str, ...]] = {}
        self._token = None

    def __enter__(self) -> "MetricsScope":
        self._token = _SCOPES.set(_SCOPES.get() + (self,))
        return self

    def __exit__(self, *exc) -> None:
        _SCOPES.reset(self._token)

    def _add(self, metric: Metric, key: Tuple[str, ...], amount: float) -> None:
        k = (metric.name, key)
        self._counters[k] = self._counters.get(k, 0.0) + amount
        self._names[k] = metric.label_names

    def _observe(self, metric: Metric, key: Tuple[str, ...], v: float) -> None:
        k = (metric.name, key)
        c, s = self._hists.get(k, (0, 0.0))
        self._hists[k] = (c + 1, s + v)
        self._names[k] = metric.label_names

    def value(self, name: str, **labels) -> float:
        for (n, k), v in self._counters.items():
            if n != name:
                continue
            names = self._names[(n, k)]
            if set(names) == set(labels) and tuple(
                str(labels[l]) for l in names
            ) == k:
                return v
        return 0.0

    def label_counts(self, name: str, label: str) -> Dict[str, float]:
        """{label value: summed delta} for one metric, keyed on one label
        dimension — how ``result.fallbacks`` reads its per-reason counts."""
        out: Dict[str, float] = {}
        for (n, k), v in self._counters.items():
            if n != name:
                continue
            names = self._names[(n, k)]
            if label in names:
                lv = k[names.index(label)]
                out[lv] = out.get(lv, 0.0) + v
        return out

    def snapshot(self) -> Dict[str, List[Dict[str, Any]]]:
        """JSON-safe view of everything this scope captured."""
        out: Dict[str, List[Dict[str, Any]]] = {}
        for (n, k), v in sorted(self._counters.items()):
            out.setdefault(n, []).append(
                {"labels": dict(zip(self._names[(n, k)], k)), "value": v}
            )
        for (n, k), (c, s) in sorted(self._hists.items()):
            out.setdefault(n, []).append(
                {"labels": dict(zip(self._names[(n, k)], k)),
                 "count": c, "sum": round(s, 9)}
            )
        return out


class MetricsRegistry:  # shared-by: lanes
    """The metric namespace: get-or-create by name, idempotent (a second
    registration with a different kind or label set is an error, not a
    silent shadow)."""

    def __init__(self):
        self._lock = threading.RLock()
        self._metrics: "Dict[str, Metric]" = {}

    def _get_or_create(self, cls, name: str, help: str,
                       labels: Sequence[str]) -> Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if type(m) is not cls or m.label_names != tuple(labels):
                    raise MetricError(
                        f"metric {name!r} re-registered as {cls.kind} "
                        f"labels={tuple(labels)} (was {m.kind} "
                        f"labels={m.label_names})"
                    )
                return m
            m = cls(self, name, help, labels)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "",
                labels: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: Sequence[str] = ()) -> Histogram:
        return self._get_or_create(Histogram, name, help, labels)

    def get(self, name: str) -> Optional[Metric]:
        with self._lock:
            return self._metrics.get(name)

    def scope(self) -> MetricsScope:
        return MetricsScope()

    def reset(self, name: Optional[str] = None) -> None:
        """Zero one metric's series, or every metric's (tests)."""
        with self._lock:
            targets = (
                [self._metrics[name]] if name is not None and name in self._metrics
                else list(self._metrics.values()) if name is None else []
            )
        for m in targets:
            m.reset()

    def snapshot(self) -> Dict[str, Any]:
        """Nested JSON-safe dump of every family and series."""
        with self._lock:
            metrics = list(self._metrics.values())
        out: Dict[str, Any] = {"schema_version": EVENT_SCHEMA_VERSION}
        fams: Dict[str, Any] = {}
        for m in sorted(metrics, key=lambda m: m.name):
            fams[m.name] = {
                "kind": m.kind,
                "help": m.help,
                "series": [
                    {"labels": lbl, "value": v} for lbl, v in m.items()
                ],
            }
        out["metrics"] = fams
        return out

    def flat(self) -> Dict[str, float]:
        """One flat {"name{a=b}": number} dict — the bench.py JSON-line
        shape (histograms flatten to _count/_sum/_p50/_p95/_max keys)."""
        out: Dict[str, float] = {}
        with self._lock:
            metrics = list(self._metrics.values())
        for m in sorted(metrics, key=lambda m: m.name):
            for lbl, v in sorted(m.items(), key=lambda kv: sorted(kv[0].items())):
                tag = ",".join(f"{k}={lbl[k]}" for k in sorted(lbl))
                base = f"{m.name}{{{tag}}}" if tag else m.name
                if isinstance(v, dict):  # histogram summary
                    for field in ("count", "sum", "p50", "p95", "max"):
                        out[f"{base}_{field}"] = v[field]
                else:
                    out[base] = v
        return out

    def prometheus_text(self) -> str:
        """Prometheus exposition text format. Counters and gauges export
        as-is; histograms export as summaries (quantile series + _sum and
        _count). Series are emitted in sorted order so output is
        deterministic (the golden test relies on it)."""
        lines: List[str] = []
        with self._lock:
            metrics = list(self._metrics.values())
        for m in sorted(metrics, key=lambda m: m.name):
            ptype = "summary" if m.kind == "histogram" else m.kind
            if m.help:
                lines.append(f"# HELP {m.name} {_escape_help(m.help)}")
            lines.append(f"# TYPE {m.name} {ptype}")
            series = sorted(m.items(), key=lambda kv: sorted(kv[0].items()))
            for lbl, v in series:
                if isinstance(v, dict):  # histogram summary
                    for q, fld in (("0.5", "p50"), ("0.95", "p95")):
                        lines.append(
                            _sample(m.name, {**lbl, "quantile": q}, v[fld])
                        )
                    lines.append(_sample(m.name + "_sum", lbl, v["sum"]))
                    lines.append(_sample(m.name + "_count", lbl, v["count"]))
                else:
                    lines.append(_sample(m.name, lbl, v))
        return "\n".join(lines) + "\n"


def _escape_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(s: str) -> str:
    return s.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _sample(name: str, labels: Dict[str, str], value: Any) -> str:
    if labels:
        tag = ",".join(
            f'{k}="{_escape_label(str(labels[k]))}"' for k in sorted(labels)
        )
        name = f"{name}{{{tag}}}"
    v = float(value)
    return f"{name} {int(v) if v == int(v) else v}"


# the process-global registry every engine layer emits through
REGISTRY = MetricsRegistry()


# ---------------------------------------------------------------------------
# JSON-lines export sink
# ---------------------------------------------------------------------------


def sink_configured() -> bool:
    return bool(METRICS_FILE.get())


def write_event(event: Dict[str, Any]) -> None:
    """Append one schema-versioned JSON line to ``TPU_CYPHER_METRICS_FILE``.
    No-op when unconfigured; an export failure must never fail the query."""
    path = METRICS_FILE.get()
    if not path:
        return
    try:
        with open(path, "a") as f:
            f.write(json.dumps({"v": EVENT_SCHEMA_VERSION, **event}) + "\n")
    except (OSError, TypeError, ValueError):  # fault-ok: export is best-effort
        pass


# ---------------------------------------------------------------------------
# stage timing (folded in from utils/measurement.py)
# ---------------------------------------------------------------------------

STAGE_SECONDS = REGISTRY.histogram(
    "tpu_cypher_stage_seconds",
    "wall seconds per pipeline phase (parse/ir/logical/.../execute)",
    labels=("stage",),
)

_TIMINGS: List[Tuple[str, float]] = []


def record_stage(name: str, seconds: float) -> None:
    """One pipeline-phase timing: registry histogram + the bounded recent
    list ``last_timings`` reads + the ``TPU_CYPHER_PRINT_TIMINGS`` echo
    (reference ``Measurement.scala:36-56`` / ``PrintTimings``)."""
    STAGE_SECONDS.observe(seconds, stage=name)
    _TIMINGS.append((name, seconds))
    del _TIMINGS[:-64]
    if PRINT_TIMINGS.get():
        print(f"[timing] {name}: {seconds * 1000:.2f} ms")


def time_stage(name: str, fn: Callable, *args, **kw):
    t0 = time.perf_counter()
    out = fn(*args, **kw)
    record_stage(name, time.perf_counter() - t0)
    return out


def last_timings() -> Dict[str, float]:
    return dict(_TIMINGS[-16:])


def clear_timings() -> None:
    _TIMINGS.clear()


# ---------------------------------------------------------------------------
# mapping views over labeled counters (legacy read-path adapters)
# ---------------------------------------------------------------------------


class CounterView(Mapping):
    """Dict-like live view over ONE label dimension of a counter — the
    compatibility shape for the old module-global tier dicts
    (``expand_op.MXU_TIER_COUNTS["tiled"]``, ``bench._tier_snapshot``'s
    ``.items()``) now that the values live in the registry."""

    def __init__(self, counter: Counter, label: str, keys: Sequence[str]):
        self._c = counter
        self._label = label
        self._keys = tuple(keys)
        for k in self._keys:  # pre-seed: zero series export explicitly
            counter.inc(0, **{label: k})

    def inc(self, key: str, amount: float = 1.0) -> float:
        return self._c.inc(amount, **{self._label: key})

    def __getitem__(self, key: str) -> int:
        return int(self._c.value(**{self._label: key}))

    def __iter__(self) -> Iterator[str]:
        seen = dict.fromkeys(self._keys)
        for lbl, _ in self._c.items():
            seen.setdefault(lbl[self._label])
        return iter(seen)

    def __len__(self) -> int:
        return len(list(iter(self)))

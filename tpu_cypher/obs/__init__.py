"""Query observability: per-operator trace spans + the unified metrics
registry + export surfaces.

The reference delegates engine observability to Spark UI /
``tableEnv.explain``; this package is the TPU stack's equivalent,
documented in ``docs/observability.md``:

* ``obs.trace`` — context-local span trees per query (phases, relational
  operators, Pallas kernel launches, bucket-lattice pad ratios, fault-site
  sync points), surfaced as ``CypherResult.profile()``.
* ``obs.metrics`` — the process-global ``REGISTRY`` of counters / gauges /
  histograms with labeled series, context-local scoping, a cardinality
  cap, Prometheus text export (``CypherSession.metrics_text()``) and a
  JSON-lines sink (``TPU_CYPHER_METRICS_FILE``).
"""

from . import metrics, trace
from .metrics import REGISTRY, MetricsRegistry, MetricsScope
from .trace import QueryProfile, QueryTrace, current_span, current_trace, span

__all__ = [
    "metrics",
    "trace",
    "REGISTRY",
    "MetricsRegistry",
    "MetricsScope",
    "QueryProfile",
    "QueryTrace",
    "current_span",
    "current_trace",
    "span",
]

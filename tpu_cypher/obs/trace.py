"""Per-query trace spans: a context-local span tree from frontend to kernels.

A ``QueryTrace`` is opened per query by ``relational/session.py`` and nested
per pipeline phase (parse -> ir -> logical -> relational -> execute) and per
relational operator (``relational/ops.py`` wraps every lazy ``table`` pull in
an operator span). Inside operators, kernel dispatches
(``backend/tpu/pallas/dispatch.py``) open kernel spans, the bucket lattice
(``backend/tpu/bucketing.round_size``) annotates the enclosing span with
padded-vs-true row counts, and every named fault site
(``runtime/faults.fault_point``) — the engine's natural device sync points —
stamps a site hit. The finished tree attaches to ``CypherResult`` as
``result.profile()`` (rendered tree + JSON): the ``PROFILE``-style sibling
of the ``EXPLAIN``-style ``result.plans``.

Costs, by design:

* spans record HOST wall time only (``perf_counter``) — never a device sync
  (``block_until_ready``), so profiling adds ZERO device syncs and an
  operator span measures dispatch time under JAX async dispatch (the
  ``collect`` span at the end absorbs the drain, like Spark UI's stage
  boundaries absorb action time);
* when no trace is active every instrumentation point is one contextvar
  read returning a shared null span;
* the device-trace backend rides ``utils/profiling.py``: with
  ``TPU_CYPHER_PROFILE_DIR`` set, each span also opens a
  ``jax.profiler.TraceAnnotation`` so the same tree shows up region-named
  inside TensorBoard/Perfetto device traces.

Context-locality: the active trace/span ride ``contextvars``, so
interleaved queries (threads, asyncio, nested view execution) each grow
their own tree — the same isolation discipline as the metrics scopes and
the execution guard.
"""

from __future__ import annotations

import contextvars
import itertools
import json
import time
from typing import Any, Dict, List, Optional

from ..utils.profiling import PROFILE_DIR
from . import metrics as M

SCHEMA_VERSION = 1


class Span:
    """One node of the tree: a named, timed region with attributes."""

    __slots__ = ("span_id", "name", "kind", "attrs", "t0", "seconds",
                 "status", "children")

    def __init__(self, span_id: int, name: str, kind: str,
                 attrs: Optional[Dict[str, Any]] = None):
        self.span_id = span_id
        self.name = name
        self.kind = kind  # "query" | "phase" | "operator" | "kernel" | "span"
        self.attrs: Dict[str, Any] = dict(attrs or {})
        self.t0: Optional[float] = None
        self.seconds: float = 0.0
        self.status = "ok"
        self.children: List["Span"] = []

    @property
    def self_seconds(self) -> float:
        """Wall time minus child spans — the per-operator cost that sums
        (within tolerance) to the parent's total."""
        return max(self.seconds - sum(c.seconds for c in self.children), 0.0)

    def note(self, key: str, value: Any) -> None:
        self.attrs[key] = value

    def count(self, key: str, amount: int = 1) -> None:
        self.attrs[key] = self.attrs.get(key, 0) + amount

    # per-span cap on retained (true, padded) pairs: enough for every
    # rounding a single operator performs, bounded against pathological
    # loops so a span never grows without limit
    ROWS_PAIRS_CAP = 64

    def add_rows(
        self,
        true_rows: int,
        padded_rows: int,
        shards: int = 1,
        local_true: Optional[int] = None,
        local_padded: Optional[int] = None,
    ) -> None:
        """Accumulate a padded-vs-true row count from the bucket lattice.

        Besides the running sums, the individual ``(true, padded)`` pairs
        are retained (bounded) so static shape predictions
        (``analysis.shapes.predict_padded``) can be checked against what
        the lattice actually produced, per rounding, not just in
        aggregate. Under a mesh the lattice rounds per shard: the span
        additionally records the shard count and the per-shard
        ``(local true extent, local padded)`` pairs, the sharded analog
        of the same static-vs-runtime agreement gate."""
        self.attrs["rows_true"] = self.attrs.get("rows_true", 0) + int(true_rows)
        self.attrs["rows_padded"] = (
            self.attrs.get("rows_padded", 0) + int(padded_rows)
        )
        pairs = self.attrs.setdefault("rows_pairs", [])
        if len(pairs) < self.ROWS_PAIRS_CAP:
            pairs.append([int(true_rows), int(padded_rows)])
        if shards > 1 and local_true is not None and local_padded is not None:
            self.attrs["shards"] = int(shards)
            spairs = self.attrs.setdefault("shard_rows_pairs", [])
            if len(spairs) < self.ROWS_PAIRS_CAP:
                spairs.append([int(local_true), int(local_padded)])

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "span_id": self.span_id,
            "name": self.name,
            "kind": self.kind,
            "seconds": round(self.seconds, 6),
            "self_seconds": round(self.self_seconds, 6),
            "status": self.status,
        }
        if self.attrs:
            out["attrs"] = dict(self.attrs)
        if self.children:
            out["children"] = [c.to_dict() for c in self.children]
        return out


class _NullSpan:
    """The no-trace fast path: every mutator is a no-op."""

    __slots__ = ()

    def note(self, key, value):  # noqa: D401
        pass

    def count(self, key, amount=1):
        pass

    def add_rows(self, true_rows, padded_rows, shards=1, local_true=None,
                 local_padded=None):
        pass


NULL_SPAN = _NullSpan()


class QueryTrace:
    """The span tree for ONE query: a root plus per-phase children. The
    root's duration is the SUM of its phase durations (a lazy result may
    sit unpulled for minutes between planning and execution — idle wall
    time between phases is not query time)."""

    def __init__(self, name: str = "query", **attrs):
        self._ids = itertools.count(1)
        self.root = Span(0, name, "query", attrs)
        # deepest span open when the current execution attempt failed —
        # reset per ladder attempt, read into ``execution_log`` entries
        self.failed_span_id: Optional[int] = None

    # -- aggregate views ---------------------------------------------------

    @property
    def total_seconds(self) -> float:
        return sum(c.seconds for c in self.root.children)

    def phase_seconds(self) -> Dict[str, float]:
        """{phase name: summed seconds} over the root's direct children
        (retried phases, e.g. ladder execute attempts, sum)."""
        out: Dict[str, float] = {}
        for c in self.root.children:
            out[c.name] = out.get(c.name, 0.0) + c.seconds
        return out

    def spans(self) -> List[Span]:
        """Every span, preorder."""
        out: List[Span] = []
        stack = [self.root]
        while stack:
            s = stack.pop()
            out.append(s)
            stack.extend(reversed(s.children))
        return out

    def to_dict(self) -> Dict[str, Any]:
        return {
            "schema_version": SCHEMA_VERSION,
            "total_seconds": round(self.total_seconds, 6),
            "root": self.root.to_dict(),
        }


# the active trace + innermost open span in THIS context
_TRACE: contextvars.ContextVar[Optional[QueryTrace]] = contextvars.ContextVar(
    "tpu_cypher_trace", default=None
)
_SPAN: contextvars.ContextVar[Optional[Span]] = contextvars.ContextVar(
    "tpu_cypher_span", default=None
)


def current_trace() -> Optional[QueryTrace]:
    return _TRACE.get()


def current_span() -> Optional[Span]:
    return _SPAN.get()


def enabled() -> bool:
    return _TRACE.get() is not None


def note(key: str, value: Any) -> None:
    sp = _SPAN.get()
    if sp is not None:
        sp.attrs[key] = value


def note_rows(
    true_rows: int,
    padded_rows: int,
    shards: int = 1,
    local_true: Optional[int] = None,
    local_padded: Optional[int] = None,
) -> None:
    """Record a bucket-lattice materialize on the innermost open span
    (plus the per-shard extent pair while a mesh is active)."""
    sp = _SPAN.get()
    if sp is not None:
        sp.add_rows(
            true_rows, padded_rows,
            shards=shards, local_true=local_true, local_padded=local_padded,
        )


def note_site(site: str) -> None:
    """Stamp a fault-site hit (a device sync point) on the innermost open
    span: ``attrs["sites"]`` maps site name -> hit count."""
    sp = _SPAN.get()
    if sp is not None:
        sites = sp.attrs.setdefault("sites", {})
        sites[site] = sites.get(site, 0) + 1


class activate:
    """``with activate(trace):`` — make ``trace`` the context's active
    trace, its root the innermost span. Used once per pipeline run AND
    re-entered by the lazy execution ladder / ``collect`` (a CypherResult
    is planned now, pulled later, possibly from another context)."""

    def __init__(self, trace: QueryTrace):
        self._trace = trace
        self._t1 = None
        self._t2 = None

    def __enter__(self) -> QueryTrace:
        self._t1 = _TRACE.set(self._trace)
        self._t2 = _SPAN.set(self._trace.root)
        return self._trace

    def __exit__(self, *exc) -> None:
        _SPAN.reset(self._t2)
        _TRACE.reset(self._t1)


class span:
    """``with span(name, kind=..., **attrs) as sp:`` — open a child of the
    innermost span. Returns ``NULL_SPAN`` (and records nothing) when no
    trace is active, so instrumentation points cost one contextvar read
    on the untraced path."""

    __slots__ = ("_name", "_kind", "_attrs", "_span", "_tok", "_dev")

    def __init__(self, name: str, kind: str = "span", **attrs):
        self._name = name
        self._kind = kind
        self._attrs = attrs
        self._span: Optional[Span] = None
        self._tok = None
        self._dev = None

    def __enter__(self):
        tr = _TRACE.get()
        if tr is None:
            return NULL_SPAN
        parent = _SPAN.get() or tr.root
        sp = Span(next(tr._ids), self._name, self._kind, self._attrs)
        parent.children.append(sp)
        self._tok = _SPAN.set(sp)
        if PROFILE_DIR.get():
            # device-trace backend: the same region, named inside the
            # jax.profiler timeline (utils/profiling.py)
            try:
                import jax

                self._dev = jax.profiler.TraceAnnotation(
                    f"tpu_cypher:{self._kind}:{self._name}"
                )
                self._dev.__enter__()
            except Exception:  # fault-ok: profiling must never fail a query
                self._dev = None
        sp.t0 = time.perf_counter()
        self._span = sp
        return sp

    def __exit__(self, exc_type, exc, tb) -> bool:
        sp = self._span
        if sp is None:
            return False
        sp.seconds = time.perf_counter() - sp.t0
        if self._dev is not None:
            try:
                self._dev.__exit__(exc_type, exc, tb)
            except Exception:  # pragma: no cover - fault-ok: best-effort profiler teardown
                pass
        _SPAN.reset(self._tok)
        if exc_type is not None:
            sp.status = "error"
            tr = _TRACE.get()
            # exits unwind deepest-first: the FIRST error exit is the
            # failing operator the execution_log entry should name
            if tr is not None and tr.failed_span_id is None:
                tr.failed_span_id = sp.span_id
        if self._kind == "phase":
            M.record_stage(self._name, sp.seconds)
        return False


# ---------------------------------------------------------------------------
# rendering
# ---------------------------------------------------------------------------

_SKIP_ATTRS = ("sites",)  # rendered separately


def _attr_str(sp: Span) -> str:
    parts = [
        f"{k}={v}" for k, v in sp.attrs.items()
        if k not in _SKIP_ATTRS and not isinstance(v, (dict, list))
    ]
    sites = sp.attrs.get("sites")
    if sites:
        parts.append("sites=" + "+".join(f"{k}:{v}" for k, v in sorted(sites.items())))
    return f"  [{', '.join(parts)}]" if parts else ""


def render(trace: QueryTrace) -> str:
    """ASCII tree with per-span total and self wall times."""
    lines = [
        f"{trace.root.name} (total {trace.total_seconds * 1000:.2f} ms)"
        f"{_attr_str(trace.root)}"
    ]

    def walk(sp: Span, prefix: str, last: bool) -> None:
        branch = "`- " if last else "|- "
        mark = " !" if sp.status == "error" else ""
        self_part = (
            f" (self {sp.self_seconds * 1000:.2f} ms)" if sp.children else ""
        )
        lines.append(
            f"{prefix}{branch}{sp.name} {sp.seconds * 1000:.2f} ms"
            f"{self_part}{mark}{_attr_str(sp)}"
        )
        child_prefix = prefix + ("   " if last else "|  ")
        for i, c in enumerate(sp.children):
            walk(c, child_prefix, i == len(sp.children) - 1)

    for i, c in enumerate(trace.root.children):
        walk(c, "", i == len(trace.root.children) - 1)
    return "\n".join(lines)


class QueryProfile:
    """What ``CypherResult.profile()`` returns: the rendered tree plus the
    JSON form of the same data."""

    def __init__(self, trace: QueryTrace):
        self.trace = trace

    def render(self) -> str:
        return render(self.trace)

    def to_dict(self) -> Dict[str, Any]:
        return self.trace.to_dict()

    def to_json(self, indent: Optional[int] = None) -> str:
        return json.dumps(self.to_dict(), indent=indent)

    def phase_seconds(self) -> Dict[str, float]:
        return self.trace.phase_seconds()

    @property
    def total_seconds(self) -> float:
        return self.trace.total_seconds

    def __str__(self) -> str:
        return self.render()

    def __repr__(self) -> str:
        n = len(self.trace.spans()) - 1
        return (
            f"QueryProfile({n} spans, "
            f"total {self.trace.total_seconds * 1000:.2f} ms)"
        )

"""Explicit hash-repartition (shuffle) equi-join for the mesh path.

The reference engines join by hash-SHUFFLING both sides so equal keys meet
on one worker (``SparkTable.scala:178`` joins ride Spark's exchange;
``flink-cypher TableOps.scala:146`` likewise) — the partitioning of the
intermediate is a deliberate plan decision, not an accident of input
layout. The engine's default device join is one global sort + binary-search
probe, which XLA/GSPMD partitions by propagating the INPUT shardings; at
pod scale a global ``lax.sort`` degenerates to an all-gather. This module
is the deliberate alternative (SURVEY §2.3 "distributed join / shuffle",
VERDICT r3 missing #3):

* each device buckets its local key block by ``key % n_shards`` — a row's
  bucket depends only on its VALUE, so equal keys land on equal shards;
* ONE ``lax.all_to_all`` per side exchanges the buckets over the mesh axis
  (ICI within a host, DCN across hosts — exactly where the engines
  shuffle);
* each shard then joins its received blocks LOCALLY (sort + searchsorted
  over per-shard data — no global collective in the join itself);
* match pairs return as GLOBAL row indices carried through the exchange.

Static-shape discipline (everything under ``shard_map`` is compiled once):
buckets get a fixed capacity ``cap_factor * fair_share``; a skewed key
distribution that overflows a bucket is detected ON DEVICE and reported
back — the caller falls back to the global sort-probe join, trading layout
quality for unconditional correctness. Join output uses the engine's
count-then-materialize discipline: phase A syncs per-shard match counts,
phase B materializes padded to the max count.

Runs bit-identically on the CPU test mesh (8 virtual devices) and a TPU
pod — only the device list changes."""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..backend.tpu.bucketing import round_up_pow2
from ..obs import trace as _obs_trace
from ..obs.metrics import REGISTRY as _REGISTRY
from .mesh import current_mesh, mesh_size, shard_map

_MESH_DISTINCT_TOTAL = _REGISTRY.counter(
    "tpu_cypher_mesh_distinct_total",
    "DISTINCT counts executed on the sharded hash-repartition tier",
)

# Key namespace: real keys ship DOUBLED (even numbers — injective, equality
# and bucket assignment preserved); pad slots use per-side odd sentinels that
# can never equal a real key or each other. Invalid rows are dropped at host
# staging, so NO data value needs a reserved encoding — negative keys
# included. Staging rejects |key| >= 2^62 (doubling would overflow).
_L_PAD = 1
_R_PAD = 3
_KEY_LIMIT = 1 << 62


def _mix64(k):
    """splitmix64 finalizer over wrapping uint64 arithmetic: equal keys mix
    equal, and ANY structured key pattern (strided id namespaces, even-only
    ids, graph-tag high bits) spreads uniformly over the shards — a plain
    ``key % nsh`` concentrates every stride that shares a factor with the
    mesh size."""
    k = k.astype(jnp.uint64)
    k = (k ^ (k >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
    k = (k ^ (k >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
    return k ^ (k >> jnp.uint64(31))


@jax.jit
def combine_keys(keys):
    """Fold several int64 key columns into ONE mixed 61-bit join key (the
    composite-key shuffle/broadcast path; the reference serializes
    multi-column keys via codegen ``Serialize.scala``). Collisions are
    possible — callers MUST post-verify every key column on the matched
    pairs."""
    acc = jnp.zeros(keys[0].shape, jnp.uint64)
    for k in keys:
        acc = acc * jnp.uint64(0x9E3779B97F4A7C15) ^ k.astype(jnp.uint64)
        acc = (acc ^ (acc >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
        acc = acc ^ (acc >> jnp.uint64(31))
    return (acc & jnp.uint64((1 << 61) - 1)).astype(jnp.int64)


def _bucketize(keys, rows, nsh: int, cap: int, pad_key: int, axis: str):
    """Route (key, global row) pairs to shard ``mix(key) % nsh`` with ONE
    tiled all_to_all. Keys arrive doubled (even); ``pad_key`` is this
    side's odd pad sentinel (staged pad rows carry it too). Returns
    (received keys, received rows, overflow flag); slots past a bucket's
    fill carry the pad key. Pads and overflowing rows scatter into a
    per-bucket SPILL slot that is sliced off before the exchange, so they
    can never overwrite a real row."""
    n = keys.shape[0]
    # bucket on the PRE-doubled value (arithmetic shift recovers the
    # original, negatives included), mixed so strided key sets spread
    is_pad = keys == pad_key
    tgt = jnp.where(
        is_pad,
        (jnp.arange(n) % nsh).astype(jnp.uint64),
        _mix64(keys >> 1) % jnp.uint64(nsh),
    ).astype(jnp.int32)
    order = jnp.argsort(tgt, stable=True)
    tgt_s = jnp.take(tgt, order)
    is_real = ~jnp.take(is_pad, order)
    # rank REAL rows only (ADVICE r4): pads sorted ahead within a bucket
    # must not inflate real ranks, or near-capacity buckets trip the
    # overflow fallback spuriously
    creal = jnp.cumsum(is_real.astype(jnp.int64))
    start = jnp.searchsorted(tgt_s, tgt_s, side="left")
    before = jnp.where(start > 0, jnp.take(creal, jnp.maximum(start - 1, 0)), 0)
    rank = creal - 1 - before
    overflow = jnp.any((rank >= cap) & is_real)
    keys_s = jnp.take(keys, order)
    rows_s = jnp.take(rows, order)
    # pads and past-capacity rows land in the spill slot (index cap)
    rank_c = jnp.where(is_real, jnp.minimum(rank, cap), cap)
    buf_k = jnp.full((nsh, cap + 1), pad_key, jnp.int64)
    buf_r = jnp.zeros((nsh, cap + 1), jnp.int64)
    buf_k = buf_k.at[tgt_s, rank_c].set(
        jnp.where(rank_c < cap, keys_s, pad_key)
    )
    buf_r = buf_r.at[tgt_s, rank_c].set(rows_s)
    buf_k = lax.all_to_all(buf_k[:, :cap], axis, 0, 0, tiled=True)
    buf_r = lax.all_to_all(buf_r[:, :cap], axis, 0, 0, tiled=True)
    return buf_k.reshape(-1), buf_r.reshape(-1), overflow


def _local_probe(lk, rk):
    """Sort the received right block, binary-search the received left block.
    Returns (r_sorted_rows-selector pieces) shared by count & materialize.
    Pad keys are odd and per-side distinct, so they never match anything."""
    r_order = jnp.argsort(rk, stable=True)
    rk_s = jnp.take(rk, r_order)
    lo = jnp.searchsorted(rk_s, lk, side="left")
    hi = jnp.searchsorted(rk_s, lk, side="right")
    counts = jnp.where(lk != _L_PAD, hi - lo, 0).astype(jnp.int64)
    return r_order, lo, counts


_COUNT_CACHE: Dict[Any, Any] = {}
_MAT_CACHE: Dict[Any, Any] = {}


def _count_fn(mesh, axis, nsh, cap_l, cap_r):
    key = (mesh, axis, cap_l, cap_r)
    got = _COUNT_CACHE.get(key)
    if got is not None:
        return got

    def local(lk, lrow, rk, rrow):
        lk2, _, ovf_l = _bucketize(lk, lrow, nsh, cap_l, _L_PAD, axis)
        rk2, _, ovf_r = _bucketize(rk, rrow, nsh, cap_r, _R_PAD, axis)
        _, _, counts = _local_probe(lk2, rk2)
        return jnp.sum(counts)[None], (ovf_l | ovf_r)[None]

    spec = P(axis)
    fn = jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=(spec, spec, spec, spec),
            out_specs=(spec, spec),
        )
    )
    _COUNT_CACHE[key] = fn
    return fn


def _materialize_fn(mesh, axis, nsh, cap_l, cap_r, out_cap):
    key = (mesh, axis, cap_l, cap_r, out_cap)
    got = _MAT_CACHE.get(key)
    if got is not None:
        return got

    def local(lk, lrow, rk, rrow):
        lk2, lrow2, _ = _bucketize(lk, lrow, nsh, cap_l, _L_PAD, axis)
        rk2, rrow2, _ = _bucketize(rk, rrow, nsh, cap_r, _R_PAD, axis)
        r_order, lo, counts = _local_probe(lk2, rk2)
        rrow_sorted = jnp.take(rrow2, r_order)
        off = jnp.cumsum(counts)
        total = off[-1] if counts.shape[0] else jnp.asarray(0, jnp.int64)
        slot = jnp.arange(out_cap, dtype=jnp.int64)
        src = jnp.searchsorted(off, slot, side="right")
        src_c = jnp.minimum(src, counts.shape[0] - 1)
        within = slot - jnp.take(off - counts, src_c)
        valid = slot < total
        l_out = jnp.where(valid, jnp.take(lrow2, src_c), 0)
        r_idx = jnp.take(lo, src_c) + within
        r_out = jnp.where(
            valid, jnp.take(rrow_sorted, jnp.minimum(r_idx, rrow_sorted.shape[0] - 1)), 0
        )
        return l_out, r_out, valid

    spec = P(axis)
    fn = jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=(spec, spec, spec, spec),
            out_specs=(spec, spec, spec),
        )
    )
    _MAT_CACHE[key] = fn
    return fn


def _pad_sharded(arr_np: np.ndarray, nsh: int, fill, mesh, axis):
    pad = (-len(arr_np)) % nsh
    if pad:
        arr_np = np.concatenate(
            [arr_np, np.full(pad, fill, dtype=arr_np.dtype)]
        )
    return jax.device_put(arr_np, NamedSharding(mesh, P(axis)))


_BCAST_COUNT_CACHE: Dict[Any, Any] = {}
_BCAST_MAT_CACHE: Dict[Any, Any] = {}


def _broadcast_limit() -> int:
    from ..utils.config import BROADCAST_LIMIT

    return int(BROADCAST_LIMIT.get())


def _bcast_count_fn(mesh, axis):
    key = (mesh, axis)
    got = _BCAST_COUNT_CACHE.get(key)
    if got is not None:
        return got

    def local(lk, rk):
        _, _, counts = _local_probe(lk, rk)
        return jnp.sum(counts)[None]

    fn = jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=(P(axis), P(None)),
            out_specs=P(axis),
        )
    )
    _BCAST_COUNT_CACHE[key] = fn
    return fn


def _bcast_materialize_fn(mesh, axis, out_cap):
    key = (mesh, axis, out_cap)
    got = _BCAST_MAT_CACHE.get(key)
    if got is not None:
        return got

    def local(lk, lrow, rk, rrow):
        r_order, lo, counts = _local_probe(lk, rk)
        rrow_sorted = jnp.take(rrow, r_order)
        off = jnp.cumsum(counts)
        total = off[-1] if counts.shape[0] else jnp.asarray(0, jnp.int64)
        slot = jnp.arange(out_cap, dtype=jnp.int64)
        src = jnp.searchsorted(off, slot, side="right")
        src_c = jnp.minimum(src, counts.shape[0] - 1)
        within = slot - jnp.take(off - counts, src_c)
        valid = slot < total
        l_out = jnp.where(valid, jnp.take(lrow, src_c), 0)
        r_idx = jnp.take(lo, src_c) + within
        r_out = jnp.where(
            valid,
            jnp.take(rrow_sorted, jnp.minimum(r_idx, rrow_sorted.shape[0] - 1)),
            0,
        )
        return l_out, r_out, valid

    fn = jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=(P(axis), P(axis), P(None), P(None)),
            out_specs=(P(axis), P(axis), P(axis)),
        )
    )
    _BCAST_MAT_CACHE[key] = fn
    return fn


def broadcast_join(
    l_key, l_valid, r_key, r_valid
) -> Optional[Tuple[Any, Any]]:
    """Broadcast (replicated-build) equi-join over the active mesh: when
    the build (right) side is small, shuffling it through ``all_to_all`` is
    the wrong plan — replicate it to every device and probe the row-sharded
    left side LOCALLY, with NO collective in the join at all (the engines'
    broadcast join, delegated to Catalyst in the reference; SURVEY §2.3
    "broadcast small relations"). Returns matching global row-index pairs,
    or None when no mesh is active or the build side exceeds the cost
    model's broadcast window (``optimizer.cost.broadcast_build_limit`` —
    at least ``TPU_CYPHER_BROADCAST_LIMIT`` rows, default 4096, extended
    past it when replication still beats repartitioning both sides; a
    pinned env knob is honoured verbatim)."""
    mesh = current_mesh()
    nsh = mesh_size()
    if mesh is None or nsh <= 1:
        return None
    n_l, n_r = int(l_key.shape[0]), int(r_key.shape[0])
    try:
        from ..optimizer.cost import broadcast_build_limit

        limit = broadcast_build_limit(n_l, nsh)
    except Exception as exc:
        from ..errors import reraise_if_device

        reraise_if_device(exc, site="shuffle.broadcast")
        limit = _broadcast_limit()
    if n_l == 0 or n_r == 0 or n_r > limit:
        return None
    from ..runtime.faults import fault_point

    fault_point("shuffle")
    for arr in (l_key, l_valid, r_key, r_valid):
        if arr is not None and not getattr(arr, "is_fully_addressable", True):
            return None
    axis = mesh.axis_names[0]

    lk_np = np.asarray(l_key, dtype=np.int64)
    rk_np = np.asarray(r_key, dtype=np.int64)
    lrow_np = np.arange(n_l, dtype=np.int64)
    rrow_np = np.arange(n_r, dtype=np.int64)
    if l_valid is not None:
        keep = np.asarray(l_valid)
        lk_np, lrow_np = lk_np[keep], lrow_np[keep]
    if r_valid is not None:
        keep = np.asarray(r_valid)
        rk_np, rrow_np = rk_np[keep], rrow_np[keep]
    if len(lk_np) == 0 or len(rk_np) == 0:
        z = jnp.zeros(0, jnp.int64)
        return z, z
    if (
        np.abs(lk_np).max(initial=0) >= _KEY_LIMIT
        or np.abs(rk_np).max(initial=0) >= _KEY_LIMIT
    ):
        return None
    lk = _pad_sharded(lk_np * 2, nsh, _L_PAD, mesh, axis)
    lrow = _pad_sharded(lrow_np, nsh, 0, mesh, axis)
    repl = NamedSharding(mesh, P(None))
    rk = jax.device_put(rk_np * 2, repl)
    rrow = jax.device_put(rrow_np, repl)

    counts = _bcast_count_fn(mesh, axis)(lk, rk)
    counts_np = np.asarray(counts)
    out_cap = int(counts_np.max()) if counts_np.size else 0
    if out_cap == 0:
        z = jnp.zeros(0, jnp.int64)
        return z, z
    # shared pow2 lattice (see hash_repartition_join): one compiled
    # broadcast-materialize per bucket instead of one per match count
    out_cap = round_up_pow2(out_cap, 16)
    l_out, r_out, valid = _bcast_materialize_fn(mesh, axis, out_cap)(
        lk, lrow, rk, rrow
    )
    from ..backend.tpu.jit_ops import mask_nonzero, tree_take

    total = int(counts_np.sum())
    # tpulint: allow[pad-invariant] reason=final exact compact of the broadcast-join result (callers take every returned row as live); the materialize capacity above is already on the pow2 lattice
    idx = mask_nonzero(valid, size=total)
    return tree_take((l_out, r_out), idx)


def hash_repartition_join(
    l_key, l_valid, r_key, r_valid, cap_factor: float = 2.0
) -> Optional[Tuple[Any, Any]]:
    """Inner equi-join row pairs over the active mesh via explicit hash
    shuffle. ``l_key``/``r_key``: int64 device arrays (element ids); valid
    masks may be None. Returns (left_rows, right_rows) int64 arrays of
    matching GLOBAL row indices (compacted), or None when no multi-device
    mesh is active or a hash bucket overflows its static capacity — the
    caller keeps the global sort-probe join."""
    mesh = current_mesh()
    nsh = mesh_size()
    if mesh is None or nsh <= 1:
        return None
    from ..runtime.faults import fault_point

    fault_point("shuffle")
    axis = mesh.axis_names[0]
    n_l, n_r = int(l_key.shape[0]), int(r_key.shape[0])
    if n_l == 0 or n_r == 0:
        return None  # trivial; the default join handles empties cheaply
    for arr in (l_key, l_valid, r_key, r_valid):
        # multi-process meshes hold row-sharded GLOBAL arrays whose remote
        # shards this process cannot read — np.asarray staging would raise,
        # so keep the default (GSPMD-partitioned) sort-probe join (ADVICE r4)
        if arr is not None and not getattr(arr, "is_fully_addressable", True):
            return None

    # host staging: drop invalid rows (null keys never match), double the
    # keys into the even namespace, pad to shard multiples with odd pad
    # sentinels. (join() depads its inputs, so the clean row sharding must
    # be rebuilt anyway.)
    lk_np = np.asarray(l_key, dtype=np.int64)
    rk_np = np.asarray(r_key, dtype=np.int64)
    lrow_np = np.arange(n_l, dtype=np.int64)
    rrow_np = np.arange(n_r, dtype=np.int64)
    if l_valid is not None:
        keep = np.asarray(l_valid)
        lk_np, lrow_np = lk_np[keep], lrow_np[keep]
    if r_valid is not None:
        keep = np.asarray(r_valid)
        rk_np, rrow_np = rk_np[keep], rrow_np[keep]
    if len(lk_np) == 0 or len(rk_np) == 0:
        z = jnp.zeros(0, jnp.int64)
        return z, z
    if (
        np.abs(lk_np).max(initial=0) >= _KEY_LIMIT
        or np.abs(rk_np).max(initial=0) >= _KEY_LIMIT
    ):
        return None  # doubling would overflow int64
    lk = _pad_sharded(lk_np * 2, nsh, _L_PAD, mesh, axis)
    rk = _pad_sharded(rk_np * 2, nsh, _R_PAD, mesh, axis)
    lrow = _pad_sharded(lrow_np, nsh, 0, mesh, axis)
    rrow = _pad_sharded(rrow_np, nsh, 0, mesh, axis)

    bl = int(lk.shape[0]) // nsh
    br = int(rk.shape[0]) // nsh
    # capacities snap to the SHARED power-of-two lattice
    # (``bucketing.round_up_pow2`` — same helper as the shape buckets): the
    # static cap is baked into the shard_map programs, so rounding makes
    # nearby input sizes reuse one compiled exchange instead of compiling
    # per size. Overflow detection keeps correctness; <=2x buffer slack.
    cap_l = round_up_pow2(int(bl / nsh * cap_factor) + 16, 16)
    cap_r = round_up_pow2(int(br / nsh * cap_factor) + 16, 16)

    counts, overflow = _count_fn(mesh, axis, nsh, cap_l, cap_r)(
        lk, lrow, rk, rrow
    )
    counts_np = np.asarray(counts)
    if bool(np.asarray(overflow).any()):
        return None  # skewed keys: fall back to the global sort-probe join
    out_cap = int(counts_np.max()) if counts_np.size else 0
    if out_cap == 0:
        z = jnp.zeros(0, jnp.int64)
        return z, z
    # same lattice for the output capacity (slots past the true per-shard
    # total come out valid=False and are compacted away below)
    out_cap = round_up_pow2(out_cap, 16)
    l_out, r_out, valid = _materialize_fn(
        mesh, axis, nsh, cap_l, cap_r, out_cap
    )(lk, lrow, rk, rrow)
    from ..backend.tpu.jit_ops import mask_nonzero, tree_take

    total = int(counts_np.sum())
    # tpulint: allow[pad-invariant] reason=final exact compact of the shuffle-join result (callers take every returned row as live); the per-shard capacities above are already on the pow2 lattice
    idx = mask_nonzero(valid, size=total)
    l_rows, r_rows = tree_take((l_out, r_out), idx)
    return l_rows, r_rows


# ---------------------------------------------------------------------------
# sharded DISTINCT: hash-repartition the equivalence keys so equal values
# meet on one shard, count run boundaries locally, psum the partial counts
# ---------------------------------------------------------------------------

_DISTINCT_CACHE: Dict[Any, Any] = {}


def _distinct_fn(mesh, axis, nsh, cap):
    key = (mesh, axis, cap)
    got = _DISTINCT_CACHE.get(key)
    if got is not None:
        return got

    def local(keys, live):
        # route by mixed VALUE so every occurrence of a key lands on one
        # shard; liveness travels as a sidecar lane (packed equivalence
        # keys use the full 63-bit namespace, so no key value can be
        # reserved as a pad sentinel the way the join's doubling does)
        n = keys.shape[0]
        is_live = live != 0
        tgt = jnp.where(
            is_live,
            _mix64(keys) % jnp.uint64(nsh),
            (jnp.arange(n) % nsh).astype(jnp.uint64),
        ).astype(jnp.int32)
        order = jnp.argsort(tgt, stable=True)
        tgt_s = jnp.take(tgt, order)
        is_real = jnp.take(is_live, order)
        creal = jnp.cumsum(is_real.astype(jnp.int64))
        start = jnp.searchsorted(tgt_s, tgt_s, side="left")
        before = jnp.where(
            start > 0, jnp.take(creal, jnp.maximum(start - 1, 0)), 0
        )
        rank = creal - 1 - before
        overflow = jnp.any((rank >= cap) & is_real)
        rank_c = jnp.where(is_real, jnp.minimum(rank, cap), cap)
        keys_s = jnp.take(keys, order)
        buf_k = jnp.zeros((nsh, cap + 1), jnp.int64)
        buf_v = jnp.zeros((nsh, cap + 1), jnp.int64)
        buf_k = buf_k.at[tgt_s, rank_c].set(
            jnp.where(rank_c < cap, keys_s, 0)
        )
        buf_v = buf_v.at[tgt_s, rank_c].set(
            jnp.where(rank_c < cap, is_real.astype(jnp.int64), 0)
        )
        rk = lax.all_to_all(buf_k[:, :cap], axis, 0, 0, tiled=True).reshape(-1)
        rv = lax.all_to_all(buf_v[:, :cap], axis, 0, 0, tiled=True).reshape(-1)
        live2 = rv != 0
        # live rows sort to the front (dead-last), grouped by key: a run
        # boundary among the live prefix is one distinct value
        order2 = jnp.lexsort((rk, (~live2).astype(jnp.int8)))
        k_s = jnp.take(rk, order2)
        l_s = jnp.take(live2, order2)
        idx = jnp.arange(k_s.shape[0])
        first = l_s & ((idx == 0) | (k_s != jnp.roll(k_s, 1)))
        local_distinct = jnp.sum(first.astype(jnp.int64))
        return lax.psum(local_distinct, axis)[None], overflow[None]

    spec = P(axis)
    fn = jax.jit(
        shard_map(
            local, mesh=mesh, in_specs=(spec, spec), out_specs=(spec, spec)
        )
    )
    _DISTINCT_CACHE[key] = fn
    return fn


def sharded_distinct_count(
    keys, valid=None, cap_factor: float = 2.0
) -> Optional[int]:
    """Distinct count of int64 equivalence keys over the active mesh: the
    DISTINCT analog of ``hash_repartition_join`` — one tiled ``all_to_all``
    routes every occurrence of a key value to ``mix(value) % n_shards``, so
    each shard's local run-boundary count is over a disjoint slice of the
    value space and the partials ``psum`` exactly. Returns the count, or
    None when no multi-device mesh is active, rows are not addressable
    from this process, or a skewed key distribution overflows the static
    bucket capacity — the caller keeps the global sort path."""
    mesh = current_mesh()
    nsh = mesh_size()
    if mesh is None or nsh <= 1:
        return None
    for arr in (keys, valid):
        if arr is not None and not getattr(arr, "is_fully_addressable", True):
            return None
    from ..runtime.faults import fault_point

    fault_point("shuffle")
    axis = mesh.axis_names[0]
    k_np = np.asarray(keys, dtype=np.int64)
    if valid is not None:
        k_np = k_np[np.asarray(valid)]
    n = len(k_np)
    if n == 0:
        return 0
    k = _pad_sharded(k_np, nsh, 0, mesh, axis)
    live = _pad_sharded(np.ones(n, dtype=np.int64), nsh, 0, mesh, axis)
    b = int(k.shape[0]) // nsh
    cap = round_up_pow2(int(b / nsh * cap_factor) + 16, 16)
    counts, overflow = _distinct_fn(mesh, axis, nsh, cap)(k, live)
    if bool(np.asarray(overflow).any()):
        return None
    _MESH_DISTINCT_TOTAL.inc()
    _obs_trace.note("distinct_shards", nsh)
    return int(np.asarray(counts)[0])

"""Device-mesh sharding for the graph kernels.

The reference delegates ALL distribution to Spark/Flink shuffle (SURVEY §2.3);
the TPU-native replacement is a ``jax.sharding.Mesh`` with XLA collectives
over ICI/DCN. Layout:

* edge arrays (``src_idx``, ``col_idx``) are sharded over the ``edges`` mesh
  axis — the analog of hash-partitioned relationship tables,
* node-indexed vectors (frontiers, degree arrays) are replicated — small
  relative to edges (the broadcast-join analog),
* per-shard partial aggregates are combined with ``psum`` over ICI
  (``shard_map``), exactly where the engines would shuffle-reduce.

Works identically on one chip, a v5e-8 slice, or a virtual
``--xla_force_host_platform_device_count`` CPU mesh (tests / dryrun)."""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence, Tuple

import numpy as np

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # JAX >= 0.7 exposes shard_map at top level
    from jax import shard_map as _shard_map

    def shard_map(f, mesh, in_specs, out_specs):
        return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs)

except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map_legacy

    def shard_map(f, mesh, in_specs, out_specs):
        return _shard_map_legacy(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs)

from ..utils import config as _config

EDGE_AXIS = "edges"

# engine-level row axis: TpuTable columns and CSR edge arrays are sharded
# over this axis while a mesh is active (SURVEY §2.3 "tables sharded on
# id/hash dim across a TPU mesh")
ROW_AXIS = "rows"

_ACTIVE_MESH: Optional[Mesh] = None


def make_mesh(devices: Optional[Sequence] = None) -> Mesh:
    if devices is None:
        devices = jax.devices()
    return Mesh(np.array(devices), (EDGE_AXIS,))


def make_row_mesh(devices: Optional[Sequence] = None) -> Mesh:
    """1-D engine mesh: every table row dimension shards over ROW_AXIS."""
    if devices is None:
        devices = jax.devices()
    return Mesh(np.array(devices), (ROW_AXIS,))


class use_mesh:
    """Context manager activating engine sharding: while active, newly
    created TpuTable columns and GraphIndex edge arrays are laid out as
    ``NamedSharding(mesh, P(ROW_AXIS))`` and every downstream op runs under
    XLA's GSPMD propagation — collectives (all_gather/all_to_all/psum) are
    inserted by the compiler where ops cross shards, the idiomatic
    replacement for the engines' shuffle exchanges (SURVEY §2.3)."""

    def __init__(self, mesh: Mesh):
        self.mesh = mesh
        self._prev: Optional[Mesh] = None

    def __enter__(self) -> Mesh:
        global _ACTIVE_MESH
        self._prev = _ACTIVE_MESH
        _ACTIVE_MESH = self.mesh
        return self.mesh

    def __exit__(self, *exc) -> None:
        global _ACTIVE_MESH
        _ACTIVE_MESH = self._prev


def resolve_mesh(spec) -> Optional[Mesh]:
    """One mesh-construction chokepoint for every activation surface
    (``CypherSession.tpu(mesh=...)``, the ``TPU_CYPHER_MESH`` env default).

    ``Mesh`` passes through; an integer N builds a row mesh over the first
    N visible devices; ``"auto"``/``"all"`` takes every device. Anything
    that resolves to a single device (or ``""``/``"off"``/``None``) means
    single-device execution and returns None."""
    if spec is None:
        return None
    if isinstance(spec, Mesh):
        return spec
    if isinstance(spec, int):
        n = spec
    else:
        s = str(spec).strip().lower()
        if s in ("", "off", "none", "0", "1"):
            return None
        if s in ("auto", "all"):
            n = len(jax.devices())
        else:
            try:
                n = int(s)
            except ValueError:
                return None
    devs = jax.devices()
    n = min(n, len(devs))
    if n <= 1:
        return None
    return make_row_mesh(devs[:n])


def activate_mesh(mesh: Optional[Mesh]) -> Optional[Mesh]:
    """Set the process-global engine mesh (None deactivates). The session
    factory uses this for persistent activation; scoped activation should
    prefer the ``use_mesh`` context manager."""
    global _ACTIVE_MESH
    _ACTIVE_MESH = mesh
    return mesh


# env-default mesh, resolved lazily and memoized per spec string so the
# hot-path current_mesh() stays a dict probe after first use
_ENV_MESH_CACHE: dict = {}


def _env_default_mesh() -> Optional[Mesh]:
    spec = _config.MESH_SPEC.get()
    if spec not in _ENV_MESH_CACHE:
        _ENV_MESH_CACHE[spec] = resolve_mesh(spec)
    return _ENV_MESH_CACHE[spec]


def current_mesh() -> Optional[Mesh]:
    if _ACTIVE_MESH is not None:
        return _ACTIVE_MESH
    return _env_default_mesh()


def shard_rows(arr):
    """Row-shard an ALREADY-SIZED device array over the active mesh when its
    leading dim is divisible by the mesh size (NamedSharding requires
    divisibility); other arrays stay as-is. Engine ingest uses
    ``padded_to_mesh`` instead, which pads arbitrary row counts to a shard
    multiple (VERDICT r2 weak #3: the divisible-only skip silently
    un-sharded real workloads — 1,999,987 edges on an 8-mesh)."""
    mesh = current_mesh()
    if mesh is None:
        return arr
    shape = getattr(arr, "shape", None)
    if not shape or shape[0] == 0:
        return arr
    size = int(np.prod(list(mesh.shape.values())))
    if shape[0] % size != 0:
        return arr
    axis = mesh.axis_names[0]
    return jax.device_put(arr, NamedSharding(mesh, P(axis)))


def mesh_size() -> int:
    mesh = current_mesh()
    if mesh is None:
        return 1
    return int(np.prod(list(mesh.shape.values())))


def padded_to_mesh(host_arr, fill) -> Tuple[Any, int]:
    """Device-put a HOST array row-sharded over the active mesh, padding the
    tail with ``fill`` up to the next shard multiple (this JAX requires the
    leading dim divisible by the mesh size — uneven NamedShardings are
    rejected even via jit out_shardings). Returns ``(device array, pad)``.
    Pad rows are semantically inert: table columns mark them invalid
    (``Column.pad``/``pad_synth``), CSR edge arrays keep them outside every
    ``row_ptr`` range, and sorted edge-key arrays use an above-everything
    sentinel. With no active mesh (or an empty input) this is a plain
    ``jnp.asarray`` with pad 0."""
    arr = np.asarray(host_arr)
    mesh = current_mesh()
    if mesh is None or arr.ndim == 0 or arr.shape[0] == 0:
        return jnp.asarray(arr), 0
    size = int(np.prod(list(mesh.shape.values())))
    pad = (-arr.shape[0]) % size
    if pad:
        tail = np.full((pad,) + arr.shape[1:], fill, dtype=arr.dtype)
        arr = np.concatenate([arr, tail])
    axis = mesh.axis_names[0]
    return jax.device_put(arr, NamedSharding(mesh, P(axis))), pad


def pad_edges(src_idx: np.ndarray, col_idx: np.ndarray, num_shards: int):
    """Pad edge arrays to a multiple of the shard count with self-loop-free
    sentinel edges pointing at a dead slot (num_nodes), so shards are equal."""
    e = len(src_idx)
    padded = ((e + num_shards - 1) // num_shards) * num_shards
    pad = padded - e
    if pad:
        src_idx = np.concatenate([src_idx, np.full(pad, -1, src_idx.dtype)])
        col_idx = np.concatenate([col_idx, np.full(pad, -1, col_idx.dtype)])
    return src_idx, col_idx, pad


def shard_edge_arrays(mesh: Mesh, *arrays):
    sharding = NamedSharding(mesh, P(EDGE_AXIS))
    return tuple(jax.device_put(a, sharding) for a in arrays)


# jitted shard_map programs, memoized per mesh (+static sizes): these
# factories used to build a FRESH jitted callable per invocation, which
# recompiled the collective program on every call — the exact hazard the
# recompile-hazard lint rule now catches
_TWO_HOP_CACHE: dict = {}
_WALK_STEP_CACHE: dict = {}
_TRAIN_STEP_CACHE: dict = {}


def sharded_two_hop_count(mesh: Mesh, deg: jnp.ndarray, col_idx: jnp.ndarray):
    """sum over edges of outdeg(dst), edges sharded, psum over ICI."""
    f = _TWO_HOP_CACHE.get(mesh)
    if f is None:

        def kernel(deg_rep, col_shard):
            valid = col_shard >= 0
            local = jnp.sum(jnp.where(valid, deg_rep[jnp.clip(col_shard, 0)], 0).astype(jnp.int64))
            return lax.psum(local, EDGE_AXIS)

        f = jax.jit(
            shard_map(kernel, mesh, in_specs=(P(), P(EDGE_AXIS)), out_specs=P())
        )
        _TWO_HOP_CACHE[mesh] = f
    return f(deg, col_idx)


def sharded_walk_step(mesh: Mesh, num_nodes: int):
    """One frontier SpMM step: p'[v] = sum over sharded edges (u,v) of p[u].

    The per-shard ``segment_sum`` produces partial next-frontiers combined
    with ``psum`` — the ICI replacement for the engines' shuffle exchange."""
    key = (mesh, num_nodes)
    f = _WALK_STEP_CACHE.get(key)
    if f is not None:
        return f

    def kernel(p, src_shard, col_shard):
        valid = src_shard >= 0
        contrib = jnp.where(valid, p[jnp.clip(src_shard, 0)], 0)
        partial_next = jax.ops.segment_sum(
            contrib, jnp.clip(col_shard, 0), num_segments=num_nodes
        )
        return lax.psum(partial_next, EDGE_AXIS)

    f = jax.jit(
        shard_map(
            kernel, mesh, in_specs=(P(), P(EDGE_AXIS), P(EDGE_AXIS)), out_specs=P()
        )
    )
    _WALK_STEP_CACHE[key] = f
    return f


def sharded_training_step(mesh: Mesh, num_nodes: int, hops: int):
    """The full multi-hop 'step': iterated sharded SpMM over the mesh +
    a final psum'd 2-hop count — the complete distributed query step used by
    the driver's multi-chip dryrun."""
    key = (mesh, num_nodes, hops)
    cached = _TRAIN_STEP_CACHE.get(key)
    if cached is not None:
        return cached

    def kernel(p0, deg, src_shard, col_shard):
        valid = src_shard >= 0

        def one_hop(p, _):
            contrib = jnp.where(valid, p[jnp.clip(src_shard, 0)], 0)
            nxt = jax.ops.segment_sum(
                contrib, jnp.clip(col_shard, 0), num_segments=num_nodes
            )
            nxt = lax.psum(nxt, EDGE_AXIS)
            return nxt, jnp.sum(nxt)

        p_final, hop_counts = lax.scan(one_hop, p0.astype(jnp.int64), None, length=hops)
        two_hop_local = jnp.sum(
            jnp.where(valid, deg[jnp.clip(col_shard, 0)], 0).astype(jnp.int64)
        )
        two_hop = lax.psum(two_hop_local, EDGE_AXIS)
        return p_final, hop_counts, two_hop

    f = jax.jit(
        shard_map(
            kernel,
            mesh,
            in_specs=(P(), P(), P(EDGE_AXIS), P(EDGE_AXIS)),
            out_specs=(P(), P(), P()),
        )
    )
    _TRAIN_STEP_CACHE[key] = f
    return f


_RANGE_COUNT_CACHE: dict = {}


def sharded_range_count(mesh: Mesh):
    """Per-query equal-key counts over ROW_AXIS-sharded sorted ``edge_keys``
    — the mesh tier of the WCOJ leapfrog intersect.

    A NamedSharding over the leading dim partitions a sorted array into
    contiguous slices, and searchsorted range counts are ADDITIVE over
    contiguous partitions: each shard counts matches in its local adjacency
    slice with two binary searches and the counts ``psum``-combine, exactly
    where a relational engine would shuffle-reduce. Queries and their
    validity mask stay replicated (they are small relative to edges — the
    broadcast-join analog); sentinel pad keys (above every real key) can
    never match a query so pads contribute zero."""
    f = _RANGE_COUNT_CACHE.get(mesh)
    if f is None:

        def kernel(keys_shard, q, qok):
            lo = jnp.searchsorted(keys_shard, q, side="left")
            hi = jnp.searchsorted(keys_shard, q, side="right")
            local = jnp.where(qok, (hi - lo).astype(jnp.int64), 0)
            return lax.psum(local, ROW_AXIS)

        f = jax.jit(
            shard_map(
                kernel, mesh, in_specs=(P(ROW_AXIS), P(), P()), out_specs=P()
            )
        )
        _RANGE_COUNT_CACHE[mesh] = f
    return f

"""Sharded segment aggregates: per-shard partials tree-combined over ICI.

The reference delegates grouped aggregation to the engines' shuffle-reduce
(partial aggregates per partition, combined at the exchange — SURVEY §2.3);
the mesh analog computes each shard's ``segment_*`` partial over its local
row block and combines the k-sized partials with ``psum``/``pmin``/``pmax``
inside one ``shard_map`` program, so no shard ever holds the full row set.

Eligibility is deliberately narrow: INTEGER data (I64/BOOL) and the
aggregates whose combine is exact over the integers (count/sum/min/max,
plus avg as an integer-sum over integer-count divide). Float addition is
not associative, so a float psum could differ from the single-device result
in the last ulp — the differential suite pins sharded results BIT-IDENTICAL
to single-device, and the float kinds keep the global path. Gate:
``TPU_CYPHER_MESH_AGG=off`` disables the tier entirely.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..obs import trace as _obs_trace
from ..obs.metrics import REGISTRY as _REGISTRY
from ..runtime.faults import fault_point
from .mesh import current_mesh, mesh_size, shard_map
from .shuffle import _pad_sharded

_MESH_AGG_TOTAL = _REGISTRY.counter(
    "tpu_cypher_mesh_agg_total",
    "grouped aggregates executed on the sharded (per-shard partial + "
    "tree combine) tier",
)

# aggregate names whose per-shard combine is exact over the integers
_INT_NAMES = ("count", "sum", "min", "max", "avg")

# jitted shard_map programs, memoized per (mesh, aggregate, dtype, k) —
# fresh factories per call would recompile the collective every query
# (the recompile-hazard lint rule)
_AGG_CACHE: Dict[Any, Any] = {}


def _agg_fn(mesh, axis: str, name: str, is_bool: bool, k: int):
    key = (mesh, axis, name, is_bool, k)
    got = _AGG_CACHE.get(key)
    if got is not None:
        return got

    def local(data, valid, seg):
        # pad rows staged valid=False: they contribute the combine identity
        cnt = jax.ops.segment_sum(
            valid.astype(jnp.int64), seg, num_segments=k
        )
        cnt = lax.psum(cnt, axis)
        if name == "count":
            return cnt, cnt
        if name in ("sum", "avg"):
            ssum = jax.ops.segment_sum(
                jnp.where(valid, data, jnp.zeros((), data.dtype)),
                seg,
                num_segments=k,
            )
            return lax.psum(ssum, axis), cnt
        # min / max: same sentinels as the global segment_aggregate so
        # empty-group payloads (masked invalid anyway) stay bit-identical
        d = data.astype(jnp.int8) if is_bool else data
        big = jnp.asarray(jnp.iinfo(d.dtype).max, d.dtype)
        if name == "min":
            agged = jax.ops.segment_min(
                jnp.where(valid, d, big), seg, num_segments=k
            )
            agged = lax.pmin(agged, axis)
        else:
            agged = jax.ops.segment_max(
                jnp.where(valid, d, -big), seg, num_segments=k
            )
            agged = lax.pmax(agged, axis)
        return agged, cnt

    spec = P(axis)
    fn = jax.jit(
        shard_map(
            local, mesh, in_specs=(spec, spec, spec), out_specs=(P(), P())
        )
    )
    _AGG_CACHE[key] = fn
    return fn


def _gate_open() -> bool:
    from ..utils.config import MESH_AGG

    return MESH_AGG.get().strip().lower() == "auto"


def sharded_segment_agg(
    data, valid, seg_j, name: str, is_bool: bool, k: int
) -> Optional[Tuple[Any, Any]]:
    """One grouped aggregate as per-shard partials + tree combine.

    ``data``/``seg_j`` device (or host) arrays over the same row extent,
    ``valid`` an optional mask. Returns ``(out_data, out_valid_or_None)``
    in the global ``segment_aggregate`` contract, or None when the tier is
    ineligible (no multi-device mesh, a non-integer-exact aggregate, the
    ``TPU_CYPHER_MESH_AGG=off`` gate, or rows this process cannot stage) —
    the caller keeps the global path."""
    mesh = current_mesh()
    nsh = mesh_size()
    if mesh is None or nsh <= 1 or name not in _INT_NAMES or k <= 0:
        return None
    if not _gate_open():
        return None
    for arr in (data, valid, seg_j):
        if arr is not None and not getattr(arr, "is_fully_addressable", True):
            return None
    fault_point("agg")  # staging rows to host for resharding syncs here
    d_np = np.asarray(data)
    n = d_np.shape[0]
    if n == 0:
        return None
    v_np = (
        np.ones(n, bool) if valid is None else np.asarray(valid, dtype=bool)
    )
    s_np = np.asarray(seg_j, dtype=np.int64)
    axis = mesh.axis_names[0]
    d = _pad_sharded(d_np, nsh, 0, mesh, axis)
    v = _pad_sharded(v_np, nsh, False, mesh, axis)
    s = _pad_sharded(s_np, nsh, 0, mesh, axis)
    out, cnt = _agg_fn(mesh, axis, name, bool(is_bool), int(k))(d, v, s)
    _MESH_AGG_TOTAL.inc()
    _obs_trace.note("agg_shards", nsh)
    if name == "count":
        return out, None
    if name == "sum":
        return out, None
    if name == "avg":
        avg = out.astype(jnp.float64) / jnp.maximum(cnt, 1)
        return avg, cnt > 0
    agged = out.astype(bool) if is_bool else out
    return agged, cnt > 0


# ---------------------------------------------------------------------------
# run-length weighted partials (factorized join intermediates)
# ---------------------------------------------------------------------------


@jax.jit
def _weighted_premultiply(data, valid, weight):
    """Per-row weighted terms: each logical row stands for ``weight``
    identical flat rows, so its count contribution is ``weight`` (0 when
    invalid) and its sum contribution is ``data * weight``."""
    w = weight if valid is None else jnp.where(valid, weight, 0)
    if data is None:
        return None, w
    zero = jnp.zeros((), data.dtype)
    d = data if valid is None else jnp.where(valid, data, zero)
    return d * w.astype(data.dtype), w


@partial(jax.jit, static_argnames=("k",))
def _weighted_segment_sums(pre_sum, pre_cnt, seg_j, k: int):
    wcnt = jax.ops.segment_sum(pre_cnt, seg_j, num_segments=k)
    if pre_sum is None:
        return None, wcnt
    return jax.ops.segment_sum(pre_sum, seg_j, num_segments=k), wcnt


def weighted_segment_partials(data, valid, weight, seg_j, k: int):
    """Weighted segment partials ``(weighted_sum_or_None, weighted_count)``
    for the factorized group path (``backend/tpu/factorized.py``): every
    source row aggregates as ``weight`` identical flat rows without ever
    decompressing. ``data=None`` computes the count partial only (count(*)
    / count(expr) need no values). Integer inputs ride the sharded tier —
    the premultiplied partials are integer sums, so the psum combine stays
    exact/bit-identical — floats and the no-mesh case take one jitted
    segment program."""
    pre_sum, pre_cnt = _weighted_premultiply(data, valid, weight)
    ints = data is None or jnp.issubdtype(data.dtype, jnp.integer)
    if ints:
        got_cnt = sharded_segment_agg(pre_cnt, None, seg_j, "sum", False, k)
        if got_cnt is not None:
            if pre_sum is None:
                return None, got_cnt[0]
            got_sum = sharded_segment_agg(pre_sum, None, seg_j, "sum", False, k)
            if got_sum is not None:
                return got_sum[0], got_cnt[0]
    return _weighted_segment_sums(pre_sum, pre_cnt, seg_j, k)

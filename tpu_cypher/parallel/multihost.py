"""Multi-host orchestration: the DCN scale-out path.

The reference scales out by deploying on a Spark/Flink cluster — the session
rides the engine's distributed ExecutionEnvironment
(``flink-cypher/src/main/scala/org/opencypher/flink/api/CAPFSession.scala:47``);
workers coordinate through the engine's RPC layer. The TPU-native analog
(SURVEY §2.3, BASELINE config #5: LDBC SF100 sharded over a v5e-64 pod) is:

* ``jax.distributed.initialize`` connects the per-host processes over DCN
  (coordinator + process id, env-driven like Spark's master/worker env),
* ONE global ``Mesh`` spans every device of every process; the engine's row
  sharding (``parallel.mesh.use_mesh``) then lays ingested columns and CSR
  arrays across the whole pod — GSPMD/shard_map collectives ride ICI within
  a host and DCN across hosts, exactly where the engines shuffle,
* results gather to process 0 (``collect_on_host0``) the way the engines
  collect to the driver.

Single-process use degenerates cleanly: ``initialize_distributed`` is a
no-op, the global mesh is the local mesh, and gathering is the identity —
so the SF100 pod run is a config change (environment variables), not new
code. The degenerate path is exercised by ``dryrun_multihost`` and tests;
the pod path cannot run in this environment (one chip) but shares every
line except the ``jax.distributed.initialize`` call."""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

import jax

from .mesh import ROW_AXIS, make_row_mesh, use_mesh

_INITIALIZED = False


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids: Optional[list] = None,
) -> bool:
    """Connect this process to the pod's coordination service.

    Arguments default to the standard env vars (``JAX_COORDINATOR_ADDRESS``,
    ``JAX_NUM_PROCESSES``, ``JAX_PROCESS_ID``) — the deployment shape of the
    engines' master/worker env. Returns True when a multi-process runtime
    was initialized, False for the single-process degenerate case (no env,
    one process). Idempotent."""
    global _INITIALIZED
    if _INITIALIZED:
        return True
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    if num_processes is None:
        num_processes = int(os.environ.get("JAX_NUM_PROCESSES", "1"))
    if process_id is None:
        process_id = int(os.environ.get("JAX_PROCESS_ID", "0"))
    if coordinator_address is None or num_processes <= 1:
        return False  # single process: nothing to coordinate
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )
    _INITIALIZED = True
    return True


def global_row_mesh():
    """Row mesh over EVERY device of every connected process (after
    ``initialize_distributed``, ``jax.devices()`` is the global list)."""
    return make_row_mesh(jax.devices())


def process_count() -> int:
    return jax.process_count()


def is_host0() -> bool:
    return jax.process_index() == 0


def collect_on_host0(arr) -> Optional[np.ndarray]:
    """Gather a (possibly sharded) device array's GLOBAL value onto process
    0 (None elsewhere) — the driver-collect step. Single-process: identity."""
    if jax.process_count() == 1:
        return np.asarray(arr)
    from jax.experimental import multihost_utils

    full = multihost_utils.process_allgather(arr, tiled=True)
    return np.asarray(full) if is_host0() else None


class multihost_session:
    """Context manager for the full scale-out recipe:

    >>> with multihost_session() as mesh:   # doctest: +SKIP
    ...     g = session.read_from(...)      # ingests sharded over the pod
    ...     g.cypher("MATCH ...")

    initialize (no-op single-process) -> global mesh -> engine row sharding
    active. BASELINE #5's v5e-64 run is this block plus the coordinator env."""

    def __init__(self, **init_kwargs):
        self._init_kwargs = init_kwargs
        self._mesh_ctx = None

    def __enter__(self):
        initialize_distributed(**self._init_kwargs)
        mesh = global_row_mesh()
        self._mesh_ctx = use_mesh(mesh)
        return self._mesh_ctx.__enter__()

    def __exit__(self, *exc):
        return self._mesh_ctx.__exit__(*exc)


def dryrun_multihost() -> dict:
    """Exercise the whole multi-host code path in whatever topology this
    process sees (single-process degenerate case included): session inside
    ``multihost_session``, a sharded engine query, host-0 gather. Returns a
    small report dict (used by tests and the driver dryrun)."""
    from tpu_cypher import CypherSession
    from tpu_cypher.api.mapping import (
        NodeMappingBuilder,
        RelationshipMappingBuilder,
    )
    from tpu_cypher.relational.graphs import ElementTable

    n, e = 51, 173  # non-divisible: exercises pad-to-shard across the mesh
    rng = np.random.default_rng(0)
    ids = np.arange(n, dtype=np.int64) * 3 + 1
    src = rng.integers(0, n, e)
    dst = rng.integers(0, n, e)
    keep = src != dst
    src, dst = src[keep], dst[keep]
    with multihost_session() as mesh:
        s = CypherSession.tpu()
        nt = s.table_cls.from_arrays({"id": ids})
        nm = NodeMappingBuilder.on("id").with_implied_label("P").build()
        rt = s.table_cls.from_arrays(
            {
                "rid": np.arange(len(src), dtype=np.int64) + 10_000,
                "s": ids[src],
                "t": ids[dst],
            }
        )
        rm = (
            RelationshipMappingBuilder.on("rid")
            .from_("s")
            .to("t")
            .with_relationship_type("K")
            .build()
        )
        g = s.read_from(ElementTable(nm, nt), ElementTable(rm, rt))
        got = g.cypher(
            "MATCH (a:P)-[:K]->(b)-[:K]->(c) RETURN count(*) AS c"
        ).records.collect()
        # row-returning query: materializes SHARDED columns, so the host
        # pull must assemble shards across processes (column.to_host's
        # collective allgather — the collect-to-driver step)
        rows = g.cypher(
            "MATCH (a:P)-[:K]->(b) RETURN id(a) AS x ORDER BY x LIMIT 5"
        ).records.collect()
    outdeg = np.bincount(np.searchsorted(np.sort(ids), ids[src]), minlength=n)
    expected = int(outdeg[np.searchsorted(np.sort(ids), ids[dst])].sum())
    count = int(got[0]["c"])
    assert count == expected, (count, expected)
    expected_rows = sorted(int(i) for i in ids[src])[:5]
    got_rows = [int(r["x"]) for r in rows]
    assert got_rows == expected_rows, (got_rows, expected_rows)
    return {
        "processes": process_count(),
        "devices": len(jax.devices()),
        "mesh_axes": dict(mesh.shape),
        "two_hop": count,
        "rows": got_rows,
        "host0": is_host0(),
    }

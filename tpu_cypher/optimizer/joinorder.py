"""Cost-based join-order search over the logical plan.

The logical planner emits pattern chains in **syntax order**: a stack of
``Expand`` / ``ExpandInto`` / ``Filter`` nodes over a base (a free
``NodeScan`` anchoring the pattern, or whatever operator bound the first
endpoint). :func:`maybe_reorder` rewrites each such chain into the order
the :class:`~tpu_cypher.optimizer.cost.CostModel` prices cheapest:

* exact dynamic programming over connected sub-patterns up to
  ``TPU_CYPHER_OPT_DP_MAX_RELS`` relationships (states are solved-rel
  subsets; connectivity keeps the reachable state count far below 2^k),
  greedy cheapest-next-step beyond that;
* when the base is a free scan the anchor node is part of the search —
  the model may start the pattern from a rarer label;
* interleaved filters are re-applied at the earliest point their
  variables are bound, exactly once each;
* every chain node's label scan travels with it, so each node's
  constraint is enforced exactly once in any order;
* **cyclic** chains (any ``ExpandInto`` closing a cycle) are left in
  syntax order: the multiway-intersect fastpath is worst-case optimal on
  cyclic patterns and the pure-count tiers fuse the syntax shape — a
  reorder that breaks that pattern-match trades a fused closed-form
  count for materialized frontiers and loses even when its modelled row
  volume is far lower.

Rewrites preserve semantics (same rows, possibly different row order) and
identity discipline: shared subtrees are memoized by object id so DAG
sharing (``Optional``/``Exists`` rhs embedding the lhs) survives, and a
chain whose chosen order equals syntax order returns the ORIGINAL object,
keeping plan-cache keys and CSE behaviour byte-stable.

``TPU_CYPHER_OPT`` gates everything: ``syntax`` disables reordering,
``auto`` (default) applies a reorder only when its modelled cost beats
syntax order by the ``TPU_CYPHER_OPT_MARGIN`` hysteresis, ``force``
always applies the model's choice.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from ..ir.expr import walk_vars
from ..logical import ops as L
from ..obs import trace as _obs_trace
from ..utils.config import OPT_DP_MAX_RELS, OPT_MARGIN, OPT_MODE
from .cost import CostModel


@dataclass
class _Rel:
    """One movable relationship of a chain (original op attrs verbatim)."""

    rel: str
    rel_type: object
    source: str
    target: str
    direction: str


@dataclass
class _Chain:
    base: L.LogicalOperator  # operator below the chain (not part of it)
    base_scan: Optional[L.NodeScan]  # set when base is a free anchor scan
    rels: List[_Rel]  # bottom-up (syntax) order
    filters: List[Tuple[L.Filter, FrozenSet[str]]]  # (op, var names), bottom-up
    node_types: Dict[str, object]  # node field -> CypherType (labelled scans)
    scans: Dict[str, L.NodeScan]  # node field -> original scan op
    qgn: str


def _is_free_scan(op) -> bool:
    return (
        isinstance(op, L.NodeScan)
        and isinstance(op.in_op, L.Start)
        and not op.in_op.input_fields
    )


def _extract_chain(head: L.LogicalOperator) -> Optional[_Chain]:
    """Walk down from a topmost Expand/ExpandInto collecting the movable
    chain; None when the shape is not one this pass understands."""
    rels: List[_Rel] = []
    filters: List[Tuple[L.Filter, FrozenSet[str]]] = []
    node_types: Dict[str, object] = {}
    scans: Dict[str, L.NodeScan] = {}
    cur = head
    while True:
        if isinstance(cur, L.Expand):
            if cur.direction not in (">", "-") or not _is_free_scan(cur.rhs):
                return None
            scan = cur.rhs
            node_types[scan.fld] = scan.node_type
            scans[scan.fld] = scan
            rels.append(
                _Rel(cur.rel, cur.rel_type, cur.source, cur.target, cur.direction)
            )
            cur = cur.lhs
        elif isinstance(cur, L.ExpandInto):
            # cycle closure: leave the whole chain in syntax order — the
            # WCOJ fastpath and the fused count tiers already key on this
            # shape and beat any materialized reorder (module docstring)
            return None
        elif isinstance(cur, L.Filter):
            names = frozenset(v.name for v in walk_vars(cur.predicate))
            filters.append((cur, names))
            cur = cur.in_op
        else:
            break
    if len(rels) < 2:
        return None
    names = [r.rel for r in rels]
    if len(set(names)) != len(names):  # repeated rel var: not a plain chain
        return None
    rels.reverse()
    filters.reverse()
    base_scan = None
    base = cur
    if _is_free_scan(cur):
        base_scan = cur
        node_types[cur.fld] = cur.node_type
        scans[cur.fld] = cur
        base = cur.in_op  # the bare Start
    try:
        qgn = cur.graph_name
    except AssertionError:
        return None
    return _Chain(base, base_scan, rels, filters, node_types, scans, qgn)


# ---------------------------------------------------------------------------
# search
# ---------------------------------------------------------------------------


def _labels_of(chain: _Chain, node: str) -> Tuple[str, ...]:
    t = chain.node_types.get(node)
    labels = getattr(t, "labels", None) if t is not None else None
    return tuple(sorted(labels)) if labels else ()


def _types_of(rel: _Rel) -> Tuple[str, ...]:
    types = getattr(rel.rel_type, "types", None)
    return tuple(sorted(types)) if types else ()


class _Search:
    """Shared step/filter pricing for DP, greedy, and the syntax-order
    baseline so every candidate is scored by the identical model."""

    def __init__(self, chain: _Chain, model: CostModel):
        self.chain = chain
        self.model = model
        # filters keyed by index so re-application stays exactly-once
        self.filter_vars = [vs for _, vs in chain.filters]

    def start_state(self, anchor: Optional[str], bound0: FrozenSet[str]):
        """(bound names, est rows, cost, applied-filter indexes) after the
        anchor scan (or the opaque base)."""
        if anchor is not None:
            est, cost = self.model.scan(_labels_of(self.chain, anchor))
            bound = frozenset([anchor])
        else:
            # opaque base: its cost is a shared constant across orders and
            # its cardinality unknowable here; a neutral prior keeps the
            # relative ranking of the movable suffix meaningful
            est = float(max(self.model.stats.node_count(()), 1))
            cost = 0.0
            bound = bound0
        return self._apply_filters(bound, est, cost, frozenset())

    def step(self, bound, est, cost, applied, rel: _Rel):
        """Price one relationship given the bound set; None when the rel
        does not touch the bound set (disconnected transition)."""
        src_b, dst_b = rel.source in bound, rel.target in bound
        types = _types_of(rel)
        if src_b and dst_b:
            est, dc = self.model.expand_into(est, types)
            cost += dc
            new_bound = bound | {rel.rel}
        elif src_b or dst_b:
            new_node = rel.target if src_b else rel.source
            reverse = dst_b
            est, dc = self.model.expand(
                est, types, reverse, _labels_of(self.chain, new_node)
            )
            if rel.direction == "-":  # both orientations traversed
                est *= 2.0
            cost += dc
            new_bound = bound | {rel.rel, new_node}
        else:
            return None
        return self._apply_filters(new_bound, est, cost, applied)

    def _apply_filters(self, bound, est, cost, applied):
        for i, vs in enumerate(self.filter_vars):
            if i not in applied and vs <= bound:
                est, dc = self.model.filter(est)
                cost += dc
                applied = applied | {i}
        return bound, est, cost, applied

    # -- candidate orders -------------------------------------------------

    def price_order(self, anchor, bound0, order: List[_Rel]) -> Optional[float]:
        bound, est, cost, applied = self.start_state(anchor, bound0)
        for rel in order:
            got = self.step(bound, est, cost, applied, rel)
            if got is None:
                return None
            bound, est, cost, applied = got
        return cost

    def best_order(self, anchors: List[Optional[str]], bound0: FrozenSet[str]):
        """Cheapest (anchor, rel order, cost) over all start choices; DP
        when the pattern is small enough, greedy otherwise."""
        best = None
        exact = len(self.chain.rels) <= int(OPT_DP_MAX_RELS.get())
        for anchor in anchors:
            got = self._dp(anchor, bound0) if exact else self._greedy(anchor, bound0)
            if got is not None and (best is None or got[2] < best[2]):
                best = got
        return best

    def _dp(self, anchor, bound0):
        rels = self.chain.rels
        init = self.start_state(anchor, bound0)
        # solved-rel index subset -> (cost, est, bound, applied, order)
        frontier: Dict[FrozenSet[int], tuple] = {
            frozenset(): (init[2], init[1], init[0], init[3], [])
        }
        for _ in range(len(rels)):
            nxt: Dict[FrozenSet[int], tuple] = {}
            for solved, (cost, est, bound, applied, order) in frontier.items():
                for i, rel in enumerate(rels):
                    if i in solved:
                        continue
                    got = self.step(bound, est, cost, applied, rel)
                    if got is None:
                        continue
                    b2, e2, c2, a2 = got
                    key = solved | {i}
                    old = nxt.get(key)
                    if old is None or c2 < old[0]:
                        nxt[key] = (c2, e2, b2, a2, order + [rel])
            if not nxt:  # chain not connected from this anchor
                return None
            frontier = nxt
        full = frontier.get(frozenset(range(len(rels))))
        if full is None:
            return None
        return anchor, full[4], full[0]

    def _greedy(self, anchor, bound0):
        bound, est, cost, applied = self.start_state(anchor, bound0)
        remaining = list(self.chain.rels)
        order: List[_Rel] = []
        while remaining:
            best = None
            for rel in remaining:
                got = self.step(bound, est, cost, applied, rel)
                if got is None:
                    continue
                if best is None or got[2] < best[1][2]:
                    best = (rel, got)
            if best is None:
                return None
            rel, (bound, est, cost, applied) = best
            order.append(rel)
            remaining.remove(rel)
        return anchor, order, cost


# ---------------------------------------------------------------------------
# rebuild
# ---------------------------------------------------------------------------


def _rebuild(chain: _Chain, base: L.LogicalOperator, anchor, order: List[_Rel]):
    """Reassemble the chain in the chosen order on the (already
    transformed) base, reusing original scan objects per node."""

    def scan_for(node: str) -> L.NodeScan:
        got = chain.scans.get(node)
        if got is not None:
            return got
        return L.NodeScan(L.Start(chain.qgn, ()), node, chain.node_types[node])

    if anchor is not None:
        plan: L.LogicalOperator = scan_for(anchor)
        bound: Set[str] = {anchor}
    else:
        plan = base
        bound = {n for n, _ in base.fields}
    applied: Set[int] = set()

    def place_filters():
        nonlocal plan
        for i, (f, vs) in enumerate(chain.filters):
            if i not in applied and vs <= bound:
                plan = L.Filter(plan, f.predicate)
                applied.add(i)

    place_filters()
    for rel in order:
        src_b, dst_b = rel.source in bound, rel.target in bound
        if src_b and dst_b:
            plan = L.ExpandInto(
                plan, rel.source, rel.rel, rel.rel_type, rel.target, rel.direction
            )
            bound.add(rel.rel)
        else:
            new_node = rel.target if src_b else rel.source
            plan = L.Expand(
                plan,
                scan_for(new_node),
                rel.source,
                rel.rel,
                rel.rel_type,
                rel.target,
                rel.direction,
            )
            bound.update((rel.rel, new_node))
        place_filters()
    # any unplaced filter (vars outside the chain scope) keeps its spot on top
    for i, (f, _) in enumerate(chain.filters):
        if i not in applied:
            plan = L.Filter(plan, f.predicate)
    return plan


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------


def _reorder_chain(head, chain: _Chain, ctx, transform) -> Optional[L.LogicalOperator]:
    graph = ctx.resolve_graph(chain.qgn)
    model = CostModel(graph, ctx)
    search = _Search(chain, model)

    if chain.base_scan is not None:
        # free anchor: every typed chain node is a candidate start
        chain_nodes = set(chain.node_types)
        anchors: List[Optional[str]] = sorted(chain_nodes)
        bound0: FrozenSet[str] = frozenset()
        syntax_anchor: Optional[str] = chain.base_scan.fld
    else:
        anchors = [None]
        bound0 = frozenset(n for n, _ in chain.base.fields)
        syntax_anchor = None

    syntax_cost = search.price_order(syntax_anchor, bound0, chain.rels)
    best = search.best_order(anchors, bound0)
    if best is None or syntax_cost is None:
        return None
    anchor, order, best_cost = best

    mode = OPT_MODE.get().strip().lower()
    unchanged = anchor == syntax_anchor and [r.rel for r in order] == [
        r.rel for r in chain.rels
    ]
    if unchanged:
        chosen = "syntax"
    elif mode == "force":
        chosen = "model"
    else:  # auto: hysteresis — only clearly-cheaper plans replace syntax order
        chosen = (
            "model" if best_cost < float(OPT_MARGIN.get()) * syntax_cost else "syntax"
        )
    _obs_trace.note(
        "join_order",
        {
            "rels": len(chain.rels),
            "chosen": chosen,
            "syntax_cost": round(float(syntax_cost), 1),
            "model_cost": round(float(best_cost), 1),
            "anchor": anchor or "(bound)",
            "factorized_steps": int(model.factorized_steps),
        },
    )
    if chosen == "syntax":
        return None
    new_base = transform(chain.base) if chain.base_scan is None else chain.base
    return _rebuild(chain, new_base, anchor, order)


def maybe_reorder(plan: L.LogicalOperator, ctx) -> L.LogicalOperator:
    """Rewrite every reorderable pattern chain in ``plan`` to its modelled
    cheapest join order. Identity-preserving: untouched subtrees (and
    chains whose best order IS syntax order) come back as the same
    objects. Never raises — any model failure returns the plan as given
    (device faults re-raise typed for the session ladder)."""
    if OPT_MODE.get().strip().lower() == "syntax":
        return plan
    memo: Dict[int, L.LogicalOperator] = {}
    # chain ops under a cycle-closing ExpandInto: the whole cyclic pattern
    # stays in syntax order (see module docstring), including the acyclic
    # prefix the generic recursion would otherwise visit on its own
    pinned: Set[int] = set()

    def pin_chain(op) -> None:
        cur = op
        while isinstance(cur, (L.Expand, L.ExpandInto, L.Filter)):
            pinned.add(id(cur))
            cur = cur.lhs if isinstance(cur, L.Expand) else cur.in_op

    def transform(op: L.LogicalOperator) -> L.LogicalOperator:
        got = memo.get(id(op))
        if got is not None:
            return got
        new = None
        if isinstance(op, L.ExpandInto):
            pin_chain(op)
        elif isinstance(op, L.Expand) and id(op) not in pinned:
            chain = _extract_chain(op)
            if chain is not None:
                new = _reorder_chain(op, chain, ctx, transform)
        if new is None:
            kids = op.children
            new_kids = tuple(
                transform(c) if isinstance(c, L.LogicalOperator) else c
                for c in kids
            )
            new = (
                op
                if all(a is b for a, b in zip(kids, new_kids))
                else op.with_new_children(new_kids)
            )
        memo[id(op)] = new
        return new

    try:
        return transform(plan)
    except Exception as exc:
        from ..errors import reraise_if_device

        reraise_if_device(exc, site="optimizer.joinorder")
        return plan

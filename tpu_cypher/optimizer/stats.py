"""Per-graph statistics: the cardinalities the cost model composes.

Collected lazily from the graph's own scan machinery and cached ON the
graph object (the ``GraphIndex.of`` idiom — graphs are immutable here, so
object identity IS the statistics version; a rebuilt graph gets fresh
statistics). Three families:

* **label cardinalities** — logical row counts of the canonical node scan
  per label set (and the unrestricted scan, which defines the node space);
* **relationship-type cardinalities** — logical row counts of the
  canonical relationship scan per type set;
* **degree distributions** — per (type set, orientation): max degree and a
  log2-bucket out-degree histogram, computed on the HOST from the same
  endpoint arrays every CSR build starts from
  (``GraphIndex._edge_endpoints``), so no extra device sync is paid.

On the host-oracle backend (no ``GraphIndex``) the degree family degrades
to the average-degree estimate ``rels / nodes``; cardinalities work on
every backend because they only read ``table.size``.
"""

from __future__ import annotations

import hashlib
from typing import Dict, Optional, Tuple

from ..api import types as T

# scan variable used for statistics-only scans; never escapes this module
_STATS_VAR = "__opt_stats"


class GraphStatistics:
    """Lazily populated per-graph statistics. ``of`` caches one instance
    per graph object; every accessor memoizes per key."""

    @staticmethod
    def of(graph, ctx) -> "GraphStatistics":
        got = getattr(graph, "_tpu_cypher_opt_stats", None)
        if got is None:
            got = GraphStatistics(graph)
            try:
                graph._tpu_cypher_opt_stats = got
            except AttributeError:  # exotic graph impl without __dict__
                pass
        got._ctx = ctx  # scans only need *a* runtime context; any works
        return got

    def __init__(self, graph):
        self.graph = graph
        self._ctx = None
        self._node_counts: Dict[Tuple[str, ...], int] = {}
        self._rel_counts: Dict[Tuple[str, ...], int] = {}
        # (types_key, reverse) -> (max_degree, log2-bucket histogram)
        self._degrees: Dict[Tuple[Tuple[str, ...], bool], Tuple[int, Tuple[int, ...]]] = {}
        self._fingerprint: Optional[str] = None

    # -- cardinalities ---------------------------------------------------

    @staticmethod
    def labels_key(labels) -> Tuple[str, ...]:
        return tuple(sorted(labels)) if labels else ()

    def node_count(self, labels=()) -> int:
        """Logical row count of the canonical node scan for a label set."""
        key = self.labels_key(labels)
        got = self._node_counts.get(key)
        if got is None:
            op = self.graph.scan_operator(
                _STATS_VAR, T.CTNodeType(frozenset(key)), self._ctx
            )
            got = self._node_counts[key] = int(op.table.size)
        return got

    def rel_count(self, types=()) -> int:
        """Logical row count of the canonical relationship scan for a
        type set."""
        key = self.labels_key(types)
        got = self._rel_counts.get(key)
        if got is None:
            op = self.graph.scan_operator(
                _STATS_VAR, T.CTRelationshipType(frozenset(key)), self._ctx
            )
            got = self._rel_counts[key] = int(op.table.size)
        return got

    def label_selectivity(self, labels=()) -> float:
        """Fraction of all nodes carrying the label set (1.0 for the
        unrestricted set; an empty graph reads as fully selective)."""
        if not labels:
            return 1.0
        total = self.node_count(())
        if total <= 0:
            return 1.0
        return min(self.node_count(labels) / total, 1.0)

    # -- degree distributions --------------------------------------------

    def avg_degree(self, types=(), reverse: bool = False) -> float:
        """Mean out-degree (``reverse`` = in-degree) over ALL nodes for a
        type set — the uniform-fanout expand estimate."""
        n = self.node_count(())
        return self.rel_count(types) / max(n, 1)

    def degree_stats(
        self, types=(), reverse: bool = False
    ) -> Tuple[int, Tuple[int, ...]]:
        """(max_degree, log2-bucket histogram) for one orientation.
        Bucket ``i`` counts nodes with degree in ``[2^i, 2^(i+1))`` (bucket
        0 holds degree-1 nodes; degree-0 nodes are uncounted). Degrades to
        an average-degree singleton on backends without a ``GraphIndex``."""
        key = (self.labels_key(types), bool(reverse))
        got = self._degrees.get(key)
        if got is not None:
            return got
        got = self._degree_stats_host(key[0], key[1])
        if got is None:
            import math

            avg = self.avg_degree(types, reverse)
            est_max = int(math.ceil(avg)) * 4 + 1
            got = (est_max, (self.node_count(()),) if avg > 0 else ())
        self._degrees[key] = got
        return got

    def max_degree(self, types=(), reverse: bool = False) -> int:
        return self.degree_stats(types, reverse)[0]

    def _degree_stats_host(self, types_key, reverse: bool):
        """Exact degree distribution from the host endpoint arrays the CSR
        build resolves anyway; None when this graph has no GraphIndex
        (host-oracle backend)."""
        import numpy as np

        from ..backend.tpu.graph_index import GraphIndex
        from ..errors import reraise_if_device

        try:
            gi = GraphIndex.of(self.graph)
            gi.node_ids(self._ctx)
            s, d, n = gi._edge_endpoints(types_key, self._ctx)
        except Exception as exc:
            reraise_if_device(exc, site="optimizer.stats")
            return None
        ends = d if reverse else s
        if len(ends) == 0:
            return 0, ()
        degs = np.bincount(ends, minlength=n)
        degs = degs[degs > 0]
        max_deg = int(degs.max()) if degs.size else 0
        if max_deg <= 0:
            return 0, ()
        hist = np.bincount(
            np.floor(np.log2(degs)).astype(np.int64),
            minlength=int(np.floor(np.log2(max_deg))) + 1,
        )
        return max_deg, tuple(int(x) for x in hist)

    # -- identity ---------------------------------------------------------

    def fingerprint(self) -> str:
        """Stable per-graph key for persisted calibration: a digest of the
        schema's label/type cardinalities. Computed from counts already
        gathered plus the unrestricted scans, so two processes ingesting
        the same graph agree on the key."""
        if self._fingerprint is None:
            parts = [f"n={self.node_count(())}", f"r={self.rel_count(())}"]
            schema = getattr(self.graph, "schema", None)
            if schema is not None:
                for lbl in sorted(getattr(schema, "labels", ()) or ()):
                    parts.append(f"l:{lbl}={self.node_count((lbl,))}")
                for typ in sorted(
                    getattr(schema, "relationship_types", ()) or ()
                ):
                    parts.append(f"t:{typ}={self.rel_count((typ,))}")
            digest = hashlib.sha256("|".join(parts).encode()).hexdigest()
            self._fingerprint = digest[:16]
        return self._fingerprint


def seed_statistics(
    graph,
    *,
    node_counts: Dict[Tuple[str, ...], int],
    rel_counts: Dict[Tuple[str, ...], int],
    fingerprint: str,
) -> GraphStatistics:
    """Stamp pre-computed statistics onto a graph object — the incremental
    versioning path for mutation snapshots (``storage/delta.py``). The
    mutable store maintains total and single-label/type cardinalities
    per write batch and chains the fingerprint
    (``advance_fingerprint``), so every snapshot carries exact counts and
    a batch-unique fingerprint with NO rescan; compound label-set counts
    and degree families stay lazy and compute against the (immutable)
    snapshot on demand. Because ``of`` caches on the graph attribute this
    writes, seeded statistics win over lazy collection."""
    st = GraphStatistics(graph)
    st._node_counts.update(node_counts)
    st._rel_counts.update(rel_counts)
    st._fingerprint = fingerprint
    try:
        graph._tpu_cypher_opt_stats = st
    except AttributeError:  # pragma: no cover - exotic graph without __dict__
        pass
    return st

"""Cost-based, statistics-fed adaptive query optimizer.

One padded-lattice cost model (``cost.py``) over per-graph statistics
(``stats.py``), searched by a bounded join-order enumerator
(``joinorder.py``) and sharpened by measured query profiles
(``feedback.py``). Replaces the engine's four ad-hoc routing heuristics
— WCOJ row threshold, serve admission bytes, broadcast-join window, and
syntax-driven join order — with one estimator; each old env knob remains
as a hand override.
"""

from .cost import (
    CostModel,
    broadcast_build_limit,
    estimate_query_cost_bytes,
    padded_rows,
    prefer_wcoj,
    wcoj_threshold,
)
from .feedback import Calibration, get as get_calibration, observe
from .joinorder import maybe_reorder
from .stats import GraphStatistics

__all__ = [
    "Calibration",
    "CostModel",
    "GraphStatistics",
    "broadcast_build_limit",
    "estimate_query_cost_bytes",
    "get_calibration",
    "maybe_reorder",
    "observe",
    "padded_rows",
    "prefer_wcoj",
    "wcoj_threshold",
]

"""Adaptive feedback: fold measured query profiles back into the model.

Every successfully executed traced query already stamps, per operator
span, the wall seconds and the true-vs-padded row pair (``obs/trace.py``).
This module reduces those spans to per-operator-class EMAs of

* **seconds per padded kilorow** — the empirical unit cost the
  :class:`~tpu_cypher.optimizer.cost.CostModel` weights with, and the
  ratio behind the measured WCOJ threshold;
* **occupancy** (true rows / padded rows) — how much of the padded work
  was real, surfaced in diagnostics.

Calibrations are **per graph**, keyed by the statistics fingerprint, and
persisted as one small JSON beside the compile cache
(``<TPU_CYPHER_COMPILE_CACHE_DIR>/optimizer_calibration.json``) so a
restarted process resumes with its measured weights; without a persistent
cache dir they are process-local. Everything here is advisory: any
failure degrades to the uncalibrated model (weights 1.0) and never takes
down the query that produced the profile.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, Optional

from ..utils.config import OPT_FEEDBACK

# EMA smoothing: one observation moves the estimate 20% of the way
_ALPHA = 0.2
# operator classes whose per-krow cost is compared against the multiway
# intersect tier to place the measured WCOJ threshold
_BINARY_EXPAND_CLASSES = ("CsrExpandOp", "CsrExpandIntoOp")
_WCOJ_CLASS = "MultiwayIntersectOp"
_PERSIST_NAME = "optimizer_calibration.json"

_LOCK = threading.Lock()
_STORE: Dict[str, "Calibration"] = {}
_LOADED_DIRS: set = set()


class Calibration:
    """Per-graph learned unit costs. All reads are safe with zero samples
    (they return the neutral 1.0)."""

    def __init__(self):
        # op class -> [ema seconds-per-padded-kilorow, samples]
        self.sec_per_krow: Dict[str, list] = {}
        # op class -> [ema true/padded occupancy, samples]
        self.occ: Dict[str, list] = {}

    # -- updates ---------------------------------------------------------

    def observe_span(
        self, op_class: str, seconds: float, rows_padded: int, rows_true: int
    ) -> None:
        if seconds <= 0.0 or rows_padded <= 0:
            return
        krow = rows_padded / 1000.0
        self._ema(self.sec_per_krow, op_class, seconds / krow)
        self._ema(self.occ, op_class, min(rows_true / rows_padded, 1.0))

    @staticmethod
    def _ema(table: Dict[str, list], key: str, value: float) -> None:
        got = table.get(key)
        if got is None:
            table[key] = [float(value), 1]
        else:
            got[0] += _ALPHA * (float(value) - got[0])
            got[1] += 1

    # -- reads -----------------------------------------------------------

    def samples(self) -> int:
        return sum(n for _, n in self.sec_per_krow.values())

    def unit_cost(self, op_class: str) -> Optional[float]:
        got = self.sec_per_krow.get(op_class)
        return got[0] if got else None

    def occupancy(self, op_class: str) -> Optional[float]:
        got = self.occ.get(op_class)
        return got[0] if got else None

    def weight(self, op_class: str) -> float:
        """Measured cost of one padded row of ``op_class`` relative to the
        mean over all measured classes; 1.0 until both sides have data.
        Clipped so a single noisy profile cannot invert plan ranking."""
        mine = self.unit_cost(op_class)
        if mine is None or not self.sec_per_krow:
            return 1.0
        mean = sum(v[0] for v in self.sec_per_krow.values()) / len(
            self.sec_per_krow
        )
        if mean <= 0.0:
            return 1.0
        return max(0.25, min(4.0, mine / mean))

    def wcoj_scale(self) -> float:
        """Multiplier on the declared WCOJ row threshold: the measured
        per-padded-krow cost of the intersect tier over the binary expand
        tier. Intersect measured slower -> threshold rises (route later);
        faster -> drops (route earlier). 1.0 until both tiers have
        samples, which makes the uncalibrated decision identical to the
        hand-tuned ``TPU_CYPHER_WCOJ_MIN_ROWS`` default."""
        wcoj = self.unit_cost(_WCOJ_CLASS)
        bins = [
            self.unit_cost(c)
            for c in _BINARY_EXPAND_CLASSES
            if self.unit_cost(c) is not None
        ]
        if wcoj is None or not bins:
            return 1.0
        binary = sum(bins) / len(bins)
        if binary <= 0.0:
            return 1.0
        return max(0.25, min(8.0, wcoj / binary))

    # -- (de)serialization ----------------------------------------------

    def to_json(self) -> dict:
        return {"sec_per_krow": self.sec_per_krow, "occ": self.occ}

    @staticmethod
    def from_json(data: dict) -> "Calibration":
        cal = Calibration()
        for field in ("sec_per_krow", "occ"):
            table = getattr(cal, field)
            for k, v in (data.get(field) or {}).items():
                if (
                    isinstance(v, list)
                    and len(v) == 2
                    and isinstance(v[0], (int, float))
                ):
                    table[str(k)] = [float(v[0]), int(v[1])]
        return cal


# ---------------------------------------------------------------------------
# per-graph store + persistence
# ---------------------------------------------------------------------------


def _persist_path() -> Optional[str]:
    from ..backend.tpu import bucketing

    cache_dir = bucketing.persistent_cache_dir()
    if not cache_dir:
        return None
    return os.path.join(cache_dir, _PERSIST_NAME)


def _load_dir(path: str) -> None:
    """Merge the persisted calibration file into the in-memory store once
    per directory; in-memory entries win (they are newer)."""
    if path in _LOADED_DIRS:
        return
    _LOADED_DIRS.add(path)
    try:
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
        for fp, entry in (data or {}).items():
            if fp not in _STORE and isinstance(entry, dict):
                _STORE[fp] = Calibration.from_json(entry)
    except (OSError, ValueError):  # fault-ok: missing/corrupt calibration file just means an uncalibrated start
        pass


def _save(path: str) -> None:
    tmp = path + ".tmp"
    payload = {fp: cal.to_json() for fp, cal in _STORE.items()}
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(payload, f, sort_keys=True)
    os.replace(tmp, path)


def _fingerprint(graph, ctx) -> str:
    from .stats import GraphStatistics

    try:
        return GraphStatistics.of(graph, ctx).fingerprint()
    except Exception as exc:
        from ..errors import reraise_if_device

        reraise_if_device(exc, site="optimizer.feedback")
        return "default"


def get(graph, ctx) -> Calibration:
    """The calibration for this graph (by statistics fingerprint),
    loading any persisted state on first touch."""
    fp = _fingerprint(graph, ctx)
    with _LOCK:
        path = _persist_path()
        if path:
            _load_dir(path)
        cal = _STORE.get(fp)
        if cal is None:
            cal = _STORE[fp] = Calibration()
        return cal


def observe(trace, graph, ctx) -> None:
    """Fold one finished query trace into the graph's calibration.
    Called from the session's success path; must never raise into it."""
    if OPT_FEEDBACK.get().strip().lower() != "on" or trace is None:
        return
    try:
        spans = trace.spans()
    except Exception:  # fault-ok: a malformed trace only costs this one calibration update
        return
    updates = []
    for sp in spans:
        if getattr(sp, "kind", None) != "operator":
            continue
        padded = int(sp.attrs.get("rows_padded", 0) or 0)
        true = int(sp.attrs.get("rows_true", 0) or 0)
        if padded <= 0 or sp.seconds <= 0.0:
            continue
        updates.append((sp.name, float(sp.seconds), padded, true))
    if not updates:
        return
    try:
        cal = get(graph, ctx)
        with _LOCK:
            for name, seconds, padded, true in updates:
                cal.observe_span(name, seconds, padded, true)
            path = _persist_path()
            if path:
                _save(path)
    except Exception as exc:
        from ..errors import reraise_if_device

        reraise_if_device(exc, site="optimizer.feedback")


def reset_for_tests() -> None:
    """Drop all in-memory calibration state (tests only)."""
    with _LOCK:
        _STORE.clear()
        _LOADED_DIRS.clear()

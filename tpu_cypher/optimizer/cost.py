"""Padded-lattice cost model: one estimator behind every routing choice.

Costs are computed in **padded** rows, not true rows: every operator's
device work is a function of its bucketed shapes (the shape-facts artifact
exports the per-operator formulas, and ``analysis.shapes.predict_padded``
is pinned equal to the runtime lattice), so composing ``round_size`` over
candidate plans prices exactly the work XLA will be asked to do — and
makes two plans with the same bucket sequence provably the same cost.

Mesh-awareness: with an active device mesh the unit of work is the
per-shard padded shape times the shard count, plus a cross-shard term for
operators that imply a shuffle/psum — this is the "mesh-aware plan
costing" item PR 13 left open.

The heuristics this module subsumes (each keeps its env knob as a
hand override, detected via ``ConfigOption.overridden``):

* ``wcoj.py`` routing — :func:`wcoj_threshold` / :func:`prefer_wcoj`
  replace the fixed ``TPU_CYPHER_WCOJ_MIN_ROWS`` comparison with a
  calibration-scaled threshold;
* ``serve/scheduler.estimate_cost_bytes`` — :func:`estimate_query_cost_bytes`
  prices admission from real cardinalities when statistics exist;
* ``parallel/shuffle.broadcast_join`` — :func:`broadcast_build_limit`
  extends the broadcast window past ``TPU_CYPHER_BROADCAST_LIMIT`` when
  the modelled replication cost still beats a hash repartition (it never
  *shrinks* the window below the declared limit);
* join-order search (``joinorder.py``) composes :class:`CostModel` steps
  instead of trusting syntax order;
* MXU tier gating — :func:`mxu_dense_node_cap` (modelled from the HBM
  budget when one is set) and :func:`mxu_tiled_node_cap` replace the
  fixed node caps in ``graph_index.dense_adj`` / ``expand_op``;
* Pallas eligibility — :func:`pallas_cap` derives each kernel's size cap
  from its VMEM working-set budget instead of a per-module constant.
"""

from __future__ import annotations

from typing import Optional, Tuple

from ..utils.config import (
    BROADCAST_LIMIT,
    MEM_BUDGET,
    MXU_DENSE_MAX,
    MXU_TILED_MAX,
    PALLAS_MAX_BUILD,
    PALLAS_MAX_FRONTIER,
    PALLAS_MAX_GROUPS,
    PALLAS_MAX_KEYS,
    PALLAS_MAX_NODES,
    WCOJ_MIN_ROWS,
)
from .stats import GraphStatistics

# generic selectivity of one residual filter predicate (no value-level
# statistics yet; only relative plan ranking needs it)
FILTER_SELECTIVITY = 0.75

# cross-shard traffic is priced at a multiple of local row work: a shuffle
# moves rows over ICI, which the scaling bench shows is worth a few local
# touches per row
SHUFFLE_WEIGHT = 4.0

# calibration-scaled WCOJ threshold is clipped to this window so one noisy
# profile can never push routing to an always/never extreme
_WCOJ_CLIP = (512, 65536)


def padded_rows(n) -> int:
    """True row count -> padded row count on the runtime lattice. Uses the
    pure shape-facts predictor (pinned equal to ``bucketing.round_size``
    by the agreement test) rather than ``round_size`` itself, because the
    runtime function stamps every call's true/padded pair on the enclosing
    trace span — estimator what-ifs must not pollute measured profiles."""
    from ..analysis.shapes import predict_padded
    from ..backend.tpu import bucketing

    return int(predict_padded(max(int(n), 0), bucketing.mode()))


def _mesh_size() -> int:
    try:
        from ..parallel.mesh import mesh_size

        return int(mesh_size())
    except Exception as exc:
        from ..errors import reraise_if_device

        reraise_if_device(exc, site="optimizer.cost")
        return 1


class CostModel:
    """Prices logical plan steps over one graph's statistics.

    Every step method returns ``(est_rows_out, cost)`` where ``cost`` is
    abstract padded-row work (comparable only within one model instance).
    Calibration factors — measured seconds per padded kilorow per operator
    class — skew the weights once feedback has samples; with no samples
    every weight is 1.0 and the model is purely structural.
    """

    def __init__(self, graph, ctx, calibration=None):
        self.stats = GraphStatistics.of(graph, ctx)
        if calibration is None:
            from . import feedback

            calibration = feedback.get(graph, ctx)
        self.cal = calibration
        self.nsh = _mesh_size()
        # plan steps this model priced at the factorized (run-compressed)
        # lane extent instead of the flat row product — exported through
        # joinorder's ``join_order`` span note for plan introspection
        self.factorized_steps = 0

    # -- mesh-aware work units -------------------------------------------

    def work(self, n_rows) -> float:
        """Device work for touching ``n_rows`` once: the per-shard padded
        shape times the shard count (sharding rounds per shard, so small
        relations on big meshes still pay the bucket floor per shard)."""
        if self.nsh <= 1:
            return float(padded_rows(n_rows))
        per = padded_rows((int(n_rows) + self.nsh - 1) // self.nsh)
        return float(per * self.nsh)

    def shuffle(self, n_rows) -> float:
        """Cross-shard movement term; zero without a mesh."""
        if self.nsh <= 1:
            return 0.0
        return SHUFFLE_WEIGHT * float(padded_rows(n_rows))

    def _w(self, op_class: str) -> float:
        return float(self.cal.weight(op_class)) if self.cal is not None else 1.0

    # -- plan steps ------------------------------------------------------

    def scan(self, labels=()) -> Tuple[float, float]:
        est = float(self.stats.node_count(labels))
        return est, self._w("scan") * self.work(est)

    def expand(
        self, est_in: float, types=(), reverse: bool = False, target_labels=()
    ) -> Tuple[float, float]:
        """Expand one hop from ``est_in`` bound rows: output is fanout
        times label selectivity of the far endpoint; cost touches both the
        input frontier and the (padded) output."""
        fanout = self.stats.avg_degree(types, reverse)
        est_out = est_in * fanout * self.stats.label_selectivity(target_labels)
        if prefer_factorized(est_out, 9):
            # factorized materialize touches the lane (prefix) extent,
            # never the flat product: device work is the input frontier
            # plus the run-bound gather over the same lanes
            self.factorized_steps += 1
            cost = self._w("expand") * (2.0 * self.work(est_in))
            return est_out, cost + self.shuffle(est_in)
        cost = self._w("expand") * (self.work(est_in) + self.work(est_out))
        return est_out, cost + self.shuffle(est_out)

    def expand_into(self, est_in: float, types=()) -> Tuple[float, float]:
        """Close an edge between two already-bound endpoints: selectivity
        is the edge probability ``rels / nodes²`` applied to the candidate
        pairs already in the row set."""
        n = max(self.stats.node_count(()), 1)
        sel = self.stats.rel_count(types) / float(n * n)
        est_out = est_in * min(sel, 1.0)
        cost = self._w("expand_into") * (self.work(est_in) + self.work(est_out))
        return est_out, cost

    def filter(self, est_in: float) -> Tuple[float, float]:
        est_out = est_in * FILTER_SELECTIVITY
        return est_out, self._w("filter") * self.work(est_in)


# -- WCOJ routing (subsumes the TPU_CYPHER_WCOJ_MIN_ROWS constant) --------


def wcoj_threshold(graph, ctx) -> int:
    """Binary-expand row-count estimate above which the multiway
    intersect (WCOJ) tier is routed. When the operator pinned
    ``TPU_CYPHER_WCOJ_MIN_ROWS`` the pin wins verbatim; otherwise the
    declared default is scaled by the measured seconds-per-padded-kilorow
    ratio of the intersect tier vs. the binary tier on THIS graph —
    a relatively slow intersect kernel raises the bar, a fast one lowers
    it. With no profile samples the scale is 1.0, i.e. exactly the
    hand-tuned default."""
    if WCOJ_MIN_ROWS.overridden:
        return int(WCOJ_MIN_ROWS.get())
    base = int(WCOJ_MIN_ROWS.default)
    scale = 1.0
    try:
        from . import feedback

        cal = feedback.get(graph, ctx)
        if cal is not None:
            scale = cal.wcoj_scale()
    except Exception as exc:
        from ..errors import reraise_if_device

        reraise_if_device(exc, site="optimizer.wcoj_threshold")
    lo, hi = _WCOJ_CLIP
    return max(lo, min(hi, int(base * scale)))


def prefer_wcoj(est_rows: int, graph, ctx) -> bool:
    """True when the modelled binary-expand blowup justifies the WCOJ
    tier for this graph."""
    return int(est_rows) > wcoj_threshold(graph, ctx)


# -- factorized materialize routing (backend/tpu/factorized.py) -----------


def factorized_rows(lanes: int) -> int:
    """Padded physical size of a factorized intermediate: the *lane*
    (prefix) extent on the runtime lattice — the sum of run counts, not
    the run-product. This is the quantity a factorized materialize pays
    admission for; the flat row product never exists on device."""
    return padded_rows(lanes)


def flat_materialize_busts(flat_rows, bytes_per_row: int) -> bool:
    """True when a flat materialize of ``flat_rows`` would bust the
    memory budget that ``bucketing.admit`` enforces — the same padded
    bytes-per-row arithmetic, run as a what-if instead of a raise. With
    no budget configured nothing busts (admission is wide open)."""
    from ..backend.tpu import bucketing

    budget = bucketing.memory_budget_bytes()
    if budget <= 0:
        return False
    eff = (int(flat_rows) + _mesh_size() - 1) // max(_mesh_size(), 1)
    return padded_rows(eff) * int(bytes_per_row) > budget


def factorized_routing_enabled() -> bool:
    """Cheap pre-gate for producers: can ``prefer_factorized`` possibly
    answer True without knowing the flat estimate? ``off`` → no; ``auto``
    with no admission budget → no (nothing busts a wide-open budget), so
    the default configuration pays ZERO per-expand work — no run-bounds
    program, no row-total sync — for the factorized route."""
    from ..utils.config import FACTORIZE

    mode = str(FACTORIZE.get()).strip().lower()
    if mode == "force":
        return True
    if mode == "off":
        return False
    from ..backend.tpu import bucketing

    return bucketing.memory_budget_bytes() > 0


def prefer_factorized(flat_rows, bytes_per_row: int) -> bool:
    """Route one materialize to the factorized (run-compressed) form.

    ``TPU_CYPHER_FACTORIZE=force`` always routes it, ``off`` never does;
    ``auto`` (default) chooses factorized exactly when the flat estimate
    busts the admission budget — the case that used to decline to the
    flat shadow tier or record an over-budget bench skip."""
    from ..utils.config import FACTORIZE

    mode = str(FACTORIZE.get()).strip().lower()
    if mode == "force":
        return True
    if mode == "off":
        return False
    return flat_materialize_busts(flat_rows, bytes_per_row)


# -- broadcast-vs-hash join window (parallel/shuffle.py) ------------------


def broadcast_build_limit(n_l: int, nsh: int) -> int:
    """Build-side row ceiling for a broadcast join given a probe side of
    ``n_l`` rows on ``nsh`` shards. Broadcasting replicates the build side
    to every shard (cost ≈ nsh × padded(build)); a hash repartition moves
    both sides once (cost ≈ padded(probe) + padded(build)); the crossover
    is ``padded(probe) / (nsh - 1)``. The returned limit only ever
    *extends* the declared ``TPU_CYPHER_BROADCAST_LIMIT`` window — and an
    operator pin of that knob is honoured verbatim."""
    limit = int(BROADCAST_LIMIT.get())
    if BROADCAST_LIMIT.overridden:
        return limit
    crossover = padded_rows(n_l) // max(int(nsh) - 1, 1)
    return max(limit, min(crossover, 1 << 20))


# -- MXU tier node caps (backend/tpu/graph_index.py, expand_op.py) --------


def mxu_dense_node_cap() -> int:
    """Node-count ceiling for the dense MXU adjacency tier
    (``GraphIndex.dense_adj``: one bf16[(Npad, Npad)] matrix per cached
    orientation). A ``TPU_CYPHER_MXU_DENSE_MAX`` pin wins verbatim.
    Otherwise, with an HBM budget set (``TPU_CYPHER_MEM_BUDGET``) the cap
    is the largest DENSE_BLOCK multiple whose padded matrix fits a quarter
    of the budget at 2 bytes/cell — the same byte-budget reasoning every
    materialize admission runs — clipped so one extreme budget cannot
    route absurd sizes; with no budget the declared default stands."""
    if MXU_DENSE_MAX.overridden:
        return int(MXU_DENSE_MAX.get())
    default = int(MXU_DENSE_MAX.default)
    budget = int(MEM_BUDGET.get())
    if budget <= 0:
        return default
    block = 256  # GraphIndex.DENSE_BLOCK
    # Npad^2 * 2 B (bf16) <= budget / 4, Npad a block multiple
    npad = int((budget / 8) ** 0.5) // block * block
    return max(block, min(npad, 1 << 16))


def mxu_tiled_node_cap() -> int:
    """Node-count ceiling for the TILED MXU close-count tier (row-block
    tiles, no full dense matrix — the cap bounds total FLOPs, not memory).
    ``TPU_CYPHER_MXU_TILED_MAX`` is honored whether pinned or defaulted;
    routing through the cost model keeps the gate a single decision
    point beside the dense cap it backstops."""
    return int(MXU_TILED_MAX.get())


# -- Pallas kernel eligibility caps (backend/tpu/pallas/*) ----------------

# per-kernel VMEM working-set model: (knob, budget bytes, bytes/element).
# The unpinned cap is budget // bytes_per_element — each knob's declared
# default equals that quotient, so routing through the model changes no
# behavior until an operator pins a knob or the budgets are retuned.
_PALLAS_BUDGETS = {
    "expand": (PALLAS_MAX_FRONTIER, 2 << 20, 8),  # cum + starts, int32
    "frontier": (PALLAS_MAX_NODES, 4 << 20, 4),  # degree vector, int32
    "intersect": (PALLAS_MAX_KEYS, 8 << 20, 8),  # two int32 key planes
    "join": (PALLAS_MAX_BUILD, 4 << 20, 32),  # 4 table vecs at LF 1/2
}


def pallas_cap(kernel: str) -> int:
    """Eligibility size cap for one Pallas kernel. A pinned
    ``TPU_CYPHER_PALLAS_MAX_*`` knob wins verbatim; otherwise the cap is
    the kernel's VMEM working-set budget divided by its bytes-per-element
    — the byte-budget decision the old per-module constants hand-encoded.
    ``aggregate`` caps GROUP BY cardinality (a compare-matrix shape, not a
    resident buffer) so it keeps its declared lane-tile default."""
    if kernel == "aggregate":
        return int(PALLAS_MAX_GROUPS.get())
    knob, vmem_bytes, bytes_per_elem = _PALLAS_BUDGETS[kernel]
    if knob.overridden:
        return int(knob.get())
    return vmem_bytes // bytes_per_elem


# -- serve admission (serve/scheduler.estimate_cost_bytes) ----------------


def estimate_query_cost_bytes(
    graph, query: str, *, fallback_rows: int, bytes_per_row: int
) -> int:
    """Admission-control byte estimate for one query text. When the graph
    already carries statistics (any prior optimized query), the hop count
    is priced through real average fanout instead of the legacy
    rows × (hops + 1) proxy; the result stays on the padded lattice so
    admission and execution agree on shapes."""
    hops = query.count("]")
    legacy = float(max(int(fallback_rows), 1) * (hops + 1))
    est = legacy
    stats: Optional[GraphStatistics] = getattr(
        graph, "_tpu_cypher_opt_stats", None
    )
    if stats is not None:
        fed = float(max(stats.node_count(()), 1))
        fanout = max(stats.avg_degree(()), 1.0)
        for _ in range(hops):
            fed = min(fed * fanout, 1e15)
        # additive over the legacy proxy: keeps the estimate strictly
        # monotone in pattern fan-out even on fanout<=1 graphs, which is
        # the ordering contract admission relies on
        est = legacy + fed
    return padded_rows(min(est, 1e15)) * int(bytes_per_row)

"""Recursive-descent Graph DDL parser.

Replaces the reference's fastparse grammar (``GraphDdlParser.scala:60-199``)
with a hand-written tokenizer + parser. Grammar surface (case-insensitive
keywords, ``--`` and ``//`` line comments, ``/* */`` block comments):

    ddl           := (setSchema | elementType | graphType | graph)*
    setSchema     := SET SCHEMA ident '.' ident ';'?
    elementType   := CREATE ELEMENT TYPE etd
    etd           := ident [EXTENDS ident (',' ident)*] [properties] [key]
    properties    := '(' [ident TYPE (',' ident TYPE)*] ')'
    key           := KEY ident '(' ident (',' ident)* ')'
    graphType     := CREATE GRAPH TYPE ident '(' (etd | nodeType | relType)^',' ')'
    nodeType      := '(' ident (',' ident)* ')'
    relType       := nodeType '-' '[' ident (',' ident)* ']' '->' nodeType
    graph         := CREATE GRAPH ident [OF ident] '(' graphStmt^',' ')'
    graphStmt     := relMapping | nodeMapping | etd | relType | nodeType
    nodeMapping   := nodeType (FROM viewId [propMapping])+
    propMapping   := '(' column AS prop (',' column AS prop)* ')'
    relMapping    := relType relToView+
    relToView     := FROM viewId alias [propMapping]
                     START NODES nodeToView END NODES nodeToView
    nodeToView    := nodeType FROM viewId alias JOIN ON joins
    joins         := qualCol '=' qualCol (AND qualCol '=' qualCol)*
    viewId        := escapedIdent ('.' escapedIdent){0,2}
"""

from __future__ import annotations

import re
from typing import List, Optional, Tuple

from ..api.type_parser import parse_cypher_type
from . import ddl_ast as A


class GraphDdlParseError(Exception):
    pass


_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+)
  | (?P<comment>--[^\n]*|//[^\n]*|/\*.*?\*/)
  | (?P<arrow>->)
  | (?P<sym>[()\[\],.;=\-])
  | (?P<escaped>`(?:[^`]|``)*`)
  | (?P<word>[A-Za-z_][A-Za-z0-9_]*)
  | (?P<qmark>\?)
    """,
    re.VERBOSE | re.DOTALL,
)

_KEYWORDS = {
    "CREATE", "ELEMENT", "EXTENDS", "KEY", "GRAPH", "TYPE", "OF", "AS",
    "FROM", "START", "END", "NODES", "JOIN", "ON", "AND", "SET", "SCHEMA",
}


class _Tok:
    __slots__ = ("kind", "text", "pos")

    def __init__(self, kind: str, text: str, pos: int):
        self.kind = kind  # 'word' | 'escaped' | 'sym' | 'arrow' | 'qmark'
        self.text = text
        self.pos = pos

    def __repr__(self):
        return f"{self.kind}:{self.text!r}"


def _tokenize(s: str) -> List[_Tok]:
    toks: List[_Tok] = []
    i = 0
    while i < len(s):
        m = _TOKEN_RE.match(s, i)
        if not m:
            raise GraphDdlParseError(f"Unexpected character {s[i]!r} at offset {i}")
        i = m.end()
        kind = m.lastgroup
        if kind in ("ws", "comment"):
            continue
        toks.append(_Tok(kind, m.group(), m.start()))
    return toks


class _Parser:
    def __init__(self, text: str):
        self.text = text
        self.toks = _tokenize(text)
        self.i = 0

    # -- token helpers -----------------------------------------------------

    def peek(self, ahead: int = 0) -> Optional[_Tok]:
        j = self.i + ahead
        return self.toks[j] if j < len(self.toks) else None

    def next(self) -> _Tok:
        t = self.peek()
        if t is None:
            raise GraphDdlParseError("Unexpected end of DDL input")
        self.i += 1
        return t

    def fail(self, what: str):
        t = self.peek()
        where = f"{t.text!r} (offset {t.pos})" if t else "end of input"
        line = self.text.count("\n", 0, t.pos) + 1 if t else "?"
        raise GraphDdlParseError(f"Expected {what} but found {where} at line {line}")

    def at_keyword(self, *kws: str) -> bool:
        t = self.peek()
        return t is not None and t.kind == "word" and t.text.upper() in kws

    def eat_keyword(self, kw: str):
        if not self.at_keyword(kw):
            self.fail(kw)
        self.next()

    def opt_keyword(self, kw: str) -> bool:
        if self.at_keyword(kw):
            self.next()
            return True
        return False

    def at_sym(self, sym: str, ahead: int = 0) -> bool:
        t = self.peek(ahead)
        return t is not None and t.kind in ("sym", "arrow") and t.text == sym

    def eat_sym(self, sym: str):
        if not self.at_sym(sym):
            self.fail(repr(sym))
        self.next()

    def opt_sym(self, sym: str) -> bool:
        if self.at_sym(sym):
            self.next()
            return True
        return False

    def identifier(self) -> str:
        t = self.peek()
        if t is None or t.kind != "word":
            self.fail("identifier")
        self.next()
        return t.text

    def escaped_identifier(self) -> str:
        t = self.peek()
        if t is None:
            self.fail("identifier")
        if t.kind == "escaped":
            self.next()
            return t.text[1:-1].replace("``", "`")
        if t.kind == "word":
            self.next()
            return t.text
        self.fail("identifier")

    # -- grammar -----------------------------------------------------------

    def parse(self) -> A.DdlDefinition:
        stmts: List[object] = []
        while self.peek() is not None:
            stmts.append(self.ddl_statement())
        return A.DdlDefinition(tuple(stmts))

    def ddl_statement(self):
        if self.at_keyword("SET"):
            return self.set_schema()
        if self.at_keyword("CREATE"):
            nxt = self.peek(1)
            if nxt is not None and nxt.kind == "word":
                up = nxt.text.upper()
                if up == "ELEMENT":
                    return self.global_element_type()
                if up == "GRAPH":
                    third = self.peek(2)
                    if (
                        third is not None
                        and third.kind == "word"
                        and third.text.upper() == "TYPE"
                    ):
                        return self.graph_type_definition()
                    return self.graph_definition()
        self.fail("SET SCHEMA, CREATE ELEMENT TYPE, CREATE GRAPH TYPE or CREATE GRAPH")

    def set_schema(self) -> A.SetSchemaDefinition:
        self.eat_keyword("SET")
        self.eat_keyword("SCHEMA")
        ds = self.identifier()
        self.eat_sym(".")
        schema = self.identifier()
        self.opt_sym(";")
        return A.SetSchemaDefinition(ds, schema)

    def global_element_type(self) -> A.ElementTypeDefinition:
        self.eat_keyword("CREATE")
        self.eat_keyword("ELEMENT")
        self.eat_keyword("TYPE")
        return self.element_type_definition()

    def element_type_definition(self) -> A.ElementTypeDefinition:
        name = self.identifier()
        parents: Tuple[str, ...] = ()
        if self.opt_keyword("EXTENDS"):
            ps = [self.identifier()]
            while self.opt_sym(","):
                ps.append(self.identifier())
            parents = tuple(ps)
        props: Tuple[A.Property, ...] = ()
        if self.at_sym("("):
            props = self.properties()
        key: Optional[A.KeyDefinition] = None
        if self.at_keyword("KEY"):
            key = self.key_definition()
        return A.ElementTypeDefinition(name, parents, props, key)

    def properties(self) -> Tuple[A.Property, ...]:
        self.eat_sym("(")
        out: List[A.Property] = []
        if not self.at_sym(")"):
            out.append(self.property())
            while self.opt_sym(","):
                out.append(self.property())
        self.eat_sym(")")
        return tuple(out)

    def property(self) -> A.Property:
        name = self.escaped_identifier()
        # collect the type's raw token span up to ',' / ')' / KEY; parens may
        # nest inside the type itself (LIST(STRING), MAP(...))
        parts: List[str] = []
        depth = 0
        while True:
            t = self.peek()
            if t is None:
                break
            if t.kind == "sym" and t.text == "(":
                depth += 1
            elif t.kind == "sym" and t.text == ")":
                if depth == 0:
                    break
                depth -= 1
            elif t.kind == "sym" and t.text == "," and depth == 0:
                break
            elif t.kind == "word" and t.text.upper() == "KEY" and depth == 0:
                break
            self.next()
            parts.append(t.text)
        if not parts:
            self.fail("a Cypher type")
        try:
            ct = parse_cypher_type(" ".join(parts))
        except Exception as e:
            raise GraphDdlParseError(
                f"Cannot parse type {' '.join(parts)!r} for property {name!r}: {e}"
            )
        return (name, ct)

    def key_definition(self) -> A.KeyDefinition:
        self.eat_keyword("KEY")
        name = self.identifier()
        self.eat_sym("(")
        cols = [self.escaped_identifier()]
        while self.opt_sym(","):
            cols.append(self.escaped_identifier())
        self.eat_sym(")")
        return (name, tuple(cols))

    def node_type_definition(self) -> A.NodeTypeDefinition:
        self.eat_sym("(")
        ets = [self.identifier()]
        while self.opt_sym(","):
            ets.append(self.identifier())
        self.eat_sym(")")
        return A.NodeTypeDefinition(tuple(ets))

    def rel_type_definition(
        self, start: Optional[A.NodeTypeDefinition] = None
    ) -> A.RelationshipTypeDefinition:
        if start is None:
            start = self.node_type_definition()
        self.eat_sym("-")
        self.eat_sym("[")
        ets = [self.identifier()]
        while self.opt_sym(","):
            ets.append(self.identifier())
        self.eat_sym("]")
        self.eat_sym("->")
        end = self.node_type_definition()
        return A.RelationshipTypeDefinition(start, tuple(ets), end)

    def _looks_like_rel_type(self) -> bool:
        """After a '(' group, a '-' begins the `-[R]->` arm of a rel type."""
        depth = 0
        j = 0
        while True:
            t = self.peek(j)
            if t is None:
                return False
            if t.kind == "sym" and t.text == "(":
                depth += 1
            elif t.kind == "sym" and t.text == ")":
                depth -= 1
                if depth == 0:
                    nxt = self.peek(j + 1)
                    return nxt is not None and nxt.kind == "sym" and nxt.text == "-"
            j += 1

    def graph_type_statement(self):
        """elementTypeDefinition | relTypeDefinition | nodeTypeDefinition —
        order matters (reference ``GraphDdlParser.scala:124-126``)."""
        if self.at_sym("("):
            if self._looks_like_rel_type():
                return self.rel_type_definition()
            return self.node_type_definition()
        return self.element_type_definition()

    def graph_type_definition(self) -> A.GraphTypeDefinition:
        self.eat_keyword("CREATE")
        self.eat_keyword("GRAPH")
        self.eat_keyword("TYPE")
        name = self.identifier()
        self.eat_sym("(")
        stmts: List[object] = []
        if not self.at_sym(")"):
            stmts.append(self.graph_type_statement())
            while self.opt_sym(","):
                stmts.append(self.graph_type_statement())
        self.eat_sym(")")
        return A.GraphTypeDefinition(name, tuple(stmts))

    # -- graph (mapping) definitions --------------------------------------

    def view_id(self) -> Tuple[str, ...]:
        parts = [self.escaped_identifier()]
        while len(parts) < 3 and self.at_sym("."):
            self.next()
            parts.append(self.escaped_identifier())
        return tuple(parts)

    def property_mapping(self) -> Tuple[Tuple[str, str], ...]:
        """``( column AS property, ... )`` → prop → column pairs."""
        self.eat_sym("(")
        out: List[Tuple[str, str]] = []
        col = self.escaped_identifier()
        self.eat_keyword("AS")
        prop = self.escaped_identifier()
        out.append((prop, col))
        while self.opt_sym(","):
            col = self.escaped_identifier()
            self.eat_keyword("AS")
            prop = self.escaped_identifier()
            out.append((prop, col))
        self.eat_sym(")")
        return tuple(out)

    def node_to_view(self) -> A.NodeToViewDefinition:
        self.eat_keyword("FROM")
        vid = self.view_id()
        pm = None
        if self.at_sym("("):
            pm = self.property_mapping()
        return A.NodeToViewDefinition(vid, pm)

    def column_identifier(self) -> Tuple[str, ...]:
        parts = [self.identifier()]
        self.eat_sym(".")
        parts.append(self.identifier())
        while self.at_sym("."):
            self.next()
            parts.append(self.identifier())
        return tuple(parts)

    def join_on(self) -> A.JoinOnDefinition:
        self.eat_keyword("JOIN")
        self.eat_keyword("ON")
        preds = []
        lhs = self.column_identifier()
        self.eat_sym("=")
        rhs = self.column_identifier()
        preds.append((lhs, rhs))
        while self.opt_keyword("AND"):
            lhs = self.column_identifier()
            self.eat_sym("=")
            rhs = self.column_identifier()
            preds.append((lhs, rhs))
        return A.JoinOnDefinition(tuple(preds))

    def node_type_to_view(self) -> A.NodeTypeToViewDefinition:
        nt = self.node_type_definition()
        self.eat_keyword("FROM")
        vid = self.view_id()
        alias = self.identifier()
        join = self.join_on()
        return A.NodeTypeToViewDefinition(nt, A.ViewDefinition(vid, alias), join)

    def rel_type_to_view(self) -> A.RelationshipTypeToViewDefinition:
        self.eat_keyword("FROM")
        vid = self.view_id()
        alias = self.identifier()
        pm = None
        if self.at_sym("("):
            pm = self.property_mapping()
        self.eat_keyword("START")
        self.eat_keyword("NODES")
        start = self.node_type_to_view()
        self.eat_keyword("END")
        self.eat_keyword("NODES")
        end = self.node_type_to_view()
        return A.RelationshipTypeToViewDefinition(
            A.ViewDefinition(vid, alias), pm, start, end
        )

    def graph_statement(self):
        """relMapping | nodeMapping | elementType | relType | nodeType —
        order matters (reference ``GraphDdlParser.scala:180-182``)."""
        if self.at_sym("("):
            if self._looks_like_rel_type():
                rel = self.rel_type_definition()
                if self.at_keyword("FROM"):
                    views = [self.rel_type_to_view()]
                    while True:
                        if self.at_keyword("FROM"):
                            views.append(self.rel_type_to_view())
                        elif self.at_sym(",") and self._comma_then("FROM"):
                            self.next()
                            views.append(self.rel_type_to_view())
                        else:
                            break
                    return A.RelationshipMappingDefinition(rel, tuple(views))
                return rel
            nt = self.node_type_definition()
            if self.at_keyword("FROM"):
                views = [self.node_to_view()]
                while True:
                    if self.at_keyword("FROM"):
                        views.append(self.node_to_view())
                    elif self.at_sym(",") and self._comma_then("FROM"):
                        self.next()
                        views.append(self.node_to_view())
                    else:
                        break
                return A.NodeMappingDefinition(nt, tuple(views))
            return nt
        return self.element_type_definition()

    def _comma_then(self, kw: str) -> bool:
        t = self.peek(1)
        return t is not None and t.kind == "word" and t.text.upper() == kw

    def graph_definition(self) -> A.GraphDefinition:
        self.eat_keyword("CREATE")
        self.eat_keyword("GRAPH")
        name = self.identifier()
        gt = None
        if self.opt_keyword("OF"):
            gt = self.identifier()
        self.eat_sym("(")
        stmts: List[object] = []
        if not self.at_sym(")"):
            stmts.append(self.graph_statement())
            while self.opt_sym(","):
                stmts.append(self.graph_statement())
        self.eat_sym(")")
        return A.GraphDefinition(name, gt, tuple(stmts))


def parse_ddl(text: str) -> A.DdlDefinition:
    """Parse a Graph DDL script into its AST
    (reference ``GraphDdlParser.parseDdl``, ``GraphDdlParser.scala:50``)."""
    return _Parser(text).parse()

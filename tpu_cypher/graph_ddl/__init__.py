"""Graph DDL: declare property-graph types and map existing SQL-style tables
("views") onto property graphs.

TPU-native re-design of the reference ``graph-ddl/`` module
(``GraphDdlAst.scala``, ``GraphDdlParser.scala:60``, ``GraphDdl.scala:38``):
a pure-Python recursive-descent parser (replacing fastparse) and a semantic
model that resolves element-type inheritance into a
:class:`~tpu_cypher.api.schema.PropertyGraphSchema` plus per-view element
mappings, feeding host-table ingestion into device-resident scan graphs.
"""

from .ddl_ast import (
    DdlDefinition,
    ElementTypeDefinition,
    GraphDefinition,
    GraphTypeDefinition,
    JoinOnDefinition,
    NodeMappingDefinition,
    NodeToViewDefinition,
    NodeTypeDefinition,
    NodeTypeToViewDefinition,
    RelationshipMappingDefinition,
    RelationshipTypeDefinition,
    RelationshipTypeToViewDefinition,
    SetSchemaDefinition,
    ViewDefinition,
)
from .model import (
    EdgeToViewMapping,
    EdgeViewKey,
    ElementType,
    Graph,
    GraphDdl,
    GraphDdlError,
    GraphType,
    Join,
    NodeToViewMapping,
    NodeType,
    NodeViewKey,
    RelationshipType,
    ViewId,
)
from .parser import GraphDdlParseError, parse_ddl

__all__ = [
    "DdlDefinition",
    "EdgeToViewMapping",
    "EdgeViewKey",
    "ElementType",
    "ElementTypeDefinition",
    "Graph",
    "GraphDdl",
    "GraphDdlError",
    "GraphDdlParseError",
    "GraphDefinition",
    "GraphType",
    "GraphTypeDefinition",
    "Join",
    "JoinOnDefinition",
    "NodeMappingDefinition",
    "NodeToViewDefinition",
    "NodeToViewMapping",
    "NodeType",
    "NodeTypeDefinition",
    "NodeTypeToViewDefinition",
    "NodeViewKey",
    "RelationshipMappingDefinition",
    "RelationshipType",
    "RelationshipTypeDefinition",
    "RelationshipTypeToViewDefinition",
    "SetSchemaDefinition",
    "ViewDefinition",
    "ViewId",
    "parse_ddl",
]

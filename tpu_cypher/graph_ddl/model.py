"""Graph DDL semantic model.

Re-design of the reference resolver (``graph-ddl/.../GraphDdl.scala:42-673``):
resolves element-type inheritance (EXTENDS) with cycle detection, merges
property declarations (conflicting types are an error), expands node/relationship
types to label sets, and attaches view mappings. The resulting
:class:`GraphDdl` exposes, per graph, a
:class:`~tpu_cypher.api.schema.PropertyGraphSchema` plus node/edge view
mappings that an ingestion layer (``tpu_cypher.io.sql``) turns into
device-resident scan graphs.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Sequence, Tuple

from ..api import types as T
from ..api.schema import PropertyGraphSchema, SchemaPattern
from . import ddl_ast as A
from .parser import parse_ddl


class GraphDdlError(Exception):
    """Semantic error in a DDL script (reference ``GraphDdlException.scala``)."""


def _duplicate(kind: str, name) -> "GraphDdlError":
    return GraphDdlError(f"Duplicate {kind}: {name}")


def _unresolved(kind: str, name, known: Sequence[str] = ()) -> "GraphDdlError":
    hint = f"; known: {sorted(known)}" if known else ""
    return GraphDdlError(f"Unresolved {kind}: {name}{hint}")


# ---------------------------------------------------------------------------
# resolved model vocabulary (reference GraphDdl.scala:447-673)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ViewId:
    """A fully / partially qualified view name plus the ambient SET SCHEMA
    (reference ``ViewId`` in ``GraphDdl.scala``)."""

    set_schema: Optional[Tuple[str, str]]  # (dataSource, schema)
    parts: Tuple[str, ...]

    @property
    def data_source(self) -> str:
        return self.resolved[0]

    @property
    def schema(self) -> str:
        return self.resolved[1]

    @property
    def table_name(self) -> str:
        return self.resolved[2]

    @property
    def resolved(self) -> Tuple[str, str, str]:
        if len(self.parts) == 3:
            return (self.parts[0], self.parts[1], self.parts[2])
        if self.set_schema is None:
            raise GraphDdlError(
                f"Relative view name {'.'.join(self.parts)!r} requires a "
                "SET SCHEMA statement or a fully qualified name "
                "(dataSource.schema.view)"
            )
        ds, schema = self.set_schema
        if len(self.parts) == 1:
            return (ds, schema, self.parts[0])
        return (ds, self.parts[0], self.parts[1])

    def __str__(self) -> str:
        return ".".join(self.resolved)


@dataclass(frozen=True)
class ElementType:
    """A resolved element type (reference ``ElementType`` in ``GraphDdl.scala``)."""

    name: str
    parents: FrozenSet[str] = frozenset()
    properties: Tuple[Tuple[str, T.CypherType], ...] = ()
    key: Optional[Tuple[str, Tuple[str, ...]]] = None

    @property
    def property_map(self) -> Dict[str, T.CypherType]:
        return dict(self.properties)


@dataclass(frozen=True)
class NodeType:
    """A node type = a label combination (reference ``NodeType``)."""

    labels: FrozenSet[str]

    @staticmethod
    def of(*labels: str) -> "NodeType":
        return NodeType(frozenset(labels))

    def __str__(self) -> str:
        return f"({','.join(sorted(self.labels))})"


@dataclass(frozen=True)
class RelationshipType:
    """A typed relationship between node types (reference ``RelationshipType``)."""

    start_node_type: NodeType
    labels: FrozenSet[str]
    end_node_type: NodeType

    @staticmethod
    def of(start: str, label: str, end: str) -> "RelationshipType":
        return RelationshipType(NodeType.of(start), frozenset({label}), NodeType.of(end))

    def __str__(self) -> str:
        return (
            f"{self.start_node_type}-[{','.join(sorted(self.labels))}]->"
            f"{self.end_node_type}"
        )


@dataclass(frozen=True)
class Join:
    """One equi-join column pair: node-view column = edge-view column
    (reference ``Join`` in ``GraphDdl.scala:383``)."""

    node_column: str
    edge_column: str


@dataclass(frozen=True)
class NodeViewKey:
    node_type: NodeType
    view_id: ViewId

    def __str__(self) -> str:
        return f"node {self.node_type} from {self.view_id}"


@dataclass(frozen=True)
class EdgeViewKey:
    rel_type: RelationshipType
    view_id: ViewId

    def __str__(self) -> str:
        return f"relationship {self.rel_type} from {self.view_id}"


@dataclass(frozen=True)
class NodeToViewMapping:
    node_type: NodeType
    view: ViewId
    property_mappings: Tuple[Tuple[str, str], ...]  # property -> column

    @property
    def key(self) -> NodeViewKey:
        return NodeViewKey(self.node_type, self.view)


@dataclass(frozen=True)
class StartNode:
    node_view_key: NodeViewKey
    join_predicates: Tuple[Join, ...]


@dataclass(frozen=True)
class EndNode:
    node_view_key: NodeViewKey
    join_predicates: Tuple[Join, ...]


@dataclass(frozen=True)
class EdgeToViewMapping:
    rel_type: RelationshipType
    view: ViewId
    start_node: StartNode
    end_node: EndNode
    property_mappings: Tuple[Tuple[str, str], ...]  # property -> column

    @property
    def key(self) -> EdgeViewKey:
        return EdgeViewKey(self.rel_type, self.view)


# ---------------------------------------------------------------------------
# graph type (resolved schema-level info)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class GraphType:
    """Resolved element/node/relationship types of a graph (type)
    (reference ``GraphType`` in ``GraphDdl.scala:464-530``)."""

    name: str
    element_types: Tuple[ElementType, ...] = ()
    node_types: Tuple[NodeType, ...] = ()
    rel_types: Tuple[RelationshipType, ...] = ()

    @property
    def element_types_by_name(self) -> Dict[str, ElementType]:
        return {e.name: e for e in self.element_types}

    def node_property_keys(self, node_type: NodeType) -> Dict[str, T.CypherType]:
        return self._merged_properties(node_type.labels)

    def rel_property_keys(self, rel_type: RelationshipType) -> Dict[str, T.CypherType]:
        return self._merged_properties(rel_type.labels)

    def _merged_properties(self, labels: FrozenSet[str]) -> Dict[str, T.CypherType]:
        by_name = self.element_types_by_name
        merged: Dict[str, T.CypherType] = {}
        for label in sorted(labels):
            et = by_name.get(label)
            if et is None:
                raise _unresolved("element type", label, by_name)
            for k, v in et.properties:
                if k in merged and merged[k] != v:
                    raise GraphDdlError(
                        f"Property {k!r} declared with conflicting types "
                        f"{merged[k]} and {v} across {sorted(labels)}"
                    )
                merged[k] = v
        return merged

    def to_schema(self) -> PropertyGraphSchema:
        """Lower to the session-level property-graph schema
        (reference ``GraphType.asOkapiSchema``)."""
        s = PropertyGraphSchema.empty()
        for nt in self.node_types:
            s = s.with_node_combination(nt.labels, self.node_property_keys(nt))
        patterns = []
        for rt in self.rel_types:
            if len(rt.labels) != 1:
                raise GraphDdlError(
                    f"Relationship type must have exactly one label: {rt}"
                )
            (label,) = rt.labels
            s = s.with_relationship_type(label, self.rel_property_keys(rt))
            patterns.append(
                SchemaPattern(rt.start_node_type.labels, label, rt.end_node_type.labels)
            )
        if patterns:
            s = s.with_schema_patterns(*patterns)
        return s


# ---------------------------------------------------------------------------
# resolution
# ---------------------------------------------------------------------------


class _PartialGraphType:
    """Accumulates element/node/rel type definitions while resolving EXTENDS
    (reference ``PartialGraphType``, ``GraphDdl.scala:152-273``)."""

    def __init__(self, name: str, element_types: Dict[str, A.ElementTypeDefinition]):
        self.name = name
        self.element_types = element_types
        self.node_defs: List[A.NodeTypeDefinition] = []
        self.rel_defs: List[A.RelationshipTypeDefinition] = []

    def push(self, name: str, statements: Sequence[object]) -> "_PartialGraphType":
        local: Dict[str, A.ElementTypeDefinition] = {}
        for st in statements:
            if isinstance(st, A.ElementTypeDefinition):
                if st.name in local:
                    raise _duplicate("element type", st.name)
                local[st.name] = st
        merged = dict(self.element_types)
        merged.update(local)  # local shadows global
        out = _PartialGraphType(name, merged)
        out.node_defs = list(self.node_defs)
        out.rel_defs = list(self.rel_defs)
        for st in statements:
            if isinstance(st, A.NodeTypeDefinition):
                out.node_defs.append(st)
            elif isinstance(st, A.RelationshipTypeDefinition):
                out.rel_defs.append(st)
        return out

    # -- element-type resolution ------------------------------------------

    def _resolve_one(self, name: str) -> A.ElementTypeDefinition:
        et = self.element_types.get(name)
        if et is None:
            raise _unresolved("element type", name, self.element_types)
        return et

    def _expand(self, name: str, path: Tuple[str, ...] = ()) -> List[A.ElementTypeDefinition]:
        """The element type plus all transitive parents; cycle-checked
        (reference ``resolveElementTypes``/``detectCircularDependency``)."""
        if name in path:
            cyc = " -> ".join(path + (name,))
            raise GraphDdlError(f"Circular element type inheritance: {cyc}")
        et = self._resolve_one(name)
        out = [et]
        for p in sorted(et.parents):
            out.extend(self._expand(p, path + (name,)))
        # de-dup preserving first occurrence
        seen = set()
        uniq = []
        for e in out:
            if e.name not in seen:
                seen.add(e.name)
                uniq.append(e)
        return uniq

    def resolve_labels(self, nt: A.NodeTypeDefinition) -> FrozenSet[str]:
        labels: set = set()
        for name in nt.element_types:
            labels.update(e.name for e in self._expand(name))
        return frozenset(labels)

    def to_node_type(self, nt: A.NodeTypeDefinition) -> NodeType:
        return NodeType(self.resolve_labels(nt))

    def to_rel_type(self, rt: A.RelationshipTypeDefinition) -> RelationshipType:
        labels: set = set()
        for name in rt.element_types:
            labels.update(e.name for e in self._expand(name))
        return RelationshipType(
            self.to_node_type(rt.start_node_type),
            frozenset(labels),
            self.to_node_type(rt.end_node_type),
        )

    def to_graph_type(self) -> GraphType:
        node_types = _distinct(self.to_node_type(n) for n in self.node_defs)
        rel_types = _distinct(self.to_rel_type(r) for r in self.rel_defs)
        # the element types actually referenced (with their parents), resolved
        # with merged properties
        needed: Dict[str, ElementType] = {}

        def add(name: str):
            for et in self._expand(name):
                if et.name not in needed:
                    merged = self._merge_inherited(et.name)
                    needed[et.name] = ElementType(
                        name=et.name,
                        parents=frozenset(et.parents),
                        properties=tuple(sorted(merged.items())),
                        key=(et.key[0], et.key[1]) if et.key else None,
                    )

        for nt in node_types:
            for label in nt.labels:
                add(label)
        for rt in rel_types:
            for label in rt.labels:
                add(label)
        return GraphType(
            self.name,
            tuple(needed[k] for k in sorted(needed)),
            tuple(node_types),
            tuple(rel_types),
        )

    def _merge_inherited(self, name: str) -> Dict[str, T.CypherType]:
        """An element type's own + inherited properties
        (reference ``mergeProperties``, ``GraphDdl.scala:237``)."""
        merged: Dict[str, T.CypherType] = {}
        for et in self._expand(name):
            for k, v in et.properties:
                if k in merged and merged[k] != v:
                    raise GraphDdlError(
                        f"Property {k!r} of element type {name!r} inherited with "
                        f"conflicting types {merged[k]} and {v}"
                    )
                merged[k] = v
        return merged


def _distinct(items) -> List:
    seen = set()
    out = []
    for it in items:
        if it not in seen:
            seen.add(it)
            out.append(it)
    return out


@dataclass(frozen=True)
class Graph:
    """A resolved graph: type + view mappings (reference ``Graph`` in
    ``GraphDdl.scala:451-462``)."""

    name: str
    graph_type: GraphType
    node_to_view_mappings: Tuple[NodeToViewMapping, ...] = ()
    edge_to_view_mappings: Tuple[EdgeToViewMapping, ...] = ()

    def node_id_columns_for(self, key: NodeViewKey) -> Optional[Tuple[str, ...]]:
        """The node-view columns that identify a node of this view — the join
        columns of the first edge mapping referencing it (reference
        ``Graph.nodeIdColumnsFor``, ``GraphDdl.scala:458``)."""
        for evm in self.edge_to_view_mappings:
            if evm.start_node.node_view_key == key:
                return tuple(j.node_column for j in evm.start_node.join_predicates)
            if evm.end_node.node_view_key == key:
                return tuple(j.node_column for j in evm.end_node.join_predicates)
        return None

    @property
    def schema(self) -> PropertyGraphSchema:
        return self.graph_type.to_schema()


@dataclass(frozen=True)
class GraphDdl:
    """The resolved result of a whole DDL script (reference ``GraphDdl`` in
    ``GraphDdl.scala:447``)."""

    graphs: Dict[str, Graph] = field(default_factory=dict)

    @staticmethod
    def parse(ddl_text: str) -> "GraphDdl":
        return resolve_ddl(parse_ddl(ddl_text))

    def union(self, other: "GraphDdl") -> "GraphDdl":
        merged = dict(self.graphs)
        merged.update(other.graphs)
        return GraphDdl(merged)


# ---------------------------------------------------------------------------
# top-level resolver
# ---------------------------------------------------------------------------


def resolve_ddl(ddl: A.DdlDefinition) -> GraphDdl:
    """AST → resolved model (reference ``GraphDdl.apply``, ``GraphDdl.scala:52``)."""
    set_schema: Optional[Tuple[str, str]] = None
    global_types: Dict[str, A.ElementTypeDefinition] = {}
    graph_types: Dict[str, Tuple[object, ...]] = {}
    graphs: Dict[str, Graph] = {}

    for st in ddl.statements:
        if isinstance(st, A.SetSchemaDefinition):
            set_schema = (st.data_source, st.schema)
        elif isinstance(st, A.ElementTypeDefinition):
            if st.name in global_types:
                raise _duplicate("element type", st.name)
            global_types[st.name] = st
        elif isinstance(st, A.GraphTypeDefinition):
            if st.name in graph_types:
                raise _duplicate("graph type", st.name)
            graph_types[st.name] = st.statements
        elif isinstance(st, A.GraphDefinition):
            if st.name in graphs:
                raise _duplicate("graph", st.name)
            graphs[st.name] = _resolve_graph(
                st, set_schema, global_types, graph_types
            )
        else:
            raise GraphDdlError(f"Unexpected top-level statement: {st!r}")
    return GraphDdl(graphs)


def _resolve_graph(
    gd: A.GraphDefinition,
    set_schema: Optional[Tuple[str, str]],
    global_types: Dict[str, A.ElementTypeDefinition],
    graph_types: Dict[str, Tuple[object, ...]],
) -> Graph:
    partial = _PartialGraphType("", dict(global_types))
    if gd.graph_type_name is not None:
        stmts = graph_types.get(gd.graph_type_name)
        if stmts is None:
            raise _unresolved("graph type", gd.graph_type_name, graph_types)
        partial = partial.push(gd.graph_type_name, stmts)

    type_stmts = [
        s
        for s in gd.statements
        if isinstance(
            s,
            (A.ElementTypeDefinition, A.NodeTypeDefinition, A.RelationshipTypeDefinition),
        )
    ]
    # node/rel types referenced only via mappings are declared implicitly
    for s in gd.statements:
        if isinstance(s, A.NodeMappingDefinition):
            type_stmts.append(s.node_type)
        elif isinstance(s, A.RelationshipMappingDefinition):
            type_stmts.append(s.rel_type)
            type_stmts.append(s.rel_type.start_node_type)
            type_stmts.append(s.rel_type.end_node_type)
    partial = partial.push(gd.name, type_stmts)
    graph_type = partial.to_graph_type()

    node_mappings: List[NodeToViewMapping] = []
    seen_node_keys: set = set()
    for s in gd.statements:
        if not isinstance(s, A.NodeMappingDefinition):
            continue
        node_type = partial.to_node_type(s.node_type)
        props = graph_type.node_property_keys(node_type)
        for ntv in s.node_to_view:
            vid = ViewId(set_schema, ntv.view_id)
            mapping = _property_mappings(props, ntv.property_mapping)
            nvm = NodeToViewMapping(node_type, vid, mapping)
            if nvm.key in seen_node_keys:
                raise _duplicate("node mapping", str(nvm.key))
            seen_node_keys.add(nvm.key)
            node_mappings.append(nvm)
    by_key = {m.key: m for m in node_mappings}

    edge_mappings: List[EdgeToViewMapping] = []
    seen_edge_keys: set = set()
    for s in gd.statements:
        if not isinstance(s, A.RelationshipMappingDefinition):
            continue
        rel_type = partial.to_rel_type(s.rel_type)
        props = graph_type.rel_property_keys(rel_type)
        for rtv in s.rel_type_to_view:
            vid = ViewId(set_schema, rtv.view_def.view_id)
            edge_alias = rtv.view_def.alias
            start = _resolve_endpoint(
                rtv.start_node, partial, set_schema, by_key, edge_alias, "START"
            )
            end = _resolve_endpoint(
                rtv.end_node, partial, set_schema, by_key, edge_alias, "END"
            )
            evm = EdgeToViewMapping(
                rel_type=rel_type,
                view=vid,
                start_node=StartNode(*start),
                end_node=EndNode(*end),
                property_mappings=_property_mappings(props, rtv.property_mapping),
            )
            if evm.key in seen_edge_keys:
                raise _duplicate("relationship mapping", str(evm.key))
            seen_edge_keys.add(evm.key)
            edge_mappings.append(evm)

    return Graph(gd.name, graph_type, tuple(node_mappings), tuple(edge_mappings))


def _resolve_endpoint(
    ntv: A.NodeTypeToViewDefinition,
    partial: _PartialGraphType,
    set_schema: Optional[Tuple[str, str]],
    node_mappings_by_key: Dict[NodeViewKey, NodeToViewMapping],
    edge_alias: str,
    side: str,
) -> Tuple[NodeViewKey, Tuple[Join, ...]]:
    node_type = partial.to_node_type(ntv.node_type)
    vid = ViewId(set_schema, ntv.view_def.view_id)
    key = NodeViewKey(node_type, vid)
    if key not in node_mappings_by_key:
        raise _unresolved(
            f"{side} node view", str(key), [str(k) for k in node_mappings_by_key]
        )
    node_alias = ntv.view_def.alias
    joins: List[Join] = []
    for lhs, rhs in ntv.join_on.join_predicates:
        joins.append(_to_join(node_alias, edge_alias, lhs, rhs))
    return key, tuple(joins)


def _to_join(
    node_alias: str, edge_alias: str, lhs: Tuple[str, ...], rhs: Tuple[str, ...]
) -> Join:
    """Orient a join predicate by alias (reference ``toJoin``,
    ``GraphDdl.scala:383-396``)."""

    def split(col: Tuple[str, ...]) -> Tuple[str, str]:
        return col[0], ".".join(col[1:])

    la, lc = split(lhs)
    ra, rc = split(rhs)
    if la == node_alias and ra == edge_alias:
        return Join(node_column=lc, edge_column=rc)
    if la == edge_alias and ra == node_alias:
        return Join(node_column=rc, edge_column=lc)
    raise GraphDdlError(
        f"Join predicate {'.'.join(lhs)} = {'.'.join(rhs)} must relate the "
        f"node view alias {node_alias!r} and the edge view alias {edge_alias!r}"
    )


def _property_mappings(
    declared: Dict[str, T.CypherType],
    explicit: Optional[Tuple[Tuple[str, str], ...]],
) -> Tuple[Tuple[str, str], ...]:
    """Explicit ``column AS property`` pairs, defaulting unmapped properties to
    identically-named columns (reference ``toPropertyMappings``,
    ``GraphDdl.scala:398-413``)."""
    out: Dict[str, str] = {}
    explicit_map = dict(explicit or ())
    for prop in explicit_map:
        if prop not in declared:
            raise _unresolved("property", prop, declared)
    for prop in declared:
        out[prop] = explicit_map.get(prop, prop)
    return tuple(sorted(out.items()))

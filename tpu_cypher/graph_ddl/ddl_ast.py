"""Graph DDL abstract syntax.

Mirrors the reference AST vocabulary (``graph-ddl/.../GraphDdlAst.scala:33-139``)
as plain frozen dataclasses; tree rewriting is not needed for DDL, so these do
not participate in the TreeNode substrate.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

from ..api import types as T

# a property declaration: name -> CypherType
Property = Tuple[str, T.CypherType]
# KEY <name> (col1, col2, ...)
KeyDefinition = Tuple[str, Tuple[str, ...]]
# dotted column identifier, e.g. ("view_alias", "column")
ColumnIdentifier = Tuple[str, ...]


@dataclass(frozen=True)
class SetSchemaDefinition:
    """``SET SCHEMA dataSource.schema`` (reference ``GraphDdlAst.scala:53``)."""

    data_source: str
    schema: str


@dataclass(frozen=True)
class ElementTypeDefinition:
    """``Name EXTENDS A, B ( prop TYPE, ... ) KEY k (col, ...)``
    (reference ``GraphDdlAst.scala:58``)."""

    name: str
    parents: Tuple[str, ...] = ()
    properties: Tuple[Property, ...] = ()
    key: Optional[KeyDefinition] = None

    @property
    def property_map(self) -> Dict[str, T.CypherType]:
        return dict(self.properties)


@dataclass(frozen=True)
class NodeTypeDefinition:
    """``(A, B)`` (reference ``GraphDdlAst.scala:80``)."""

    element_types: Tuple[str, ...]

    def __str__(self) -> str:
        return f"({','.join(self.element_types)})"


@dataclass(frozen=True)
class RelationshipTypeDefinition:
    """``(A)-[R]->(B)`` (reference ``GraphDdlAst.scala:95``)."""

    start_node_type: NodeTypeDefinition
    element_types: Tuple[str, ...]
    end_node_type: NodeTypeDefinition

    def __str__(self) -> str:
        return (
            f"{self.start_node_type}-[{','.join(self.element_types)}]->"
            f"{self.end_node_type}"
        )


@dataclass(frozen=True)
class GraphTypeDefinition:
    """``CREATE GRAPH TYPE name ( ... )`` (reference ``GraphDdlAst.scala:65``)."""

    name: str
    statements: Tuple[object, ...] = ()


@dataclass(frozen=True)
class ViewDefinition:
    """``view.id alias`` (reference ``GraphDdlAst.scala:117``)."""

    view_id: Tuple[str, ...]
    alias: str


@dataclass(frozen=True)
class JoinOnDefinition:
    """``JOIN ON a.x = b.y AND ...`` (reference ``GraphDdlAst.scala:120``)."""

    join_predicates: Tuple[Tuple[ColumnIdentifier, ColumnIdentifier], ...]


@dataclass(frozen=True)
class NodeToViewDefinition:
    """``FROM view (col AS prop, ...)`` (reference ``GraphDdlAst.scala:105``)."""

    view_id: Tuple[str, ...]
    property_mapping: Optional[Tuple[Tuple[str, str], ...]] = None  # prop -> column


@dataclass(frozen=True)
class NodeMappingDefinition:
    """``(A) FROM v1 (...), FROM v2 (...)`` (reference ``GraphDdlAst.scala:111``)."""

    node_type: NodeTypeDefinition
    node_to_view: Tuple[NodeToViewDefinition, ...] = ()


@dataclass(frozen=True)
class NodeTypeToViewDefinition:
    """``(A) FROM view alias JOIN ON ...`` (reference ``GraphDdlAst.scala:122``)."""

    node_type: NodeTypeDefinition
    view_def: ViewDefinition
    join_on: JoinOnDefinition


@dataclass(frozen=True)
class RelationshipTypeToViewDefinition:
    """``FROM view alias (cols) START NODES ... END NODES ...``
    (reference ``GraphDdlAst.scala:128``)."""

    view_def: ViewDefinition
    property_mapping: Optional[Tuple[Tuple[str, str], ...]]
    start_node: NodeTypeToViewDefinition
    end_node: NodeTypeToViewDefinition


@dataclass(frozen=True)
class RelationshipMappingDefinition:
    """``(A)-[R]->(B) FROM ...`` (reference ``GraphDdlAst.scala:135``)."""

    rel_type: RelationshipTypeDefinition
    rel_type_to_view: Tuple[RelationshipTypeToViewDefinition, ...] = ()


@dataclass(frozen=True)
class GraphDefinition:
    """``CREATE GRAPH name OF type ( ... )`` (reference ``GraphDdlAst.scala:71``)."""

    name: str
    graph_type_name: Optional[str] = None
    statements: Tuple[object, ...] = ()


@dataclass(frozen=True)
class DdlDefinition:
    """A whole DDL script (reference ``GraphDdlAst.scala:45``)."""

    statements: Tuple[object, ...] = field(default_factory=tuple)

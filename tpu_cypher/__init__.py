"""tpu-cypher: a TPU-native openCypher property-graph query engine.

Brand-new framework with the capabilities of the reference CAPF/Morpheus
stack (soerenreichardt/cypher-for-apache-flink): the backend-agnostic Cypher
compiler pipeline (parse -> IR -> logical plan -> relational plan) bottoms out
in an abstract Table algebra with two backends — a pure-Python local table
(correctness oracle) and sharded JAX arrays on TPU.

Quick start::

    from tpu_cypher import CypherSession
    session = CypherSession.local()
    g = session.create_graph_from_create_query(
        "CREATE (a:Person {name:'Alice'})-[:KNOWS]->(:Person {name:'Bob'})")
    print(g.cypher("MATCH (a:Person)-[:KNOWS]->(b) RETURN a.name, b.name").show())
"""

from . import errors, obs
from .api.mapping import NodeMappingBuilder, RelationshipMappingBuilder
from .api.schema import PropertyGraphSchema, SchemaPattern
from .api.values import CypherMap, Duration, Node, Relationship
from .errors import TpuCypherError
from .relational.graphs import ElementTable, ScanGraph
from .relational.session import CypherResult, CypherSession, PropertyGraph

__version__ = "0.1.0"

__all__ = [
    "errors",
    "obs",
    "TpuCypherError",
    "CypherSession",
    "PropertyGraph",
    "CypherResult",
    "ElementTable",
    "ScanGraph",
    "PropertyGraphSchema",
    "SchemaPattern",
    "NodeMappingBuilder",
    "RelationshipMappingBuilder",
    "Node",
    "Relationship",
    "CypherMap",
    "Duration",
]

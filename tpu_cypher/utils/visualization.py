"""Notebook / Zeppelin-style visualization of records and graphs.

Re-design of the reference's ``ZeppelinSupport``
(``okapi-api/src/main/scala/org/opencypher/okapi/api/util/ZeppelinSupport.scala:42-280``):

* ``records_to_table_tsv``   — the ``%table`` tab-separated rendering
* ``records_to_graph_json``  — the ``%network`` JSON: element columns of a
                               result deduplicated by id into
                               ``{nodes, edges, labels, types, directed}``
* ``graph_to_json``          — same JSON for a whole property graph
* ``visualize``              — graph if the result returns one, else table

Node JSON: ``{id, label, labels, data}`` (label = first label,
lexicographically — the reference uses ``labels.headOption``); relationship
JSON: ``{id, source, target, label, data}``. Ids are strings, as in the
reference's Zeppelin format.
"""

from __future__ import annotations

import json as _json
import math
from typing import Any, Dict, Iterable, List

from ..api.values import Node, Relationship, to_cypher_string


def _json_value(v: Any) -> Any:
    """Property value -> JSON-compatible value (Cypher-formatted when the
    type has no JSON analog)."""
    if v is None or isinstance(v, (bool, int, str)):
        return v
    if isinstance(v, float):
        if math.isnan(v) or math.isinf(v):
            return to_cypher_string(v)
        return v
    if isinstance(v, (list, tuple)):
        return [_json_value(x) for x in v]
    if isinstance(v, dict):
        return {k: _json_value(x) for k, x in v.items()}
    return to_cypher_string(v).strip("'")


def node_json(n: Node) -> Dict[str, Any]:
    labels = sorted(n.labels)
    return {
        "id": str(n.id),
        "label": labels[0] if labels else "",
        "labels": labels,
        "data": {k: _json_value(v) for k, v in sorted(n.properties.items())},
    }


def relationship_json(r: Relationship) -> Dict[str, Any]:
    return {
        "id": str(r.id),
        "source": str(r.start),
        "target": str(r.end),
        "label": r.rel_type,
        "data": {k: _json_value(v) for k, v in sorted(r.properties.items())},
    }


def elements_to_graph_json(
    nodes: Iterable[Node], rels: Iterable[Relationship], indent: int = 2
) -> str:
    by_id: Dict[Any, Node] = {}
    for n in nodes:
        by_id.setdefault(n.id, n)
    rel_by_id: Dict[Any, Relationship] = {}
    for r in rels:
        rel_by_id.setdefault(r.id, r)
    labels = sorted({l for n in by_id.values() for l in n.labels})
    types = sorted({r.rel_type for r in rel_by_id.values()})
    obj = {
        "nodes": [node_json(n) for _, n in sorted(by_id.items())],
        "edges": [relationship_json(r) for _, r in sorted(rel_by_id.items())],
        "labels": labels,
        "types": types,
        "directed": True,
    }
    return _json.dumps(obj, indent=indent)


def records_to_graph_json(records, indent: int = 2) -> str:
    """Element columns of a result, deduplicated by id
    (reference ``toZeppelinGraph``, ``ZeppelinSupport.scala:144-180``)."""
    rows = records.collect()
    nodes: List[Node] = []
    rels: List[Relationship] = []
    for row in rows:
        for v in row.values():
            if isinstance(v, Node):
                nodes.append(v)
            elif isinstance(v, Relationship):
                rels.append(v)
    return elements_to_graph_json(nodes, rels, indent)


def records_to_table_tsv(records) -> str:
    """``%table`` rendering (reference ``toZeppelinTable``): header row then
    one tab-separated Cypher-formatted line per record."""
    cols = records.columns
    lines = ["\t".join(cols)]
    for row in records.collect():
        lines.append("\t".join(to_cypher_string(row[c]) for c in cols))
    return "\n".join(lines)


def records_to_html(records, max_rows: int = 100) -> str:
    """Notebook ``_repr_html_`` table."""
    import html

    cols = records.columns
    rows = records.collect()[:max_rows]
    head = "".join(f"<th>{html.escape(c)}</th>" for c in cols)
    body = "".join(
        "<tr>"
        + "".join(f"<td>{html.escape(to_cypher_string(r[c]))}</td>" for c in cols)
        + "</tr>"
        for r in rows
    )
    return (
        f"<table><thead><tr>{head}</tr></thead><tbody>{body}</tbody></table>"
        f"<p>{records.size} row(s)</p>"
    )


def graph_to_json(graph, indent: int = 2) -> str:
    """Whole-graph ``%network`` JSON via full node/relationship scans
    (reference ``ZeppelinGraph.printGraph``)."""
    node_rows = graph.nodes("n").collect()
    rel_rows = graph.relationships("r").collect()
    return elements_to_graph_json(
        (row["n"] for row in node_rows),
        (row["r"] for row in rel_rows),
        indent,
    )


def visualize(result) -> str:
    """Graph rendering if the result carries a graph (RETURN GRAPH), else the
    table (reference ``ResultVisualizer.visualize``)."""
    recs = result.records
    if recs is None or not recs.columns:  # graph-returning query
        return graph_to_json(result.graph)
    return records_to_table_tsv(recs)

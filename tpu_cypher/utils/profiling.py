"""Device-level observability: jax.profiler traces and compiled-HLO dumps.

The reference delegates engine-level profiling to Spark UI /
``tableEnv.explain`` (used in ``flink-cypher/.../Demo.scala:84``); the TPU
equivalents are the XLA profiler (TensorBoard-compatible traces) and the
compiled HLO of the jitted kernels. Gated by ``TPU_CYPHER_PROFILE_DIR``:
when set, ``CypherSession.cypher`` executions are wrapped in a profiler
trace automatically, AND the ``obs.trace`` span tree uses this module as
its device-trace backend — every engine span opens a matching
``jax.profiler.TraceAnnotation``, so the phase/operator/kernel tree shows
up region-named inside the TensorBoard/Perfetto timeline
(``docs/observability.md``).
"""

from __future__ import annotations

import contextlib
import os
from typing import Any, Callable, Optional

from .config import PROFILE_DIR


@contextlib.contextmanager
def profile_trace(log_dir: Optional[str] = None):
    """Wrap a block in a ``jax.profiler`` trace (viewable in TensorBoard /
    Perfetto). No-op when no directory is configured or the profiler is
    unavailable."""
    d = log_dir or PROFILE_DIR.get()
    if not d:
        yield
        return
    try:
        import jax

        jax.profiler.start_trace(d)
    except Exception:  # pragma: no cover - fault-ok: profiler start is best-effort (no jax, double-start)
        yield
        return
    try:
        yield
    finally:
        try:
            jax.profiler.stop_trace()
        except Exception:  # pragma: no cover - fault-ok: best-effort profiler stop
            pass


def lowered_hlo(fn: Callable, *args: Any, **kw: Any) -> str:
    """StableHLO text for a jittable function on example args — the per-node
    plan introspection analog of the reference's ``tableEnv.explain``."""
    import jax

    # tpulint: allow[recompile-hazard] reason=one-shot plan introspection, not on the query path
    return jax.jit(fn).lower(*args, **kw).as_text()


def compiled_hlo(fn: Callable, *args: Any, **kw: Any) -> str:
    """Post-XLA-optimization HLO (what actually runs on the device)."""
    import jax

    # tpulint: allow[recompile-hazard] reason=one-shot HLO dump for diagnostics, not on the query path
    compiled = jax.jit(fn).lower(*args, **kw).compile()
    return "\n".join(m.to_string() for m in compiled.runtime_executable().hlo_modules())


def annotate(name: str):
    """Named profiler span for region attribution inside traces."""
    import jax

    return jax.profiler.TraceAnnotation(name)

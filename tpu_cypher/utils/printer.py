"""ASCII table printing (reference ``TablePrinter.scala`` / ``RecordsPrinter``)."""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

from ..api.values import to_cypher_string


def format_rows(columns: Sequence[str], rows: Sequence[Sequence[Any]], max_rows: Optional[int] = None) -> str:
    shown = list(rows[:max_rows]) if max_rows is not None else list(rows)
    cells = [[to_cypher_string(v) for v in r] for r in shown]
    widths = [len(c) for c in columns]
    for r in cells:
        for i, v in enumerate(r):
            widths[i] = max(widths[i], len(v))
    sep = "+" + "+".join("-" * (w + 2) for w in widths) + "+" if columns else "++\n||\n++"

    def fmt_row(vals):
        return "|" + "|".join(f" {v:<{w}} " for v, w in zip(vals, widths)) + "|"

    lines = [sep, fmt_row(columns), sep]
    for r in cells:
        lines.append(fmt_row(r))
    lines.append(sep)
    n = len(rows)
    lines.append(f"({n} row{'s' if n != 1 else ''})")
    return "\n".join(lines)


def format_table(table, n: int = 20) -> str:
    cols = table.physical_columns
    rows = []
    for i, r in enumerate(table.rows()):
        if i >= n:
            break
        rows.append([r[c] for c in cols])
    return format_rows(cols, rows)

"""Deprecated shim: stage timing moved into ``tpu_cypher.obs.metrics``.

The ``time_stage``/``last_timings``/``clear_timings`` trio (reference
``Measurement.scala:36-56`` + ``PrintTimings``) now lives in the unified
metrics registry, where each stage observation also lands in the
``tpu_cypher_stage_seconds`` histogram (p50/p95/max per stage). Import from
``tpu_cypher.obs.metrics`` instead."""

from __future__ import annotations

import warnings

from ..obs.metrics import clear_timings, last_timings, time_stage  # noqa: F401

warnings.warn(
    "tpu_cypher.utils.measurement is deprecated; use tpu_cypher.obs.metrics",
    DeprecationWarning,
    stacklevel=2,
)

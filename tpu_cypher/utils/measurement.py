"""Stage timing (reference ``Measurement.scala:36-56`` + ``PrintTimings`` flag)."""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Tuple

from .config import PRINT_TIMINGS

_TIMINGS: List[Tuple[str, float]] = []


def time_stage(name: str, fn: Callable, *args, **kw):
    t0 = time.perf_counter()
    out = fn(*args, **kw)
    dt = time.perf_counter() - t0
    _TIMINGS.append((name, dt))
    if PRINT_TIMINGS.get():
        print(f"[timing] {name}: {dt * 1000:.2f} ms")
    return out


def last_timings() -> Dict[str, float]:
    return dict(_TIMINGS[-16:])


def clear_timings():
    _TIMINGS.clear()

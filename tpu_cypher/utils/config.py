"""Typed config registry: every ``TPU_CYPHER_*`` knob, declared ONCE.

Re-design of the reference's ``ConfigOption``/``ConfigFlag`` system
(``okapi-api/.../impl/configuration/ConfigOption.scala:31-60``; per-layer
flag objects like ``CoraConfiguration.scala:33-39``): JVM system properties
become environment variables with in-process overrides.

PRs 1-4 grew knobs organically — ``ConfigOption``s declared in six modules
plus raw ``os.environ`` reads in four more, with one var
(``TPU_CYPHER_PRINT_TIMINGS``) read through two different paths. This
module is now the SINGLE declaration point: ``declare``/``declare_flag``
register each option in ``REGISTRY`` so the engine's whole configuration
surface is enumerable (``options()``), and the ``env-var-registry`` lint
rule (``tpu_cypher.analysis``) fails any raw ``TPU_CYPHER_*`` read or any
``ConfigOption`` constructed outside this file. Engine modules import
their options from here (often under a local alias, e.g.
``bucketing.MODE is config.BUCKET_MODE``) so existing ``MODE.set(..)``
call sites keep working on the same object.
"""

from __future__ import annotations

import os
from typing import Callable, Dict, Generic, Mapping, Optional, TypeVar

T = TypeVar("T")


class ConfigOption(Generic[T]):
    def __init__(
        self,
        name: str,
        default: T,
        parse: Callable[[str], T],
        help: str = "",
    ):
        self.name = name
        self.default = default
        self.parse = parse
        self.help = help
        self._override: Optional[T] = None

    def get(self) -> T:
        if self._override is not None:
            return self._override
        raw = os.environ.get(self.name)
        if raw is None:
            return self.default
        try:
            return self.parse(raw)
        except ValueError:
            return self.default

    def set(self, value: T):
        self._override = value

    def reset(self):
        self._override = None

    @property
    def overridden(self) -> bool:
        """True when the operator pinned this knob explicitly — an
        in-process ``set()`` or a live environment variable. Adaptive
        layers (the cost-based optimizer) treat an overridden knob as a
        hand-tuned constant to respect, and only substitute their own
        modelled value for knobs still at the declared default."""
        return self._override is not None or self.name in os.environ

    def __repr__(self) -> str:  # pragma: no cover - debugging nicety
        return f"ConfigOption({self.name!r}, default={self.default!r})"


def _parse_bool(s: str) -> bool:
    return s.strip().lower() in ("1", "true", "yes", "on")


class ConfigFlag(ConfigOption[bool]):
    def __init__(self, name: str, default: bool = False, help: str = ""):
        super().__init__(name, default, _parse_bool, help=help)


# ---------------------------------------------------------------------------
# the registry
# ---------------------------------------------------------------------------

REGISTRY: Dict[str, ConfigOption] = {}


def declare(
    name: str,
    default: T,
    parse: Callable[[str], T],
    help: str = "",
) -> ConfigOption[T]:
    """Declare one typed env-backed option. Idempotent per name (repeat
    declarations return the first object so every importer shares override
    state); the name must carry the engine prefix."""
    if name in REGISTRY:
        return REGISTRY[name]
    opt = ConfigOption(name, default, parse, help=help)
    REGISTRY[name] = opt
    return opt


def declare_flag(name: str, default: bool = False, help: str = "") -> ConfigFlag:
    if name in REGISTRY:
        return REGISTRY[name]  # type: ignore[return-value]
    opt = ConfigFlag(name, default, help=help)
    REGISTRY[name] = opt
    return opt


def options() -> Mapping[str, ConfigOption]:
    """Every declared option, by env var name — the engine's enumerable
    configuration surface."""
    return dict(REGISTRY)


# ---------------------------------------------------------------------------
# declarations: the engine's whole TPU_CYPHER_* surface
# ---------------------------------------------------------------------------

# per-stage debug flags (reference PrintTimings / PrintIr / PrintLogicalPlan
# / PrintRelationalPlan, Configuration.scala:36, CoraConfiguration.scala:33-39)
PRINT_TIMINGS = declare_flag(
    "TPU_CYPHER_PRINT_TIMINGS", help="echo per-stage wall timings to stdout"
)
PRINT_IR = declare_flag("TPU_CYPHER_PRINT_IR", help="dump the query IR")
PRINT_LOGICAL = declare_flag(
    "TPU_CYPHER_PRINT_LOGICAL_PLAN", help="dump the logical plan"
)
PRINT_RELATIONAL = declare_flag(
    "TPU_CYPHER_PRINT_RELATIONAL_PLAN", help="dump the relational plan"
)

# shape bucketing + memory admission (backend/tpu/bucketing.py)
BUCKET_MODE = declare(
    "TPU_CYPHER_BUCKET",
    "off",
    str,
    help="materialize-size bucket lattice: off | pow2 | 1.25",
)
MEM_BUDGET = declare(
    "TPU_CYPHER_MEM_BUDGET",
    0,
    int,
    help="HBM budget (bytes) for any single padded materialize; 0 = off",
)

# execution guard / degrade-and-retry ladder (runtime/guard.py)
LADDER_MODE = declare(
    "TPU_CYPHER_LADDER", "on", str, help="degrade-and-retry ladder: on | off"
)
CHUNK_ROWS = declare(
    "TPU_CYPHER_CHUNK_ROWS",
    65536,
    int,
    help="row slice size at the chunked-gather ladder rung",
)
DEADLINE_S = declare(
    "TPU_CYPHER_QUERY_DEADLINE_S",
    0.0,
    float,
    help="per-query wall deadline in seconds; 0 = none",
)

# deterministic fault injection (runtime/faults.py)
FAULTS = declare(
    "TPU_CYPHER_FAULTS",
    "",
    str,
    help="fault schedule: kind@site[:n|:a-b|:*], comma-separated",
)

# Pallas kernel tier (backend/tpu/pallas/dispatch.py)
PALLAS_MODE = declare(
    "TPU_CYPHER_PALLAS", "auto", str, help="kernel tier: auto | interpret | off"
)

# MXU dense-expand tiers (backend/tpu/expand_op.py)
MXU_DENSE = declare(
    "TPU_CYPHER_MXU_DENSE",
    "auto",
    str,
    help="dense MXU expand: auto | 1 | force | off",
)
MXU_TILED_MAX = declare(
    "TPU_CYPHER_MXU_TILED_MAX",
    1 << 17,
    int,
    help="node-count ceiling for the tiled MXU close-count tier",
)

# MXU dense-adjacency node cap (backend/tpu/graph_index.py dense_adj).
# The effective cap is a CostModel decision (optimizer/cost.py
# mxu_dense_node_cap): a pin here is honored verbatim; otherwise the cap
# is modelled from TPU_CYPHER_MEM_BUDGET when one is set.
MXU_DENSE_MAX = declare(
    "TPU_CYPHER_MXU_DENSE_MAX",
    16384,
    int,
    help="node-count ceiling for the dense MXU adjacency tier "
    "(Npad^2 bf16 per matrix); modelled from the HBM budget unless pinned",
)

# per-kernel Pallas eligibility caps (backend/tpu/pallas/*). Each default
# mirrors the kernel's VMEM working-set budget; the effective cap routes
# through optimizer/cost.pallas_cap so a pin is honored verbatim while the
# unpinned value stays a derived byte-budget decision.
PALLAS_MAX_FRONTIER = declare(
    "TPU_CYPHER_PALLAS_MAX_FRONTIER",
    1 << 18,
    int,
    help="frontier cap for the Pallas expand kernel (resident cum+starts "
    "state, ~8 B per frontier element of a ~2 MiB VMEM budget)",
)
PALLAS_MAX_NODES = declare(
    "TPU_CYPHER_PALLAS_MAX_NODES",
    1 << 20,
    int,
    help="node cap for the Pallas frontier-degree kernel (resident int32 "
    "degree vector, 4 B per node of a ~4 MiB VMEM budget)",
)
PALLAS_MAX_KEYS = declare(
    "TPU_CYPHER_PALLAS_MAX_KEYS",
    1 << 20,
    int,
    help="pow2-padded key cap for the Pallas intersect kernel (two int32 "
    "planes, 8 B per key of an ~8 MiB VMEM budget)",
)
PALLAS_MAX_BUILD = declare(
    "TPU_CYPHER_PALLAS_MAX_BUILD",
    1 << 17,
    int,
    help="build-side cap for the Pallas hash-join kernel (4 int32 table "
    "vectors at load factor 1/2, 32 B per build row of a ~4 MiB budget)",
)
PALLAS_MAX_GROUPS = declare(
    "TPU_CYPHER_PALLAS_MAX_GROUPS",
    256,
    int,
    help="GROUP BY cardinality cap for the Pallas segment-aggregate "
    "kernel (the (k_pad, block) compare matrix budget)",
)

# worst-case-optimal multiway join (backend/tpu/wcoj.py)
WCOJ_MODE = declare(
    "TPU_CYPHER_WCOJ",
    "auto",
    str,
    help="cyclic-pattern multiway intersection: auto (EmptyHeaded-style "
    "eligibility from degree stats) | force | off",
)
WCOJ_MIN_ROWS = declare(
    "TPU_CYPHER_WCOJ_MIN_ROWS",
    4096,
    int,
    help="auto mode routes a cyclic pattern to WCOJ only when the "
    "estimated binary-join intermediate exceeds this many rows",
)

# factorized join intermediates (backend/tpu/factorized.py)
FACTORIZE = declare(
    "TPU_CYPHER_FACTORIZE",
    "auto",
    str,
    help="compressed (prefix x suffix-run) materialize tier for expand and "
    "multiway-join intermediates: auto (only when the flat row set would "
    "bust the admission budget) | force | off",
)
FACTORIZE_CHUNK_ROWS = declare(
    "TPU_CYPHER_FACTORIZE_CHUNK_ROWS",
    131072,
    int,
    help="logical rows decompressed per chunk when a factorized table is "
    "enumerated (collect / one-shot flatten); floor 1024",
)

# cost-based adaptive query optimizer (tpu_cypher/optimizer/)
OPT_MODE = declare(
    "TPU_CYPHER_OPT",
    "auto",
    str,
    help="cost-based join-order optimizer: auto (apply the padded-lattice "
    "cost model's plan when it predicts a win) | syntax (keep the "
    "syntax-driven order — pre-PR-14 behavior) | force (always apply the "
    "model's chosen order, even on ties; differential tests)",
)
OPT_DP_MAX_RELS = declare(
    "TPU_CYPHER_OPT_DP_MAX_RELS",
    8,
    int,
    help="pattern-size ceiling for exact DP join-order enumeration over "
    "connected subpatterns; larger patterns use the greedy fallback",
)
OPT_MARGIN = declare(
    "TPU_CYPHER_OPT_MARGIN",
    0.9,
    float,
    help="auto mode applies a reordered plan only when its modelled cost "
    "is below margin x the syntax-order cost (hysteresis against churning "
    "plans on estimate noise); force ignores the margin",
)
OPT_FEEDBACK = declare(
    "TPU_CYPHER_OPT_FEEDBACK",
    "on",
    str,
    help="adaptive feedback: fold result.profile() span timings and "
    "true-vs-padded row counts back into per-graph calibration factors "
    "(persisted beside the compile cache): on | off",
)

# sharded shuffle (parallel/shuffle.py)
BROADCAST_LIMIT = declare(
    "TPU_CYPHER_BROADCAST_LIMIT",
    4096,
    int,
    help="max rows broadcast to every shard instead of hash-shuffled",
)

# mesh execution (parallel/mesh.py): table algebra runs mesh-native when a
# mesh is active — either via parallel.mesh.use_mesh / CypherSession.tpu(
# mesh=...) or the TPU_CYPHER_MESH env default below
MESH_SPEC = declare(
    "TPU_CYPHER_MESH",
    "",
    str,
    help="default engine mesh: '' / 'off' = single device; 'auto' / 'all' "
    "= one row-sharding mesh over every visible device; an integer N = "
    "mesh over the first N devices",
)
MESH_AGG = declare(
    "TPU_CYPHER_MESH_AGG",
    "auto",
    str,
    help="sharded segment aggregates / distinct-count tier while a mesh "
    "is active: auto (integer data only, bit-identical psum combine) | off",
)
MESH_WCOJ = declare(
    "TPU_CYPHER_MESH_WCOJ",
    "auto",
    str,
    help="sharded WCOJ count tier: each shard range-counts its local "
    "slice of the sorted edge_keys and counts psum-combine: auto | off",
)

# compiler diagnostics (backend/tpu/compiler.py)
ISLAND_WARN_ROWS = declare(
    "TPU_CYPHER_ISLAND_WARN_ROWS",
    1_000_000,
    int,
    help="row count above which a cartesian island emits a warning",
)

# persistent compile cache (relational/session.py)
COMPILE_CACHE_DIR = declare(
    "TPU_CYPHER_COMPILE_CACHE_DIR",
    "",
    str,
    help="persistent XLA compile cache directory; empty = disabled",
)

# multi-tenant query server (serve/): the asyncio front end that admits,
# schedules, and micro-batches concurrent queries on one warm engine
SERVE_PORT = declare(
    "TPU_CYPHER_SERVE_PORT",
    7687,
    int,
    help="query-server TCP port (0 = ephemeral, for tests)",
)
SERVE_MAX_CONCURRENT = declare(
    "TPU_CYPHER_SERVE_MAX_CONCURRENT",
    8,
    int,
    help="max queries executing concurrently; the rest wait in the "
    "cost-ordered admission queue",
)
SERVE_BATCH_WINDOW_MS = declare(
    "TPU_CYPHER_SERVE_BATCH_WINDOW_MS",
    2.0,
    float,
    help="micro-batch coalescing window: same-bucket queries arriving "
    "within it share one device dispatch; 0 = batching off",
)
SERVE_TENANT_QUOTA = declare(
    "TPU_CYPHER_SERVE_TENANT_QUOTA",
    0,
    int,
    help="max in-flight queries per tenant; 0 = no quota (fair-share only)",
)

# fault-isolated multi-process serving (serve/cluster.py): a router fans
# requests out to N supervised engine-worker processes so one libtpu abort
# never takes down every tenant
SERVE_WORKERS = declare(
    "TPU_CYPHER_SERVE_WORKERS",
    0,
    int,
    help="supervised engine-worker processes behind the router; "
    "0 = single-process in-session serving (PR 6 mode)",
)
SERVE_BREAKER_THRESHOLD = declare(
    "TPU_CYPHER_SERVE_BREAKER_THRESHOLD",
    3,
    int,
    help="consecutive worker failures that open its circuit breaker",
)
SERVE_BREAKER_COOLDOWN_S = declare(
    "TPU_CYPHER_SERVE_BREAKER_COOLDOWN_S",
    1.0,
    float,
    help="seconds an open breaker waits before half-open canary probing",
)
SERVE_RESTART_BACKOFF_S = declare(
    "TPU_CYPHER_SERVE_RESTART_BACKOFF_S",
    0.25,
    float,
    help="initial supervisor restart delay for a crashed worker; doubles "
    "per consecutive failure",
)
SERVE_RESTART_BACKOFF_MAX_S = declare(
    "TPU_CYPHER_SERVE_RESTART_BACKOFF_MAX_S",
    5.0,
    float,
    help="exponential restart backoff cap (seconds)",
)
SERVE_HEALTH_INTERVAL_S = declare(
    "TPU_CYPHER_SERVE_HEALTH_INTERVAL_S",
    0.5,
    float,
    help="supervisor liveness/readiness probe period (seconds)",
)
SERVE_DRAIN_TIMEOUT_S = declare(
    "TPU_CYPHER_SERVE_DRAIN_TIMEOUT_S",
    30.0,
    float,
    help="graceful-drain budget: in-flight queries finish, new submits "
    "are rejected typed, workers exit",
)
SERVE_HEDGE_MS = declare(
    "TPU_CYPHER_SERVE_HEDGE_MS",
    0.0,
    float,
    help="hedged-dispatch delay: a read still unanswered after this many "
    "ms is duplicated to a second replica (first reply wins); 0 = off",
)
SERVE_QUEUE_HIGH = declare(
    "TPU_CYPHER_SERVE_QUEUE_HIGH",
    0,
    int,
    help="admission queue-depth shed watermark: deeper queues reject new "
    "queries typed before queueing; 0 = off",
)
SERVE_RETRY_MAX = declare(
    "TPU_CYPHER_SERVE_RETRY_MAX",
    2,
    int,
    help="max replica retries of an idempotent read after WorkerLost",
)

# zero-dispatch result cache + backpressured cursor streaming (serve/)
SERVE_CACHE_BYTES = declare(
    "TPU_CYPHER_SERVE_CACHE_BYTES",
    64 << 20,
    int,
    help="byte budget of the serving-tier result cache (host-side encoded "
    "row pages, LRU-evicted); 0 = cache off",
)
SERVE_STREAM_WINDOW = declare(
    "TPU_CYPHER_SERVE_STREAM_WINDOW",
    4,
    int,
    help="cursor-stream credit window: row pages the server may send "
    "ahead of client 'next' credits before backpressure blocks the cursor",
)
SERVE_STREAM_CHUNK_ROWS = declare(
    "TPU_CYPHER_SERVE_STREAM_CHUNK_ROWS",
    0,
    int,
    help="rows decoded per cursor-stream chunk (the streaming face of the "
    "ladder's chunk machinery); 0 = follow TPU_CYPHER_CHUNK_ROWS",
)

# transactional mutation (storage/): write-ahead-log durability and
# delta-overlay compaction (docs/mutation.md)
WAL_DIR = declare(
    "TPU_CYPHER_WAL_DIR",
    "",
    str,
    help="write-ahead log directory; empty = derive '<compile cache>/wal' "
    "when a persistent compile cache is configured, else mutations are "
    "in-memory only (no durability)",
)
WAL_SYNC = declare(
    "TPU_CYPHER_WAL_SYNC",
    "fsync",
    str,
    help="WAL commit durability: fsync (default, survives SIGKILL and "
    "power loss) | flush (OS buffers only: survives SIGKILL, not power "
    "loss) | off (test-only, no flush at commit)",
)
COMPACT_DELTA_MAX = declare(
    "TPU_CYPHER_COMPACT_DELTA_MAX",
    256,
    int,
    help="delta-overlay row threshold: a committed batch leaving more "
    "than this many live+tombstone delta rows triggers compaction into a "
    "fresh immutable base",
)
COMPACT_MIN_BUCKET = declare(
    "TPU_CYPHER_COMPACT_MIN_BUCKET",
    8,
    int,
    help="minimum row bucket a delta-overlay table is host-padded to when "
    "shape bucketing is on, so small deltas share one program shape "
    "across write batches",
)

# observability (obs/metrics.py, utils/profiling.py, obs/trace.py)
METRICS_FILE = declare(
    "TPU_CYPHER_METRICS_FILE",
    "",
    str,
    help="JSON-lines per-query event sink; empty = disabled",
)
PROFILE_DIR = declare(
    "TPU_CYPHER_PROFILE_DIR",
    "",
    str,
    help="jax.profiler trace directory; also annotates spans",
)

"""Typed config flags backed by environment variables.

Re-design of the reference's ``ConfigOption``/``ConfigFlag`` system
(``okapi-api/.../impl/configuration/ConfigOption.scala:31-60``; per-layer flag
objects like ``CoraConfiguration.scala:33-39``): JVM system properties become
environment variables with in-process overrides."""

from __future__ import annotations

import os
from typing import Callable, Generic, Optional, TypeVar

T = TypeVar("T")


class ConfigOption(Generic[T]):
    def __init__(self, name: str, default: T, parse: Callable[[str], T]):
        self.name = name
        self.default = default
        self.parse = parse
        self._override: Optional[T] = None

    def get(self) -> T:
        if self._override is not None:
            return self._override
        raw = os.environ.get(self.name)
        if raw is None:
            return self.default
        try:
            return self.parse(raw)
        except ValueError:
            return self.default

    def set(self, value: T):
        self._override = value

    def reset(self):
        self._override = None


def _parse_bool(s: str) -> bool:
    return s.strip().lower() in ("1", "true", "yes", "on")


class ConfigFlag(ConfigOption[bool]):
    def __init__(self, name: str, default: bool = False):
        super().__init__(name, default, _parse_bool)


# per-stage debug flags (reference PrintTimings / PrintIr / PrintLogicalPlan /
# PrintRelationalPlan / PrintOptimizedRelationalPlan, Configuration.scala:36,
# CoraConfiguration.scala:33-39)
PRINT_TIMINGS = ConfigFlag("TPU_CYPHER_PRINT_TIMINGS")
PRINT_IR = ConfigFlag("TPU_CYPHER_PRINT_IR")
PRINT_LOGICAL = ConfigFlag("TPU_CYPHER_PRINT_LOGICAL_PLAN")
PRINT_RELATIONAL = ConfigFlag("TPU_CYPHER_PRINT_RELATIONAL_PLAN")

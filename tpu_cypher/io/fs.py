"""Filesystem graph persistence.

Re-design of the reference's FS data sources
(``morpheus/.../api/io/fs/FSGraphSource.scala``,
``AbstractPropertyGraphDataSource.scala:73-190``,
``GraphDirectoryStructure.scala:85``). Same directory layout:

    <root>/<graphName>/propertyGraphSchema.json
    <root>/<graphName>/metadata.json
    <root>/<graphName>/nodes/<labelCombo>/part.<fmt>
    <root>/<graphName>/relationships/<relType>/part.<fmt>

Formats: ``parquet`` (pyarrow, default — typed, null-safe) and ``csv``
(lists/maps stored as JSON strings). Node tables are canonical: column
``id`` plus one column per property key; relationship tables add ``source``
and ``target``. The schema JSON mirrors the reference's upickle
serialization (``JsonSerialization.scala``) with our type-string lattice.
"""

from __future__ import annotations

import json
import os
import shutil
import urllib.parse
from typing import Dict, List, Optional, Tuple

import numpy as np
import pandas as pd

from ..api import types as T
from ..api.mapping import NodeMapping, RelationshipMapping
from ..api.schema import PropertyGraphSchema
from ..api.values import Duration
from ..ir import expr as E
from ..relational.graphs import ElementTable, ScanGraph
from .datasource import DataSourceError, PropertyGraphDataSource

SCHEMA_FILE = "propertyGraphSchema.json"
METADATA_FILE = "metadata.json"


def _escape_label(label: str) -> str:
    # '_' is the combo separator and '.' enables '..' path traversal; quote()
    # leaves both unescaped, so escape them by hand — {'A','B_C'} vs
    # {'A_B','C'} stay distinct and (:`..`) cannot climb out of the graph dir
    return (
        urllib.parse.quote(label, safe="").replace("_", "%5F").replace(".", "%2E")
    )


def _combo_dir(labels) -> str:
    return "_".join(_escape_label(l) for l in sorted(labels)) or "__no_label__"


def _rel_dir(rel_type: str) -> str:
    return _escape_label(rel_type)


# ---------------------------------------------------------------------------
# canonical tables <-> pandas
# ---------------------------------------------------------------------------


def canonical_node_columns(graph, combo, ctx) -> Tuple[pd.DataFrame, Dict[str, T.CypherType]]:
    """Rows whose label set is EXACTLY ``combo``, as columns id + props
    (reference ``MorpheusGraphExport.canonicalNodeTable``)."""
    from ..relational.ops import FilterOp

    op = graph.scan_operator("n", T.CTNodeType(frozenset(combo)), ctx)
    h = op.header
    v = h.var("n")
    # exact-combo filter: all other labels false
    for e in h.labels_for(v):
        if e.label not in combo:
            op = FilterOp(op, E.Not(e).with_type(T.CTBoolean))
    h = op.header
    prop_types = graph.schema.node_property_keys(frozenset(combo))
    pairs = [(h.column(h.id_expr(v)), "id")]
    for e in h.properties_for(v):
        if e.key in prop_types:
            pairs.append((h.column(e), e.key))
    t = op.table.project(pairs)
    return _table_to_pandas(t), {"id": T.CTInteger, **prop_types}


def canonical_rel_columns(graph, rel_type: str, ctx) -> Tuple[pd.DataFrame, Dict[str, T.CypherType]]:
    op = graph.scan_operator("r", T.CTRelationshipType(frozenset({rel_type})), ctx)
    h = op.header
    v = h.var("r")
    start = next(e for e in h.expressions_for(v) if isinstance(e, E.StartNode))
    end = next(e for e in h.expressions_for(v) if isinstance(e, E.EndNode))
    prop_types = graph.schema.relationship_property_keys(rel_type)
    pairs = [
        (h.column(h.id_expr(v)), "id"),
        (h.column(start), "source"),
        (h.column(end), "target"),
    ]
    for e in h.properties_for(v):
        if e.key in prop_types:
            pairs.append((h.column(e), e.key))
    t = op.table.project(pairs)
    return _table_to_pandas(t), {
        "id": T.CTInteger,
        "source": T.CTInteger,
        "target": T.CTInteger,
        **prop_types,
    }


def _table_to_pandas(t) -> pd.DataFrame:
    cols: Dict[str, List] = {c: [] for c in t.physical_columns}
    for row in t.rows():
        for c in cols:
            cols[c].append(row.get(c))
    return pd.DataFrame(cols, columns=list(cols))


def _pandas_to_values(df: pd.DataFrame, types: Dict[str, T.CypherType]) -> Dict[str, List]:
    out: Dict[str, List] = {}
    for c in df.columns:
        t = types.get(c)
        mat = t.material if t is not None else None
        vals = []
        for v in df[c].tolist():
            if v is None or (np.isscalar(v) and isinstance(v, float) and np.isnan(v)):
                vals.append(None)
            elif mat is T.CTInteger or c in ("id", "source", "target"):
                vals.append(int(v))
            elif mat is T.CTFloat:
                vals.append(float(v))
            elif mat is T.CTBoolean:
                vals.append(bool(v))
            elif mat is T.CTString:
                vals.append(str(v))
            elif isinstance(v, np.ndarray):
                vals.append(v.tolist())
            else:
                vals.append(v)
        out[c] = vals
    return out


# ---------------------------------------------------------------------------
# serialization of exotic values for parquet/csv
# ---------------------------------------------------------------------------

_JSON_TAG = "__tpu_cypher_json__:"


def _encode_cell(v):
    import datetime as _dt

    if isinstance(v, Duration):
        return _JSON_TAG + json.dumps(
            {"__duration__": [v.months, v.days, v.seconds, v.microseconds]}
        )
    if isinstance(v, _dt.datetime):
        return _JSON_TAG + json.dumps({"__localdatetime__": v.isoformat()})
    if isinstance(v, _dt.date):
        return _JSON_TAG + json.dumps({"__date__": v.isoformat()})
    if isinstance(v, (list, tuple, dict)):
        return _JSON_TAG + json.dumps(v)
    if isinstance(v, str):
        # protects CSV strings from NA-token mangling ('NA', 'null', '')
        return _JSON_TAG + json.dumps(v)
    return v


def _decode_cell(v):
    import datetime as _dt

    if isinstance(v, str) and v.startswith(_JSON_TAG):
        doc = json.loads(v[len(_JSON_TAG):])
        if isinstance(doc, dict) and "__duration__" in doc:
            m, d, s, us = doc["__duration__"]
            return Duration(m, d, s, us)
        if isinstance(doc, dict) and "__date__" in doc:
            return _dt.date.fromisoformat(doc["__date__"])
        if isinstance(doc, dict) and "__localdatetime__" in doc:
            return _dt.datetime.fromisoformat(doc["__localdatetime__"])
        return doc
    return v


def _needs_encoding(t: Optional[T.CypherType], csv: bool = False) -> bool:
    if t is None:
        return True
    m = t.material
    if csv and m is T.CTString:
        # CSV cannot distinguish null from 'NA'/'null'/'NaN'/'' — JSON-wrap
        return True
    return not (
        m is T.CTInteger or m is T.CTFloat or m is T.CTBoolean or m is T.CTString
    )


# ---------------------------------------------------------------------------
# the data source
# ---------------------------------------------------------------------------


class FSGraphSource(PropertyGraphDataSource):
    """Parquet/CSV graph persistence with the reference's directory layout."""

    def __init__(self, root: str, fmt: str = "parquet"):
        if fmt not in ("parquet", "csv"):
            raise DataSourceError(f"Unsupported format {fmt!r}")
        self.root = root
        self.fmt = fmt
        os.makedirs(root, exist_ok=True)

    # -- helpers -----------------------------------------------------------

    def _graph_dir(self, name: str) -> str:
        return os.path.join(self.root, urllib.parse.quote(name, safe=""))

    def _part(self, d: str, fmt: Optional[str] = None) -> str:
        return os.path.join(d, f"part.{fmt or self.fmt}")

    def _write_df(self, df: pd.DataFrame, types: Dict[str, T.CypherType], path: str):
        os.makedirs(os.path.dirname(path), exist_ok=True)
        df = df.copy()
        for c in df.columns:
            if _needs_encoding(types.get(c), csv=self.fmt == "csv"):
                df[c] = [
                    None if v is None else _encode_cell(v) for v in df[c].tolist()
                ]
        if self.fmt == "parquet":
            df.to_parquet(path, index=False)
        else:
            df.to_csv(path, index=False, na_rep="")

    def _read_df(
        self, path: str, types: Dict[str, T.CypherType], fmt: Optional[str] = None
    ) -> pd.DataFrame:
        fmt = fmt or self.fmt
        if not os.path.isfile(path):
            raise DataSourceError(f"Missing graph table file {path}")
        if fmt == "parquet":
            df = pd.read_parquet(path)
        else:
            df = pd.read_csv(path, keep_default_na=True)
            df = df.astype(object).where(pd.notnull(df), None)
        for c in df.columns:
            if _needs_encoding(types.get(c), csv=fmt == "csv"):
                df[c] = [
                    None if v is None else _decode_cell(v) for v in df[c].tolist()
                ]
        return df

    def _stored_format(self, name: str) -> str:
        """The format the graph was written with (``metadata.json``) — reads
        succeed even when the source is configured with the other format."""
        p = os.path.join(self._graph_dir(name), METADATA_FILE)
        if os.path.isfile(p):
            with open(p) as f:
                fmt = json.load(f).get("format")
            if fmt in ("parquet", "csv"):
                return fmt
        return self.fmt

    # -- PGDS --------------------------------------------------------------

    def has_graph(self, name: str) -> bool:
        return os.path.isfile(os.path.join(self._graph_dir(name), SCHEMA_FILE))

    def graph_names(self) -> List[str]:
        if not os.path.isdir(self.root):
            return []
        return sorted(
            urllib.parse.unquote(d)
            for d in os.listdir(self.root)
            if os.path.isfile(os.path.join(self.root, d, SCHEMA_FILE))
        )

    def schema(self, name: str) -> Optional[PropertyGraphSchema]:
        p = os.path.join(self._graph_dir(name), SCHEMA_FILE)
        if not os.path.isfile(p):
            return None
        with open(p) as f:
            return PropertyGraphSchema.from_json(f.read())

    def store(self, name: str, graph) -> None:
        if self.has_graph(name):
            raise DataSourceError(f"Graph {name!r} already exists; delete it first")
        d = self._graph_dir(name)
        schema = graph.schema
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, SCHEMA_FILE), "w") as f:
            f.write(schema.to_json())
        with open(os.path.join(d, METADATA_FILE), "w") as f:
            json.dump({"format": self.fmt, "version": 1}, f)
        ctx = _plain_ctx(graph)
        # table EXTRACTION stays serial (it drives the device); each file
        # WRITE is submitted to a thread pool AS extracted, so at most
        # pool-depth DataFrames are live at once and failures propagate
        # after all complete — the reference's async write discipline
        # (``AbstractPropertyGraphDataSource.scala:186``)
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=8) as pool:
            futures = []
            for combo in schema.label_combinations:
                df, types = canonical_node_columns(graph, combo, ctx)
                path = self._part(os.path.join(d, "nodes", _combo_dir(combo)))
                futures.append(pool.submit(self._write_df, df, types, path))
                del df
            for rt in schema.relationship_types:
                df, types = canonical_rel_columns(graph, rt, ctx)
                path = self._part(
                    os.path.join(d, "relationships", _rel_dir(rt))
                )
                futures.append(pool.submit(self._write_df, df, types, path))
                del df
            for f in futures:
                f.result()  # re-raises the worker's exception

    def graph(self, name: str, session):
        schema = self.schema(name)
        if schema is None:
            raise DataSourceError(f"Graph {name!r} not found under {self.root}")
        d = self._graph_dir(name)
        fmt = self._stored_format(name)
        tables: List[ElementTable] = []
        for combo in schema.label_combinations:
            prop_types = schema.node_property_keys(combo)
            types = {"id": T.CTInteger, **prop_types}
            df = self._read_df(
                self._part(os.path.join(d, "nodes", _combo_dir(combo)), fmt), types, fmt
            )
            cols = _pandas_to_values(df, types)
            mapping = NodeMapping(
                id_key="id",
                implied_labels=frozenset(combo),
                property_mapping=tuple(sorted((k, k) for k in prop_types)),
            )
            tables.append(ElementTable(mapping, session.table_cls.from_columns(cols)))
        for rt in schema.relationship_types:
            prop_types = schema.relationship_property_keys(rt)
            types = {
                "id": T.CTInteger,
                "source": T.CTInteger,
                "target": T.CTInteger,
                **prop_types,
            }
            df = self._read_df(
                self._part(os.path.join(d, "relationships", _rel_dir(rt)), fmt),
                types,
                fmt,
            )
            cols = _pandas_to_values(df, types)
            mapping = RelationshipMapping(
                id_key="id",
                source_key="source",
                target_key="target",
                rel_type=rt,
                property_mapping=tuple(sorted((k, k) for k in prop_types)),
            )
            tables.append(ElementTable(mapping, session.table_cls.from_columns(cols)))
        return ScanGraph(tables, schema)

    def delete(self, name: str) -> None:
        d = self._graph_dir(name)
        if os.path.isdir(d):
            shutil.rmtree(d)


def _plain_ctx(graph):
    """Runtime context for canonical-table extraction: the table factory is
    taken from the graph's own tables so empty scans (e.g. a union member
    lacking a relationship type) build tables of the right backend."""
    from ..relational.ops import RelationalRuntimeContext

    return RelationalRuntimeContext(
        resolve_graph=lambda qgn: None,
        parameters={},
        table_cls=_graph_table_cls(graph),
    )


def _graph_table_cls(graph):
    cls = _find_table_cls(graph)
    if cls is not None:
        return cls
    from ..backend.local.table import LocalTable

    return LocalTable


def _find_table_cls(graph):
    scans = getattr(graph, "scans", None)
    if scans:
        return type(scans[0].table)
    for member in getattr(graph, "members", []) or []:
        cls = _find_table_cls(member)
        if cls is not None:
            return cls
    inner = getattr(graph, "graph", None)
    if inner is not None:
        return _find_table_cls(inner)
    return None

"""Edge-list graph source.

Re-design of the reference ``EdgeListDataSource``
(``morpheus/.../api/io/EdgeListDataSource.scala:42-110``): loads SNAP-style
``src dst`` whitespace/comma-separated edge lists as the fixed schema
``(:V)-[:E]->(:V)``. Lines starting with ``#`` are comments. Node ids are
the union of endpoint ids; edge ids are the line index tagged into a
separate range so they never collide with node ids (both live in the same
int64 id space)."""

from __future__ import annotations

import os
from typing import List, Optional

import numpy as np

from ..api.mapping import NodeMapping, RelationshipMapping
from ..api.schema import PropertyGraphSchema
from ..relational.graphs import ElementTable, ScanGraph
from .datasource import DataSourceError, PropertyGraphDataSource

NODE_LABEL = "V"
REL_TYPE = "E"

# edge ids are offset into the top half of the non-tagged id space so they
# never collide with node ids (graph tags live in bits 54+, see PrefixId)
EDGE_ID_OFFSET = 1 << 53


def load_edge_list(path: str, session, delimiter: Optional[str] = None) -> ScanGraph:
    src_a: Optional[np.ndarray] = None
    if delimiter is None:  # native fast path handles the default format
        from ..native import parse_edge_list_native

        with open(path, "rb") as fb:
            data = fb.read()
        try:
            parsed = parse_edge_list_native(data)
        except ValueError as e:
            raise DataSourceError(f"Malformed edge list {path!r}: {e}")
        if parsed is not None:
            src_a, dst_a = parsed
    if src_a is None:
        src: List[int] = []
        dst: List[int] = []
        with open(path) as f:
            for lineno, line in enumerate(f, start=1):
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                parts = line.replace(",", " ").split() if delimiter is None else line.split(delimiter)
                try:
                    src.append(int(parts[0]))
                    dst.append(int(parts[1]))
                except (IndexError, ValueError) as e:
                    raise DataSourceError(
                        f"Malformed edge-list line {lineno} in {path!r}: {line!r} ({e})"
                    )
        src_a = np.asarray(src, dtype=np.int64)
        dst_a = np.asarray(dst, dtype=np.int64)
    node_ids = np.unique(np.concatenate([src_a, dst_a])) if len(src_a) else np.zeros(0, np.int64)
    if len(src_a) and int(node_ids.max(initial=0)) >= EDGE_ID_OFFSET:
        raise DataSourceError("Edge-list node ids exceed the supported id range")
    edge_ids = np.arange(len(src_a), dtype=np.int64) + EDGE_ID_OFFSET

    node_table = session.table_cls.from_columns({"id": node_ids.tolist()})
    rel_table = session.table_cls.from_columns(
        {
            "id": edge_ids.tolist(),
            "source": src_a.tolist(),
            "target": dst_a.tolist(),
        }
    )
    schema = (
        PropertyGraphSchema.empty()
        .with_node_combination(frozenset({NODE_LABEL}), {})
        .with_relationship_type(REL_TYPE, {})
    )
    return ScanGraph(
        [
            ElementTable(
                NodeMapping(id_key="id", implied_labels=frozenset({NODE_LABEL})),
                node_table,
            ),
            ElementTable(
                RelationshipMapping(
                    id_key="id", source_key="source", target_key="target", rel_type=REL_TYPE
                ),
                rel_table,
            ),
        ],
        schema,
    )


class EdgeListDataSource(PropertyGraphDataSource):
    """Maps graph names to ``<root>/<name>`` edge-list files."""

    def __init__(self, root: str, delimiter: Optional[str] = None):
        self.root = root
        self.delimiter = delimiter

    def _path(self, name: str) -> str:
        return os.path.join(self.root, name)

    def has_graph(self, name: str) -> bool:
        return os.path.isfile(self._path(name))

    def graph_names(self) -> List[str]:
        if not os.path.isdir(self.root):
            return []
        return sorted(f for f in os.listdir(self.root) if os.path.isfile(self._path(f)))

    def schema(self, name: str):
        return (
            PropertyGraphSchema.empty()
            .with_node_combination(frozenset({NODE_LABEL}), {})
            .with_relationship_type(REL_TYPE, {})
        )

    def graph(self, name: str, session):
        if not self.has_graph(name):
            raise DataSourceError(f"No edge list {name!r} under {self.root}")
        return load_edge_list(self._path(name), session, self.delimiter)

    def store(self, name: str, graph) -> None:
        raise DataSourceError("EdgeListDataSource is read-only")

    def delete(self, name: str) -> None:
        raise DataSourceError("EdgeListDataSource is read-only")

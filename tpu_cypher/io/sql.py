"""SQL-table property-graph data source driven by Graph DDL.

Re-design of the reference SQL PGDS
(``morpheus-spark-cypher/.../api/io/sql/SqlPropertyGraphDataSource.scala:75-330``
with ``IdGenerationStrategy.scala:29``): existing "SQL" tables (here: in-memory
column dicts or parquet/CSV files — the TPU framework ingests host-side and
ships shards to the device) are mapped onto property graphs by a
:class:`~tpu_cypher.graph_ddl.GraphDdl` document.

Element ids (reference ``IdGenerationStrategy``):

* ``HASHED_ID`` — 63-bit content hash of (view key, id-column values); node ids
  are recomputed on the edge side from the JOIN ON columns, so no host join is
  needed (the reference's ``HashedId`` hash64 strategy).
* ``SERIALIZED_ID`` — monotonically increasing ids per view (reference
  ``SerializedId``); edge endpoint ids are resolved by a host-side hash join of
  the edge's join columns against the node view.
"""

from __future__ import annotations

import enum
import hashlib
import os
from typing import Dict, List, Optional, Sequence, Tuple

from ..api import types as T
from ..api.mapping import NodeMapping, RelationshipMapping
from ..api.schema import PropertyGraphSchema
from ..graph_ddl.model import (
    EdgeToViewMapping,
    Graph,
    GraphDdl,
    GraphDdlError,
    NodeToViewMapping,
    NodeViewKey,
    ViewId,
)
from .datasource import DataSourceError, PropertyGraphDataSource

Columns = Dict[str, list]


class IdGenerationStrategy(enum.Enum):
    HASHED_ID = "hashed"
    SERIALIZED_ID = "serialized"


def hash64(*parts) -> int:
    """Deterministic 63-bit content hash (the reference uses xxhash via
    ``MorpheusFunctions.hash64``, ``MorpheusFunctions.scala:91``; any stable
    64-bit mix works — we use blake2b-8)."""
    h = hashlib.blake2b(digest_size=8)
    for p in parts:
        h.update(repr(p).encode())
        h.update(b"\x1f")
    return int.from_bytes(h.digest(), "big") & 0x7FFF_FFFF_FFFF_FFFF


class SqlTableProvider:
    """Resolves ``schema.view`` names to host tables (column dicts)."""

    def table(self, schema: str, view: str) -> Columns:
        raise NotImplementedError


class InMemoryTables(SqlTableProvider):
    """Tables registered as ``{"schema.view": {col: [values]}}`` — the analog
    of the reference's Hive/H2 fixtures for tests and notebooks."""

    def __init__(self, tables: Dict[str, Columns]):
        self._tables = tables

    def table(self, schema: str, view: str) -> Columns:
        key = f"{schema}.{view}"
        if key not in self._tables:
            raise DataSourceError(
                f"View {key!r} not registered; known: {sorted(self._tables)}"
            )
        cols = self._tables[key]
        n = len(next(iter(cols.values()))) if cols else 0
        for c, vs in cols.items():
            if len(vs) != n:
                raise DataSourceError(f"Ragged column {c!r} in view {key!r}")
        return cols


class FileTables(SqlTableProvider):
    """Tables stored as ``<root>/<schema>/<view>.(parquet|csv)`` (reference
    ``SqlDataSourceConfig.File``/``readFile``,
    ``SqlPropertyGraphDataSource.scala:279``)."""

    def __init__(self, root: str, fmt: str = "parquet"):
        if fmt not in ("parquet", "csv"):
            raise DataSourceError(f"Unsupported format {fmt!r}")
        self.root = root
        self.fmt = fmt

    def table(self, schema: str, view: str) -> Columns:
        import pandas as pd

        path = os.path.join(self.root, schema, f"{view}.{self.fmt}")
        if not os.path.isfile(path):
            raise DataSourceError(f"No table file at {path}")
        if self.fmt == "parquet":
            df = pd.read_parquet(path)
        else:
            df = pd.read_csv(path)
        df = df.astype(object).where(pd.notnull(df), None)
        return {c: df[c].tolist() for c in df.columns}


class SqlPropertyGraphDataSource(PropertyGraphDataSource):
    """Maps SQL-style tables to property graphs via Graph DDL (reference
    ``SqlPropertyGraphDataSource.scala:75``)."""

    def __init__(
        self,
        graph_ddl: GraphDdl,
        data_sources: Dict[str, SqlTableProvider],
        id_strategy: IdGenerationStrategy = IdGenerationStrategy.HASHED_ID,
    ):
        if isinstance(graph_ddl, str):
            graph_ddl = GraphDdl.parse(graph_ddl)
        self.graph_ddl = graph_ddl
        self.data_sources = data_sources
        self.id_strategy = id_strategy

    # -- PGDS interface ----------------------------------------------------

    def has_graph(self, name: str) -> bool:
        return name in self.graph_ddl.graphs

    def graph_names(self) -> List[str]:
        return sorted(self.graph_ddl.graphs)

    def schema(self, name: str) -> Optional[PropertyGraphSchema]:
        g = self.graph_ddl.graphs.get(name)
        return g.schema if g is not None else None

    def store(self, name: str, graph) -> None:
        raise DataSourceError("SqlPropertyGraphDataSource does not support store")

    def delete(self, name: str) -> None:
        raise DataSourceError("SqlPropertyGraphDataSource does not support delete")

    def graph(self, name: str, session):
        from ..relational.graphs import ElementTable, ScanGraph

        ddl_graph = self.graph_ddl.graphs.get(name)
        if ddl_graph is None:
            raise DataSourceError(f"Graph {name!r} not declared in DDL")
        schema = ddl_graph.schema
        tables: List[ElementTable] = []
        # serialized ids must be globally unique across views: per-view offsets
        offsets = _SerialOffsets()
        node_index: Dict[NodeViewKey, Dict[Tuple, int]] = {}

        for nvm in ddl_graph.node_to_view_mappings:
            cols = self._read_view(nvm.view)
            id_cols = self._node_id_columns(ddl_graph, nvm, cols)
            ids = self._generate_ids(nvm.key, cols, id_cols, offsets)
            if self.id_strategy is IdGenerationStrategy.SERIALIZED_ID:
                node_index[nvm.key] = _key_index(cols, id_cols, ids)
            out: Columns = {"$id": ids}
            for prop, col in nvm.property_mappings:
                out[f"$p_{prop}"] = _require_column(cols, col, nvm.view)
            mapping = NodeMapping(
                id_key="$id",
                implied_labels=nvm.node_type.labels,
                property_mapping=tuple(
                    (prop, f"$p_{prop}") for prop, _ in nvm.property_mappings
                ),
            )
            tables.append(ElementTable(mapping, session.table_cls.from_columns(out)))

        for evm in ddl_graph.edge_to_view_mappings:
            if len(evm.rel_type.labels) != 1:
                raise GraphDdlError(
                    f"Single relationship type required, got {sorted(evm.rel_type.labels)}"
                )
            (rel_label,) = evm.rel_type.labels
            cols = self._read_view(evm.view)
            n = _num_rows(cols)
            ids = self._generate_ids(
                evm.key, cols, tuple(sorted(cols)) or (), offsets
            )
            src = self._endpoint_ids(
                ddl_graph, evm.start_node.node_view_key,
                evm.start_node.join_predicates, cols, node_index, evm.view,
            )
            dst = self._endpoint_ids(
                ddl_graph, evm.end_node.node_view_key,
                evm.end_node.join_predicates, cols, node_index, evm.view,
            )
            out = {"$id": ids, "$source": src, "$target": dst}
            for prop, col in evm.property_mappings:
                out[f"$p_{prop}"] = _require_column(cols, col, evm.view)
            mapping = RelationshipMapping(
                id_key="$id",
                source_key="$source",
                target_key="$target",
                rel_type=rel_label,
                property_mapping=tuple(
                    (prop, f"$p_{prop}") for prop, _ in evm.property_mappings
                ),
            )
            assert len(src) == n and len(dst) == n
            tables.append(ElementTable(mapping, session.table_cls.from_columns(out)))

        return ScanGraph(tables, schema)

    # -- helpers -----------------------------------------------------------

    def _read_view(self, vid: ViewId) -> Columns:
        ds, schema, view = vid.resolved
        provider = self.data_sources.get(ds)
        if provider is None:
            raise DataSourceError(
                f"Data source {ds!r} not configured; known: {sorted(self.data_sources)}"
            )
        return provider.table(schema, view)

    def _node_id_columns(
        self, g: Graph, nvm: NodeToViewMapping, cols: Columns
    ) -> Tuple[str, ...]:
        """Identity columns of a node view: the JOIN ON columns of the first
        referencing edge mapping, else all columns (reference
        ``SqlPropertyGraphDataSource.extractNode``, ``:200-207``)."""
        id_cols = g.node_id_columns_for(nvm.key)
        if id_cols is None:
            id_cols = tuple(sorted(cols))
        return id_cols

    def _generate_ids(
        self,
        view_key,
        cols: Columns,
        id_cols: Sequence[str],
        offsets: "_SerialOffsets",
    ) -> List[int]:
        n = _num_rows(cols)
        if self.id_strategy is IdGenerationStrategy.SERIALIZED_ID:
            base = offsets.claim(str(view_key), n)
            return list(range(base, base + n))
        key_cols = [_require_column(cols, c, view_key) for c in id_cols]
        tag = str(view_key)
        return [hash64(tag, *(kc[i] for kc in key_cols)) for i in range(n)]

    def _endpoint_ids(
        self,
        g: Graph,
        node_key: NodeViewKey,
        joins,
        edge_cols: Columns,
        node_index: Dict[NodeViewKey, Dict[Tuple, int]],
        edge_view: ViewId,
    ) -> List[int]:
        n = _num_rows(edge_cols)
        # order edge join columns to match the node view's id-column order
        node_id_cols = g.node_id_columns_for(node_key) or ()
        by_node_col = {j.node_column: j.edge_column for j in joins}
        try:
            edge_join_cols = [by_node_col[c] for c in node_id_cols]
        except KeyError as e:
            raise GraphDdlError(
                f"Edge view {edge_view} joins to {node_key} on columns "
                f"{sorted(by_node_col)} but the node view is identified by "
                f"{list(node_id_cols)} (missing {e})"
            )
        key_cols = [_require_column(edge_cols, c, edge_view) for c in edge_join_cols]
        if self.id_strategy is IdGenerationStrategy.HASHED_ID:
            tag = str(node_key)
            return [hash64(tag, *(kc[i] for kc in key_cols)) for i in range(n)]
        index = node_index.get(node_key)
        if index is None:
            raise GraphDdlError(f"No node mapping materialized for {node_key}")
        out: List[int] = []
        for i in range(n):
            key = tuple(kc[i] for kc in key_cols)
            if key not in index:
                raise DataSourceError(
                    f"Edge view {edge_view} references missing node {key} in {node_key}"
                )
            out.append(index[key])
        return out


class _SerialOffsets:
    """Allocates disjoint contiguous id ranges per view (the reference's
    partitioned monotonic ids, ``MorpheusFunctions.scala:76``)."""

    def __init__(self):
        self._next = 0
        self._claimed: Dict[str, int] = {}

    def claim(self, key: str, n: int) -> int:
        if key in self._claimed:
            return self._claimed[key]
        base = self._next
        self._claimed[key] = base
        self._next += n
        return base


def _key_index(cols: Columns, key_cols: Sequence[str], ids: List[int]) -> Dict[Tuple, int]:
    """Host-side join index: id-column values tuple → generated id."""
    key_vals = [cols[c] for c in key_cols]
    return {
        tuple(kc[i] for kc in key_vals): ids[i] for i in range(len(ids))
    }


def _num_rows(cols: Columns) -> int:
    return len(next(iter(cols.values()))) if cols else 0


def _require_column(cols: Columns, name: str, where) -> list:
    if name not in cols:
        raise DataSourceError(
            f"Column {name!r} not found in view {where}; has {sorted(cols)}"
        )
    return cols[name]

"""Neo4j IO: bulk-import CSV sink, read/write query builders, gated PGDS.

Re-design of the reference's Neo4j integration:

* ``okapi-neo4j-io/.../ElementReader.scala:34`` — per-label-combination and
  per-relationship-type read queries (built here as plain strings, testable
  without a server)
* ``okapi-neo4j-io/.../SchemaFromProcedure.scala:39`` — schema via the
  ``db.schema.nodeTypeProperties`` / ``relTypeProperties`` procedures
* ``morpheus/.../sync/Neo4jGraphMerge.scala:53,77,132`` — delta write-back:
  ``CREATE INDEX`` on element keys + batched ``UNWIND $batch ... MERGE``
* ``morpheus/.../Neo4jBulkCSVDataSink.scala`` — export in the
  ``neo4j-admin import`` bulk format plus a parameterized ``import.sh``

The live driver connection is OPTIONAL: ``Neo4jPropertyGraphDataSource``
gates on the ``neo4j`` Python package at call time with a clear error; every
query-construction path and the bulk CSV sink are fully functional without
it.
"""

from __future__ import annotations

import csv
import os
import re
import stat
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence

from ..api import types as T
from ..api.schema import PropertyGraphSchema
from .datasource import DataSourceError, PropertyGraphDataSource

ID_KEY = "___id"
START_KEY = "___source"
END_KEY = "___target"

_SAFE_NAME = re.compile(r"[^A-Za-z0-9_]")


# ---------------------------------------------------------------------------
# connection config + driver gate
# ---------------------------------------------------------------------------


@dataclass
class Neo4jConfig:
    """Reference ``Neo4jConfig.scala``."""

    uri: str = "bolt://localhost:7687"
    user: str = "neo4j"
    password: Optional[str] = None
    database: str = "neo4j"


def _require_driver():
    try:
        import neo4j  # type: ignore

        return neo4j
    except ImportError as e:  # pragma: no cover - driver not in test image
        raise DataSourceError(
            "The Neo4j data source needs the optional 'neo4j' Python driver "
            "(pip install neo4j). Query construction and the bulk CSV sink "
            "(Neo4jBulkCSVDataSink) work without it."
        ) from e


# ---------------------------------------------------------------------------
# read-side query builders (ElementReader.scala:34)
# ---------------------------------------------------------------------------


def _label_predicate(labels: Iterable[str]) -> str:
    return "".join(f":`{l}`" for l in sorted(labels))


def exact_label_match_query(labels: Sequence[str], prop_keys: Sequence[str]) -> str:
    """Rows whose label set is EXACTLY ``labels``
    (reference ``flatExactLabelQuery``)."""
    props = "".join(f", n.`{k}`" for k in sorted(prop_keys))
    return (
        f"MATCH (n{_label_predicate(labels)}) "
        f"WHERE size(labels(n)) = {len(set(labels))} "
        f"RETURN id(n) AS {ID_KEY}{props}"
    )


def rel_type_query(rel_type: str, prop_keys: Sequence[str]) -> str:
    """Reference ``flatRelTypeQuery``."""
    props = "".join(f", r.`{k}`" for k in sorted(prop_keys))
    return (
        f"MATCH (s)-[r:`{rel_type}`]->(t) "
        f"RETURN id(r) AS {ID_KEY}, id(s) AS {START_KEY}, "
        f"id(t) AS {END_KEY}{props}"
    )


NODE_SCHEMA_PROCEDURE = "db.schema.nodeTypeProperties"
REL_SCHEMA_PROCEDURE = "db.schema.relTypeProperties"


def node_schema_query() -> str:
    return f"CALL {NODE_SCHEMA_PROCEDURE}()"


def rel_schema_query() -> str:
    return f"CALL {REL_SCHEMA_PROCEDURE}()"


# ---------------------------------------------------------------------------
# write-side statement builders (Neo4jGraphMerge.scala)
# ---------------------------------------------------------------------------


def create_index_statement(label: str, keys: Sequence[str]) -> str:
    """Neo4j 4+/5 index syntax (``CREATE INDEX ... FOR (n:L) ON (n.k)``).
    The reference targets Neo4j 3.x (``CREATE INDEX ON :L(k)``,
    ``Neo4jGraphMerge.scala:97-111``) — see
    ``create_index_statement_legacy`` for that form."""
    props = ", ".join(f"n.`{k}`" for k in keys)
    safe = _SAFE_NAME.sub("_", label) + "_" + "_".join(
        _SAFE_NAME.sub("_", k) for k in keys
    )
    return (
        f"CREATE INDEX `idx_{safe}` IF NOT EXISTS "
        f"FOR (n:`{label}`) ON ({props})"
    )


def create_index_statement_legacy(label: str, keys: Sequence[str]) -> str:
    """Neo4j 3.x syntax used by the reference."""
    cols = ", ".join(f"`{k}`" for k in keys)
    return f"CREATE INDEX ON :`{label}`({cols})"


def merge_node_statement(
    labels: Sequence[str], key_props: Sequence[str], other_props: Sequence[str]
) -> str:
    """Batched node MERGE by element key: ``UNWIND $batch AS row MERGE
    (n:Labels {keys...}) SET n += rest`` (reference ``mergeNodes``)."""
    keys = ", ".join(f"`{k}`: row.`{k}`" for k in sorted(key_props))
    stmt = f"UNWIND $batch AS row MERGE (n{_label_predicate(labels)} {{{keys}}})"
    if other_props:
        sets = ", ".join(f"n.`{k}` = row.`{k}`" for k in sorted(other_props))
        stmt += f" SET {sets}"
    return stmt


def merge_relationship_statement(
    rel_type: str,
    start_labels: Sequence[str],
    end_labels: Sequence[str],
    start_keys: Sequence[str],
    end_keys: Sequence[str],
    key_props: Sequence[str],
    other_props: Sequence[str],
) -> str:
    """Batched relationship MERGE between key-matched endpoints
    (reference ``mergeRelationships``)."""
    s_match = ", ".join(f"`{k}`: row.`source_{k}`" for k in sorted(start_keys))
    e_match = ", ".join(f"`{k}`: row.`target_{k}`" for k in sorted(end_keys))
    r_keys = ", ".join(f"`{k}`: row.`{k}`" for k in sorted(key_props))
    r_key_part = f" {{{r_keys}}}" if r_keys else ""
    stmt = (
        f"UNWIND $batch AS row "
        f"MATCH (s{_label_predicate(start_labels)} {{{s_match}}}) "
        f"MATCH (t{_label_predicate(end_labels)} {{{e_match}}}) "
        f"MERGE (s)-[r:`{rel_type}`{r_key_part}]->(t)"
    )
    if other_props:
        sets = ", ".join(f"r.`{k}` = row.`{k}`" for k in sorted(other_props))
        stmt += f" SET {sets}"
    return stmt


# ---------------------------------------------------------------------------
# bulk CSV sink (Neo4jBulkCSVDataSink.scala)
# ---------------------------------------------------------------------------

IMPORT_SCRIPT_NAME = "import.sh"

_IMPORT_SCRIPT_TEMPLATE = """#!/bin/sh
if [ $# -ne 1 ]
then
  echo "Please provide the path to your Neo4j installation (e.g. /usr/share/neo4j/)"
else
  ${{1}}bin/neo4j-admin import \\
  --database={database} \\
  --delimiter="," \\
  --array-delimiter="{array_delimiter}" \\
  --id-type=INTEGER \\
{node_args} \\
{rel_args}
fi
"""


def _clean_value(v, t: Optional[T.CypherType]):
    """Undo pandas NaN/float64 artifacts on optional columns: NaN -> None,
    and integer-typed floats back to int (pandas upcasts an int column with
    missing values to float64, which would corrupt int properties as
    '23.0'/'nan' on export)."""
    import math as _math

    import numpy as _np

    if v is None:
        return None
    if isinstance(v, (float, _np.floating)) and _math.isnan(v):
        return None
    m = t.material if t is not None else None
    if m is T.CTInteger and isinstance(v, (float, _np.floating)):
        return int(v)
    if isinstance(v, _np.integer):
        return int(v)
    if isinstance(v, _np.floating):
        return float(v)
    if isinstance(v, _np.bool_):
        return bool(v)
    return v


def _clean_records(df, types: Dict[str, T.CypherType]) -> List[Dict]:
    return [
        {c: _clean_value(row[c], types.get(c)) for c in df.columns}
        for _, row in df.iterrows()
    ]


def _bulk_type(t: Optional[T.CypherType]) -> str:
    """CypherType -> neo4j-admin import column type
    (reference ``DataTypeOps.toNeo4jBulkImportType``)."""
    m = t.material if t is not None else None
    if m is None or m is T.CTString or m is T.CTNull or m is T.CTAny:
        return "string"
    if m is T.CTInteger:
        return "int"
    if m is T.CTBoolean:
        return "boolean"
    if m is T.CTFloat:
        return "double"
    if isinstance(m, T.CTListType):
        return _bulk_type(m.inner) + "[]"
    return "string"


class Neo4jBulkCSVDataSink:
    """Writes a property graph into the ``neo4j-admin import`` bulk format:
    per label combination ``nodes/<combo>/{schema.csv,part_0.csv}``, per
    relationship type ``relationships/<type>/...``, plus an ``import.sh``
    parameterized with the Neo4j installation path. Needs no driver."""

    def __init__(self, root: str, array_delimiter: str = "|"):
        self.root = root
        self.array_delimiter = array_delimiter

    def _node_dir(self, name: str, combo) -> str:
        from .fs import _combo_dir

        return os.path.join(self.root, name, "nodes", _combo_dir(combo))

    def _rel_dir(self, name: str, rel_type: str) -> str:
        from .fs import _rel_dir

        return os.path.join(self.root, name, "relationships", _rel_dir(rel_type))

    def store(self, name: str, graph) -> None:
        from .fs import _plain_ctx, canonical_node_columns, canonical_rel_columns

        schema = graph.schema
        ctx = _plain_ctx(graph)
        node_args: List[str] = []
        rel_args: List[str] = []

        for combo in sorted(schema.label_combinations, key=sorted):
            df, types = canonical_node_columns(graph, combo, ctx)
            d = self._node_dir(name, combo)
            header = ["id:ID"] + [
                f"{k}:{_bulk_type(types.get(k))}" for k in df.columns if k != "id"
            ]
            self._write_table(d, header, df, [c for c in df.columns], types)
            # unlabeled nodes: plain --nodes, no empty label specifier
            label_spec = ":" + ":".join(sorted(combo)) if combo else ""
            node_args.append(
                f'  --nodes{label_spec} '
                f'"{os.path.join(d, "schema.csv")},{os.path.join(d, "part_0.csv")}"'
            )

        for rt in sorted(schema.relationship_types):
            df, types = canonical_rel_columns(graph, rt, ctx)
            d = self._rel_dir(name, rt)
            cols = [c for c in df.columns if c != "id"]
            header = []
            for c in cols:
                if c == "source":
                    header.append(":START_ID")
                elif c == "target":
                    header.append(":END_ID")
                else:
                    header.append(f"{c}:{_bulk_type(types.get(c))}")
            self._write_table(d, header, df, cols, types)
            rel_args.append(
                f'  --relationships:{rt} '
                f'"{os.path.join(d, "schema.csv")},{os.path.join(d, "part_0.csv")}"'
            )

        script = _IMPORT_SCRIPT_TEMPLATE.format(
            database=name,
            array_delimiter=self.array_delimiter,
            node_args=" \\\n".join(node_args),
            rel_args=" \\\n".join(rel_args),
        )
        script_path = os.path.join(self.root, name, IMPORT_SCRIPT_NAME)
        os.makedirs(os.path.dirname(script_path), exist_ok=True)
        with open(script_path, "w") as f:
            f.write(script)
        os.chmod(script_path, os.stat(script_path).st_mode | stat.S_IXUSR)

    def _write_table(
        self,
        d: str,
        header: List[str],
        df,
        cols: List[str],
        types: Dict[str, T.CypherType],
    ) -> None:
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, "schema.csv"), "w", newline="") as f:
            csv.writer(f).writerow(header)
        with open(os.path.join(d, "part_0.csv"), "w", newline="") as f:
            w = csv.writer(f)
            for record in _clean_records(df, types):
                out = []
                for c in cols:
                    v = record[c]
                    if isinstance(v, (list, tuple)):
                        v = self.array_delimiter.join(str(x) for x in v)
                    elif v is None:
                        v = ""
                    elif isinstance(v, bool):
                        v = "true" if v else "false"
                    out.append(v)
                w.writerow(out)


# ---------------------------------------------------------------------------
# live PGDS (driver-gated)
# ---------------------------------------------------------------------------


class Neo4jPropertyGraphDataSource(PropertyGraphDataSource):
    """Reads a live Neo4j database as a property graph: one node table per
    exact label combination, one relationship table per type, schema via the
    ``db.schema.*`` procedures (reference ``ElementReader`` +
    ``SchemaFromProcedure``). Write-back is MERGE-by-element-key
    (reference ``Neo4jGraphMerge``). All server communication is gated on the
    optional ``neo4j`` Python driver."""

    def __init__(self, config: Neo4jConfig, graph_name: str = "graph"):
        self.config = config
        self._graph_name = graph_name
        self._schema_cache: Optional[PropertyGraphSchema] = None
        self._driver = None

    # -- driver plumbing ---------------------------------------------------

    def _get_driver(self):
        """One driver (connection pool) per source, created lazily."""
        if self._driver is None:
            neo4j = _require_driver()
            auth = (
                (self.config.user, self.config.password)
                if self.config.password
                else None
            )
            self._driver = neo4j.GraphDatabase.driver(self.config.uri, auth=auth)
        return self._driver

    def _session(self):
        return self._get_driver().session(database=self.config.database)

    def _run(self, query: str, **params) -> List[Dict]:
        with self._session() as s:
            return [dict(r) for r in s.run(query, **params)]

    def close(self) -> None:
        if self._driver is not None:
            self._driver.close()
            self._driver = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- PGDS --------------------------------------------------------------

    def has_graph(self, name: str) -> bool:
        return name == self._graph_name

    def graph_names(self) -> List[str]:
        return [self._graph_name]

    def schema(self, name: str) -> Optional[PropertyGraphSchema]:
        if name != self._graph_name:
            return None
        if self._schema_cache is None:
            self._schema_cache = self._schema_from_procedure()
        return self._schema_cache

    def _schema_from_procedure(self) -> PropertyGraphSchema:
        """Reference ``SchemaFromProcedure.scala:39``."""
        schema = PropertyGraphSchema.empty()
        for row in self._run(node_schema_query()):
            labels = frozenset(row.get("nodeLabels") or [])
            prop = row.get("propertyName")
            types = row.get("propertyTypes") or []
            # a non-mandatory property can be absent -> nullable type
            # (the reference consumes 'mandatory' the same way)
            mandatory = bool(row.get("mandatory"))
            keys = (
                {prop: _cypher_type_for(types, mandatory)} if prop else {}
            )
            schema = schema.with_node_combination(labels, keys)
        for row in self._run(rel_schema_query()):
            rel_type = (row.get("relType") or "").strip(":`")
            prop = row.get("propertyName")
            types = row.get("propertyTypes") or []
            mandatory = bool(row.get("mandatory"))
            keys = (
                {prop: _cypher_type_for(types, mandatory)} if prop else {}
            )
            schema = schema.with_relationship_type(rel_type, keys)
        return schema

    def graph(self, name: str, session):
        if name != self._graph_name:
            raise DataSourceError(f"Unknown graph {name!r}; this source exposes "
                                  f"{self._graph_name!r}")
        schema = self.schema(name)
        from ..api.mapping import NodeMappingBuilder, RelationshipMappingBuilder
        from ..relational.graphs import ElementTable, ScanGraph

        table_cls = session.table_cls
        tables = []
        for combo in schema.label_combinations:
            keys = schema.node_property_keys(combo)
            rows = self._run(exact_label_match_query(sorted(combo), sorted(keys)))
            cols = {ID_KEY: [r[ID_KEY] for r in rows]}
            for k in sorted(keys):
                cols[f"n.`{k}`"] = [r.get(f"n.`{k}`") for r in rows]
            b = NodeMappingBuilder.on(ID_KEY).with_implied_label(*combo)
            for k in sorted(keys):
                b = b.with_property_key(k, f"n.`{k}`")
            tables.append(ElementTable(b.build(), table_cls.from_columns(cols)))
        for rt in schema.relationship_types:
            keys = schema.relationship_property_keys(rt)
            rows = self._run(rel_type_query(rt, sorted(keys)))
            cols = {
                ID_KEY: [r[ID_KEY] for r in rows],
                START_KEY: [r[START_KEY] for r in rows],
                END_KEY: [r[END_KEY] for r in rows],
            }
            for k in sorted(keys):
                cols[f"r.`{k}`"] = [r.get(f"r.`{k}`") for r in rows]
            b = (
                RelationshipMappingBuilder.on(ID_KEY)
                .from_(START_KEY)
                .to(END_KEY)
                .with_relationship_type(rt)
            )
            for k in sorted(keys):
                b = b.with_property_key(k, f"r.`{k}`")
            tables.append(ElementTable(b.build(), table_cls.from_columns(cols)))
        return ScanGraph(tables, schema, table_cls)

    def store(self, name: str, graph) -> None:
        """MERGE write-back by element key (reference ``Neo4jGraphMerge``):
        node batches per label combination keyed on all properties named in
        ``element_keys``; here we key on the exported ``id`` column."""
        from .fs import _plain_ctx, canonical_node_columns, canonical_rel_columns

        schema = graph.schema
        ctx = _plain_ctx(graph)
        with self._session() as s:
            # index the merge key per label first, as the reference does —
            # without it every MERGE row is a full store scan. Try the
            # modern (4+/5) syntax first, then the 3.x form the reference
            # uses; only an already-existing index is silently accepted.
            for label in sorted({l for combo in schema.label_combinations for l in combo}):
                for stmt in (
                    create_index_statement(label, ["id"]),
                    create_index_statement_legacy(label, ["id"]),
                ):
                    try:
                        s.run(stmt)
                        break
                    except Exception as e:  # noqa: BLE001 - fault-ok: index-create probe against external Neo4j, no device state
                        if "already exists" in str(e).lower() or "equivalent" in str(e).lower():
                            break
            for combo in schema.label_combinations:
                df, types = canonical_node_columns(graph, combo, ctx)
                props = [c for c in df.columns if c != "id"]
                stmt = merge_node_statement(sorted(combo), ["id"], props)
                s.run(stmt, batch=_clean_records(df, types))
            for rt in schema.relationship_types:
                df, types = canonical_rel_columns(graph, rt, ctx)
                props = [c for c in df.columns if c not in ("id", "source", "target")]
                # endpoint labels (when the schema knows the pattern) let the
                # MATCHes use the per-label id index instead of a full scan
                pats = [p for p in schema.schema_patterns if p.rel_type == rt]
                shapes = {(p.source_labels, p.target_labels) for p in pats}
                if len(shapes) == 1:
                    (sl, tl) = next(iter(shapes))
                    start_labels, end_labels = sorted(sl), sorted(tl)
                else:
                    start_labels, end_labels = [], []
                stmt = merge_relationship_statement(
                    rt, start_labels, end_labels, ["id"], ["id"], ["id"], props
                )
                batch = [
                    {
                        **{k: v for k, v in rec.items() if k not in ("source", "target")},
                        "source_id": rec["source"],
                        "target_id": rec["target"],
                    }
                    for rec in _clean_records(df, types)
                ]
                s.run(stmt, batch=batch)

    def delete(self, name: str) -> None:
        raise DataSourceError("Deleting a live Neo4j database is not supported")


def _cypher_type_for(
    neo4j_types: Sequence[str], mandatory: bool = True
) -> T.CypherType:
    """Neo4j procedure type names -> CypherType; non-mandatory properties are
    nullable (reference ``SchemaFromProcedure``)."""
    mapping = {
        "String": T.CTString,
        "Long": T.CTInteger,
        "Integer": T.CTInteger,
        "Double": T.CTFloat,
        "Boolean": T.CTBoolean,
        "StringArray": T.CTList(T.CTString),
        "LongArray": T.CTList(T.CTInteger),
        "DoubleArray": T.CTList(T.CTFloat),
    }
    ts = [mapping.get(t, T.CTAny) for t in neo4j_types]
    if not ts:
        return T.CTAny.nullable
    out = T.join_types(ts)
    return out if mandatory else out.nullable

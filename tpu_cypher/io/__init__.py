"""IO subsystem: property-graph data sources and persistence.

Mirrors the reference's PGDS layer (SURVEY.md section 2.2): session/catalog
sources, filesystem parquet/CSV persistence with the reference's directory
layout, SNAP edge lists, and a caching decorator."""

from .datasource import (
    CachedDataSource,
    DataSourceError,
    PropertyGraphDataSource,
    SessionGraphDataSource,
)
from .edge_list import EdgeListDataSource, load_edge_list
from .fs import FSGraphSource
from .ldbc import generate_snb, load_snb_csv
from .neo4j import (
    Neo4jBulkCSVDataSink,
    Neo4jConfig,
    Neo4jPropertyGraphDataSource,
)

__all__ = [
    "CachedDataSource",
    "DataSourceError",
    "EdgeListDataSource",
    "FSGraphSource",
    "generate_snb",
    "load_snb_csv",
    "Neo4jBulkCSVDataSink",
    "Neo4jConfig",
    "Neo4jPropertyGraphDataSource",
    "PropertyGraphDataSource",
    "SessionGraphDataSource",
    "load_edge_list",
]

"""Property-graph data sources.

Re-design of the reference's PGDS layer (``okapi-api/.../api/io/
PropertyGraphDataSource.scala:42``, ``impl/io/SessionGraphDataSource.scala``,
``morpheus/.../api/io/util/CachedDataSource.scala:45``): a namespace mounted
on the session catalog resolves graph names to a data source; sources load
graphs into backend tables and store graphs back out.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Dict, List, Optional

from ..api.schema import PropertyGraphSchema


class DataSourceError(Exception):
    pass


class PropertyGraphDataSource(ABC):
    """Reference ``PropertyGraphDataSource.scala:42``."""

    @abstractmethod
    def has_graph(self, name: str) -> bool: ...

    @abstractmethod
    def graph_names(self) -> List[str]: ...

    @abstractmethod
    def schema(self, name: str) -> Optional[PropertyGraphSchema]:
        """The stored schema, if the source can provide it without a full load."""
        ...

    @abstractmethod
    def graph(self, name: str, session) -> "RelationalCypherGraph":  # noqa: F821
        ...

    @abstractmethod
    def store(self, name: str, graph: "RelationalCypherGraph") -> None:  # noqa: F821
        ...

    @abstractmethod
    def delete(self, name: str) -> None: ...


class SessionGraphDataSource(PropertyGraphDataSource):
    """In-memory source backing the ``session.*`` namespace
    (reference ``SessionGraphDataSource.scala``)."""

    def __init__(self):
        self._graphs: Dict[str, object] = {}

    def has_graph(self, name: str) -> bool:
        return name in self._graphs

    def graph_names(self) -> List[str]:
        return sorted(self._graphs)

    def schema(self, name: str):
        g = self._graphs.get(name)
        return g.schema if g is not None else None

    def graph(self, name: str, session):
        if name not in self._graphs:
            raise DataSourceError(f"Graph {name!r} not found in session catalog")
        return self._graphs[name]

    def store(self, name: str, graph) -> None:
        self._graphs[name] = graph

    def delete(self, name: str) -> None:
        self._graphs.pop(name, None)


class CachedDataSource(PropertyGraphDataSource):
    """Decorator caching loaded graphs
    (reference ``CachedDataSource.scala:45-90`` — there caching at a Spark
    StorageLevel; here the loaded graph's device/host tables stay resident)."""

    def __init__(self, underlying: PropertyGraphDataSource):
        self.underlying = underlying
        self._cache: Dict[str, object] = {}

    def has_graph(self, name: str) -> bool:
        return name in self._cache or self.underlying.has_graph(name)

    def graph_names(self) -> List[str]:
        return self.underlying.graph_names()

    def schema(self, name: str):
        g = self._cache.get(name)
        return g.schema if g is not None else self.underlying.schema(name)

    def graph(self, name: str, session):
        if name not in self._cache:
            self._cache[name] = self.underlying.graph(name, session)
        return self._cache[name]

    def store(self, name: str, graph) -> None:
        self.underlying.store(name, graph)
        self._cache[name] = graph

    def delete(self, name: str) -> None:
        self.underlying.delete(name)
        self._cache.pop(name, None)

"""LDBC SNB graph support: datagen CSV loader + synthetic generator.

The driver-defined benchmark ladder (``BASELINE.md``) is LDBC Social Network
Benchmark shaped: Person/KNOWS at SF1..SF100 with 2-hop friends-of-friends,
triangle closure, and IS3-style property queries. Two entry points:

* ``load_snb_csv(dir)``  — reads the LDBC datagen "social_network" CSV layout
  (``person_0_0.csv``, ``person_knows_person_0_0.csv``, pipe-delimited with
  headers) into a property graph.
* ``generate_snb(scale)`` — synthesizes an SNB-like Person/KNOWS graph with
  power-law degrees for benchmarks when datagen output is unavailable
  (deterministic per seed).

The reference has no LDBC loader — its benchmark story is a JMH microbench
harness (``morpheus-jmh``); this module exists to back the TPU bench ladder.
"""

from __future__ import annotations

import csv
import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..api import types as T
from ..api.mapping import NodeMapping, RelationshipMapping
from ..api.schema import PropertyGraphSchema
from ..relational.graphs import ElementTable, ScanGraph
from .datasource import DataSourceError

PERSON_LABEL = "Person"
KNOWS_TYPE = "KNOWS"

# LDBC person ids collide with nothing; KNOWS edge ids go in a disjoint range
EDGE_ID_OFFSET = 1 << 53


def _read_csv(path: str, delimiter: str = "|") -> Tuple[List[str], List[List[str]]]:
    with open(path, newline="") as f:
        r = csv.reader(f, delimiter=delimiter)
        header = next(r)
        return header, list(r)


def load_snb_csv(directory: str, session, delimiter: str = "|") -> ScanGraph:
    """Load the LDBC datagen person/knows slice from a ``social_network``
    CSV directory. Recognizes both ``person_0_0.csv`` (datagen v0.3) and
    ``Person.csv`` style names."""

    def find(*names: str) -> Optional[str]:
        for n in names:
            p = os.path.join(directory, n)
            if os.path.isfile(p):
                return p
        return None

    person_path = find("person_0_0.csv", "Person.csv", "person.csv")
    knows_path = find(
        "person_knows_person_0_0.csv", "Person_knows_Person.csv",
        "person_knows_person.csv",
    )
    if person_path is None or knows_path is None:
        raise DataSourceError(
            f"No LDBC person/knows CSVs under {directory!r} "
            "(expected person_0_0.csv + person_knows_person_0_0.csv)"
        )

    header, rows = _read_csv(person_path, delimiter)
    cols = {h.split(":")[0].lower(): i for i, h in enumerate(header)}
    if "id" not in cols:
        raise DataSourceError(f"LDBC person CSV lacks an id column: {header}")
    ids = [int(r[cols["id"]]) for r in rows]
    person_cols: Dict[str, List] = {"id": ids}
    prop_types: Dict[str, T.CypherType] = {}
    for key, ct in (
        ("firstname", T.CTString),
        ("lastname", T.CTString),
        ("gender", T.CTString),
        ("birthday", T.CTString),
        ("creationdate", T.CTString),
    ):
        if key in cols:
            person_cols[key] = [r[cols[key]] for r in rows]
            prop_types[key] = ct.nullable

    kh, krows = _read_csv(knows_path, delimiter)
    kcols = {h.split(":")[0].lower(): i for i, h in enumerate(kh)}
    # datagen names the endpoint columns Person1Id/Person2Id (or :START_ID)
    s_i = kcols.get("person1id", kcols.get("person.id", 0))
    t_i = kcols.get("person2id", 1 if len(kh) > 1 else 0)
    src = [int(r[s_i]) for r in krows]
    dst = [int(r[t_i]) for r in krows]

    return _graph_from_arrays(
        session,
        np.asarray(ids, dtype=np.int64),
        person_cols,
        prop_types,
        np.asarray(src, dtype=np.int64),
        np.asarray(dst, dtype=np.int64),
        undirected_knows=True,
    )


def generate_snb(
    scale: float, session, seed: int = 42
) -> ScanGraph:
    """Synthetic SNB-like Person/KNOWS graph. ``scale=1.0`` approximates SF1
    density (~10k persons, ~450k directed KNOWS edges); degrees are
    power-law-ish (preferential-attachment flavored)."""
    num_people = max(2, int(10_000 * scale))
    num_knows = int(num_people * 45)
    rng = np.random.default_rng(seed)
    ids = np.arange(num_people, dtype=np.int64) * 7 + 1
    head = rng.zipf(1.35, size=num_knows) % num_people
    uni = rng.integers(0, num_people, size=num_knows)
    src_i = np.where(rng.random(num_knows) < 0.5, head, uni)
    dst_i = rng.integers(0, num_people, size=num_knows)
    keep = src_i != dst_i
    src, dst = ids[src_i[keep]], ids[dst_i[keep]]
    # birthday: days-since-epoch ints (IS3-style property filters); numpy so
    # the bulk ingestion path stays one H2D copy per column at SF10 scale
    person_cols: Dict[str, List] = {
        "id": ids,
        "birthday": rng.integers(0, 18_000, size=num_people, dtype=np.int64),
    }
    # expose the id column as a property too (LDBC queries anchor on
    # ``a.id`` ranges; the bench's var-length source filter does the same)
    prop_types: Dict[str, T.CypherType] = {
        "id": T.CTInteger.nullable,
        "birthday": T.CTInteger.nullable,
    }
    if num_people <= 200_000:  # string props only at list-walkable sizes
        person_cols["firstname"] = [f"p{i}" for i in range(num_people)]
        prop_types["firstname"] = T.CTString.nullable
    return _graph_from_arrays(
        session,
        ids,
        person_cols,
        prop_types,
        src,
        dst,
        undirected_knows=False,
    )


def _graph_from_arrays(
    session,
    ids: np.ndarray,
    person_cols: Dict[str, List],
    prop_types: Dict[str, T.CypherType],
    src: np.ndarray,
    dst: np.ndarray,
    undirected_knows: bool,
) -> ScanGraph:
    """Assemble the Person/KNOWS ScanGraph. LDBC datagen stores KNOWS once
    per unordered pair; Cypher's SNB queries traverse it both ways, so
    ``undirected_knows=True`` materializes both orientations (the reference
    models undirected traversal as a union of orientations at plan time; for
    a benchmark-focused loader, storing both directions keeps every hop a
    plain directed expand)."""
    if undirected_knows:
        src, dst = (
            np.concatenate([src, dst]),
            np.concatenate([dst, src]),
        )
    edge_ids = np.arange(len(src), dtype=np.int64) + EDGE_ID_OFFSET
    if len(ids) and int(ids.max(initial=0)) >= EDGE_ID_OFFSET:
        raise DataSourceError("LDBC ids exceed the supported id range")

    node_table = session.table_cls.from_arrays(person_cols)
    rel_table = session.table_cls.from_arrays(
        {"id": edge_ids, "source": src, "target": dst}
    )
    schema = (
        PropertyGraphSchema.empty()
        .with_node_combination(frozenset({PERSON_LABEL}), prop_types)
        .with_relationship_type(KNOWS_TYPE, {})
    )
    return ScanGraph(
        [
            ElementTable(
                NodeMapping(
                    id_key="id",
                    implied_labels=frozenset({PERSON_LABEL}),
                    property_mapping=tuple((k, k) for k in prop_types),
                ),
                node_table,
            ),
            ElementTable(
                RelationshipMapping(
                    id_key="id",
                    source_key="source",
                    target_key="target",
                    rel_type=KNOWS_TYPE,
                ),
                rel_table,
            ),
        ],
        schema,
    )


# The SNB query shapes the benchmark ladder runs (BASELINE.md configs 2-4)
FRIENDS_OF_FRIENDS = (
    "MATCH (a:Person)-[:KNOWS]->(b)-[:KNOWS]->(c) "
    "RETURN count(*) AS paths"
)
TRIANGLES = (
    "MATCH (a:Person)-[:KNOWS]->(b)-[:KNOWS]->(c)-[:KNOWS]->(a) "
    "RETURN count(*) AS triangles"
)

"""Tree rewriting substrate.

Re-design of ``okapi-trees`` (``TreeNode.scala:47``, ``AbstractTreeNode.scala:55``,
``TreeTransformerStackSafe.scala:63``): self-typed immutable rewritable trees with
bottom-up / top-down rewriting, folds and pretty-printing.

Python adaptation: tree nodes are frozen dataclasses; children are discovered by
introspecting dataclass fields whose values are ``TreeNode`` instances or
tuples/lists of them (cached per class, mirroring the reference's cached
product-args copy in ``AbstractTreeNode.scala:55``). All rewrites are iterative
(explicit work stacks), matching the reference's stack-safe transformers —
deep plan trees (e.g. unrolled var-length expands) must not hit Python's
recursion limit.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple, Type, TypeVar

T = TypeVar("T", bound="TreeNode")

_CHILD_FIELD_CACHE: Dict[type, Tuple[str, ...]] = {}


def _field_names(cls: type) -> Tuple[str, ...]:
    names = _CHILD_FIELD_CACHE.get(cls)
    if names is None:
        names = tuple(f.name for f in dataclasses.fields(cls))
        _CHILD_FIELD_CACHE[cls] = names
    return names


class TreeNode:
    """Mixin for frozen dataclasses forming rewritable trees."""

    __slots__ = ()

    # -- children ---------------------------------------------------------

    @property
    def children(self) -> Tuple["TreeNode", ...]:
        out: List[TreeNode] = []
        for name in _field_names(type(self)):
            v = getattr(self, name)
            if isinstance(v, TreeNode):
                out.append(v)
            elif isinstance(v, (tuple, list)):
                out.extend(c for c in v if isinstance(c, TreeNode))
        return tuple(out)

    def with_new_children(self: T, new_children: Tuple["TreeNode", ...]) -> T:
        """Rebuild this node with children replaced positionally."""
        if not new_children and not self.children:
            return self
        it = iter(new_children)
        updates: Dict[str, Any] = {}
        changed = False
        for name in _field_names(type(self)):
            v = getattr(self, name)
            if isinstance(v, TreeNode):
                nv = next(it)
                if nv is not v:
                    changed = True
                updates[name] = nv
            elif isinstance(v, (tuple, list)):
                elems = []
                any_tree = False
                for c in v:
                    if isinstance(c, TreeNode):
                        any_tree = True
                        nc = next(it)
                        if nc is not c:
                            changed = True
                        elems.append(nc)
                    else:
                        elems.append(c)
                if any_tree:
                    updates[name] = tuple(elems) if isinstance(v, tuple) else list(elems)
        if not changed:
            return self
        return dataclasses.replace(self, **updates)  # type: ignore[type-var]

    # -- traversal --------------------------------------------------------

    def iter_nodes(self) -> Iterator["TreeNode"]:
        """Pre-order iteration (iterative)."""
        stack: List[TreeNode] = [self]
        while stack:
            node = stack.pop()
            yield node
            stack.extend(reversed(node.children))

    @property
    def height(self) -> int:
        h = 0
        stack: List[Tuple[TreeNode, int]] = [(self, 1)]
        while stack:
            node, d = stack.pop()
            h = max(h, d)
            for c in node.children:
                stack.append((c, d + 1))
        return h

    @property
    def size(self) -> int:
        return sum(1 for _ in self.iter_nodes())

    def exists(self, pred: Callable[["TreeNode"], bool]) -> bool:
        return any(pred(n) for n in self.iter_nodes())

    def collect(self, fn: Callable[["TreeNode"], Optional[Any]]) -> List[Any]:
        out = []
        for n in self.iter_nodes():
            v = fn(n)
            if v is not None:
                out.append(v)
        return out

    def collect_nodes(self, cls) -> List[Any]:
        return [n for n in self.iter_nodes() if isinstance(n, cls)]

    # -- rewriting (stack-safe, reference TreeTransformerStackSafe) --------

    def rewrite(self: T, rule: Callable[["TreeNode"], "TreeNode"]) -> T:
        """Bottom-up rewrite: children first, then the node (``TreeNode.rewrite``)."""
        return _rewrite_bottom_up(self, rule)

    def rewrite_top_down(self: T, rule: Callable[["TreeNode"], "TreeNode"]) -> T:
        """Top-down rewrite: node first, then recurse into its (new) children."""
        return _rewrite_top_down(self, rule)

    def transform(self, fn: Callable[["TreeNode", List[Any]], Any]) -> Any:
        """Bottom-up fold: ``fn(node, child_results)`` (``TreeNode.transform``)."""
        # post-order iterative fold
        results: Dict[int, Any] = {}
        stack: List[Tuple[TreeNode, bool]] = [(self, False)]
        while stack:
            node, expanded = stack.pop()
            if expanded:
                child_vals = [results[id(c)] for c in node.children]
                results[id(node)] = fn(node, child_vals)
            else:
                stack.append((node, True))
                for c in reversed(node.children):
                    stack.append((c, False))
        return results[id(self)]

    # -- pretty printing ---------------------------------------------------

    def _show_inner(self) -> str:
        """Non-child args to display; override for custom rendering."""
        parts = []
        for name in _field_names(type(self)):
            v = getattr(self, name)
            if isinstance(v, TreeNode):
                continue
            if isinstance(v, (tuple, list)) and any(isinstance(c, TreeNode) for c in v):
                continue
            parts.append(f"{name}={v!r}")
        return ", ".join(parts)

    def pretty(self) -> str:
        """ASCII tree rendering (reference ``TreeNode.pretty``)."""
        lines: List[str] = []

        def label(n: TreeNode) -> str:
            inner = n._show_inner()
            return f"{type(n).__name__}({inner})" if inner else type(n).__name__

        # iterative DFS with prefixes
        stack: List[Tuple[TreeNode, str, bool, bool]] = [(self, "", True, True)]
        while stack:
            node, prefix, is_last, is_root = stack.pop()
            if is_root:
                lines.append(label(node))
                child_prefix = ""
            else:
                connector = "╚═" if is_last else "╠═"
                lines.append(prefix + connector + label(node))
                child_prefix = prefix + ("  " if is_last else "║ ")
            kids = node.children
            for i in range(len(kids) - 1, -1, -1):
                stack.append((kids[i], child_prefix, i == len(kids) - 1, False))
        return "\n".join(lines)


def _rewrite_bottom_up(root: T, rule: Callable[[TreeNode], TreeNode]) -> T:
    results: Dict[int, TreeNode] = {}
    stack: List[Tuple[TreeNode, bool]] = [(root, False)]
    while stack:
        node, expanded = stack.pop()
        if expanded:
            new_children = tuple(results[id(c)] for c in node.children)
            rebuilt = node.with_new_children(new_children)
            results[id(node)] = rule(rebuilt)
        else:
            stack.append((node, True))
            for c in reversed(node.children):
                stack.append((c, False))
    return results[id(root)]  # type: ignore[return-value]


def _rewrite_top_down(root: T, rule: Callable[[TreeNode], TreeNode]) -> T:
    new_root = rule(root)

    # process: rewrite children of node top-down, iteratively.
    # We model the continuation as: (node, state) where state tracks child idx.
    # Simpler approach: recursion-free via explicit result reconstruction.
    class Frame:
        __slots__ = ("node", "kids", "done", "idx")

        def __init__(self, node: TreeNode):
            self.node = node
            self.kids = node.children
            self.done: List[TreeNode] = []
            self.idx = 0

    top = Frame(new_root)
    stack = [top]
    while True:
        f = stack[-1]
        if f.idx < len(f.kids):
            child = rule(f.kids[f.idx])
            f.idx += 1
            stack.append(Frame(child))
        else:
            rebuilt = f.node.with_new_children(tuple(f.done))
            stack.pop()
            if not stack:
                return rebuilt  # type: ignore[return-value]
            stack[-1].done.append(rebuilt)

"""Gherkin-lite parser for TCK ``.feature`` files.

Supports the subset the openCypher TCK uses: ``Feature:``, ``Background:``,
``Scenario:``, ``Scenario Outline:`` + ``Examples:`` expansion, steps
(Given/When/Then/And/But), ``\"\"\"`` docstrings, ``|``-delimited data tables,
``@tags`` and ``#`` comments. (The reference consumes the TCK through the
published ``tck-api`` artifact; our framework owns the whole pipeline.)
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple


class GherkinParseError(Exception):
    pass


@dataclass
class Step:
    keyword: str  # Given / When / Then / And / But
    text: str
    docstring: Optional[str] = None
    table: Optional[List[List[str]]] = None  # rows of raw cell strings

    def __repr__(self):
        return f"{self.keyword} {self.text}"


@dataclass
class Scenario:
    feature: str
    name: str
    steps: List[Step] = field(default_factory=list)
    tags: Tuple[str, ...] = ()
    example_index: Optional[int] = None

    def __str__(self):
        # the reference blacklists by "Feature "x": Scenario "y"" strings
        # (TCKFixture ScenariosFor); we key the same way
        suffix = f" (example {self.example_index})" if self.example_index is not None else ""
        return f'Feature "{self.feature}": Scenario "{self.name}"{suffix}'


@dataclass
class Feature:
    name: str
    scenarios: List[Scenario] = field(default_factory=list)
    source: str = ""  # raw feature text (test generator re-embeds it)


def _split_table_row(line: str) -> List[str]:
    # | a | b c |  -> ['a', 'b c']; escaped \| inside cells
    s = line.strip()
    if not (s.startswith("|") and s.endswith("|")):
        raise GherkinParseError(f"Malformed table row: {line!r}")
    cells: List[str] = []
    cur = []
    i = 1
    while i < len(s):
        ch = s[i]
        if ch == "\\" and i + 1 < len(s) and s[i + 1] == "|":
            cur.append("|")
            i += 2
            continue
        if ch == "|":
            cells.append("".join(cur).strip())
            cur = []
            i += 1
            continue
        cur.append(ch)
        i += 1
    return cells


_STEP_KEYWORDS = ("Given", "When", "Then", "And", "But")


def parse_feature(text: str, path: str = "<string>") -> Feature:
    lines = text.splitlines()
    feature: Optional[Feature] = None
    background: List[Step] = []
    pending_tags: List[str] = []

    i = 0
    n = len(lines)

    def peek_stripped(j: int) -> str:
        return lines[j].strip()

    current: Optional[Scenario] = None
    in_background = False
    outline_steps: Optional[List[Step]] = None
    outline_name: Optional[str] = None
    outline_tags: Tuple[str, ...] = ()

    def flush_outline(examples: List[List[str]]):
        nonlocal outline_steps, outline_name
        if outline_steps is None:
            return
        header, *rows = examples
        for idx, row in enumerate(rows):
            subs = dict(zip(header, row))
            steps = []
            for st in background + outline_steps:
                steps.append(
                    Step(
                        st.keyword,
                        _substitute(st.text, subs),
                        _substitute(st.docstring, subs) if st.docstring else None,
                        [[_substitute(c, subs) for c in r] for r in st.table]
                        if st.table
                        else None,
                    )
                )
            feature.scenarios.append(
                Scenario(feature.name, outline_name, steps, outline_tags, idx + 1)
            )
        outline_steps = None
        outline_name = None

    while i < n:
        raw = lines[i]
        line = raw.strip()
        i += 1
        if not line or line.startswith("#"):
            continue
        if line.startswith("@"):
            pending_tags.extend(t for t in line.split() if t.startswith("@"))
            continue
        if line.startswith("Feature:"):
            feature = Feature(line[len("Feature:"):].strip(), source=text)
            pending_tags = []
            continue
        if feature is None:
            raise GherkinParseError(f"{path}: content before Feature: header")
        if line.startswith("Background:"):
            in_background = True
            current = None
            continue
        if line.startswith("Scenario Outline:") or line.startswith("Scenario Template:"):
            in_background = False
            current = None
            outline_steps = []
            outline_name = line.split(":", 1)[1].strip()
            outline_tags = tuple(pending_tags)
            pending_tags = []
            continue
        if line.startswith("Scenario:") or line.startswith("Example:"):
            in_background = False
            current = Scenario(
                feature.name,
                line.split(":", 1)[1].strip(),
                list(background),
                tuple(pending_tags),
            )
            pending_tags = []
            feature.scenarios.append(current)
            continue
        if line.startswith("Examples:") or line.startswith("Scenarios:"):
            rows: List[List[str]] = []
            while i < n and peek_stripped(i).startswith("|"):
                rows.append(_split_table_row(lines[i]))
                i += 1
            if not rows:
                raise GherkinParseError(f"{path}: Examples without table")
            flush_outline(rows)
            continue
        kw = next((k for k in _STEP_KEYWORDS if line.startswith(k + " ")), None)
        if kw is None:
            raise GherkinParseError(f"{path}: unparseable line {line!r}")
        step = Step(kw, line[len(kw):].strip())
        # attached docstring?
        if i < n and peek_stripped(i).startswith('"""'):
            i += 1
            doc: List[str] = []
            while i < n and not peek_stripped(i).startswith('"""'):
                doc.append(lines[i])
                i += 1
            if i >= n:
                raise GherkinParseError(f"{path}: unterminated docstring")
            i += 1
            step.docstring = _dedent(doc)
        # attached table?
        elif i < n and peek_stripped(i).startswith("|"):
            tbl: List[List[str]] = []
            while i < n and peek_stripped(i).startswith("|"):
                tbl.append(_split_table_row(lines[i]))
                i += 1
            step.table = tbl
        if in_background:
            background.append(step)
        elif outline_steps is not None:
            outline_steps.append(step)
        elif current is not None:
            current.steps.append(step)
        else:
            raise GherkinParseError(f"{path}: step outside scenario: {line!r}")
    return feature


def _dedent(doc: List[str]) -> str:
    indents = [len(l) - len(l.lstrip()) for l in doc if l.strip()]
    cut = min(indents) if indents else 0
    return "\n".join(l[cut:] if len(l) >= cut else l for l in doc)


def _substitute(text: str, subs) -> str:
    for k, v in subs.items():
        text = text.replace(f"<{k}>", v)
    return text

"""openCypher-TCK-style conformance harness.

Re-design of the reference TCK integration (``okapi-tck/.../TCKFixture.scala:84``,
``TckSparkCypherTest.scala:39-76``): a gherkin-lite ``.feature`` parser, a TCK
expected-value grammar, a scenario runner adapting a
:class:`~tpu_cypher.CypherSession`, and whitelist/blacklist partitioning where
a *passing blacklisted scenario fails the build* (false positive), keeping the
blacklist honest as coverage grows.
"""

from .gherkin import Feature, Scenario, Step, parse_feature
from .runner import ScenarioResult, ScenariosFor, TckRunner, load_features

__all__ = [
    "Feature",
    "Scenario",
    "ScenarioResult",
    "ScenariosFor",
    "Step",
    "TckRunner",
    "load_features",
    "parse_feature",
]

"""TCK expected-value grammar and result comparison.

The TCK describes expected results as strings (``1``, ``'a'``, ``true``,
``null``, ``[1, 2]``, ``{k: 1}``, ``(:L {p: 1})``, ``[:T {p: 1}]``,
``<(:A)-[:T]->(:B)>``). The reference converts both sides through the
``tck-api`` value classes (``TCKFixture.scala:156-213``); here we parse the
strings ourselves and compare structurally — nodes by label set + properties,
relationships by type + properties, ids ignored (TCK semantics).
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass
from typing import Any, Dict, List, Tuple

from ..api.values import Node, Path, Relationship


class TckValueError(Exception):
    pass


@dataclass(frozen=True)
class TckNode:
    labels: frozenset
    properties: Tuple[Tuple[str, Any], ...]


@dataclass(frozen=True)
class TckRelationship:
    rel_type: str
    properties: Tuple[Tuple[str, Any], ...]


@dataclass(frozen=True)
class TckPath:
    # alternating node / rel / node / ... with relationship directions:
    # elements[i] for odd i is (TckRelationship, forward: bool)
    elements: Tuple[Any, ...]


_NUM_INT = re.compile(r"[+-]?\d+$")
_NUM_FLOAT = re.compile(r"[+-]?(\d+\.\d*|\.\d+|\d+)([eE][+-]?\d+)?$")


class _P:
    def __init__(self, s: str):
        self.s = s
        self.i = 0

    def ws(self):
        while self.i < len(self.s) and self.s[self.i].isspace():
            self.i += 1

    def peek(self) -> str:
        return self.s[self.i] if self.i < len(self.s) else ""

    def expect(self, ch: str):
        if not self.s.startswith(ch, self.i):
            raise TckValueError(
                f"Expected {ch!r} at {self.i} in {self.s!r}"
            )
        self.i += len(ch)

    def try_eat(self, ch: str) -> bool:
        self.ws()
        if self.s.startswith(ch, self.i):
            self.i += len(ch)
            return True
        return False

    # -- values ------------------------------------------------------------

    def value(self):
        self.ws()
        c = self.peek()
        if c == "'":
            return self.string()
        if c == "[":
            # relationship or list
            save = self.i
            try:
                return self.relationship()
            except TckValueError:
                self.i = save
                return self.list_()
        if c == "{":
            return self.map_()
        if c == "(":
            return self.node()
        if c == "<":
            return self.path()
        return self.scalar()

    def string(self) -> str:
        self.expect("'")
        out = []
        while True:
            if self.i >= len(self.s):
                raise TckValueError(f"Unterminated string in {self.s!r}")
            ch = self.s[self.i]
            if ch == "\\" and self.i + 1 < len(self.s):
                nxt = self.s[self.i + 1]
                if nxt in ("'", "\\"):
                    out.append(nxt)
                    self.i += 2
                    continue
                out.append(ch)
                self.i += 1
                continue
            if ch == "'":
                self.i += 1
                return "".join(out)
            out.append(ch)
            self.i += 1

    def scalar(self):
        j = self.i
        while j < len(self.s) and self.s[j] not in ",]}|)>":
            j += 1
        tok = self.s[self.i:j].strip()
        self.i = j
        if tok == "null":
            return None
        if tok == "true":
            return True
        if tok == "false":
            return False
        if tok == "NaN":
            return float("nan")
        if tok in ("Inf", "Infinity", "+Inf"):
            return math.inf
        if tok in ("-Inf", "-Infinity"):
            return -math.inf
        if _NUM_INT.match(tok):
            return int(tok)
        if _NUM_FLOAT.match(tok):
            return float(tok)
        raise TckValueError(f"Cannot parse scalar {tok!r} in {self.s!r}")

    def list_(self) -> list:
        self.expect("[")
        out = []
        self.ws()
        if self.try_eat("]"):
            return out
        out.append(self.value())
        while self.try_eat(","):
            out.append(self.value())
        self.ws()
        self.expect("]")
        return out

    def map_(self) -> dict:
        self.expect("{")
        out: Dict[str, Any] = {}
        self.ws()
        if self.try_eat("}"):
            return out
        while True:
            self.ws()
            key = self.ident()
            self.ws()
            self.expect(":")
            out[key] = self.value()
            if self.try_eat(","):
                continue
            self.ws()
            self.expect("}")
            return out

    def ident(self) -> str:
        if self.peek() == "`":
            self.i += 1
            j = self.s.index("`", self.i)
            out = self.s[self.i:j]
            self.i = j + 1
            return out
        m = re.match(r"[A-Za-z_][A-Za-z0-9_]*", self.s[self.i:])
        if not m:
            raise TckValueError(f"Expected identifier at {self.i} in {self.s!r}")
        self.i += m.end()
        return m.group()

    def _labels(self) -> frozenset:
        labels = set()
        while self.try_eat(":"):
            labels.add(self.ident())
        return frozenset(labels)

    def node(self) -> TckNode:
        self.expect("(")
        self.ws()
        labels = self._labels()
        self.ws()
        props: Dict[str, Any] = {}
        if self.peek() == "{":
            props = self.map_()
        self.ws()
        self.expect(")")
        return TckNode(labels, tuple(sorted(props.items(), key=lambda kv: kv[0])))

    def relationship(self) -> TckRelationship:
        self.expect("[")
        self.ws()
        if not self.try_eat(":"):
            raise TckValueError("not a relationship")
        t = self.ident()
        self.ws()
        props: Dict[str, Any] = {}
        if self.peek() == "{":
            props = self.map_()
        self.ws()
        self.expect("]")
        return TckRelationship(t, tuple(sorted(props.items(), key=lambda kv: kv[0])))

    def path(self) -> TckPath:
        self.expect("<")
        elements: List[Any] = [self.node()]
        while True:
            self.ws()
            if self.try_eat(">"):
                return TckPath(tuple(elements))
            if self.try_eat("<-["):
                self.i -= len("[")
                rel = self.relationship()
                self.ws()
                self.expect("-")
                elements.append((rel, False))
            elif self.try_eat("-["):
                self.i -= len("[")
                rel = self.relationship()
                self.ws()
                self.expect("->")
                elements.append((rel, True))
            else:
                raise TckValueError(f"Bad path syntax in {self.s!r}")
            self.ws()
            elements.append(self.node())


def parse_tck_value(cell: str):
    p = _P(cell.strip())
    v = p.value()
    p.ws()
    if p.i != len(p.s):
        raise TckValueError(f"Trailing input in TCK value {cell!r}")
    return v


# ---------------------------------------------------------------------------
# comparison: engine result value vs parsed TCK expectation
# ---------------------------------------------------------------------------


def normalize_result_value(v, ignore_list_order: bool = False):
    """Engine → comparable: elements become structural Tck* values."""
    if isinstance(v, Node):
        return TckNode(
            frozenset(v.labels),
            tuple(
                sorted(
                    (
                        (k, normalize_result_value(x, ignore_list_order))
                        for k, x in v.properties.items()
                    ),
                    key=lambda kv: kv[0],
                )
            ),
        )
    if isinstance(v, Relationship):
        return TckRelationship(
            v.rel_type,
            tuple(
                sorted(
                    (
                        (k, normalize_result_value(x, ignore_list_order))
                        for k, x in v.properties.items()
                    ),
                    key=lambda kv: kv[0],
                )
            ),
        )
    if isinstance(v, Path):
        els: List[Any] = []
        prev_node_id = None
        for el in v.elements:
            if isinstance(el, Node):
                els.append(normalize_result_value(el, ignore_list_order))
                prev_node_id = el.id
            else:
                fwd = el.start == prev_node_id
                els.append((normalize_result_value(el, ignore_list_order), fwd))
                prev_node_id = el.end if fwd else el.start
        return TckPath(tuple(els))
    if isinstance(v, (list, tuple)):
        items = [normalize_result_value(x, ignore_list_order) for x in v]
        if ignore_list_order:
            return ("bag", _bag_key(items))
        return tuple(items)
    if isinstance(v, dict):
        return (
            "map",
            tuple(
                sorted(
                    (k, normalize_result_value(x, ignore_list_order))
                    for k, x in v.items()
                )
            ),
        )
    # tag numeric kinds: the TCK distinguishes 1 from 1.0 and true from 1
    if isinstance(v, bool):
        return ("bool", v)
    if isinstance(v, int):
        return ("int", v)
    if isinstance(v, float):
        if math.isnan(v):
            return ("float", "NaN")
        return ("float", v)
    return v


def normalize_expected_value(v, ignore_list_order: bool = False):
    if isinstance(v, TckNode):
        return TckNode(
            v.labels,
            tuple(
                (k, normalize_expected_value(x, ignore_list_order))
                for k, x in v.properties
            ),
        )
    if isinstance(v, TckRelationship):
        return TckRelationship(
            v.rel_type,
            tuple(
                (k, normalize_expected_value(x, ignore_list_order))
                for k, x in v.properties
            ),
        )
    if isinstance(v, TckPath):
        out = []
        for el in v.elements:
            if isinstance(el, tuple):
                rel, fwd = el
                out.append((normalize_expected_value(rel, ignore_list_order), fwd))
            else:
                out.append(normalize_expected_value(el, ignore_list_order))
        return TckPath(tuple(out))
    if isinstance(v, list):
        items = [normalize_expected_value(x, ignore_list_order) for x in v]
        if ignore_list_order:
            return ("bag", _bag_key(items))
        return tuple(items)
    if isinstance(v, dict):
        return (
            "map",
            tuple(
                sorted(
                    (k, normalize_expected_value(x, ignore_list_order))
                    for k, x in v.items()
                )
            ),
        )
    if isinstance(v, bool):
        return ("bool", v)
    if isinstance(v, int):
        return ("int", v)
    if isinstance(v, float):
        if math.isnan(v):
            return ("float", "NaN")
        return ("float", v)
    return v


def _bag_key(items: list):
    return tuple(sorted((repr(x) for x in items)))

"""TCK scenario runner with whitelist/blacklist semantics.

Mirrors the reference harness behavior (``TCKFixture.scala:84-150``,
``TckSparkCypherTest.scala:39-76``): scenarios not on the blacklist MUST pass;
blacklisted scenarios MUST fail — a passing blacklisted scenario is itself an
error ("false positive"), which keeps the blacklist shrinking honestly.
Blacklist files are plain text, one scenario key per line, ``#`` comments
(reference resources ``morpheus-tck/src/test/resources/failing_blacklist`` etc).
"""

from __future__ import annotations

import glob
import math
import os
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from .gherkin import Feature, Scenario, parse_feature
from .tck_values import (
    normalize_expected_value,
    normalize_result_value,
    parse_tck_value,
)


class TckHarnessError(Exception):
    pass


@dataclass
class ScenarioResult:
    scenario: Scenario
    passed: bool
    message: str = ""

    def __repr__(self):
        status = "PASS" if self.passed else "FAIL"
        return f"[{status}] {self.scenario}: {self.message}"


def load_features(feature_dir: str) -> List[Feature]:
    feats = []
    for path in sorted(glob.glob(os.path.join(feature_dir, "**", "*.feature"), recursive=True)):
        with open(path) as f:
            feats.append(parse_feature(f.read(), path))
    if not feats:
        raise TckHarnessError(f"No .feature files under {feature_dir}")
    return feats


def load_blacklist(*paths: str) -> frozenset:
    entries: List[str] = []
    for p in paths:
        with open(p) as f:
            for line in f:
                # strip trailing reason comments ("... # [unbounded]")
                line = line.split("  #", 1)[0].strip()
                if line and not line.startswith("#"):
                    entries.append(line)
    dupes = {e for e in entries if entries.count(e) > 1}
    if dupes:
        # the reference asserts the same (TCKFixture ScenariosFor apply)
        raise TckHarnessError(f"Blacklist contains duplicate scenarios: {sorted(dupes)}")
    return frozenset(entries)


class ScenariosFor:
    """Partition scenarios into whitelist and blacklist (reference
    ``ScenariosFor``, ``TCKFixture.scala:113-134``)."""

    def __init__(self, features: Sequence[Feature], blacklist: frozenset = frozenset()):
        self.scenarios: List[Scenario] = [s for f in features for s in f.scenarios]
        keys = {str(s) for s in self.scenarios}
        unknown = set(blacklist) - keys
        if unknown:
            raise TckHarnessError(
                f"Blacklist entries match no scenario: {sorted(unknown)}"
            )
        self.blacklist_keys = blacklist

    @property
    def white_list(self) -> List[Scenario]:
        return [s for s in self.scenarios if str(s) not in self.blacklist_keys]

    @property
    def black_list(self) -> List[Scenario]:
        return [s for s in self.scenarios if str(s) in self.blacklist_keys]

    def get(self, name: str) -> List[Scenario]:
        return [s for s in self.scenarios if s.name == name]


class TckRunner:
    """Executes scenarios against a session factory (the adapter role of the
    reference's ``TCKGraph``)."""

    def __init__(self, session_factory: Callable[[], object]):
        self.session_factory = session_factory

    # -- step execution ----------------------------------------------------

    def run(self, scenario: Scenario) -> ScenarioResult:
        try:
            self._run_steps(scenario)
            return ScenarioResult(scenario, True)
        except AssertionError as e:
            return ScenarioResult(scenario, False, f"assertion: {e}")
        except Exception as e:  # fault-ok: scenario verdict — the failure IS the recorded result
            return ScenarioResult(scenario, False, f"{type(e).__name__}: {e}")

    def _run_steps(self, scenario: Scenario):
        session = self.session_factory()
        graph = None
        init_queries: List[str] = []
        parameters: Dict[str, object] = {}
        result = None
        error: Optional[Exception] = None
        executed = False

        def build_graph():
            nonlocal graph
            if init_queries:
                graph = session.create_graph_from_create_query(
                    "\n".join(init_queries)
                )
            else:
                from ..relational.graphs import EmptyGraph
                from ..relational.session import PropertyGraph

                graph = PropertyGraph(session, EmptyGraph())

        for step in scenario.steps:
            text = step.text
            low = text.lower().rstrip(":")
            if low in ("an empty graph", "any graph", "an empty graph with no data"):
                init_queries = []
            elif low.startswith("having executed") or low.startswith(
                "after having executed"
            ):
                if step.docstring is None:
                    raise TckHarnessError(f"Step needs docstring: {step}")
                init_queries.append(step.docstring)
            elif low.startswith("parameters are") or low.startswith(
                "parameter values are"
            ):
                if not step.table:
                    raise TckHarnessError(f"Step needs table: {step}")
                for row in step.table:
                    if len(row) != 2:
                        raise TckHarnessError(f"Bad parameter row {row}")
                    parameters[row[0]] = _to_engine_value(parse_tck_value(row[1]))
            elif low.startswith("executing query") or low.startswith(
                "executing control query"
            ):
                if step.docstring is None:
                    raise TckHarnessError(f"Step needs docstring: {step}")
                build_graph()
                executed = True
                result, error = None, None
                try:
                    res = graph.cypher(step.docstring, dict(parameters))
                    records = res.records
                    result = list(records.collect()) if records is not None else []
                except Exception as e:  # noqa: BLE001 — fault-ok: error steps assert on this
                    error = e
            elif low.startswith("the result should be empty"):
                self._require_no_error(error)
                assert result == [], f"expected empty result, got {result}"
            elif low.startswith("the result should be"):
                self._require_no_error(error)
                assert executed, "no query executed"
                in_order = ", in order" in low
                ignore_list_order = "ignoring element order for lists" in low
                self._compare(step, result, in_order, ignore_list_order)
            elif "should be raised" in low:
                assert error is not None, (
                    f"expected an error ({text}) but the query succeeded"
                )
                error = None  # consumed
            elif low.startswith("no side effects") or low.startswith(
                "the side effects should be"
            ):
                # engine is read-only over immutable device tables; CREATE-
                # style init queries run before execution, so side-effect
                # accounting is structurally impossible to violate
                pass
            else:
                raise TckHarnessError(f"Unsupported TCK step: {step}")
        if error is not None:
            raise error

    @staticmethod
    def _require_no_error(error: Optional[Exception]):
        if error is not None:
            raise error

    def _compare(self, step, result, in_order: bool, ignore_list_order: bool):
        if step.table is None:
            raise TckHarnessError(f"Step needs table: {step}")
        header, *rows = step.table
        expected = []
        for row in rows:
            if len(row) != len(header):
                raise TckHarnessError(f"Ragged expected row {row}")
            expected.append(
                tuple(
                    normalize_expected_value(parse_tck_value(cell), ignore_list_order)
                    for cell in row
                )
            )
        got = []
        for rec in result:
            missing = [c for c in header if c not in rec]
            assert not missing, f"result lacks columns {missing}; has {list(rec)}"
            got.append(
                tuple(
                    normalize_result_value(rec[c], ignore_list_order) for c in header
                )
            )
        if in_order:
            assert got == expected, f"\nexpected (in order): {expected}\ngot: {got}"
        else:
            # true multiset equality — repr-based keys are NOT canonical
            # (equal frozensets may iterate, and so repr, in different orders
            # depending on insertion history)
            from collections import Counter

            assert Counter(got) == Counter(expected), (
                f"\nexpected (any order): {expected}\ngot: {got}"
            )

    # -- suite-level entry points -----------------------------------------

    def run_all(
        self, scenarios: ScenariosFor
    ) -> Tuple[List[ScenarioResult], List[ScenarioResult]]:
        """Returns (failures, false_positives): whitelisted scenarios that
        failed, and blacklisted scenarios that passed."""
        failures = [
            r for s in scenarios.white_list if not (r := self.run(s)).passed
        ]
        false_positives = [
            r for s in scenarios.black_list if (r := self.run(s)).passed
        ]
        return failures, false_positives


def _to_engine_value(v):
    """Parsed TCK parameter → engine-side value."""
    from .tck_values import TckNode, TckPath, TckRelationship

    if isinstance(v, (TckNode, TckRelationship, TckPath)):
        raise TckHarnessError("Graph elements are not valid parameters")
    if isinstance(v, list):
        return [_to_engine_value(x) for x in v]
    if isinstance(v, dict):
        return {k: _to_engine_value(x) for k, x in v.items()}
    if isinstance(v, float) and math.isnan(v):
        return float("nan")
    return v

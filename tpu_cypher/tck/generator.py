"""Standalone acceptance-test generation from TCK feature files.

Re-design of the reference's ``AcceptanceTestGenerator``
(``okapi-tck/.../AcceptanceTestGenerator.scala:36`` +
``morpheus-tck/src/generator/.../MorpheusTestGenerator.scala:34``): emits one
pytest module per feature, with whitelisted scenarios as plain tests and
blacklisted scenarios as ``xfail(strict=True)`` (a passing blacklisted
scenario fails the run — the same false-positive discipline as the live TCK
suite). The generated files are standalone: debugging one scenario no longer
means running the whole parametrized harness."""

from __future__ import annotations

import os
import re
from typing import Iterable, List, Optional, Sequence

from .gherkin import Feature
from .runner import ScenariosFor, load_blacklist, load_features

_HEADER = '''"""GENERATED acceptance tests from TCK feature {feature!r} — do not edit.

Regenerate with:
    python -m tpu_cypher.tck.generator <features_dir> <out_dir> [blacklist]
(reference analog: AcceptanceTestGenerator.scala:36)."""

import pytest

from tpu_cypher import CypherSession
from tpu_cypher.tck.runner import TckRunner
from tpu_cypher.tck.gherkin import parse_feature

_FEATURE_TEXT = {feature_text}

_runner = TckRunner(CypherSession.{session_factory})
# indexed, not name-keyed: duplicate scenario names must each keep their steps
_scenarios = list(parse_feature(_FEATURE_TEXT).scenarios)


def _run(index, name):
    sc = _scenarios[index]
    assert str(sc) == name, f"feature drifted: {{str(sc)!r}} != {{name!r}}"
    r = _runner.run(sc)
    assert r.passed, r.message

'''

_WHITE_CASE = '''
def test_{safe_name}():
    _run({index}, {name!r})
'''

_BLACK_CASE = '''
@pytest.mark.xfail(strict=True, reason="blacklisted: not yet supported")
def test_{safe_name}():
    _run({index}, {name!r})
'''


def _safe(name: str) -> str:
    s = re.sub(r"[^A-Za-z0-9]+", "_", name).strip("_").lower()
    return s or "scenario"


def generate_feature_module(
    feature: Feature,
    blacklisted: Iterable[str],
    session_factory: str = "local",
    keywords: Sequence[str] = (),
) -> Optional[str]:
    """Source text of one generated pytest module; None when ``keywords``
    filter out every scenario. Indices are positions in the FULL feature
    (the module re-parses the embedded source), so filtering never shifts
    them; duplicate scenario names each keep their own steps."""
    black = set(blacklisted)
    out = [
        _HEADER.format(
            feature=feature.name,
            feature_text=repr(feature.source),
            session_factory=session_factory,
        )
    ]
    used: set = set()
    emitted = 0
    for index, sc in enumerate(feature.scenarios):
        if keywords and not any(k in sc.name for k in keywords):
            continue
        base = _safe(sc.name)
        if sc.example_index is not None:
            base = f"{base}_ex{sc.example_index}"
        name = base
        i = 1
        while name in used:
            i += 1
            name = f"{base}_{i}"
        used.add(name)
        tpl = _BLACK_CASE if str(sc) in black else _WHITE_CASE
        out.append(tpl.format(safe_name=name, name=str(sc), index=index))
        emitted += 1
    if not emitted:
        return None
    return "".join(out)


def generate_all(
    features_dir: str,
    out_dir: str,
    blacklist_path: Optional[str] = None,
    session_factory: str = "local",
    keywords: Sequence[str] = (),
) -> List[str]:
    """Emit one ``test_tck_<feature>.py`` per feature; returns written paths.
    ``keywords`` restricts generation to scenarios whose name contains any
    keyword (reference ``generateGivenScenarios``)."""
    features = load_features(features_dir)
    black = load_blacklist(blacklist_path) if blacklist_path else []
    # validate blacklist scope exactly like the live harness
    ScenariosFor(features, black)
    os.makedirs(out_dir, exist_ok=True)
    written: List[str] = []
    used_names: set = set()
    for f in features:
        src = generate_feature_module(f, black, session_factory, keywords)
        if src is None:
            continue
        # dedup module filenames: distinct features may sanitize identically
        base = f"test_tck_{_safe(f.name)}"
        name = base
        i = 1
        while name in used_names:
            i += 1
            name = f"{base}_{i}"
        used_names.add(name)
        path = os.path.join(out_dir, f"{name}.py")
        with open(path, "w") as fh:
            fh.write(src)
        written.append(path)
    return written


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("features_dir")
    p.add_argument("out_dir")
    p.add_argument("blacklist", nargs="?", default=None)
    p.add_argument("--session", default="local", choices=["local", "tpu"])
    p.add_argument("--keyword", action="append", default=[])
    a = p.parse_args(argv)
    paths = generate_all(
        a.features_dir, a.out_dir, a.blacklist, a.session, a.keyword
    )
    for path in paths:
        print(path)
    return 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())

"""Logical operator ADT.

Mirrors the reference's ``LogicalOperator`` hierarchy
(``okapi-logical/.../impl/LogicalOperator.scala:39-342``): ``PatternScan``
(here NodeScan/PatternScan), ``Expand``, ``ExpandInto``,
``BoundedVarLengthExpand``, ``ValueJoin``, ``CartesianProduct``, ``Optional``,
``ExistsSubQuery``, ``Filter``, ``Project``, ``Aggregate``, ``Distinct``,
``Select``, ``OrderBy``, ``Skip``, ``Limit``, ``Unwind``, ``TabularUnionAll``,
``FromGraph``, ``ReturnGraph``, ``Start``, ``DrivingTable``, ``EmptyRecords``,
``ConstructGraph``.

Every operator exposes ``fields`` — the solved (name -> CypherType) scope —
the analog of the reference's ``SolvedQueryModel``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional as Opt, Tuple

from ..api.types import CypherType
from ..frontend.ast import SortItem
from ..ir.blocks import ConstructBlock
from ..ir.expr import Agg, Expr, Var
from ..trees import TreeNode

FieldsT = Tuple[Tuple[str, CypherType], ...]


def fields_dict(f: FieldsT) -> Dict[str, CypherType]:
    return dict(f)


class LogicalOperator(TreeNode):
    @property
    def fields(self) -> FieldsT:
        raise NotImplementedError

    @property
    def graph_name(self) -> str:
        for c in self.children:
            if isinstance(c, LogicalOperator):
                return c.graph_name
        raise AssertionError("no graph")

    def _show_inner(self) -> str:
        return ""


# -- leaves -----------------------------------------------------------------


@dataclass(frozen=True)
class Start(LogicalOperator):
    """Start from a catalog graph (reference ``Start``)."""

    qgn: str
    input_fields: FieldsT = ()

    @property
    def fields(self) -> FieldsT:
        return self.input_fields

    @property
    def graph_name(self) -> str:
        return self.qgn

    def _show_inner(self) -> str:
        return self.qgn


@dataclass(frozen=True)
class DrivingTable(LogicalOperator):
    """Start from an externally supplied table (reference ``DrivingTable``)."""

    qgn: str
    input_fields: FieldsT = ()

    @property
    def fields(self) -> FieldsT:
        return self.input_fields

    @property
    def graph_name(self) -> str:
        return self.qgn


@dataclass(frozen=True)
class EmptyRecords(LogicalOperator):
    qgn: str
    empty_fields: FieldsT = ()

    @property
    def fields(self) -> FieldsT:
        return self.empty_fields

    @property
    def graph_name(self) -> str:
        return self.qgn


# -- unary ------------------------------------------------------------------


@dataclass(frozen=True)
class UnaryOp(LogicalOperator):
    in_op: LogicalOperator

    @property
    def fields(self) -> FieldsT:
        return self.in_op.fields


@dataclass(frozen=True)
class NodeScan(UnaryOp):
    """Scan all nodes matching a node type (reference ``PatternScan`` with a
    single-node pattern, ``LogicalOperator.scala:136``)."""

    fld: str
    node_type: CypherType

    @property
    def fields(self) -> FieldsT:
        return self.in_op.fields + ((self.fld, self.node_type),)

    def _show_inner(self) -> str:
        return f"{self.fld}: {self.node_type!r}"


@dataclass(frozen=True)
class PatternScan(UnaryOp):
    """Scan a stored composite pattern (NodeRel / Triplet): one table scan
    binds several query fields at once. Produced by the optimizer rule
    ``replace_scans_with_recognized_patterns``
    (``LogicalOptimizer.scala:67``, ``Pattern.scala:135-182``)."""

    binds: FieldsT  # all fields bound by the stored pattern, entity order
    entity_map: Tuple[Tuple[str, str], ...]  # (pattern entity name, field)
    pattern: object = None  # the search GraphPattern (frozen, hashable)

    @property
    def fields(self) -> FieldsT:
        return self.in_op.fields + self.binds

    def _show_inner(self) -> str:
        return ", ".join(f"{e}={f}" for e, f in self.entity_map)


@dataclass(frozen=True)
class BindPath(UnaryOp):
    """Bind a named path variable to its ordered member element fields
    (``MATCH p = (...)``). No reference analog — the reference blacklists all
    named-path TCK scenarios (``morpheus-tck/.../failing_blacklist``)."""

    path_var: str = ""
    entities: Tuple[str, ...] = ()

    @property
    def fields(self) -> FieldsT:
        from ..api import types as _T

        return self.in_op.fields + ((self.path_var, _T.CTPath),)

    def _show_inner(self) -> str:
        return f"{self.path_var} = ({', '.join(self.entities)})"


@dataclass(frozen=True)
class Filter(UnaryOp):
    predicate: Expr

    def _show_inner(self) -> str:
        return self.predicate.pretty_expr()


@dataclass(frozen=True)
class Project(UnaryOp):
    projection: Expr
    fld: Opt[str] = None

    @property
    def fields(self) -> FieldsT:
        if self.fld is None:
            return self.in_op.fields
        t = self.projection.cypher_type
        return tuple((n, ty) for n, ty in self.in_op.fields if n != self.fld) + (
            (self.fld, t),
        )

    def _show_inner(self) -> str:
        return f"{self.fld} := {self.projection.pretty_expr()}"


@dataclass(frozen=True)
class Unwind(UnaryOp):
    list_expr: Expr
    fld: str
    fld_type: CypherType

    @property
    def fields(self) -> FieldsT:
        return self.in_op.fields + ((self.fld, self.fld_type),)

    def _show_inner(self) -> str:
        return f"{self.fld} IN {self.list_expr.pretty_expr()}"


@dataclass(frozen=True)
class Aggregate(UnaryOp):
    group: FieldsT
    aggregations: Tuple[Tuple[str, Agg], ...]

    @property
    def fields(self) -> FieldsT:
        out = list(self.group)
        for name, agg in self.aggregations:
            out.append((name, agg.cypher_type))
        return tuple(out)

    def _show_inner(self) -> str:
        g = ", ".join(n for n, _ in self.group)
        a = ", ".join(f"{n}:={a.pretty_expr()}" for n, a in self.aggregations)
        return f"group=[{g}] aggs=[{a}]"


@dataclass(frozen=True)
class Distinct(UnaryOp):
    on_fields: Tuple[str, ...]

    def _show_inner(self) -> str:
        return ", ".join(self.on_fields)


@dataclass(frozen=True)
class Select(UnaryOp):
    select_fields: Tuple[str, ...]

    @property
    def fields(self) -> FieldsT:
        d = dict(self.in_op.fields)
        return tuple((n, d[n]) for n in self.select_fields)

    def _show_inner(self) -> str:
        return ", ".join(self.select_fields)


@dataclass(frozen=True)
class OrderBy(UnaryOp):
    sort_items: Tuple[SortItem, ...]


@dataclass(frozen=True)
class Skip(UnaryOp):
    expr: Expr


@dataclass(frozen=True)
class Limit(UnaryOp):
    expr: Expr


@dataclass(frozen=True)
class FromGraph(UnaryOp):
    qgn: str

    @property
    def graph_name(self) -> str:
        return self.qgn

    def _show_inner(self) -> str:
        return self.qgn


@dataclass(frozen=True)
class ReturnGraph(UnaryOp):
    pass


@dataclass(frozen=True)
class ConstructGraph(UnaryOp):
    construct: ConstructBlock
    new_graph_name: str

    @property
    def graph_name(self) -> str:
        return self.new_graph_name


# -- binary -----------------------------------------------------------------


@dataclass(frozen=True)
class BinaryOp(LogicalOperator):
    lhs: LogicalOperator
    rhs: LogicalOperator

    @property
    def fields(self) -> FieldsT:
        d = dict(self.lhs.fields)
        for n, t in self.rhs.fields:
            d.setdefault(n, t)
        return tuple(d.items())

    @property
    def graph_name(self) -> str:
        return self.lhs.graph_name


@dataclass(frozen=True)
class CartesianProduct(BinaryOp):
    pass


@dataclass(frozen=True)
class ValueJoin(BinaryOp):
    """Inner join on equality predicates (reference ``ValueJoin``)."""

    predicates: Tuple[Expr, ...]

    def _show_inner(self) -> str:
        return ", ".join(p.pretty_expr() for p in self.predicates)


@dataclass(frozen=True)
class Optional(BinaryOp):
    """OPTIONAL MATCH: rhs plans the optional part over lhs's fields."""


@dataclass(frozen=True)
class ExistsSubQuery(BinaryOp):
    """rhs existence flag bound to ``target_field`` (reference
    ``ExistsSubQuery``, planned as semijoin flag ``RelationalPlanner.scala:224-246``).

    ``correlated``: the lhs fields the subquery actually references — the
    semijoin key. Joining on ALL common columns would break under null
    outer columns (OPTIONAL MATCH): null keys never match."""

    target_field: str
    correlated: Tuple[str, ...] = ()

    @property
    def fields(self) -> FieldsT:
        from ..api.types import CTBoolean

        return self.lhs.fields + ((self.target_field, CTBoolean),)


@dataclass(frozen=True)
class PatternComprehension(BinaryOp):
    """Per-lhs-row list of ``projection`` values over rhs pattern matches,
    bound to ``target_field``; no matches yield the empty list. Planned as
    collect-aggregate + left outer join (the reference blacklists pattern
    comprehensions at TCK level — ``failing_blacklist`` — we execute them)."""

    projection: Expr
    target_field: str
    list_type: CypherType
    correlated: Tuple[str, ...] = ()

    @property
    def fields(self) -> FieldsT:
        return self.lhs.fields + ((self.target_field, self.list_type),)


@dataclass(frozen=True)
class Expand(BinaryOp):
    """(source)-[rel]->(target): lhs solves ONE endpoint (source or target —
    inspect ``lhs.fields``), rhs scans the other
    (reference ``Expand``, ``LogicalOperator.scala:162``)."""

    source: str
    rel: str
    rel_type: CypherType
    target: str
    direction: str  # '>' outgoing from source, '-' undirected

    @property
    def fields(self) -> FieldsT:
        return BinaryOp.fields.fget(self) + ((self.rel, self.rel_type),)

    def _show_inner(self) -> str:
        arrow = "->" if self.direction == ">" else "-"
        return f"({self.source})-[{self.rel}:{self.rel_type!r}]{arrow}({self.target})"


@dataclass(frozen=True)
class ExpandInto(UnaryOp):
    """Both endpoints already bound (reference ``ExpandInto``,
    ``LogicalOperator.scala:209``)."""

    source: str
    rel: str
    rel_type: CypherType
    target: str
    direction: str

    @property
    def fields(self) -> FieldsT:
        return self.in_op.fields + ((self.rel, self.rel_type),)

    def _show_inner(self) -> str:
        return f"({self.source})-[{self.rel}]-({self.target}) INTO"


@dataclass(frozen=True)
class BoundedVarLengthExpand(BinaryOp):
    """(source)-[rel*lo..hi]->(target) (reference ``BoundedVarLengthExpand``,
    ``LogicalOperator.scala:177``)."""

    source: str
    rel: str
    rel_type: CypherType  # element type; the bound list var is CTList(rel_type)
    target: str
    direction: str
    lower: int
    upper: Opt[int]  # None = unbounded '*' (resolved at relational planning)
    # when a named path spans this rel, intermediate hop nodes are captured
    # (per-hop node-scan joins + hidden companion list column) so the path
    # value carries full node elements, not id-only placeholders
    capture_path_nodes: bool = False

    @property
    def fields(self) -> FieldsT:
        from ..api.types import CTListType

        return BinaryOp.fields.fget(self) + ((self.rel, CTListType(self.rel_type)),)

    def _show_inner(self) -> str:
        return f"({self.source})-[{self.rel}*{self.lower}..{self.upper}]->({self.target})"


@dataclass(frozen=True)
class TabularUnionAll(BinaryOp):
    @property
    def fields(self) -> FieldsT:
        return self.lhs.fields


@dataclass(frozen=True)
class GraphUnionAll(LogicalOperator):
    graphs: Tuple[LogicalOperator, ...]
    qgn: str

    @property
    def fields(self) -> FieldsT:
        return ()

    @property
    def graph_name(self) -> str:
        return self.qgn

"""Logical planner: IR blocks -> logical operator tree.

Re-design of the reference ``LogicalPlanner``
(``okapi-logical/.../impl/LogicalPlanner.scala:47``, planBlock/planLeaf/
planNonLeaf ``:93-190``) and ``LogicalOperatorProducer``: connected-component
analysis of match patterns produces Expand chains joined by CartesianProduct;
optional matches become ``Optional``; pattern predicates become
``ExistsSubQuery``; projections/aggregations/slices map 1:1 onto operators.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field as dc_field, replace as dc_replace
from typing import Dict, List, Optional as Opt, Set, Tuple

from ..api import types as T
from ..frontend.ast import SortItem
from ..ir import blocks as B
from ..ir import expr as E
from ..ir.pattern import BOTH, Connection, IRPattern
from . import ops as L


class LogicalPlanningError(Exception):
    pass


@dataclass
class LogicalPlannerContext:
    working_graph: str = "session.ambient"
    input_fields: L.FieldsT = ()


class LogicalPlanner:
    def __init__(self, ctx: LogicalPlannerContext):
        self.ctx = ctx
        self._fresh = itertools.count()
        # path var -> member entity fields (shadowing checks: a projection
        # that rebinds a member name must not corrupt later path reads)
        self._path_entities: Dict[str, Tuple[str, ...]] = {}

    def fresh(self, prefix: str) -> str:
        return f"__{prefix}_{next(self._fresh)}"

    # ------------------------------------------------------------------

    def plan(self, ir) -> L.LogicalOperator:
        if isinstance(ir, B.UnionIR):
            plans = [self.plan(q) for q in ir.queries]
            out = plans[0]
            for p in plans[1:]:
                out = L.TabularUnionAll(out, p)
            if not ir.all:
                out = L.Distinct(out, tuple(ir.returns or ()))
            return out
        assert isinstance(ir, B.QueryIR)
        graph = ir.source_graph
        if self.ctx.input_fields:
            plan: L.LogicalOperator = L.DrivingTable(graph, self.ctx.input_fields)
        else:
            plan = L.Start(graph, ())
        for blk in ir.blocks:
            plan = self.plan_block(blk, plan)
        return plan

    # ------------------------------------------------------------------

    def plan_block(self, blk: B.Block, plan: L.LogicalOperator) -> L.LogicalOperator:
        if isinstance(blk, B.MatchBlock):
            return self.plan_match(blk, plan)
        if isinstance(blk, B.ProjectBlock):
            # All items are evaluated against the PRE-projection scope
            # (simultaneous assignment: WITH a AS b, b AS a must swap).
            assigned = {
                name
                for name, ex in blk.items
                if not (isinstance(ex, E.Var) and ex.name == name)
            }

            # paths materialize LAZILY from their member columns, so a
            # projection that rebinds a member name (RETURN x.name AS x
            # with p = (x)-->(y) in scope) would corrupt every later path
            # read. Re-alias the shadowed members to hidden names and
            # re-register the path over them BEFORE any rebinding — the
            # hidden names are never assigned, so the path survives both
            # same-block reads (p IS NULL) and being carried forward (p).
            in_fields = dict(plan.fields)
            for pname, fields in list(self._path_entities.items()):
                if pname in assigned or pname not in in_fields:
                    # the path name itself is rebound / out of scope: it is
                    # no longer a live path — drop the stale registration
                    self._path_entities.pop(pname, None)
                    continue
                if not any(m in assigned for m in fields):
                    continue
                new_fields = []
                for m in fields:
                    if m in assigned and m in in_fields:
                        hid = self.fresh("pmem")
                        plan = L.Project(
                            plan, E.Var(m).with_type(in_fields[m]), hid
                        )
                        new_fields.append(hid)
                    else:
                        new_fields.append(m)
                plan = L.BindPath(plan, pname, tuple(new_fields))
                self._path_entities[pname] = tuple(new_fields)

            def _referenced(ex: E.Expr) -> set:
                # a PATH var reference depends on its member entities too
                names = {v.name for v in E.walk_vars(ex)}
                for n in list(names):
                    names |= set(self._path_entities.get(n, ()))
                return names

            item_refs = [
                (name, ex, _referenced(ex))
                for name, ex in blk.items
                if not (isinstance(ex, E.Var) and ex.name == name)
            ]
            needs_temps = any(
                name in refs and name != other
                for other, _, refs in item_refs
                for name in assigned
            )
            if needs_temps:
                renames: List[Tuple[str, E.Expr]] = []
                for name, ex in blk.items:
                    if isinstance(ex, E.Var) and ex.name == name:
                        continue
                    ex, plan = self._extract_exists(ex, plan)
                    tmp = self.fresh("proj")
                    plan = L.Project(plan, ex, tmp)
                    renames.append((name, E.Var(tmp).with_type(ex.cypher_type)))
                for name, var in renames:
                    plan = L.Project(plan, var, name)
            else:
                for name, ex in blk.items:
                    if isinstance(ex, E.Var) and ex.name == name:
                        continue
                    ex, plan = self._extract_exists(ex, plan)
                    plan = L.Project(plan, ex, name)
            return plan
        if isinstance(blk, B.AggregationBlock):
            for name, ex in blk.group:
                if not (isinstance(ex, E.Var) and ex.name == name):
                    ex, plan = self._extract_exists(ex, plan)
                    plan = L.Project(plan, ex, name)
            # aggregation INPUTS can hold exists patterns too:
            # count(exists((a)-->())) / sum(CASE WHEN exists(...) ...)
            aggs = []
            for name, agg in blk.aggregations:
                inner = getattr(agg, "expr", None)
                if inner is not None and any(
                    isinstance(nd, E.ExistsPattern) for nd in inner.iter_nodes()
                ):
                    inner, plan = self._extract_exists(inner, plan)
                    rebuilt = dc_replace(agg, expr=inner)
                    # dataclasses.replace drops the typer's non-field _typ —
                    # restore it or the output column degrades to ANY?
                    if agg.typ is not None:
                        rebuilt = rebuilt.with_type(agg.typ)
                    agg = rebuilt
                aggs.append((name, agg))
            d = dict(plan.fields)
            group = tuple((n, d[n]) for n, _ in blk.group)
            return L.Aggregate(plan, group, tuple(aggs))
        if isinstance(blk, B.FilterBlock):
            return self._plan_predicate(blk.predicate, plan)
        if isinstance(blk, B.DistinctBlock):
            return L.Distinct(plan, blk.fields)
        if isinstance(blk, B.OrderAndSliceBlock):
            if blk.sort_items:
                items: List[SortItem] = []
                for s in blk.sort_items:
                    if isinstance(s.expr, E.Var):
                        items.append(s)
                    else:
                        ex, plan = self._extract_exists(s.expr, plan)
                        f = self.fresh("sort")
                        plan = L.Project(plan, ex, f)
                        items.append(
                            SortItem(E.Var(f).with_type(ex.cypher_type), s.ascending)
                        )
                plan = L.OrderBy(plan, tuple(items))
            if blk.skip is not None:
                plan = L.Skip(plan, blk.skip)
            if blk.limit is not None:
                plan = L.Limit(plan, blk.limit)
            return plan
        if isinstance(blk, B.UnwindBlock):
            lx, plan = self._extract_exists(blk.list_expr, plan)
            inner = lx.cypher_type.material
            t = inner.inner if isinstance(inner, T.CTListType) else T.CTAny.nullable
            return L.Unwind(plan, lx, blk.fld, t)
        if isinstance(blk, (B.SelectBlock, B.ResultBlock)):
            current = tuple(n for n, _ in plan.fields)
            if current == tuple(blk.fields):
                return plan
            return L.Select(plan, tuple(blk.fields))
        if isinstance(blk, B.FromGraphBlock):
            return L.FromGraph(plan, blk.qgn)
        if isinstance(blk, B.GraphResultBlock):
            return L.ReturnGraph(plan)
        if isinstance(blk, B.ConstructBlock):
            # SET / property-map values may contain subquery expressions
            # (exists, pattern comprehensions) — extract them into the
            # binding plan before the construct consumes it
            import dataclasses

            def _ex(items):
                nonlocal plan
                out = []
                for owner, key, expr in items:
                    ex, plan = self._extract_exists(expr, plan)
                    out.append((owner, key, ex))
                return tuple(out)

            new_properties = _ex(blk.new_properties)
            sets = _ex(blk.sets)
            if new_properties != blk.new_properties or sets != blk.sets:
                blk = dataclasses.replace(
                    blk, new_properties=new_properties, sets=sets
                )
            return L.ConstructGraph(plan, blk, self.fresh("constructed"))
        raise LogicalPlanningError(f"Cannot plan block {type(blk).__name__}")

    # ------------------------------------------------------------------
    # MATCH planning
    # ------------------------------------------------------------------

    def plan_match(self, blk: B.MatchBlock, plan: L.LogicalOperator) -> L.LogicalOperator:
        # paths bind before predicates so WHERE can reference the path var
        if blk.optional:
            rhs = self._plan_pattern(blk.pattern, plan)
            for pname, fields in sorted(blk.pattern.paths.items()):
                rhs = L.BindPath(rhs, pname, tuple(fields))
                self._path_entities[pname] = tuple(fields)
            for p in blk.predicates:
                rhs = self._plan_predicate(p, rhs)
            return L.Optional(plan, rhs)
        plan = self._plan_pattern(blk.pattern, plan)
        for pname, fields in sorted(blk.pattern.paths.items()):
            plan = L.BindPath(plan, pname, tuple(fields))
            self._path_entities[pname] = tuple(fields)
        for p in blk.predicates:
            plan = self._plan_predicate(p, plan)
        return plan

    def _plan_pattern(
        self, pattern: IRPattern, base: L.LogicalOperator
    ) -> L.LogicalOperator:
        graph = base.graph_name
        bound: Set[str] = {n for n, _ in base.fields}
        solved_nodes: Set[str] = {n for n in pattern.node_types if n in bound}
        unsolved_conns: Dict[str, Connection] = {
            r: c for r, c in pattern.topology.items() if r not in bound
        }
        plan = base

        def node_scan(fld: str, on: Opt[L.LogicalOperator] = None) -> L.LogicalOperator:
            src = on if on is not None else L.Start(graph, ())
            return L.NodeScan(src, fld, pattern.node_types[fld])

        # deterministic component order: components containing bound nodes
        # first, then fixed-length-only components before ones with
        # var-length connections (so fixed rels are in scope when a
        # var-length plans — its isomorphism-vs-fixed predicates can then
        # push into the fused walk as forbidden edges instead of filtering
        # a materialized rel list), then by smallest member name
        def comp_key(comp):
            has_var = any(
                c.is_var_length
                for r, c in unsolved_conns.items()
                if c.source in comp or c.target in comp
            )
            return (not any(n in bound for n in comp), has_var, sorted(comp)[0])

        comps = sorted(pattern.components(), key=comp_key)
        for comp in comps:
            comp_conns = {
                r: c
                for r, c in unsolved_conns.items()
                if c.source in comp or c.target in comp
            }
            if not any(n in solved_nodes for n in comp):
                # need a fresh scan to anchor this component
                start = self._pick_start(comp, pattern)
                scan = node_scan(start)
                if not plan.fields and isinstance(plan, L.Start):
                    plan = scan
                else:
                    plan = L.CartesianProduct(plan, scan)
                solved_nodes.add(start)
            # expand until the whole component is solved
            while comp_conns:
                progress = False
                for r in sorted(
                    comp_conns, key=lambda n: (comp_conns[n].is_var_length, n)
                ):
                    c = comp_conns[r]
                    src_solved = c.source in solved_nodes
                    dst_solved = c.target in solved_nodes
                    if not (src_solved or dst_solved):
                        continue
                    plan = self._plan_connection(
                        plan, pattern, r, c, src_solved, dst_solved, graph
                    )
                    solved_nodes.add(c.source)
                    solved_nodes.add(c.target)
                    del comp_conns[r]
                    del unsolved_conns[r]
                    progress = True
                    break
                if not progress:  # pragma: no cover - components guarantee progress
                    raise LogicalPlanningError("Disconnected pattern component")
            # isolated unsolved nodes (no connections)
            for n in sorted(comp):
                if n not in solved_nodes:
                    plan = L.CartesianProduct(plan, node_scan(n))
                    solved_nodes.add(n)
        return plan

    @staticmethod
    def _pick_start(comp, pattern: IRPattern) -> str:
        # prefer labelled nodes (cheaper scans), then name determinism
        def key(n):
            t = pattern.node_types[n]
            return (-len(t.labels), n)

        return min(comp, key=key)

    def _plan_connection(
        self,
        plan: L.LogicalOperator,
        pattern: IRPattern,
        rel: str,
        c: Connection,
        src_solved: bool,
        dst_solved: bool,
        graph: str,
    ) -> L.LogicalOperator:
        rel_type = pattern.rel_types[rel]
        if not c.is_var_length:
            if src_solved and dst_solved:
                return L.ExpandInto(plan, c.source, rel, rel_type, c.target, c.direction)
            new_node = c.target if src_solved else c.source
            scan = L.NodeScan(L.Start(graph, ()), new_node, pattern.node_types[new_node])
            return L.Expand(plan, scan, c.source, rel, rel_type, c.target, c.direction)
        # var-length; upper None = unbounded, resolved at relational planning
        upper = c.upper
        capture = any(rel in fields for fields in pattern.paths.values())
        if dst_solved and not src_solved:
            # the walk reached this connection from its TARGET: the classic
            # cascade and the fused frontier loop both expand FROM the
            # source, so bring the source into the plan (cartesian) and
            # reuse the both-solved alignment below. The optimizer's
            # filter/value-join rewrites then tighten the product.
            scan = L.NodeScan(
                L.Start(graph, ()), c.source, pattern.node_types[c.source]
            )
            plan = L.CartesianProduct(plan, scan)
            src_solved = True
        if src_solved and dst_solved:
            # expand to a fresh target, then align on id equality
            fresh_t = self.fresh(f"vt_{c.target}")
            t_type = pattern.node_types[c.target]
            scan = L.NodeScan(L.Start(graph, ()), fresh_t, t_type)
            expand = L.BoundedVarLengthExpand(
                plan, scan, c.source, rel, rel_type, fresh_t, c.direction,
                c.lower, upper, capture,
            )
            eq = E.Equals(
                E.Id(E.Var(fresh_t).with_type(t_type)).with_type(T.CTInteger),
                E.Id(E.Var(c.target).with_type(t_type)).with_type(T.CTInteger),
            ).with_type(T.CTBoolean)
            return L.Filter(expand, eq)
        new_node = c.target if src_solved else c.source
        scan = L.NodeScan(L.Start(graph, ()), new_node, pattern.node_types[new_node])
        return L.BoundedVarLengthExpand(
            plan, scan, c.source, rel, rel_type, c.target, c.direction,
            c.lower, upper, capture,
        )

    # ------------------------------------------------------------------
    # predicates (incl. exists subqueries)
    # ------------------------------------------------------------------

    def _extract_exists(
        self, expr: E.Expr, plan: L.LogicalOperator
    ) -> Tuple[E.Expr, L.LogicalOperator]:
        """Replace every exists-pattern inside ``expr`` with the boolean
        flag var of a planned ``ExistsSubQuery`` (works in WHERE and in
        projections alike — reference
        ``extractSubqueryFromPatternExpression``)."""
        subs = [
            n
            for n in expr.iter_nodes()
            if isinstance(n, (E.ExistsPattern, E.PatternComprehension))
        ]
        mapping: Dict[E.Expr, E.Expr] = {}
        for ep in subs:
            sub_pattern = getattr(ep, "_ir_pattern", None)
            if sub_pattern is None:
                raise LogicalPlanningError(
                    f"{type(ep).__name__} missing IR pattern"
                )
            # the lhs fields the subquery actually references: pattern vars
            # plus free vars of its predicates/projection (including inside
            # nested subquery bodies). These are the semijoin/group keys —
            # joining on ALL common columns breaks under null outer columns
            # (OPTIONAL MATCH): null keys never match, silently emptying
            # the subquery result
            lhs_fields = {n for n, _ in plan.fields}
            used = (
                set(sub_pattern.node_types)
                | set(sub_pattern.rel_types)
                | set(sub_pattern.paths)
            )
            for p in getattr(ep, "_ir_predicates", ()):
                used |= _subquery_free_vars(p)
            if isinstance(ep, E.ExistsPattern):
                correlated = tuple(sorted(used & lhs_fields))
                target = ep.target_field or self.fresh("exists")
                rhs = self._plan_pattern(sub_pattern, plan)
                for p in getattr(ep, "_ir_predicates", ()):
                    rhs = self._plan_predicate(p, rhs)
                plan = L.ExistsSubQuery(plan, rhs, target, correlated)
                mapping[ep] = E.Var(target).with_type(T.CTBoolean)
                continue
            target = ep.target_field or self.fresh("pc")
            used |= _subquery_free_vars(ep._ir_projection)
            correlated = tuple(sorted(used & lhs_fields))
            # expand from outer rows deduplicated on the CORRELATED fields
            # (the collect group keys): outer rows that are distinct in
            # other columns but share the correlated bindings must drive
            # the pattern exactly once, or the collected list is inflated
            # by the duplicate count. An UNcorrelated comprehension is
            # driven by a single row (DistinctOp treats an empty field list
            # as distinct-over-all, which would keep the duplicates).
            if correlated:
                dedup: L.LogicalOperator = L.Distinct(plan, correlated)
            else:
                dedup = L.Limit(plan, E.Lit(1).with_type(T.CTInteger))
            rhs = self._plan_pattern(sub_pattern, dedup)
            for pname, fields in sorted(sub_pattern.paths.items()):
                rhs = L.BindPath(rhs, pname, tuple(fields))
            for p in getattr(ep, "_ir_predicates", ()):
                rhs = self._plan_predicate(p, rhs)
            # nested comprehensions/exists in the projection extract into rhs
            proj, rhs = self._extract_exists(ep._ir_projection, rhs)
            list_type = T.CTListType(proj.cypher_type)
            plan = L.PatternComprehension(
                plan, rhs, proj, target, list_type, correlated
            )
            mapping[ep] = E.Var(target).with_type(list_type)
        if mapping:
            expr = E.substitute(expr, mapping)
        return expr, plan

    def _plan_predicate(self, pred: E.Expr, plan: L.LogicalOperator) -> L.LogicalOperator:
        pred, plan = self._extract_exists(pred, plan)
        return L.Filter(plan, pred)


def _subquery_free_vars(expr: E.Expr) -> set:
    """Variable names an expression references, INCLUDING inside nested
    subquery bodies (exists patterns / pattern comprehensions), whose inner
    expressions are boxed away from generic traversal."""
    out = set()
    stack = [expr]
    while stack:
        e = stack.pop()
        for n in e.iter_nodes():
            if isinstance(n, E.Var):
                out.add(n.name)
            if isinstance(n, (E.ExistsPattern, E.PatternComprehension)):
                sub = getattr(n, "_ir_pattern", None)
                if sub is not None:
                    out |= (
                        set(sub.node_types)
                        | set(sub.rel_types)
                        | set(sub.paths)
                    )
                stack.extend(getattr(n, "_ir_predicates", ()))
                inner = getattr(n, "_ir_projection", None)
                if inner is not None:
                    stack.append(inner)
    return out


def plan_logical(ir, ctx: Opt[LogicalPlannerContext] = None) -> L.LogicalOperator:
    return LogicalPlanner(ctx or LogicalPlannerContext()).plan(ir)

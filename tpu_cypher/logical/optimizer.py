"""Logical optimizer.

Mirrors the reference's rule pipeline (``LogicalOptimizer.scala:41``):

* ``discard_scans_for_nonexistent_labels`` — scans on labels absent from the
  schema become EmptyRecords (``LogicalOptimizer.scala`` rule 1),
* ``replace_cartesian_with_value_join`` — a Filter(Equals) above a
  CartesianProduct whose sides each solve one operand becomes a ValueJoin
  (``LogicalOptimizer.scala:53``),
* filter pushdown below cartesian products (our addition — the reference
  relies on engine optimizers (Catalyst/Calcite) for this; we have no engine
  below us, so simple pushdown lives here).
"""

from __future__ import annotations

from typing import Dict, Optional, Set

from ..api.schema import PropertyGraphSchema
from ..api import types as T
from ..ir import expr as E
from ..trees import TreeNode
from . import ops as L


def optimize(
    plan: L.LogicalOperator,
    schema: Optional[PropertyGraphSchema] = None,
    catalog_schemas: Optional[Dict[str, PropertyGraphSchema]] = None,
    ambient_qgn: Optional[str] = None,
    graph_patterns: Optional[Dict[str, frozenset]] = None,
) -> L.LogicalOperator:
    if schema is not None:
        plan = discard_scans_for_nonexistent_labels(
            plan, schema, catalog_schemas, ambient_qgn
        )
    plan = replace_cartesian_with_value_join(plan)
    if graph_patterns:
        plan = replace_scans_with_recognized_patterns(plan, graph_patterns)
    return plan


def replace_scans_with_recognized_patterns(
    plan: L.LogicalOperator, graph_patterns: Dict[str, object]
) -> L.LogicalOperator:
    """Rewrite Expand over a graph that STORES a matching composite pattern
    into a single ``PatternScan`` (reference
    ``LogicalOptimizer.replaceScansWithRecognizedPatterns``,
    ``LogicalOptimizer.scala:67-130``):

    * stored TripletPattern covering (source, rel, target): the expand's
      rel-scan + 2 joins collapse to one scan; when the source is already
      solved by a larger subtree, the pattern scan (with a renamed source)
      value-joins that subtree on the source id.
    * stored NodeRelPattern covering (source, rel): the source scan + rel
      scan collapse; one join against the target scan remains.
    """
    from ..api.graph_pattern import (
        NODE_ENTITY,
        REL_ENTITY,
        SOURCE_ENTITY,
        TARGET_ENTITY,
        NodeRelPattern,
        TripletPattern,
    )

    def field_type(op: L.LogicalOperator, name: str):
        for n, t in op.fields:
            if n == name:
                return t
        return None

    def scan_qgn(op: L.LogicalOperator) -> Optional[str]:
        if isinstance(op, L.NodeScan) and isinstance(op.in_op, L.Start):
            return op.in_op.qgn
        return None

    def rewrite(op: L.LogicalOperator) -> L.LogicalOperator:
        if not isinstance(op, L.Expand) or op.direction != ">":
            return op
        qgn = scan_qgn(op.rhs)
        if qgn is None or qgn not in graph_patterns:
            return op
        graph = graph_patterns[qgn]
        src_t = field_type(op.lhs, op.source)
        tgt_t = field_type(op.rhs, op.target)
        rel_t = op.rel_type
        if src_t is None or tgt_t is None:
            return op
        src_m = src_t.material if hasattr(src_t, "material") else src_t
        tgt_m = tgt_t.material if hasattr(tgt_t, "material") else tgt_t
        rel_m = rel_t.material if hasattr(rel_t, "material") else rel_t
        if not isinstance(src_m, T.CTNodeType) or not isinstance(
            tgt_m, T.CTNodeType
        ):
            return op
        triplet = TripletPattern(src_m, rel_m, tgt_m)
        has_triplet = graph.supports_pattern_rewrite(triplet)
        node_rel = NodeRelPattern(src_m, rel_m)
        has_node_rel = not has_triplet and graph.supports_pattern_rewrite(
            node_rel
        )
        bare_source = (
            isinstance(op.lhs, L.NodeScan)
            and isinstance(op.lhs.in_op, L.Start)
            and op.lhs.fld == op.source
        )
        start = L.Start(qgn, ())
        if has_triplet:
            if bare_source:
                return L.PatternScan(
                    start,
                    binds=(
                        (op.source, src_t),
                        (op.rel, rel_t),
                        (op.target, tgt_t),
                    ),
                    entity_map=(
                        (SOURCE_ENTITY, op.source),
                        (REL_ENTITY, op.rel),
                        (TARGET_ENTITY, op.target),
                    ),
                    pattern=triplet,
                )
            renamed = op.source + "$ps"
            ps = L.PatternScan(
                start,
                binds=((renamed, src_t), (op.rel, rel_t), (op.target, tgt_t)),
                entity_map=(
                    (SOURCE_ENTITY, renamed),
                    (REL_ENTITY, op.rel),
                    (TARGET_ENTITY, op.target),
                ),
                pattern=triplet,
            )
            join = E.Equals(
                E.Id(E.Var(op.source).with_type(src_t)),
                E.Id(E.Var(renamed).with_type(src_t)),
            ).with_type(T.CTBoolean)
            return L.ValueJoin(op.lhs, ps, (join,))
        if has_node_rel:
            if bare_source:
                base: L.LogicalOperator = L.PatternScan(
                    start,
                    binds=((op.source, src_t), (op.rel, rel_t)),
                    entity_map=((NODE_ENTITY, op.source), (REL_ENTITY, op.rel)),
                    pattern=node_rel,
                )
            else:
                renamed = op.source + "$ps"
                ps = L.PatternScan(
                    start,
                    binds=((renamed, src_t), (op.rel, rel_t)),
                    entity_map=((NODE_ENTITY, renamed), (REL_ENTITY, op.rel)),
                    pattern=node_rel,
                )
                join = E.Equals(
                    E.Id(E.Var(op.source).with_type(src_t)),
                    E.Id(E.Var(renamed).with_type(src_t)),
                ).with_type(T.CTBoolean)
                base = L.ValueJoin(op.lhs, ps, (join,))
            end_join = E.Equals(
                E.EndNode(E.Var(op.rel).with_type(rel_t)).with_type(T.CTInteger),
                E.Id(E.Var(op.target).with_type(tgt_t)).with_type(T.CTInteger),
            ).with_type(T.CTBoolean)
            return L.ValueJoin(base, op.rhs, (end_join,))
        return op

    return plan.rewrite(rewrite)


def discard_scans_for_nonexistent_labels(
    plan: L.LogicalOperator,
    schema: PropertyGraphSchema,
    catalog_schemas: Optional[Dict[str, PropertyGraphSchema]] = None,
    ambient_qgn: Optional[str] = None,
) -> L.LogicalOperator:
    """A scan whose labels can't exist in its source graph's schema becomes
    EmptyRecords. The scan's OWN graph (``n.graph_name``) decides — a scan
    after FROM GRAPH must be pruned against that graph's schema, not the
    ambient one (reference ``LogicalOptimizer.discardScansForNonexistentLabels``)."""

    def schema_for(qgn: str) -> Optional[PropertyGraphSchema]:
        if ambient_qgn is not None and qgn == ambient_qgn:
            return schema
        if catalog_schemas is not None and qgn in catalog_schemas:
            return catalog_schemas[qgn]
        if ambient_qgn is None and catalog_schemas is None:
            return schema  # legacy single-schema call
        return None  # unknown graph (e.g. mid-query CONSTRUCT result): keep scan

    def rule(n: TreeNode) -> TreeNode:
        if isinstance(n, L.NodeScan):
            t = n.node_type
            s = schema_for(n.graph_name)
            if s is not None and isinstance(t, T.CTNodeType) and not (
                t.labels <= s.labels
            ):
                return L.EmptyRecords(n.graph_name, n.fields)
        return n

    return plan.rewrite(rule)


def _vars_of(e: E.Expr) -> Set[str]:
    return {v.name for v in e.iter_nodes() if isinstance(v, E.Var)}


def replace_cartesian_with_value_join(plan: L.LogicalOperator) -> L.LogicalOperator:
    """Filter(Equals(l, r), CartesianProduct(a, b)) -> ValueJoin(a, b, l=r)."""

    def rule(n: TreeNode) -> TreeNode:
        if not isinstance(n, L.Filter):
            return n
        pred = n.predicate
        eqs = [pred] if isinstance(pred, E.Equals) else (
            [p for p in pred.exprs if isinstance(p, E.Equals)]
            if isinstance(pred, E.Ands)
            else []
        )
        if not eqs or not isinstance(n.in_op, L.CartesianProduct):
            return n
        cart = n.in_op
        lhs_fields = {f for f, _ in cart.lhs.fields}
        rhs_fields = {f for f, _ in cart.rhs.fields}
        join_preds = []
        rest = (
            list(pred.exprs) if isinstance(pred, E.Ands) else [pred]
        )
        for eq in eqs:
            lv, rv = _vars_of(eq.lhs), _vars_of(eq.rhs)
            if lv <= lhs_fields and rv <= rhs_fields:
                join_preds.append(eq)
                rest.remove(eq)
            elif lv <= rhs_fields and rv <= lhs_fields:
                join_preds.append(E.Equals(eq.rhs, eq.lhs).with_type(eq.cypher_type))
                rest.remove(eq)
        if not join_preds:
            return n
        out: L.LogicalOperator = L.ValueJoin(cart.lhs, cart.rhs, tuple(join_preds))
        if rest:
            remaining = rest[0] if len(rest) == 1 else E.Ands(tuple(rest)).with_type(
                T.CTBoolean.nullable
            )
            out = L.Filter(out, remaining)
        return out

    return plan.rewrite(rule)

"""Logical optimizer.

Mirrors the reference's rule pipeline (``LogicalOptimizer.scala:41``):

* ``discard_scans_for_nonexistent_labels`` — scans on labels absent from the
  schema become EmptyRecords (``LogicalOptimizer.scala`` rule 1),
* ``replace_cartesian_with_value_join`` — a Filter(Equals) above a
  CartesianProduct whose sides each solve one operand becomes a ValueJoin
  (``LogicalOptimizer.scala:53``),
* filter pushdown below cartesian products (our addition — the reference
  relies on engine optimizers (Catalyst/Calcite) for this; we have no engine
  below us, so simple pushdown lives here).
"""

from __future__ import annotations

from typing import Dict, Optional, Set

from ..api.schema import PropertyGraphSchema
from ..api import types as T
from ..ir import expr as E
from ..trees import TreeNode
from . import ops as L


def optimize(
    plan: L.LogicalOperator,
    schema: Optional[PropertyGraphSchema] = None,
    catalog_schemas: Optional[Dict[str, PropertyGraphSchema]] = None,
    ambient_qgn: Optional[str] = None,
) -> L.LogicalOperator:
    if schema is not None:
        plan = discard_scans_for_nonexistent_labels(
            plan, schema, catalog_schemas, ambient_qgn
        )
    plan = replace_cartesian_with_value_join(plan)
    return plan


def discard_scans_for_nonexistent_labels(
    plan: L.LogicalOperator,
    schema: PropertyGraphSchema,
    catalog_schemas: Optional[Dict[str, PropertyGraphSchema]] = None,
    ambient_qgn: Optional[str] = None,
) -> L.LogicalOperator:
    """A scan whose labels can't exist in its source graph's schema becomes
    EmptyRecords. The scan's OWN graph (``n.graph_name``) decides — a scan
    after FROM GRAPH must be pruned against that graph's schema, not the
    ambient one (reference ``LogicalOptimizer.discardScansForNonexistentLabels``)."""

    def schema_for(qgn: str) -> Optional[PropertyGraphSchema]:
        if ambient_qgn is not None and qgn == ambient_qgn:
            return schema
        if catalog_schemas is not None and qgn in catalog_schemas:
            return catalog_schemas[qgn]
        if ambient_qgn is None and catalog_schemas is None:
            return schema  # legacy single-schema call
        return None  # unknown graph (e.g. mid-query CONSTRUCT result): keep scan

    def rule(n: TreeNode) -> TreeNode:
        if isinstance(n, L.NodeScan):
            t = n.node_type
            s = schema_for(n.graph_name)
            if s is not None and isinstance(t, T.CTNodeType) and not (
                t.labels <= s.labels
            ):
                return L.EmptyRecords(n.graph_name, n.fields)
        return n

    return plan.rewrite(rule)


def _vars_of(e: E.Expr) -> Set[str]:
    return {v.name for v in e.iter_nodes() if isinstance(v, E.Var)}


def replace_cartesian_with_value_join(plan: L.LogicalOperator) -> L.LogicalOperator:
    """Filter(Equals(l, r), CartesianProduct(a, b)) -> ValueJoin(a, b, l=r)."""

    def rule(n: TreeNode) -> TreeNode:
        if not isinstance(n, L.Filter):
            return n
        pred = n.predicate
        eqs = [pred] if isinstance(pred, E.Equals) else (
            [p for p in pred.exprs if isinstance(p, E.Equals)]
            if isinstance(pred, E.Ands)
            else []
        )
        if not eqs or not isinstance(n.in_op, L.CartesianProduct):
            return n
        cart = n.in_op
        lhs_fields = {f for f, _ in cart.lhs.fields}
        rhs_fields = {f for f, _ in cart.rhs.fields}
        join_preds = []
        rest = (
            list(pred.exprs) if isinstance(pred, E.Ands) else [pred]
        )
        for eq in eqs:
            lv, rv = _vars_of(eq.lhs), _vars_of(eq.rhs)
            if lv <= lhs_fields and rv <= rhs_fields:
                join_preds.append(eq)
                rest.remove(eq)
            elif lv <= rhs_fields and rv <= lhs_fields:
                join_preds.append(E.Equals(eq.rhs, eq.lhs).with_type(eq.cypher_type))
                rest.remove(eq)
        if not join_preds:
            return n
        out: L.LogicalOperator = L.ValueJoin(cart.lhs, cart.rhs, tuple(join_preds))
        if rest:
            remaining = rest[0] if len(rest) == 1 else E.Ands(tuple(rest)).with_type(
                T.CTBoolean.nullable
            )
            out = L.Filter(out, remaining)
        return out

    return plan.rewrite(rule)

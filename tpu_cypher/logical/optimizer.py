"""Logical optimizer.

Mirrors the reference's rule pipeline (``LogicalOptimizer.scala:41``):

* ``discard_scans_for_nonexistent_labels`` — scans on labels absent from the
  schema become EmptyRecords (``LogicalOptimizer.scala`` rule 1),
* ``replace_cartesian_with_value_join`` — a Filter(Equals) above a
  CartesianProduct whose sides each solve one operand becomes a ValueJoin
  (``LogicalOptimizer.scala:53``),
* filter pushdown below cartesian products (our addition — the reference
  relies on engine optimizers (Catalyst/Calcite) for this; we have no engine
  below us, so simple pushdown lives here).
"""

from __future__ import annotations

from typing import Dict, Optional, Set

from ..api.schema import PropertyGraphSchema
from ..api import types as T
from ..ir import expr as E
from ..trees import TreeNode
from . import ops as L


def optimize(
    plan: L.LogicalOperator, schema: Optional[PropertyGraphSchema] = None
) -> L.LogicalOperator:
    if schema is not None:
        plan = discard_scans_for_nonexistent_labels(plan, schema)
    plan = replace_cartesian_with_value_join(plan)
    return plan


def discard_scans_for_nonexistent_labels(
    plan: L.LogicalOperator, schema: PropertyGraphSchema
) -> L.LogicalOperator:
    known = schema.labels

    def rule(n: TreeNode) -> TreeNode:
        if isinstance(n, L.NodeScan):
            t = n.node_type
            if isinstance(t, T.CTNodeType) and not (t.labels <= known):
                return L.EmptyRecords(n.graph_name, n.fields)
        return n

    return plan.rewrite(rule)


def _vars_of(e: E.Expr) -> Set[str]:
    return {v.name for v in e.iter_nodes() if isinstance(v, E.Var)}


def replace_cartesian_with_value_join(plan: L.LogicalOperator) -> L.LogicalOperator:
    """Filter(Equals(l, r), CartesianProduct(a, b)) -> ValueJoin(a, b, l=r)."""

    def rule(n: TreeNode) -> TreeNode:
        if not isinstance(n, L.Filter):
            return n
        pred = n.predicate
        eqs = [pred] if isinstance(pred, E.Equals) else (
            [p for p in pred.exprs if isinstance(p, E.Equals)]
            if isinstance(pred, E.Ands)
            else []
        )
        if not eqs or not isinstance(n.in_op, L.CartesianProduct):
            return n
        cart = n.in_op
        lhs_fields = {f for f, _ in cart.lhs.fields}
        rhs_fields = {f for f, _ in cart.rhs.fields}
        join_preds = []
        rest = (
            list(pred.exprs) if isinstance(pred, E.Ands) else [pred]
        )
        for eq in eqs:
            lv, rv = _vars_of(eq.lhs), _vars_of(eq.rhs)
            if lv <= lhs_fields and rv <= rhs_fields:
                join_preds.append(eq)
                rest.remove(eq)
            elif lv <= rhs_fields and rv <= lhs_fields:
                join_preds.append(E.Equals(eq.rhs, eq.lhs).with_type(eq.cypher_type))
                rest.remove(eq)
        if not join_preds:
            return n
        out: L.LogicalOperator = L.ValueJoin(cart.lhs, cart.rhs, tuple(join_preds))
        if rest:
            remaining = rest[0] if len(rest) == 1 else E.Ands(tuple(rest)).with_type(
                T.CTBoolean.nullable
            )
            out = L.Filter(out, remaining)
        return out

    return plan.rewrite(rule)

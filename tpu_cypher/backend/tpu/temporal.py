"""Device calendar math for temporal columns.

The reference executes temporal accessors and arithmetic inside the engine
on executors (``morpheus-spark-cypher/.../impl/temporal/TemporalUdfs.scala:40-160``);
the TPU-native equivalent stores date as days-since-epoch int32 and
localdatetime as microseconds-since-epoch int64 (SURVEY §2.2 temporal row)
and computes the civil-calendar fields with branch-free integer arithmetic
on the VPU (the standard era/year-of-era decomposition of the proleptic
Gregorian calendar — Howard Hinnant's public-domain ``civil_from_days``
construction — vectorized with ``jnp.where`` instead of branches).

All functions here are TRACED helpers (called inside jitted programs or the
eager compiler path); every input/output is a device array.
"""

from __future__ import annotations

import datetime as _dt

import jax.numpy as jnp

EPOCH_ORDINAL = _dt.date(1970, 1, 1).toordinal()
US_PER_SECOND = 1_000_000
US_PER_DAY = 86_400 * US_PER_SECOND


def encode_date(d: _dt.date) -> int:
    return d.toordinal() - EPOCH_ORDINAL


def decode_date(z: int) -> _dt.date:
    return _dt.date.fromordinal(int(z) + EPOCH_ORDINAL)


def encode_ldt(dt: _dt.datetime) -> int:
    days = dt.toordinal() - EPOCH_ORDINAL
    tod = (
        (dt.hour * 3600 + dt.minute * 60 + dt.second) * US_PER_SECOND
        + dt.microsecond
    )
    return days * US_PER_DAY + tod


US_PER_HOUR = 3600 * US_PER_SECOND


def offset_seconds_of(v) -> int:
    """Fixed zone offset of an aware datetime/time value, in seconds."""
    off = v.utcoffset()
    return int(off.total_seconds())


def offset_str(off_seconds: int) -> str:
    from ...api.values import format_utc_offset

    return format_utc_offset(off_seconds)


def parse_offset_str(s: str) -> int:
    sign = -1 if s.startswith("-") else 1
    parts = s.lstrip("+-").split(":")
    total = int(parts[0]) * 3600 + int(parts[1]) * 60
    if len(parts) > 2:
        total += int(parts[2])
    return sign * total


def encode_zdt(v: _dt.datetime) -> int:
    """Aware datetime -> UTC microseconds since epoch (the device lane;
    the column-level offset rides separately)."""
    off = offset_seconds_of(v)
    return encode_ldt(v.replace(tzinfo=None)) - off * US_PER_SECOND


def decode_zdt(utc_us: int, off_seconds: int) -> _dt.datetime:
    local = decode_ldt(int(utc_us) + off_seconds * US_PER_SECOND)
    return local.replace(
        tzinfo=_dt.timezone(_dt.timedelta(seconds=off_seconds))
    )


def encode_time_of_day(t: _dt.time) -> int:
    return (
        (t.hour * 3600 + t.minute * 60 + t.second) * US_PER_SECOND
        + t.microsecond
    )


def encode_zt(t: _dt.time) -> int:
    """Aware time -> SIGNED unwrapped UTC-adjusted micros (local minus
    offset, range (-14h, 38h)). The host oracle (Python aware-time
    comparison) and Neo4j order/compare zoned times by this value WITHOUT
    wrapping — a mod-24h lane would sort +02:00's 01:00 after 12:00 and
    alias 23:00Z with 01:00+02:00. The wrap belongs only in duration
    arithmetic and ``decode_zt``."""
    off = offset_seconds_of(t)
    return encode_time_of_day(t) - off * US_PER_SECOND


def decode_zt(adj_us: int, off_seconds: int) -> _dt.time:
    local = (int(adj_us) + off_seconds * US_PER_SECOND) % US_PER_DAY
    return decode_lt(local).replace(
        tzinfo=_dt.timezone(_dt.timedelta(seconds=off_seconds))
    )


def decode_lt(us: int) -> _dt.time:
    us = int(us)
    secs, micro = divmod(us, US_PER_SECOND)
    h, rem = divmod(secs, 3600)
    m, sec = divmod(rem, 60)
    return _dt.time(h % 24, m, sec, micro)


def decode_ldt(us: int) -> _dt.datetime:
    days, tod = divmod(int(us), US_PER_DAY)
    secs, micro = divmod(tod, US_PER_SECOND)
    h, rem = divmod(secs, 3600)
    m, s = divmod(rem, 60)
    d = _dt.date.fromordinal(days + EPOCH_ORDINAL)
    return _dt.datetime(d.year, d.month, d.day, h, m, s, micro)


# ---------------------------------------------------------------------------
# traced calendar decomposition
# ---------------------------------------------------------------------------


def civil_from_days(z):
    """days-since-1970 -> (year, month, day), all int64 device arrays."""
    z = z.astype(jnp.int64) + 719468
    era = jnp.floor_divide(z, 146097)
    doe = z - era * 146097  # [0, 146096]
    yoe = jnp.floor_divide(
        doe - doe // 1460 + doe // 36524 - doe // 146096, 365
    )  # [0, 399]
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)  # [0, 365]
    mp = (5 * doy + 2) // 153  # [0, 11]
    d = doy - (153 * mp + 2) // 5 + 1  # [1, 31]
    m = mp + jnp.where(mp < 10, 3, -9)  # [1, 12]
    return y + (m <= 2), m, d


def days_from_civil(y, m, d):
    """(year, month, day) -> days-since-1970 (inverse of civil_from_days)."""
    y = y.astype(jnp.int64) - (m <= 2)
    era = jnp.floor_divide(y, 400)
    yoe = y - era * 400
    doy = (153 * (m + jnp.where(m > 2, -3, 9)) + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


# host constant; converted per-trace (a module-level device array would
# bake an int32 before column.py enables x64)
_DAYS_IN_MONTH = (31, 28, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31)


def add_duration_micros(us, months, ddays, dmicros):
    """local-micros + (months, days, micros) with the oracle's semantics
    (``eval._add_duration``): months first with end-of-month day clamping,
    then whole days, then the time remainder. All inputs are traced int64
    arrays; jnp's floored // and non-negative % match Python on negative
    month totals."""
    days, tod = split_ldt(us)
    y, m, d = civil_from_days(days)
    tot = y * 12 + (m - 1) + months
    ny = tot // 12
    nm = tot % 12 + 1
    leap = ((ny % 4 == 0) & (ny % 100 != 0)) | (ny % 400 == 0)
    dim = jnp.take(
        jnp.asarray(_DAYS_IN_MONTH, jnp.int64), nm - 1
    ) + jnp.where((nm == 2) & leap, 1, 0)
    nd = jnp.minimum(d, dim)
    days2 = days_from_civil(ny, nm, nd)
    # (result, month-shifted intermediate days): the oracle raises its
    # range error at the month step, so callers must bound-check BOTH
    return (days2 + ddays) * US_PER_DAY + tod + dmicros, days2


def iso_weekday(z):
    """ISO day of week (Mon=1..Sun=7); 1970-01-01 (day 0) was a Thursday.
    ``jnp.mod`` is floor-mod, so negative days (pre-1970) wrap correctly."""
    return (z.astype(jnp.int64) + 3) % 7 + 1


def _ordinal_day(z, y):
    """1-based day of year."""
    jan1 = days_from_civil(y, jnp.ones_like(y), jnp.ones_like(y))
    return (z.astype(jnp.int64) - jan1 + 1).astype(jnp.int64)


def _iso_weeks_in_year(y):
    """52 or 53 (ISO): 53 iff Jan 1 or Dec 31 falls on a Thursday."""
    jan1 = days_from_civil(y, jnp.ones_like(y), jnp.ones_like(y))
    dec31 = days_from_civil(y, jnp.full_like(y, 12), jnp.full_like(y, 31))
    return jnp.where(
        (iso_weekday(jan1) == 4) | (iso_weekday(dec31) == 4), 53, 52
    )


def iso_week_and_year(z):
    """(ISO week number, ISO week-based year)."""
    y, _, _ = civil_from_days(z)
    doy = _ordinal_day(z, y)
    dow = iso_weekday(z)
    woy = (doy - dow + 10) // 7
    prev_weeks = _iso_weeks_in_year(y - 1)
    this_weeks = _iso_weeks_in_year(y)
    week = jnp.where(woy < 1, prev_weeks, jnp.where(woy > this_weeks, 1, woy))
    weekyear = jnp.where(woy < 1, y - 1, jnp.where(woy > this_weeks, y + 1, y))
    return week, weekyear


def split_ldt(us):
    """micros-since-epoch -> (days, time-of-day micros), floor semantics."""
    us = us.astype(jnp.int64)
    days = jnp.floor_divide(us, US_PER_DAY)
    return days, us - days * US_PER_DAY


def date_accessor(key: str, days):
    """One temporal accessor over a days array -> int64 data, or None when
    the key is not a date field (mirrors ``ir.functions.TEMPORAL_ACCESSORS``)."""
    y, m, d = civil_from_days(days)
    if key == "year":
        return y
    if key == "month":
        return m
    if key == "day":
        return d
    if key == "quarter":
        return (m - 1) // 3 + 1
    if key == "dayofweek":
        return iso_weekday(days)
    if key == "ordinalday":
        return _ordinal_day(days, y)
    if key == "week":
        return iso_week_and_year(days)[0]
    if key == "weekyear":
        return iso_week_and_year(days)[1]
    if key == "dayofquarter":
        qm = 3 * ((m - 1) // 3) + 1
        qstart = days_from_civil(y, qm, jnp.ones_like(y))
        return days.astype(jnp.int64) - qstart + 1
    return None


_TRUNC_UNIT_US = {
    "hour": 3600 * US_PER_SECOND,
    "minute": 60 * US_PER_SECOND,
    "second": US_PER_SECOND,
    "millisecond": 1000,
    "microsecond": 1,
}


def truncate_days(unit: str, days):
    """Truncate a days-since-epoch array to the start of ``unit`` (day-or-
    coarser units; proleptic-range-risky millennium/century/decade return
    None — callers fall back to the host, which raises properly on year 0)."""
    if unit == "day":
        return days.astype(jnp.int64)
    if unit == "week":
        return days.astype(jnp.int64) - (iso_weekday(days) - 1)
    y, m, _ = civil_from_days(days)
    one = jnp.ones_like(y)
    if unit == "year":
        return days_from_civil(y, one, one)
    if unit == "quarter":
        return days_from_civil(y, 3 * ((m - 1) // 3) + 1, one)
    if unit == "month":
        return days_from_civil(y, m, one)
    return None


def truncate_ldt_micros(unit: str, us):
    """Truncate a micros-since-epoch array to the start of ``unit``; None
    for unsupported units."""
    days, tod = split_ldt(us)
    if unit in _TRUNC_UNIT_US:
        u = _TRUNC_UNIT_US[unit]
        return days * US_PER_DAY + (tod - tod % u)
    tdays = truncate_days(unit, days)
    if tdays is None:
        return None
    return tdays * US_PER_DAY


def time_accessor(key: str, tod):
    """Accessor over a time-of-day micros array -> int64 data or None."""
    if key == "hour":
        return tod // (3600 * US_PER_SECOND)
    if key == "minute":
        return (tod // (60 * US_PER_SECOND)) % 60
    if key == "second":
        return (tod // US_PER_SECOND) % 60
    if key == "millisecond":
        return (tod % US_PER_SECOND) // 1000
    if key == "microsecond":
        return tod % US_PER_SECOND
    return None

"""Worst-case-optimal multiway join: leapfrog intersection on sorted CSR.

Cyclic Cypher patterns (triangles, diamonds, cliques) are where binary
join plans blow up: closing a cycle over a k-hop chain first materializes
the full k-hop row set — at SF10 the triangle's 2-hop intermediate alone
is ~10^8 rows, which is why the bench ladder skipped the large triangle
rung outright. The WCOJ literature (Ngo/Porat/Re/Rudra generic join,
leapfrog triejoin; TrieJax shows the dataflow mapping, EmptyHeaded the
planner rule) bounds cyclic joins by the fractional edge cover instead:
intersect the candidate's adjacency lists directly and never materialize
the acyclic intermediate.

``MultiwayIntersectOp`` is that operator for ONE cycle-closing binding:
the candidate variable ``c`` must lie in the intersection of K adjacency
lists, each anchored at a variable already bound per input row —

* the PIVOT list: the peeled top expand ``(b)-[r]->(c)`` — candidates
  are ``N(b)`` with pivot-edge multiplicity;
* one CLOSE list per cycle-closing relationship ``(a)-[q]->(c)`` (or
  ``(c)-[q]->(a)``): membership + multiplicity via range counts over the
  sorted ``anchor*N + candidate`` edge keys (``GraphIndex.edge_keys``,
  both orientations — the sorted-by-neighbor CSR contract
  ``GraphIndex.csr_sorted`` is what makes the range contiguous).

Execution is vertex-ordered and per-row ADAPTIVE (the leapfrog move):
every list can serve either role, so each input row iterates its
MINIMUM-degree list and binary-searches the others. Total expanded lanes
are bounded by sum(min_k deg_k) — the AGM-style bound that keeps the
SF10 triangle at ~E*log instead of ~E*d rows. All intermediate sizes
round up the bucket lattice (one compiled program per bucket, pad lanes
masked dead), the sorted-range search dispatches to the hand-scheduled
``pallas/intersect.py`` kernel behind the usual registry, and every
failure degrades: kernel -> jnp searchsorted (dispatch), fused op ->
classic shadow plan (``GraphIndexError``), query -> guard ladder.

Bag semantics match the classic cascade by construction: one output row
per (input row, pivot edge, close-edge combination), candidate label
masks applied once. Relationship uniqueness (openCypher isomorphism)
rides ``enforced_pairs`` exactly like the other fused ops: provably
redundant pairs are dropped by ``plan_filter_fastpath``; the rest are
enforced on the materializing path by comparing global element ids
(output-sized, i.e. cycle-count-sized — small).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, NamedTuple, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ...ir import expr as E
from ...obs import trace as _obs_trace
from ...obs.metrics import REGISTRY as _OBS_REGISTRY
from ...obs.metrics import CounterView
from ...runtime.faults import fault_point
from ...relational.ops import RelationalOperator
from . import bucketing
from . import jit_ops as J
from .column import (
    OBJ,
    Column,
    TpuBackendError,
    mask_to_idx as _mask_to_idx,
    mask_to_idx_bucketed as _mask_to_idx_bucketed,
)
from .expand_op import (
    CsrExpandOp,
    _FusedExpandBase,
    _chain_rel_ends,
    _owner_name,
)
from .graph_index import (
    CANON_NODE,
    CANON_REL,
    GraphIndex,
    GraphIndexError,
    rekey_element_expr,
)

# which tier answered each multiway-intersect pull — bench.py reports these
# per rung (wcoj_count / wcoj_materialize / wcoj_factorized / wcoj_shadow)
WCOJ_TIER_COUNTS = CounterView(
    _OBS_REGISTRY.counter(
        "tpu_cypher_wcoj_tier_total",
        "multiway-intersect executions per resolved tier",
        labels=("tier",),
    ),
    "tier",
    ("count", "materialize", "factorized", "shadow"),
)

_MESH_WCOJ_TOTAL = _OBS_REGISTRY.counter(
    "tpu_cypher_mesh_wcoj_total",
    "WCOJ count executions whose range probes ran on the sharded "
    "(per-shard local searchsorted + psum) intersect tier",
)


def _mesh_range_counter(lists):
    """The sharded range-count program for the WCOJ count tier, or None.

    Eligible when a multi-device mesh is active, ``TPU_CYPHER_MESH_WCOJ``
    is ``auto``, and every intersection list's sorted ``edge_keys`` length
    is shard-divisible (free whenever the graph was ingested under the
    mesh: ``padded_to_mesh`` pads edge keys to a shard multiple with the
    above-everything sentinel, which can never match a probe). Each shard
    then leapfrog-intersects its LOCAL adjacency slice — two binary
    searches over the local keys — and the per-query counts tree-combine
    with ``psum`` (see ``parallel.mesh.sharded_range_count``)."""
    from ...parallel import mesh as PM

    mesh = PM.current_mesh()
    nsh = PM.mesh_size()
    if mesh is None or nsh <= 1:
        return None
    from ...utils.config import MESH_WCOJ

    if MESH_WCOJ.get().strip().lower() != "auto":
        return None
    for lst in lists:
        n_keys = int(lst.keys.shape[0])
        if n_keys == 0 or n_keys % nsh != 0:
            return None
    return PM.sharded_range_count(mesh), nsh


class PivotSpec(NamedTuple):
    """The peeled top expand supplying candidate+multiplicity by CSR row."""

    frontier_fld: str
    rel_fld: str
    far_fld: str  # the candidate variable
    types_key: Tuple[str, ...]
    backwards: bool
    far_labels: Tuple[str, ...]


class CloseSpec(NamedTuple):
    """One cycle-closing relationship tested by sorted-key range count.
    ``rev=True`` means the closing edge runs candidate -> anchor (the
    membership probe uses the reverse-orientation edge keys)."""

    anchor_fld: str
    rel_fld: str
    types_key: Tuple[str, ...]
    rev: bool


class _ListSpec(NamedTuple):
    """One intersection list, fully resolved against the GraphIndex."""

    rp: Any
    ci: Any
    eo: Any
    keys: Any
    pos: Any
    ok: Any
    rel_fld: str


@jax.jit
def _argmin_arm(degs, valid):
    """Per-row index of the minimum-degree list (ties -> first, i.e. the
    pivot); rows with any absent anchor never win an arm (their degrees
    read as +inf and their masked degree is 0 everywhere anyway)."""
    d = jnp.stack(degs)
    big = jnp.int64(1) << 62
    masked = jnp.where(valid[None, :], d, big)
    return jnp.argmin(masked, axis=0).astype(jnp.int32)


@jax.jit
def _arm_degrees(deg, arm, a, valid):
    """Degrees restricted to rows whose minimum list is ``a`` (a python
    int literal — one program per arm index, stable across queries)."""
    deg_a = jnp.where((arm == a) & valid, deg, 0)
    return deg_a, jnp.sum(deg_a)


@partial(jax.jit, static_argnames=("n",))
def _probe_queries(a_pos, a_ok, row, cand, live, n: int):
    """Sorted-key probes ``anchor*N + candidate`` for one searched list.
    Pad lanes (``live`` False, row/cand sanitized to 0) come out invalid so
    their range counts are zeroed inside the range-count contract."""
    q = jnp.take(a_pos, row) * n + cand
    ok = jnp.take(a_ok, row)
    if live is not None:
        ok = ok & live
    return q, ok


@jax.jit
def _mul(a, b):
    return a * b


@jax.jit
def _apply_label_mask(m, mask, cand):
    return m * jnp.take(mask, cand).astype(jnp.int64)


@jax.jit
def _sum_counts(m):
    return jnp.sum(m)


@jax.jit
def _clamp_rows(far_rows):
    # pad lanes may gather a label-filtered node's -1 row-map entry; they
    # are dead past the true count, so clamping keeps the gather in-bounds
    return jnp.maximum(far_rows, 0)


@jax.jit
def _zero_counts(m, keep):
    # lane-domain uniqueness folds into the run multiplicities: a dropped
    # lane contributes zero flat rows, so the factorized form never even
    # decodes it
    return jnp.where(keep, m, 0)


@jax.jit
def _eo_at(eo, pos):
    # run positions of dead/pad rows are clamped by the decode; clip keeps
    # the orig-edge gather in-bounds regardless (OOB under jit fills with
    # int64 min, which would poison downstream rel-scan gathers)
    return jnp.take(eo, jnp.clip(pos, 0, eo.shape[0] - 1))


class MultiwayIntersectOp(_FusedExpandBase):
    """Relational operator: candidate = intersection of K adjacency lists.

    ``children = (in_plan, classic)`` like every fused op: ``in_plan`` is
    the PIVOT's input (it binds the pivot frontier and every close
    anchor), ``classic`` the ExpandInto join cascade with identical
    header — the shadow plan for anything the fused path declines."""

    def __init__(
        self,
        in_plan: RelationalOperator,
        classic: RelationalOperator,
        graph_obj,
        *,
        pivot: PivotSpec,
        closes: Tuple[CloseSpec, ...],
        enforced_pairs: Tuple[Tuple[str, str], ...] = (),
    ):
        super().__init__(in_plan, classic, graph_obj)
        self.pivot = pivot
        self.closes = closes
        self.enforced_pairs = enforced_pairs

    @property
    def candidate_fld(self) -> str:
        return self.pivot.far_fld

    def _ctor_kwargs(self) -> Dict[str, Any]:
        return dict(pivot=self.pivot, closes=self.closes)

    def _show_inner(self) -> str:
        p = self.pivot
        arrow = "<-" if p.backwards else "->"
        t = "|".join(p.types_key) or "*"
        parts = [f"({p.frontier_fld}){arrow}[{p.rel_fld}:{t}]({p.far_fld})"]
        for c in self.closes:
            ct = "|".join(c.types_key) or "*"
            ca = "<-" if c.rev else "->"
            parts.append(f"({c.anchor_fld}){ca}[{c.rel_fld}:{ct}](.)")
        uniq = (
            " uniq" + ",".join(f"({a}<>{b})" for a, b in self.enforced_pairs)
            if self.enforced_pairs
            else ""
        )
        return "wcoj " + " x ".join(parts) + uniq

    # -- uniqueness-proof support -----------------------------------------

    def _rel_ends(self) -> Optional[Dict[str, Tuple[str, str, Tuple[str, ...]]]]:
        """Per-rel GRAPH-direction endpoints over this op's whole fused
        subtree (input chain + pivot + closes) for the redundancy proof in
        ``plan_filter_fastpath``; None when orientation-ambiguous or a rel
        repeats. An unrecognized input contributes nothing — pairs naming
        its rels simply stay unproven."""
        from ...relational.ops import CacheOp

        in_op = self.children[0]
        while isinstance(in_op, CacheOp):
            in_op = in_op.children[0]
        if (
            isinstance(in_op, MultiwayIntersectOp)
            and in_op._graph_obj is self._graph_obj
        ):
            out = in_op._rel_ends()
            if out is None:
                return None
        elif (
            isinstance(in_op, CsrExpandOp)
            and in_op._graph_obj is self._graph_obj
        ):
            out = _chain_rel_ends(in_op._chain_hops())
            if out is None:
                return None
        else:
            out = {}
        p = self.pivot
        ends = [
            (
                p.rel_fld,
                (p.far_fld, p.frontier_fld, p.types_key)
                if p.backwards
                else (p.frontier_fld, p.far_fld, p.types_key),
            )
        ]
        for c in self.closes:
            ends.append(
                (
                    c.rel_fld,
                    (p.far_fld, c.anchor_fld, c.types_key)
                    if c.rev
                    else (c.anchor_fld, p.far_fld, c.types_key),
                )
            )
        for r, v in ends:
            if r in out:
                return None
            out[r] = v
        return out

    # -- execution ---------------------------------------------------------

    def _anchor_flds(self) -> Tuple[str, ...]:
        return (self.pivot.frontier_fld,) + tuple(
            c.anchor_fld for c in self.closes
        )

    def _id_positions(self, gi: GraphIndex, ctx):
        """Compact positions + presence per anchor variable; ``valid`` is
        the all-anchors-present row mask (an absent anchor matches no
        edge, exactly the classic join's null semantics)."""
        from .table import ensure_flat

        in_op = self.children[0]
        in_t = ensure_flat(in_op.table)
        h = in_op.header
        out = []
        valid = None
        for f in self._anchor_flds():
            try:
                col = in_t._cols[h.column(h.id_expr(h.var(f)))]
            except (KeyError, ValueError) as exc:
                raise GraphIndexError(f"intersect anchor {f!r} unmapped") from exc
            pos, ok = gi.compact_of(col, ctx)
            out.append((pos, ok))
            valid = ok if valid is None else valid & ok
        return out, valid

    def _lists(self, gi: GraphIndex, ctx, positions):
        """The unified intersection lists: [0] = pivot, [1:] = closes.
        Each list's CSR orientation puts its ANCHOR on the row axis, and
        its edge keys sort by (anchor*N + candidate) in the same order —
        the one orientation serves both iteration and range counting."""
        p = self.pivot
        specs = [(p.types_key, p.backwards, p.rel_fld)] + [
            (c.types_key, c.rev, c.rel_fld) for c in self.closes
        ]
        out = []
        for (types_key, rev, rel_fld), (pos, ok) in zip(specs, positions):
            rp, ci, eo = gi.csr(types_key, rev, ctx)
            keys = gi.edge_keys(types_key, ctx, reverse=rev)
            out.append(_ListSpec(rp, ci, eo, keys, pos, ok, rel_fld))
        return out

    def _count(self, gi: GraphIndex, ctx, lists, valid) -> int:
        """Pure count tier — the WCOJ hot path. Per arm: expand the rows
        whose minimum-degree list is that arm, range-count every other
        list, multiply, sum. No output materialize, no acyclic
        intermediate; expanded lanes total sum(min_k deg_k)."""
        from . import pallas as P

        fault_point("expand")  # the per-arm count-tier syncs below

        mask = gi.label_mask(self.pivot.far_labels, ctx)
        degs = []
        for lst in lists:
            deg, _ = J.expand_degrees_total(lst.rp, lst.pos, valid)
            degs.append(deg)
        arm = _argmin_arm(tuple(degs), valid)
        bucketed = bucketing.enabled()
        n = gi.num_nodes
        mesh_tier = _mesh_range_counter(lists)
        if mesh_tier is not None:
            mesh_count, nsh = mesh_tier
            _MESH_WCOJ_TOTAL.inc()
            _obs_trace.note("wcoj_shards", nsh)
        total = 0
        for a, lst in enumerate(lists):
            deg_a, t_dev = _arm_degrees(degs[a], arm, a, valid)
            n_a = int(t_dev)
            if n_a == 0:
                continue
            # lanes: row + cand + orig (24B) plus one 8B count per probe
            bucketing.admit(n_a, 24 + 8 * (len(lists) - 1), "intersect")
            if bucketed:
                size = bucketing.round_size(n_a)
                row, cand, _, live = P.expand_materialize_counted(
                    lst.rp, lst.ci, lst.eo, lst.pos, deg_a, t_dev, size=size
                )
            else:
                row, cand, _ = J.expand_materialize(
                    lst.rp, lst.ci, lst.eo, lst.pos, deg_a, total=n_a
                )
                live = None
            m = None
            for b, other in enumerate(lists):
                if b == a:
                    continue
                q, qok = _probe_queries(
                    other.pos, other.ok, row, cand, live, n=n
                )
                if mesh_tier is not None:
                    cnt = mesh_count(other.keys, q, qok)
                else:
                    _, cnt, _ = P.intersect_range_count(other.keys, q, qok)
                m = cnt if m is None else _mul(m, cnt)
            if mask is not None:
                m = _apply_label_mask(m, mask, cand)
            total += int(_sum_counts(m))
        return total

    def _materialize(self, gi: GraphIndex, ctx, lists, valid):
        """Materializing tier (row-producing headers and/or uniqueness
        enforcement): iterate the pivot, expand each lane by its close
        range count so close-edge origs are recoverable as ``eo[lo+k]``.
        Single close keeps the classic output-bound flat path unless the
        factorized router (``optimizer.cost.prefer_factorized``) swaps in
        the run-compressed form; a multi-close materialize (a 4-clique
        whose rel vars someone reads) runs through
        :meth:`_materialize_multi_close` instead of declining to the
        shadow."""
        from . import pallas as P
        from .table import TpuTable
        from ...optimizer.cost import prefer_factorized

        if len(self.closes) != 1:
            return self._materialize_multi_close(gi, ctx, lists, valid)
        pivot, close = lists[0], lists[1]
        n = gi.num_nodes
        mask = gi.label_mask(self.pivot.far_labels, ctx)
        deg, t_dev = J.expand_degrees_total(pivot.rp, pivot.pos, valid)
        total = int(t_dev)
        bucketing.admit(total, 40, "intersect")
        bucketed = bucketing.enabled()
        if bucketed:
            size = bucketing.round_size(total)
            row, cand, orig_p, live = P.expand_materialize_counted(
                pivot.rp, pivot.ci, pivot.eo, pivot.pos, deg, t_dev, size=size
            )
        else:
            row, cand, orig_p = J.expand_materialize(
                pivot.rp, pivot.ci, pivot.eo, pivot.pos, deg, total=total
            )
            live = None
        q, qok = _probe_queries(close.pos, close.ok, row, cand, live, n=n)
        lo, m, out_dev = P.intersect_range_count(close.keys, q, qok)
        if mask is not None:
            m = _apply_label_mask(m, mask, cand)
            out_dev = _sum_counts(m)
        n_out = int(out_dev)
        pair_flds = {r for pr in self.enforced_pairs for r in pr}
        if (
            self.header.expressions
            and self.closes[0].rel_fld not in pair_flds
            and prefer_factorized(
                n_out, 32 + 9 * max(len(self.header.expressions), 1)
            )
        ):
            if self.enforced_pairs:
                # no pair names the close rel, so uniqueness reads only
                # lane-indexed ids and folds into the run multiplicities
                fault_point("compact")
                keep = self._wcoj_pair_keep(gi, ctx, row, orig_p, {})
                m = _zero_counts(m, keep)
                out_dev = _sum_counts(m)
                n_out = int(out_dev)
            fact = self._factorized_assemble(
                gi, ctx, (close,), row, cand, orig_p, total, (lo,), (m,), n_out
            )
            if fact is not None:
                return fact
        bucketing.admit(
            n_out, 32 + 9 * max(len(self.header.expressions), 1), "intersect"
        )
        # one materialize for both modes: with bucketing off, round_size is
        # the identity and the live mask degenerates to all-True, so the
        # counted path IS the exact path — and the size always routes
        # through the lattice
        size2 = bucketing.round_size(n_out)
        lane, orig_c, _ = J.into_materialize_counted(
            close.eo, lo, m, out_dev, size=size2
        )
        in_row, cand2, orig_p2 = J.tree_take((row, cand, orig_p), lane)
        if self.enforced_pairs and n_out:
            # same compaction discipline as _apply_enforced_pairs (two own
            # rels here, so the keep mask is built locally)
            fault_point("compact")
            keep = self._wcoj_pair_keep(
                gi, ctx, in_row, orig_p2, {self.closes[0].rel_fld: orig_c}
            )
            if bucketed:
                if int(in_row.shape[0]) != n_out:
                    keep = keep & J.row_tail_mask(in_row, n_out)
                idx, n_out = _mask_to_idx_bucketed(keep)
                in_row, cand2, orig_p2, orig_c = J.tree_take(
                    (in_row, cand2, orig_p2, orig_c), idx
                )
            else:
                n2 = int(J.mask_sum(keep))
                if n2 != n_out:
                    # tpulint: allow[pad-invariant] reason=bucketing-off branch only (the enabled branch above routes through _mask_to_idx_bucketed); exact size is the contract here
                    idx = J.mask_nonzero(keep, size=n2)
                    in_row, cand2, orig_p2, orig_c = J.tree_take(
                        (in_row, cand2, orig_p2, orig_c), idx
                    )
                    n_out = n2
        if not self.header.expressions:
            return TpuTable({}, n_out)
        _, _, row_map = gi.node_scan(self.pivot.far_labels, ctx)
        far_rows, _ = J.far_lookup(row_map, cand2)
        far_rows = _clamp_rows(far_rows)
        return self._assemble_multi(
            gi, ctx, in_row, orig_p2,
            {self.closes[0].rel_fld: orig_c}, far_rows, n_out,
        )

    def _materialize_multi_close(self, gi: GraphIndex, ctx, lists, valid):
        """Multi-close materialize (a 4-clique whose rel vars someone
        reads, or whose uniqueness pairs survive the planner proof)
        through the run-compressed representation: one suffix level per
        close, lane weights = per-lane range-count products. The flat row
        product (clique4 at SF1: ~878M rows) never materializes — either
        the output stays a ``FactorizedTable``, or the decode walks the
        runs directly at the OUTPUT extent (cycle-count-sized).
        ``TPU_CYPHER_FACTORIZE=off`` keeps the classic decline-to-shadow."""
        from . import pallas as P
        from .factorized import _decode_runs, _runs_weights, factorize_mode
        from .table import TpuTable
        from ...optimizer.cost import prefer_factorized

        if factorize_mode() == "off":
            raise GraphIndexError(
                "multiway materialize supports exactly one close constraint"
            )
        fault_point("expand")  # lane/output totals sync below
        pivot, closes = lists[0], lists[1:]
        n = gi.num_nodes
        mask = gi.label_mask(self.pivot.far_labels, ctx)
        deg, t_dev = J.expand_degrees_total(pivot.rp, pivot.pos, valid)
        total = int(t_dev)
        bucketing.admit(total, 24 + 16 * len(closes), "intersect")
        bucketed = bucketing.enabled()
        if bucketed:
            size = bucketing.round_size(total)
            row, cand, orig_p, live = P.expand_materialize_counted(
                pivot.rp, pivot.ci, pivot.eo, pivot.pos, deg, t_dev, size=size
            )
        else:
            row, cand, orig_p = J.expand_materialize(
                pivot.rp, pivot.ci, pivot.eo, pivot.pos, deg, total=total
            )
            live = None
        los, cnts = [], []
        for j, close in enumerate(closes):
            q, qok = _probe_queries(close.pos, close.ok, row, cand, live, n=n)
            lo_j, m_j, _ = P.intersect_range_count(close.keys, q, qok)
            if j == 0 and mask is not None:
                m_j = _apply_label_mask(m_j, mask, cand)
            los.append(lo_j)
            cnts.append(m_j)
        pair_flds = {r for pr in self.enforced_pairs for r in pr}
        pairs_on_close = bool(pair_flds & {c.rel_fld for c in self.closes})
        if self.enforced_pairs and not pairs_on_close:
            fault_point("compact")
            keep = self._wcoj_pair_keep(gi, ctx, row, orig_p, {})
            cnts[0] = _zero_counts(cnts[0], keep)
        w, W, tot = _runs_weights(tuple(cnts), t_dev)
        n_out = int(tot)
        nexprs = max(len(self.header.expressions), 1)
        if (
            self.header.expressions
            and not pairs_on_close
            and prefer_factorized(n_out, 32 + 9 * nexprs)
        ):
            fact = self._factorized_assemble(
                gi, ctx, closes, row, cand, orig_p, total,
                tuple(los), tuple(cnts), n_out,
            )
            if fact is not None:
                return fact
        # flat through the runs: decode positions at the OUTPUT extent —
        # the per-close blowup never exists on device
        bucketing.admit(n_out, 32 + 9 * nexprs, "intersect")
        size2 = bucketing.round_size(n_out)
        i, pos, live2 = _decode_runs(
            W, w, tuple(los), tuple(cnts), np.int64(0), np.int64(n_out), size2
        )
        in_row, cand2, orig_p2 = J.tree_take((row, cand, orig_p), i)
        orig_cs = {
            c.rel_fld: _eo_at(lst.eo, p_j)
            for c, lst, p_j in zip(self.closes, closes, pos)
        }
        if self.enforced_pairs and pairs_on_close and n_out:
            fault_point("compact")
            keep = self._wcoj_pair_keep(gi, ctx, in_row, orig_p2, orig_cs)
            if bucketed:
                keep = keep & live2
                idx, n_out = _mask_to_idx_bucketed(keep)
                in_row, cand2, orig_p2 = J.tree_take(
                    (in_row, cand2, orig_p2), idx
                )
                orig_cs = J.tree_take(orig_cs, idx)
            else:
                idx, n2 = _mask_to_idx(keep)
                if n2 != n_out:
                    in_row, cand2, orig_p2 = J.tree_take(
                        (in_row, cand2, orig_p2), idx
                    )
                    orig_cs = J.tree_take(orig_cs, idx)
                    n_out = n2
        if not self.header.expressions:
            return TpuTable({}, n_out)
        _, _, row_map = gi.node_scan(self.pivot.far_labels, ctx)
        far_rows, _ = J.far_lookup(row_map, cand2)
        far_rows = _clamp_rows(far_rows)
        return self._assemble_multi(
            gi, ctx, in_row, orig_p2, orig_cs, far_rows, n_out
        )

    def _factorized_assemble(
        self, gi: GraphIndex, ctx, closes, row, cand, orig_p, total,
        los, cnts, n_out: int,
    ):
        """The materialize output in factorized form: prefix = the pivot
        expansion's lane table (input pass-through at ``row``, pivot rel
        at ``orig_p``, candidate node columns at ``far_rows``), one
        suffix run level per close whose columns decode through the
        ``eo[pos]`` gather-map chain at collect time. Admission pays for
        LANES, never the flat product. Returns None when a close-rel
        header column cannot ride the device decode (OBJ or empty rel
        scan) — the caller keeps the flat path."""
        from .factorized import FactorizedTable, RunLevel, note_factorized
        from .table import TpuTable, ensure_flat

        p = self.pivot
        in_op = self.children[0]
        in_t = ensure_flat(in_op.table)
        relp_cols, relp_header = gi.rel_scan(p.types_key, ctx)
        node_cols, node_header, row_map = gi.node_scan(p.far_labels, ctx)
        canon_rel = E.Var(CANON_REL)
        canon_node = E.Var(CANON_NODE)
        close_index = {c.rel_fld: j for j, c in enumerate(self.closes)}
        plan: Dict[str, Tuple[Column, str]] = {}
        level_plans = tuple({} for _ in closes)
        for e in self.header.expressions:
            col = self.header.column(e)
            if col in plan or any(col in lp for lp in level_plans):
                continue
            if e in in_op.header:
                plan[col] = (in_t._cols[in_op.header.column(e)], "row")
                continue
            owner = _owner_name(e)
            if owner == p.rel_fld or owner in close_index:
                key = rekey_element_expr(e, canon_rel)
                if owner == p.rel_fld:
                    cc, hh = relp_cols, relp_header
                else:
                    cc, hh = gi.rel_scan(
                        self.closes[close_index[owner]].types_key, ctx
                    )
                if key is None or key not in hh:
                    raise GraphIndexError(f"unmapped rel expr {e!r}")
                src = cc[hh.column(key)]
                if owner == p.rel_fld:
                    plan[col] = (src, "origp")
                    continue
                if src.kind == OBJ or len(src) == 0:
                    return None
                level_plans[close_index[owner]][col] = src
                continue
            if owner == p.far_fld:
                key = rekey_element_expr(e, canon_node)
                if key is None or key not in node_header:
                    raise GraphIndexError(f"unmapped node expr {e!r}")
                plan[col] = (node_cols[node_header.column(key)], "far")
                continue
            raise GraphIndexError(f"unmapped expr {e!r}")
        far_rows, _ = J.far_lookup(row_map, cand)
        far_rows = _clamp_rows(far_rows)
        bucketing.admit(total, 9 * max(len(plan), 1), "factorized")
        count = total if bucketing.enabled() else None
        pfx_cols = self._gather_plan(
            plan, {"row": row, "origp": orig_p, "far": far_rows}, count=count
        )
        levels = [
            RunLevel(lo_j, m_j, {c: (src, (lst.eo,)) for c, src in lp.items()})
            for lo_j, m_j, lst, lp in zip(los, cnts, closes, level_plans)
        ]
        out = FactorizedTable(TpuTable(pfx_cols, total), levels, nrows=n_out)
        note_factorized(n_out, int(row.shape[0]), total)
        return out

    def _wcoj_pair_keep(self, gi: GraphIndex, ctx, row, orig_p, orig_cs):
        """Row-keep mask for enforced uniqueness pairs: the pivot rel reads
        its canonical rel-scan id at ``orig_p``, a close rel its scan at
        ``orig_cs[rel]`` (an empty dict means the caller proved no pair
        names a close — the lane-domain fold), any other rel its
        input-table id column at ``row`` — element ids are global, so
        cross-type comparisons stay sound."""
        from .table import ensure_flat

        in_op = self.children[0]
        in_t = ensure_flat(in_op.table)
        p = self.pivot
        close_types = {c.rel_fld: c.types_key for c in self.closes}
        cache: Dict[str, Any] = {}

        def ids_of(r):
            if r in cache:
                return cache[r]
            if r == p.rel_fld or r in orig_cs:
                types_key = p.types_key if r == p.rel_fld else close_types[r]
                orig = orig_p if r == p.rel_fld else orig_cs[r]
                cols, hh = gi.rel_scan(types_key, ctx)
                cid = hh.id_expr(hh.var(CANON_REL))
                out = jnp.take(cols[hh.column(cid)].data, orig)
            else:
                h = in_op.header
                try:
                    col = in_t._cols[h.column(h.id_expr(h.var(r)))]
                except (KeyError, ValueError) as exc:
                    raise GraphIndexError(
                        f"uniqueness rel {r!r} unmapped"
                    ) from exc
                out = jnp.take(col.data, row)
            cache[r] = out
            return out

        keep = None
        for ra, rb in self.enforced_pairs:
            k = ids_of(ra) != ids_of(rb)
            keep = k if keep is None else keep & k
        return keep

    def _assemble_multi(self, gi: GraphIndex, ctx, row, orig_p, orig_cs,
                        far_rows, n_out: int):
        """Column assembly with one rel source per fused rel: input
        pass-through at ``row``, pivot rel at ``orig_p``, close rel ``r``
        at ``orig_cs[r]``, candidate node columns at ``far_rows``
        (``_assemble`` handles one rel var; everything else is the same
        tagged-gather plan)."""
        from .table import TpuTable, ensure_flat

        in_op = self.children[0]
        in_t = ensure_flat(in_op.table)
        p = self.pivot
        relp_cols, relp_header = gi.rel_scan(p.types_key, ctx)
        close_scans = {
            c.rel_fld: gi.rel_scan(c.types_key, ctx)
            for c in self.closes
            if c.rel_fld in orig_cs
        }
        node_cols, node_header, _ = gi.node_scan(p.far_labels, ctx)
        canon_rel = E.Var(CANON_REL)
        canon_node = E.Var(CANON_NODE)
        tags = {r: f"origc{j}" for j, r in enumerate(orig_cs)}
        plan: Dict[str, Tuple[Column, str]] = {}
        for e in self.header.expressions:
            col = self.header.column(e)
            if col in plan:
                continue
            if e in in_op.header:
                plan[col] = (in_t._cols[in_op.header.column(e)], "row")
                continue
            owner = _owner_name(e)
            if owner == p.rel_fld or owner in close_scans:
                key = rekey_element_expr(e, canon_rel)
                if owner == p.rel_fld:
                    cc, hh, tag = relp_cols, relp_header, "origp"
                else:
                    cc, hh = close_scans[owner]
                    tag = tags[owner]
                if key is None or key not in hh:
                    raise GraphIndexError(f"unmapped rel expr {e!r}")
                plan[col] = (cc[hh.column(key)], tag)
                continue
            if owner == p.far_fld:
                key = rekey_element_expr(e, canon_node)
                if key is None or key not in node_header:
                    raise GraphIndexError(f"unmapped node expr {e!r}")
                plan[col] = (node_cols[node_header.column(key)], "far")
                continue
            raise GraphIndexError(f"unmapped expr {e!r}")
        count = n_out if bucketing.enabled() else None
        idx_by_tag = {"row": row, "origp": orig_p, "far": far_rows}
        for r, tag in tags.items():
            idx_by_tag[tag] = orig_cs[r]
        out = self._gather_plan(plan, idx_by_tag, count=count)
        return TpuTable(out, n_out)

    def _fused_table(self):
        from ...utils.config import WCOJ_MODE
        from .table import TpuTable

        # the multiway count/materialize syncs sit behind the expand-class
        # fault site like every other fused CSR operator; the kernel tier
        # adds its own kernel_intersect site per dispatch
        fault_point("expand")
        gi = GraphIndex.of(self.graph)
        ctx = self.context
        gi.node_ids(ctx)
        if gi.num_nodes == 0:
            raise GraphIndexError("empty node space: shadow answers")
        if gi.num_nodes >= (1 << 30):
            raise GraphIndexError("intersect keys need pos*N+cand in int64")
        if (
            not self.header.expressions
            and not self.enforced_pairs
            and len(self.closes) == 1
            and WCOJ_MODE.get().strip().lower() != "force"
            and _fused_binary_count_available(gi)
        ):
            # WCOJ's edge is avoiding the MATERIALIZED intermediate. A
            # pure count never materializes on the binary side either
            # when a fused counting tier is in reach (the CPU native
            # stamping kernels, or the dense MXU A@A tier under its node
            # cap) — those count the blowup without ever building it, and
            # measure faster than sum(min-deg) probing. Auto mode hands
            # the count back to the classic plan; force keeps the pure
            # WCOJ path (the bench's wcoj-vs-binary rung, differentials).
            # ONLY single-close shapes hand back: the classic fused tiers
            # count one cycle close, so a multi-close count (clique4+)
            # would shadow into the materialized blowup (the 878M-row
            # r06 note) when `_count`'s range-count products answer it
            # without materializing anything.
            raise GraphIndexError(
                "fused binary count tier predicted faster: shadow answers"
            )
        positions, valid = self._id_positions(gi, ctx)
        lists = self._lists(gi, ctx, positions)
        if not self.header.expressions and not self.enforced_pairs:
            WCOJ_TIER_COUNTS.inc("count")
            _obs_trace.note("wcoj_tier", "count")
            return TpuTable({}, self._count(gi, ctx, lists, valid))
        from .factorized import FactorizedTable

        out = self._materialize(gi, ctx, lists, valid)
        tier = "factorized" if isinstance(out, FactorizedTable) else "materialize"
        WCOJ_TIER_COUNTS.inc(tier)
        _obs_trace.note("wcoj_tier", tier)
        return out

    def _compute_table(self):
        try:
            return self._fused_table()
        except (GraphIndexError, TpuBackendError):
            WCOJ_TIER_COUNTS.inc("shadow")
            _obs_trace.note("wcoj_tier", "shadow")
            return self.children[1].table


# ---------------------------------------------------------------------------
# Planner hook (installed via TpuTable.plan_multiway_intersect_fastpath)
# ---------------------------------------------------------------------------


def _fused_binary_count_available(gi: GraphIndex) -> bool:
    """Will the CLASSIC plan answer a pure cycle-close count through a
    fused counting tier that never materializes the intermediate? True on
    the CPU backend (the native stamping kernels in ``expand_op`` — the
    0.06s-at-SF1 path) and whenever the dense MXU ``A @ A`` tier is live
    under ``dense_adj``'s node cap. In both cases the binary side dodges
    the blowup WCOJ exists to avoid, and its per-edge stamping/matmul
    beats per-lane sorted probing — so auto mode should not steal the
    count. Materializing shapes are untouched: there the binary plan
    really does build the blowup and the multiway intersection wins."""
    from .expand_op import _mxu_dense_mode

    if jax.default_backend() == "cpu":
        return True
    # dense_adj's size gate (max_nodes=16384): past it the dense form is
    # declined and the binary plan falls back to materializing frontiers
    return _mxu_dense_mode() and 0 < gi.num_nodes <= 16384


def _est_binary_blowup(gi: GraphIndex, ctx, types_key, rev: bool) -> int:
    """Upper bound on the binary plan's intermediate for closing a cycle
    over the pivot: edges(pivot types) * max_degree(pivot orientation) —
    each frontier row of an edge-shaped input can expand by up to the max
    degree before the close filters. Host-cached per (types, orientation);
    the EmptyHeaded-style rule compares it against the cost model's
    per-graph routing threshold (``optimizer.cost.wcoj_threshold``)."""
    cache = getattr(gi, "_wcoj_est", None)
    if cache is None:
        cache = gi._wcoj_est = {}
    got = cache.get((types_key, rev))
    if got is None:
        s, _, _ = gi._edge_endpoints(types_key, ctx)
        max_deg, _ = gi.csr_degree_stats(types_key, rev, ctx)
        got = cache[(types_key, rev)] = int(len(s)) * int(max(max_deg, 1))
    return got


def plan_multiway_intersect_fastpath(
    planner, op, in_plan, classic
) -> Optional[RelationalOperator]:
    """Route a cycle-closing ExpandInto to ``MultiwayIntersectOp``.

    The planner only calls this when its join-variable cycle detection
    fired (``_closes_pattern_cycle``); this hook adds the BACKEND half of
    the EmptyHeaded rule: structural fit (a directed fused expand to peel
    as the pivot, or an existing multiway op to extend with one more
    close) plus, in ``auto`` mode, the degree-stats blowup estimate —
    small graphs keep today's binary plan, blowup-prone ones switch.
    ``TPU_CYPHER_WCOJ=force`` routes every structural fit (differential
    tests), ``off`` disables routing entirely."""
    from ...relational.ops import CacheOp
    from ...utils.config import WCOJ_MODE

    mode = WCOJ_MODE.get().strip().lower()
    if mode not in ("auto", "force"):
        return None
    if op.direction != ">":
        return None
    in_vars = {v.name for v in in_plan.header.vars}
    if op.rel in in_vars or op.source not in in_vars or op.target not in in_vars:
        return None
    if op.source == op.target:
        return None
    node = in_plan
    while isinstance(node, CacheOp):
        node = node.children[0]
    types = getattr(op.rel_type.material, "types", frozenset()) or frozenset()
    types_key = GraphIndex.types_key(types)

    def shadow_plan():
        # the shadow child should be the plan "off" mode would have built
        # — the FUSED CsrExpandIntoOp (native/MXU count tiers, edge-key
        # probe), not the naive rel-scan JoinOp the planner hands us. A
        # tier decline (auto count hand-back, multi-close materialize,
        # corner graphs) then costs what the binary plan costs, instead
        # of paying a full hash-join cascade. The JoinOp stays the
        # fallback for anything the fused fastpath itself declines.
        fast_into = getattr(planner.ctx.table_cls, "plan_expand_into_fastpath", None)
        if fast_into is not None:
            upgraded = fast_into(planner, op, in_plan, classic)
            if upgraded is not None:
                return upgraded
        return classic

    if isinstance(node, MultiwayIntersectOp):
        # extend: one more close constraint on the same candidate
        # (4-cliques and denser); eligibility was already decided when the
        # base op routed
        cand = node.candidate_fld
        if cand not in (op.source, op.target):
            return None
        anchor = op.target if cand == op.source else op.source
        rel_names = {node.pivot.rel_fld} | {c.rel_fld for c in node.closes}
        if op.rel in rel_names or anchor == cand:
            return None
        if anchor not in {v.name for v in node.children[0].header.vars}:
            return None
        close = CloseSpec(anchor, op.rel, types_key, rev=cand == op.source)
        return MultiwayIntersectOp(
            node.children[0],
            shadow_plan(),
            node._graph_obj,
            pivot=node.pivot,
            closes=node.closes + (close,),
            enforced_pairs=node.enforced_pairs,
        )

    if not isinstance(node, CsrExpandOp) or node.undirected:
        return None
    cand = node.far_fld
    if cand not in (op.source, op.target):
        return None
    anchor = op.target if cand == op.source else op.source
    if anchor == cand or op.rel == node.rel_fld:
        return None
    if anchor not in {v.name for v in node.children[0].header.vars}:
        return None
    graph_obj = node._graph_obj
    try:
        gi = GraphIndex.of(graph_obj)
        ctx = in_plan.context
        gi.node_ids(ctx)
        if gi.num_nodes == 0 or gi.num_nodes >= (1 << 30):
            return None
        if mode == "auto":
            # the routing threshold is the cost model's, not the env
            # constant: `wcoj_threshold` returns the measured per-graph
            # crossover (intersect-vs-binary unit costs from profile
            # feedback), honouring TPU_CYPHER_WCOJ_MIN_ROWS verbatim when
            # the operator pinned it and reproducing the hand-tuned
            # default exactly while uncalibrated
            from ...optimizer.cost import prefer_wcoj

            est = _est_binary_blowup(gi, ctx, node.types_key, node.backwards)
            if not prefer_wcoj(est, graph_obj, ctx):
                return None
    except (GraphIndexError, TpuBackendError):
        return None
    pivot = PivotSpec(
        node.frontier_fld,
        node.rel_fld,
        node.far_fld,
        node.types_key,
        node.backwards,
        node.far_labels,
    )
    close = CloseSpec(anchor, op.rel, types_key, rev=cand == op.source)
    return MultiwayIntersectOp(
        node.children[0],
        shadow_plan(),
        graph_obj,
        pivot=pivot,
        closes=(close,),
        enforced_pairs=node.enforced_pairs,
    )
